"""Pluggable AST rule framework + the project's invariant rules.

A rule is a class with an ``id``, a one-line ``summary``, a
``rationale`` (why the invariant exists — shown by ``--list-rules``
and docs/static-analysis.md), and a ``check(project)`` generator of
:class:`Finding`.  Register with ``@register``; the lint driver runs
every registered rule unless ``--rule`` narrows the set.

Suppressions (see docs/static-analysis.md):

- inline — ``# lint: allow[rule-id] reason`` on the flagged line or
  the line directly above.  A suppression with no reason is itself a
  finding: the comment is the review trail.
- baseline — a JSON file of ``{"rule", "path", "reason"}`` entries so
  a PR can land enforcement before every legacy finding is fixed.

Rules read the tree through :class:`Project`, which seeded-violation
tests instantiate over a synthetic mini-tree (and override the
declared fault-site / knob tables) to prove each rule actually fires.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass

from trivy_tpu.analysis import lockstatic

SUPPRESS_RX = re.compile(
    r"#\s*lint:\s*allow\[(?P<rules>[a-z0-9_,\- ]+)\]\s*(?P<reason>.*)")


@dataclass
class Finding:
    rule: str
    path: str   # project-root-relative, forward slashes
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


class PyFile:
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)


class Project:
    """The linted file set: ``trivy_tpu/**/*.py`` plus ``bench.py``,
    tests excluded (they seed violations on purpose).  Declared tables
    (fault sites, knobs) default to the real registries; tests override
    the attributes to exercise coherence rules in isolation."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._files: dict[str, PyFile] = {}
        self._load_order: list[str] = []
        self._collect()
        self.declared_fault_sites = self._extract_fault_sites()
        self.declared_fault_actions = self._extract_fault_actions()
        self.declared_knobs = self._extract_knobs()
        self.declared_span_taxonomy = self._extract_span_taxonomy()
        self.declared_event_kinds = self._extract_event_kinds()
        self.declared_action_kinds = self._extract_action_kinds()
        self.declared_chaos_manifest = self._extract_chaos_manifest()
        self.declared_usage_fields = self._extract_usage_fields()

    def _collect(self) -> None:
        pkg = os.path.join(self.root, "trivy_tpu")
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    self._add(os.path.join(dirpath, fn))
        bench = os.path.join(self.root, "bench.py")
        if os.path.exists(bench):
            self._add(bench)

    def _add(self, path: str) -> None:
        rel = os.path.relpath(path, self.root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            source = f.read()
        self._files[rel] = PyFile(rel, source)
        self._load_order.append(rel)

    def files(self) -> list[PyFile]:
        return [self._files[r] for r in self._load_order]

    def file(self, relpath: str) -> PyFile | None:
        return self._files.get(relpath)

    def doc_text(self, relname: str) -> str | None:
        path = os.path.join(self.root, relname)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as f:
            return f.read()

    # The declared registries come from the LINTED tree's own source
    # (AST-extracted — both tables are literal enough), not from the
    # interpreter's imported trivy_tpu package: `lint --root WORKTREE`
    # must validate the worktree against the worktree's registries.
    # Trees without the registry file (seeded mini-projects) fall back
    # to the real import, and tests override the attributes directly.

    def _registry_assign(self, relpath: str, name: str):
        pf = self.file(relpath)
        if pf is None:
            return None
        for node in pf.tree.body:
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target]
                       if isinstance(node, ast.AnnAssign) else [])
            if any(isinstance(t, ast.Name) and t.id == name
                   for t in targets):
                return node.value
        return None

    def _extract_fault_sites(self):
        value = self._registry_assign(
            "trivy_tpu/resilience/faults.py", "SITES")
        if value is not None:
            try:
                return [(s, tuple(a)) for s, a in ast.literal_eval(value)]
            except (ValueError, TypeError):
                pass
        return self._real_fault_sites()

    def _extract_fault_actions(self):
        value = self._registry_assign(
            "trivy_tpu/resilience/faults.py", "ACTIONS")
        if value is not None:
            try:
                return set(ast.literal_eval(value))
            except (ValueError, TypeError):
                pass
        try:
            from trivy_tpu.resilience import faults
            return set(faults.ACTIONS)
        except ImportError:
            return None  # no action vocabulary known -> skip the check

    def _extract_knobs(self):
        value = self._registry_assign(
            "trivy_tpu/analysis/knobs.py", "KNOBS")
        if isinstance(value, ast.Tuple):
            try:
                from trivy_tpu.analysis.knobs import Knob
                return [Knob(*[ast.literal_eval(a) for a in c.args],
                             **{k.arg: ast.literal_eval(k.value)
                                for k in c.keywords})
                        for c in value.elts]
            except Exception:  # malformed table -> import fallback
                pass
        return self._real_knobs()

    def _extract_span_taxonomy(self):
        """Attribution taxonomy from the LINTED tree's obs/attrib.py
        (lane map, structural set, prefix families, lane vocabulary) —
        AST-extracted like the knob/fault tables; import fallback for
        trees without the module; tests override the attribute."""
        attrib_py = "trivy_tpu/obs/attrib.py"
        vals = {}
        for name in ("SPAN_LANES", "SPAN_STRUCTURAL",
                     "SPAN_PREFIX_LANES", "LANES"):
            node = self._registry_assign(attrib_py, name)
            if node is None:
                vals = None
                break
            try:
                vals[name] = ast.literal_eval(node)
            except (ValueError, TypeError):
                vals = None
                break
        if vals is not None:
            return {
                "span_lanes": dict(vals["SPAN_LANES"]),
                "structural": set(vals["SPAN_STRUCTURAL"]),
                "prefixes": tuple(tuple(p)
                                  for p in vals["SPAN_PREFIX_LANES"]),
                "lanes": tuple(vals["LANES"]),
            }
        try:
            from trivy_tpu.obs import attrib
        except ImportError:
            return None
        return {
            "span_lanes": dict(attrib.SPAN_LANES),
            "structural": set(attrib.SPAN_STRUCTURAL),
            "prefixes": tuple(attrib.SPAN_PREFIX_LANES),
            "lanes": tuple(attrib.LANES),
        }

    def _extract_event_kinds(self):
        """Fleet event-kind registry from the LINTED tree's
        fleet/slo.py EVENTS table (AST-extracted like the fault/knob
        tables; import fallback; tests override the attribute).
        ``None`` means no registry is known and the event-kind rule
        skips (seeded mini-trees override explicitly)."""
        value = self._registry_assign("trivy_tpu/fleet/slo.py", "EVENTS")
        if value is not None:
            try:
                return [(k, d) for k, d in ast.literal_eval(value)]
            except (ValueError, TypeError):
                pass
        if self.file("trivy_tpu/fleet/slo.py") is not None:
            return []  # present but unparsable: the rule flags it
        try:
            from trivy_tpu.fleet import slo
            return list(slo.EVENTS)
        except ImportError:
            return None

    def _extract_action_kinds(self):
        """Fleet-controller action registry from the LINTED tree's
        fleet/controller.py ACTIONS table.  ``None`` means the tree
        has no controller — the event-kind rule then skips its action
        checks entirely (NO import fallback: a seeded mini-tree
        without a controller must keep the pre-controller rule
        behavior, and tests override the attribute to opt in)."""
        value = self._registry_assign(
            "trivy_tpu/fleet/controller.py", "ACTIONS")
        if value is not None:
            try:
                return [(k, d) for k, d in ast.literal_eval(value)]
            except (ValueError, TypeError):
                pass
        if self.file("trivy_tpu/fleet/controller.py") is not None:
            return []  # present but unparsable: the rule flags it
        return None

    def _extract_chaos_manifest(self):
        """Chaos scenario coverage map from the LINTED tree's
        chaos/scenarios.py MANIFEST table.  ``None`` means the tree
        has no chaos package — the chaos-coverage rule then skips
        entirely (NO import fallback: a seeded mini-tree without the
        package must keep pre-chaos rule behavior, and tests override
        the attribute to opt in)."""
        value = self._registry_assign(
            "trivy_tpu/chaos/scenarios.py", "MANIFEST")
        if value is not None:
            try:
                raw = ast.literal_eval(value)
                return {name: [(site, tuple(actions))
                               for site, actions in entries]
                        for name, entries in raw.items()}
            except (ValueError, TypeError):
                pass
        if self.file("trivy_tpu/chaos/scenarios.py") is not None:
            return {}  # present but unparsable: the rule flags it
        return None

    def _extract_usage_fields(self):
        """Cost-vector field catalog from the LINTED tree's
        obs/usage.py FIELDS table.  ``None`` means the tree has no
        usage module — the usage-field rule then skips entirely (NO
        import fallback: seeded mini-trees without the module keep
        pre-metering rule behavior; tests override the attribute)."""
        value = self._registry_assign("trivy_tpu/obs/usage.py", "FIELDS")
        if value is not None:
            try:
                return [(n, d) for n, d in ast.literal_eval(value)]
            except (ValueError, TypeError):
                pass
        if self.file("trivy_tpu/obs/usage.py") is not None:
            return []  # present but unparsable: the rule flags it
        return None

    @staticmethod
    def _real_fault_sites():
        try:
            from trivy_tpu.resilience import faults
            return list(getattr(faults, "SITES", ()))
        except ImportError:  # seeded mini-projects override anyway
            return []

    @staticmethod
    def _real_knobs():
        from trivy_tpu.analysis import knobs
        return list(knobs.KNOBS)


# -------------------------------------------------------------- registry

RULES: dict[str, type] = {}


def register(cls):
    RULES[cls.id] = cls
    return cls


class Rule:
    id = ""
    summary = ""
    rationale = ""

    def check(self, project: Project):
        raise NotImplementedError


# ------------------------------------------------------------- helpers

def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _func_tail(func) -> str | None:
    """Rightmost identifier of a call target: ``a.b.c(...)`` -> ``c``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _module_consts(tree: ast.Module) -> dict[str, str]:
    """Module-level ``NAME = "literal"`` string constants."""
    out: dict[str, str] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            val = _const_str(node.value)
            if val is not None:
                out[node.targets[0].id] = val
    return out


def _walk_with_parents(tree):
    """Yield (node, func_stack) — the enclosing FunctionDef chain."""
    stack: list[ast.AST] = []

    def rec(node):
        is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        if is_fn:
            stack.append(node)
        for child in ast.iter_child_nodes(node):
            yield child, tuple(stack)
            yield from rec(child)
        if is_fn:
            stack.pop()

    yield from rec(tree)


# ======================================================= 1. atomic-write

@register
class AtomicWriteRule(Rule):
    id = "atomic-write"
    summary = ("raw open-for-write / os.replace outside durability/ — "
               "durable state must use durability.atomic")
    rationale = (
        "PR 2 made every persistent write crash-safe (tmp + fsync + "
        "rename + checksum framing). A raw open(path, 'w') reintroduces "
        "torn-write windows the whole durability matrix exists to "
        "close. User-facing output streams are legitimate — suppress "
        "those with a reason.")

    SCOPE = "trivy_tpu/"
    EXEMPT = ("trivy_tpu/durability/",)

    def check(self, project: Project):
        for pf in project.files():
            if not pf.relpath.startswith(self.SCOPE):
                continue
            if pf.relpath.startswith(self.EXEMPT):
                continue
            for node in ast.walk(pf.tree):
                if not isinstance(node, ast.Call):
                    continue
                tail = _func_tail(node.func)
                if tail == "open" and isinstance(node.func, ast.Name):
                    mode = None
                    if len(node.args) >= 2:
                        mode = _const_str(node.args[1])
                    for kw in node.keywords:
                        if kw.arg == "mode":
                            mode = _const_str(kw.value)
                    if mode and any(c in mode for c in "wax"):
                        yield Finding(
                            self.id, pf.relpath, node.lineno,
                            f"raw open(..., {mode!r}) — persistent state "
                            "must go through durability.atomic."
                            "atomic_write (suppress for user-facing "
                            "output streams)")
                elif (tail == "replace"
                      and isinstance(node.func, ast.Attribute)
                      and isinstance(node.func.value, ast.Name)
                      and node.func.value.id == "os"):
                    yield Finding(
                        self.id, pf.relpath, node.lineno,
                        "os.replace outside durability/ — promote via "
                        "durability.atomic (or suppress if this IS an "
                        "atomic-publish idiom)")


# ========================================================= 2. fault-site

@register
class FaultSiteRule(Rule):
    id = "fault-site"
    summary = ("every fault site used in code ⇔ declared in "
               "faults.SITES ⇔ listed in docs/resilience.md")
    rationale = (
        "The fault matrix is only as good as its site list: an "
        "instrumented call site missing from the grammar cannot be "
        "exercised by TRIVY_TPU_FAULTS specs, and a documented site "
        "that no code fires is a matrix hole reviewers trust but "
        "nothing tests. faults.SITES is the single source of truth.")

    FAULT_FNS = {"fire", "check_kill", "check_device", "mangle_write"}
    # site families synthesized at runtime (faults.rpc_site();
    # fleet.endpoint.<index> per replica), never appearing as code
    # literals
    DYNAMIC_FAMILIES = {"rpc", "rpc.scan", "rpc.cache",
                        "fleet.endpoint"}
    DOC = "docs/resilience.md"

    def _used_sites(self, project: Project):
        # a site counts as USED only when it flows into a fault call
        # (directly or via a module constant) — a surviving *_SITE
        # constant whose fire() was deleted must not mask the
        # declared-but-never-fired check
        used: dict[str, tuple[str, int]] = {}
        for pf in project.files():
            consts = _module_consts(pf.tree)
            for node in ast.walk(pf.tree):
                if not isinstance(node, ast.Call):
                    continue
                tail = _func_tail(node.func)
                if tail in self.FAULT_FNS and node.args:
                    site = _const_str(node.args[0])
                    if site is None and isinstance(node.args[0], ast.Name):
                        site = consts.get(node.args[0].id)
                    if site:
                        used.setdefault(site, (pf.relpath, node.lineno))
                for kw in node.keywords:
                    if kw.arg == "fault_site":
                        site = _const_str(kw.value)
                        if site is None and isinstance(kw.value, ast.Name):
                            site = consts.get(kw.value.id)
                        if site:
                            used.setdefault(site,
                                            (pf.relpath, node.lineno))
        return used

    @staticmethod
    def _covered(site: str, declared: set[str]) -> bool:
        return site in declared or any(
            site.startswith(d + ".") for d in declared)

    def check(self, project: Project):
        declared_pairs = project.declared_fault_sites
        declared = {s for s, _ in declared_pairs}
        faults_py = "trivy_tpu/resilience/faults.py"
        if not declared_pairs:
            yield Finding(self.id, faults_py, 1,
                          "faults.SITES is missing or empty — the site "
                          "grammar must be exported as structured data")
            return
        valid_actions = project.declared_fault_actions
        if valid_actions is not None:
            for site, actions in declared_pairs:
                for a in actions:
                    if a not in valid_actions:
                        yield Finding(
                            self.id, faults_py, 1,
                            f"SITES declares unknown action {a!r} for "
                            f"site {site!r}")
        used = self._used_sites(project)
        for site, (path, line) in sorted(used.items()):
            if not self._covered(site, declared):
                yield Finding(
                    self.id, path, line,
                    f"fault site {site!r} used in code but not declared "
                    "in faults.SITES")
        for site in sorted(declared):
            if site in self.DYNAMIC_FAMILIES:
                continue
            if site not in used and not any(
                    u == site or u.startswith(site + ".") for u in used):
                yield Finding(
                    self.id, faults_py, 1,
                    f"fault site {site!r} declared in faults.SITES but "
                    "no code fires it")
        doc = project.doc_text(self.DOC)
        if doc is None:
            yield Finding(self.id, self.DOC, 1,
                          "docs/resilience.md missing — the fault-site "
                          "grammar must be documented")
        else:
            doc_sites = self._doc_sites(doc)
            for site in sorted(declared):
                listed = (site in doc_sites if doc_sites is not None
                          else site in doc)
                if not listed:
                    yield Finding(
                        self.id, self.DOC, 1,
                        f"declared fault site {site!r} not listed in "
                        "docs/resilience.md")
            for site in sorted(doc_sites or ()):
                if not self._covered(site, declared):
                    yield Finding(
                        self.id, self.DOC, 1,
                        f"doc grammar lists fault site {site!r} but "
                        "faults.SITES does not declare it")

    @staticmethod
    def _doc_sites(doc: str):
        """Tokens of the doc's ``site :=`` grammar production (exact
        set — substring matching against prose is unsound: deleting
        ``db.save`` would still 'match' inside ``db.save.metadata``).
        None when the doc has no parseable production; the declared→doc
        check then degrades to the substring test and the reverse
        direction is skipped (seeded mini-project docs)."""
        m = re.search(r"^site\s*:=(.*(?:\n\s*\|.*)*)", doc, re.M)
        if not m:
            return None
        return {t for t in re.split(r"[|\s]+", m.group(1)) if t}


# ======================================================== 3. metric-name

@register
class MetricNameRule(Rule):
    id = "metric-name"
    summary = ("every trivy_tpu_* metric: registered snake_case, "
               "bounded literal label set, cataloged in "
               "docs/observability.md (both directions)")
    rationale = (
        "Dashboards and alerts key on metric names; PR 3's golden test "
        "keeps old names byte-stable but nothing stopped NEW metrics "
        "from skipping the docs catalog or declaring open-ended label "
        "sets. The registry bounds series cardinality at runtime — "
        "this rule bounds it at review time.")

    NAME_RX = re.compile(r"^trivy_tpu_[a-z0-9]+(_[a-z0-9]+)*$")
    REG_FNS = {"counter", "gauge", "histogram"}
    DOC = "docs/observability.md"
    DOC_ROW_RX = re.compile(r"\|\s*`(trivy_tpu_[a-zA-Z0-9_]+)`")

    def check(self, project: Project):
        registered: dict[str, tuple[str, int]] = {}
        for pf in project.files():
            for node in ast.walk(pf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in self.REG_FNS
                        and node.args):
                    continue
                name = _const_str(node.args[0])
                if name is None or not name.startswith("trivy_tpu_"):
                    continue
                registered.setdefault(name, (pf.relpath, node.lineno))
                if not self.NAME_RX.match(name):
                    yield Finding(
                        self.id, pf.relpath, node.lineno,
                        f"metric {name!r} is not snake_case "
                        "(trivy_tpu_[a-z0-9_]+)")
                labels = None
                for kw in node.keywords:
                    if kw.arg == "labels":
                        labels = kw.value
                if labels is not None and not (
                        isinstance(labels, (ast.Tuple, ast.List))
                        and all(_const_str(e) is not None
                                for e in labels.elts)):
                    yield Finding(
                        self.id, pf.relpath, node.lineno,
                        f"metric {name!r}: labels must be a literal "
                        "tuple of names (a computed label set defeats "
                        "the cardinality bound)")
        doc = project.doc_text(self.DOC)
        if doc is None:
            yield Finding(self.id, self.DOC, 1,
                          "docs/observability.md missing — the metric "
                          "catalog lives there")
            return
        # both directions match against parsed catalog ROWS — a prose
        # mention of the name elsewhere in the doc is not a catalog entry
        doc_names = set(self.DOC_ROW_RX.findall(doc))
        for name, (path, line) in sorted(registered.items()):
            if name not in doc_names:
                yield Finding(
                    self.id, path, line,
                    f"metric {name!r} registered but absent from the "
                    "docs/observability.md catalog")
        for name in sorted(doc_names):
            if name not in registered:
                yield Finding(
                    self.id, self.DOC, 1,
                    f"docs/observability.md catalogs {name!r} but no "
                    "code registers it")


# ========================================================== 4. env-knob

@register
class EnvKnobRule(Rule):
    id = "env-knob"
    summary = ("every TRIVY_TPU_* env read declared in analysis.knobs "
               "(and vice versa); docs/knobs.md regenerated")
    rationale = (
        "Undocumented knobs are how operators discover behavior by "
        "reading source at 3am. The knobs table is the contract: every "
        "read is declared with a default and doc line, every declared "
        "knob is actually read, and docs/knobs.md is generated from "
        "the table so it cannot drift.")

    ENV_FNS = {"get", "pop", "getenv"}
    DOC = "docs/knobs.md"

    @staticmethod
    def _is_environ(node) -> bool:
        return (isinstance(node, ast.Attribute)
                and node.attr == "environ"
                and isinstance(node.value, ast.Name)
                and node.value.id == "os")

    def _key_name(self, node, consts) -> tuple[str | None, bool]:
        """-> (resolved TRIVY_TPU_* name or None, is_dynamic)."""
        val = _const_str(node)
        if val is None and isinstance(node, ast.Name):
            val = consts.get(node.id)
        if val is not None:
            return (val, False) if val.startswith("TRIVY_TPU_") else \
                (None, False)
        # computed key: dynamic iff any resolvable fragment carries the
        # prefix (cli/config.py's ENV_PREFIX + flag wildcard)
        for sub in ast.walk(node):
            frag = _const_str(sub)
            if frag is None and isinstance(sub, ast.Name):
                frag = consts.get(sub.id)
            if frag and frag.startswith("TRIVY_TPU_"):
                return None, True
        return None, False

    def _reads(self, pf: PyFile):
        consts = _module_consts(pf.tree)
        for node in ast.walk(pf.tree):
            key = None
            if isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in self.ENV_FNS
                        and (self._is_environ(func.value)
                             or (isinstance(func.value, ast.Name)
                                 and func.value.id == "os"
                                 and func.attr == "getenv"))
                        and node.args):
                    key = node.args[0]
            elif isinstance(node, ast.Subscript):
                if self._is_environ(node.value):
                    key = node.slice
            elif isinstance(node, ast.Compare):
                if (len(node.ops) == 1
                        and isinstance(node.ops[0], (ast.In, ast.NotIn))
                        and self._is_environ(node.comparators[0])):
                    key = node.left
            if key is None:
                continue
            name, dynamic = self._key_name(key, consts)
            if name is not None:
                yield name, node.lineno, False
            elif dynamic:
                yield "", node.lineno, True

    def check(self, project: Project):
        declared = {k.name for k in project.declared_knobs}
        knobs_py = "trivy_tpu/analysis/knobs.py"
        read: set[str] = set()
        for pf in project.files():
            for name, line, dynamic in self._reads(pf):
                if dynamic:
                    yield Finding(
                        self.id, pf.relpath, line,
                        "dynamic TRIVY_TPU_* env read — computed knob "
                        "names bypass the registry (suppress with the "
                        "wildcard's contract if intentional)")
                    continue
                read.add(name)
                if name not in declared:
                    yield Finding(
                        self.id, pf.relpath, line,
                        f"env knob {name!r} read here but not declared "
                        "in analysis.knobs.KNOBS")
        for name in sorted(declared - read):
            yield Finding(
                self.id, knobs_py, 1,
                f"knob {name!r} declared but nothing reads it")
        doc = project.doc_text(self.DOC)
        if doc is not None or project.doc_text("README.md") is not None:
            # staleness is judged against the LINTED tree's extracted
            # table (a --root worktree that adds a knob but forgets to
            # regenerate must fail); seeded mini-projects have no docs/
            # at all -> doc is None AND no README -> skip
            from trivy_tpu.analysis import knobs as knobs_mod
            want = knobs_mod.generate_knobs_md(project.declared_knobs)
            if doc is None:
                yield Finding(
                    self.id, self.DOC, 1,
                    "docs/knobs.md missing — generate it with "
                    "`python -m trivy_tpu.analysis.lint "
                    "--write-knobs-doc`")
            elif doc != want:
                yield Finding(
                    self.id, self.DOC, 1,
                    "docs/knobs.md is stale vs analysis.knobs — "
                    "regenerate with `python -m "
                    "trivy_tpu.analysis.lint --write-knobs-doc`")


# ==================================================== 5. monotonic-clock

@register
class MonotonicClockRule(Rule):
    id = "monotonic-clock"
    summary = ("time.time() banned in retry/deadline/scheduler "
               "arithmetic — use time.monotonic()")
    rationale = (
        "Wall clocks jump (NTP step, VM resume); a deadline computed "
        "from time.time() can expire a request instantly or never. "
        "Elapsed-time math in the timing-sensitive modules must use "
        "the monotonic clock. Wall-clock timestamps persisted for "
        "humans (journals, report clocks, mtime comparisons) live "
        "outside this scope or carry a suppression.")

    SCOPE = (
        "trivy_tpu/resilience/", "trivy_tpu/sched/", "trivy_tpu/rpc/",
        "trivy_tpu/fanal/", "trivy_tpu/detector/", "trivy_tpu/cache/",
        "trivy_tpu/utils/pipeline.py", "trivy_tpu/k8s/node_collector.py",
    )

    def check(self, project: Project):
        for pf in project.files():
            if not pf.relpath.startswith(self.SCOPE):
                continue
            for node in ast.walk(pf.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "time"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "time"):
                    yield Finding(
                        self.id, pf.relpath, node.lineno,
                        "time.time() in a timing-sensitive module — "
                        "use time.monotonic() for elapsed/deadline "
                        "math (suppress only for persisted wall-clock "
                        "timestamps)")


# =================================================== 6. tracing-capture

@register
class TracingCaptureRule(Rule):
    id = "tracing-capture"
    summary = ("callables handed to threads/executors in "
               "obs-instrumented modules must capture/adopt the "
               "tracing context")
    rationale = (
        "PR 3's single-trace-tree guarantee depends on every "
        "cross-thread handoff using tracing.capture() in the submitter "
        "and tracing.adopt() in the worker; one missed handoff turns a "
        "scan's spans into orphaned roots and breaks trace-correlated "
        "log grepping. Server accept loops with no ambient scan "
        "context suppress with that reason.")

    SCOPE = "trivy_tpu/"
    EXECUTOR_RX = re.compile(r"(^|_)(ex|executor|pool)$|executor",
                             re.IGNORECASE)

    @staticmethod
    def _module_instrumented(pf: PyFile) -> bool:
        return "trivy_tpu.obs" in pf.source

    @staticmethod
    def _has_capture(node) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                tail = _func_tail(sub.func)
                if tail in ("capture", "adopt"):
                    return True
        return False

    def check(self, project: Project):
        for pf in project.files():
            if not pf.relpath.startswith(self.SCOPE):
                continue
            if not self._module_instrumented(pf):
                continue
            # class name -> ClassDef, for resolving self.<method> targets
            classes = {n.name: n for n in ast.walk(pf.tree)
                       if isinstance(n, ast.ClassDef)}
            class_of_fn: dict[ast.AST, ast.ClassDef] = {}
            for cls in classes.values():
                for item in cls.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        class_of_fn[item] = cls
            module_fns = {n.name: n for n in pf.tree.body
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))}
            for node, fn_stack in _walk_with_parents(pf.tree):
                if not isinstance(node, ast.Call):
                    continue
                target = None
                kind = None
                tail = _func_tail(node.func)
                if tail == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                            kind = "threading.Thread"
                elif (tail == "submit"
                      and isinstance(node.func, ast.Attribute)):
                    recv = node.func.value
                    recv_name = (recv.id if isinstance(recv, ast.Name)
                                 else recv.attr
                                 if isinstance(recv, ast.Attribute)
                                 else "")
                    if recv_name and self.EXECUTOR_RX.search(recv_name) \
                            and node.args:
                        target = node.args[0]
                        kind = f"{recv_name}.submit"
                if target is None:
                    continue
                # pass if the enclosing function captures/adopts ...
                if fn_stack and self._has_capture(fn_stack[-1]):
                    continue
                # ... or the resolved target function / its class does
                resolved = None
                if isinstance(target, ast.Name):
                    resolved = module_fns.get(target.id)
                elif (isinstance(target, ast.Attribute)
                      and isinstance(target.value, ast.Name)
                      and target.value.id == "self" and fn_stack):
                    cls = class_of_fn.get(fn_stack[-1])
                    if cls is not None:
                        resolved = cls  # whole class: worker methods
                        # often delegate adopt() to a helper method
                if resolved is not None and self._has_capture(resolved):
                    continue
                yield Finding(
                    self.id, pf.relpath, node.lineno,
                    f"{kind} handoff in an obs-instrumented module "
                    "without tracing.capture()/adopt() — worker spans "
                    "will orphan from the submitting scan's trace")


# ====================================================== 7. bare-except

@register
class BareExceptRule(Rule):
    id = "bare-except"
    summary = ("no bare `except:`; `except BaseException` must "
               "re-raise (or carry a suppression explaining delivery)")
    rationale = (
        "InjectedKill is a BaseException precisely so crash simulations "
        "unwind without cleanup handlers running; a handler that "
        "swallows BaseException also swallows the injected kill, "
        "KeyboardInterrupt and interpreter shutdown. Handlers that "
        "transport the exception to another thread re-raise there — "
        "they suppress with that reason.")

    def check(self, project: Project):
        for pf in project.files():
            for node in ast.walk(pf.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if node.type is None:
                    yield Finding(
                        self.id, pf.relpath, node.lineno,
                        "bare `except:` — name the exception type "
                        "(this also catches KeyboardInterrupt and "
                        "InjectedKill)")
                    continue
                names = []
                types = (node.type.elts
                         if isinstance(node.type, ast.Tuple)
                         else [node.type])
                for t in types:
                    if isinstance(t, ast.Name):
                        names.append(t.id)
                if "BaseException" not in names:
                    continue
                if any(isinstance(sub, ast.Raise)
                       for sub in ast.walk(node)):
                    continue
                yield Finding(
                    self.id, pf.relpath, node.lineno,
                    "`except BaseException` without a re-raise — this "
                    "swallows InjectedKill / KeyboardInterrupt; "
                    "re-raise or suppress with the delivery path")


# ======================================================= 8. lock-order

@register
class LockOrderRule(Rule):
    id = "lock-order"
    summary = ("the static `with <lock>` nesting graph must be acyclic "
               "(companion to the runtime witness)")
    rationale = (
        "A lock-order cycle is a deadlock waiting for the right "
        "interleaving. The runtime witness sees real acquisitions in "
        "the concurrency tests; this static pass sees every nesting "
        "the code spells out, so an inversion is caught even when no "
        "test drives both arms. The two graphs share one naming "
        "convention and are unioned in tests/test_analysis.py.")

    SCOPE = "trivy_tpu/"

    def check(self, project: Project):
        files = [(pf.relpath, pf.tree) for pf in project.files()
                 if pf.relpath.startswith(self.SCOPE)]
        edges, where = lockstatic.static_graph(files)
        cyc = lockstatic_find_cycle(edges)
        if cyc:
            spots = []
            for a, b in zip(cyc, cyc[1:]):
                path, line = where.get((a, b), ("?", 0))
                spots.append(f"{a} -> {b} ({path}:{line})")
            first = where.get((cyc[0], cyc[1]), ("trivy_tpu", 1))
            yield Finding(
                self.id, first[0], first[1],
                "static lock-order cycle: " + "; ".join(spots))


def lockstatic_find_cycle(edges):
    from trivy_tpu.analysis.witness import find_cycle
    return find_cycle(edges)


# ==================================================== 9. span-taxonomy

@register
class SpanTaxonomyRule(Rule):
    id = "span-taxonomy"
    summary = ("every span name emitted under trivy_tpu/ ⇔ classified "
               "in obs/attrib.py's attribution taxonomy (both "
               "directions; dynamic families via declared prefixes)")
    rationale = (
        "The bottleneck attribution layer (/debug/profile, bench "
        "capstone) is only as honest as its span taxonomy: an emitted "
        "span the classifier doesn't know silently lands in 'other' "
        "and the roofline verdict drifts, while a classified span no "
        "code emits is vocabulary reviewers trust but nothing feeds. "
        "obs/attrib.py is the single source of truth; bench.py's "
        "harness-only spans are out of scope by design.")

    SPAN_FNS = {"span", "phase", "server_span"}
    SCOPE = "trivy_tpu/"
    ATTRIB_PY = "trivy_tpu/obs/attrib.py"

    def _emitted(self, project: Project):
        """-> ({name: (path, line)}, [(prefix_frag, path, line)]).
        A span name counts when the first argument of a span/phase/
        server_span call resolves to a literal (directly or via a
        module constant); f-string names contribute their leading
        literal fragment as a dynamic-family probe. Unresolvable
        names (helper parameters like obs.phase's forwarding call)
        are ignored — they re-emit a name classified at the real
        call site."""
        used: dict[str, tuple[str, int]] = {}
        dynamic: list[tuple[str, str, int]] = []
        for pf in project.files():
            if not pf.relpath.startswith(self.SCOPE):
                continue
            consts = _module_consts(pf.tree)
            for node in ast.walk(pf.tree):
                if not (isinstance(node, ast.Call)
                        and _func_tail(node.func) in self.SPAN_FNS
                        and node.args):
                    continue
                arg = node.args[0]
                name = _const_str(arg)
                if name is None and isinstance(arg, ast.Name):
                    name = consts.get(arg.id)
                if name is not None:
                    used.setdefault(name, (pf.relpath, node.lineno))
                elif isinstance(arg, ast.JoinedStr) and arg.values:
                    frag = _const_str(arg.values[0])
                    if frag:
                        dynamic.append((frag, pf.relpath, node.lineno))
        return used, dynamic

    def check(self, project: Project):
        tax = project.declared_span_taxonomy
        if tax is None:
            return  # no taxonomy known (mini-tree without attrib)
        lanes = set(tax["lanes"])
        span_lanes = tax["span_lanes"]
        structural = set(tax["structural"])
        prefixes = tuple(tax["prefixes"])
        for name, lane in sorted(span_lanes.items()):
            if lane not in lanes:
                yield Finding(
                    self.id, self.ATTRIB_PY, 1,
                    f"SPAN_LANES maps {name!r} to unknown lane "
                    f"{lane!r} (not in LANES)")
        for prefix, lane in prefixes:
            if lane not in lanes:
                yield Finding(
                    self.id, self.ATTRIB_PY, 1,
                    f"SPAN_PREFIX_LANES maps {prefix!r} to unknown "
                    f"lane {lane!r} (not in LANES)")
        used, dynamic = self._emitted(project)
        declared = set(span_lanes) | structural
        for name, (path, line) in sorted(used.items()):
            if name in declared:
                continue
            if any(name.startswith(p) for p, _l in prefixes):
                continue
            yield Finding(
                self.id, path, line,
                f"span {name!r} emitted here but not classified in "
                "obs/attrib.py (SPAN_LANES / SPAN_STRUCTURAL / a "
                "declared prefix family) — unclassified spans land "
                "in the attribution report's 'other' bucket")
        for frag, path, line in dynamic:
            if not any(frag.startswith(p) or p.startswith(frag)
                       for p, _l in prefixes):
                yield Finding(
                    self.id, path, line,
                    f"dynamic span family {frag!r}… not covered by "
                    "any SPAN_PREFIX_LANES entry in obs/attrib.py")
        for name in sorted(declared):
            if name not in used:
                yield Finding(
                    self.id, self.ATTRIB_PY, 1,
                    f"taxonomy classifies span {name!r} but no "
                    "instrumented call site emits it")
        for prefix, _lane in prefixes:
            if not any(f.startswith(prefix) or prefix.startswith(f)
                       for f, _p, _ln in dynamic) \
                    and not any(u.startswith(prefix) for u in used):
                yield Finding(
                    self.id, self.ATTRIB_PY, 1,
                    f"SPAN_PREFIX_LANES declares family {prefix!r} "
                    "but no call site emits a span under it")


# ====================================================== 10. event-kind

@register
class EventKindRule(Rule):
    id = "event-kind"
    summary = ("every fleet event kind emitted via emit_event() ⇔ "
               "declared in fleet/slo.py EVENTS ⇔ cataloged in "
               "docs/fleet.md, all directions")
    rationale = (
        "The fleet ops event log is the durable record operators "
        "replay after an incident; its value rests on a closed "
        "vocabulary. A kind emitted but undeclared bypasses the "
        "registry's validation and the docs catalog; a declared kind "
        "nothing emits is operational vocabulary reviewers trust but "
        "no code produces; an undocumented kind is a journal record "
        "nobody can interpret at 3am. fleet/slo.py's EVENTS table is "
        "the single source of truth.")

    EMIT_FNS = {"emit_event"}
    # controller action kinds surface at two literal-first-arg sites:
    # the emit funnel (emit_action) and the decision constructor
    # (_Decision) — either one anchors "some code produces this kind"
    ACTION_EMIT_FNS = {"emit_action"}
    ACTION_SITE_FNS = {"emit_action", "_Decision"}
    SLO_PY = "trivy_tpu/fleet/slo.py"
    CONTROLLER_PY = "trivy_tpu/fleet/controller.py"
    DOC = "docs/fleet.md"
    # catalog rows: | `kind` | description |  (the event + controller-
    # action catalogs are the only docs/fleet.md tables whose first
    # cell is a backticked lowercase identifier)
    DOC_ROW_RX = re.compile(r"^\|\s*`([a-z][a-z0-9_]*)`\s*\|", re.M)

    def _calls(self, project: Project, fns: set):
        """(literal-kind-or-None, (path, line)) per call of ``fns`` —
        literal first arguments deduped to their first site, computed
        ones yielded per site."""
        used: dict[str, tuple[str, int]] = {}
        for pf in project.files():
            consts = _module_consts(pf.tree)
            for node in ast.walk(pf.tree):
                if not (isinstance(node, ast.Call)
                        and _func_tail(node.func) in fns
                        and node.args):
                    continue
                kind = _const_str(node.args[0])
                if kind is None and isinstance(node.args[0], ast.Name):
                    kind = consts.get(node.args[0].id)
                if kind is not None:
                    used.setdefault(kind, (pf.relpath, node.lineno))
                else:
                    yield None, (pf.relpath, node.lineno)
        for kind, where in used.items():
            yield kind, where

    def _emitted(self, project: Project):
        yield from self._calls(project, self.EMIT_FNS)

    def check(self, project: Project):
        declared_pairs = project.declared_event_kinds
        if declared_pairs is None:
            return  # no registry known (mini-tree without fleet/slo.py)
        if not declared_pairs:
            yield Finding(self.id, self.SLO_PY, 1,
                          "fleet.slo.EVENTS is missing or empty — the "
                          "event vocabulary must be exported as "
                          "structured data")
            return
        declared = {k for k, _ in declared_pairs}
        used: dict[str, tuple[str, int]] = {}
        for kind, (path, line) in self._emitted(project):
            if kind is None:
                yield Finding(
                    self.id, path, line,
                    "emit_event() with a computed kind — event kinds "
                    "must be literal so the registry/docs coherence "
                    "is checkable (suppress with the contract if "
                    "intentional)")
                continue
            used.setdefault(kind, (path, line))
            if kind not in declared:
                yield Finding(
                    self.id, path, line,
                    f"fleet event kind {kind!r} emitted here but not "
                    "declared in fleet.slo.EVENTS")
        for kind in sorted(declared - set(used)):
            yield Finding(
                self.id, self.SLO_PY, 1,
                f"fleet event kind {kind!r} declared in EVENTS but "
                "no code emits it")
        # ---- controller actions (docs/fleet.md "Self-driving fleet")
        action_pairs = getattr(project, "declared_action_kinds", None)
        actions: set = set()
        if action_pairs is not None:
            if not action_pairs:
                yield Finding(
                    self.id, self.CONTROLLER_PY, 1,
                    "fleet.controller.ACTIONS is missing or empty — "
                    "the controller action vocabulary must be "
                    "exported as structured data")
            actions = {k for k, _ in action_pairs}
            for kind in sorted(actions & declared):
                yield Finding(
                    self.id, self.CONTROLLER_PY, 1,
                    f"kind {kind!r} declared in BOTH fleet.slo.EVENTS "
                    "and fleet.controller.ACTIONS — the vocabularies "
                    "must stay disjoint (actions ride inside "
                    "controller_action events)")
            action_sites: dict[str, tuple[str, int]] = {}
            for kind, (path, line) in self._calls(
                    project, self.ACTION_EMIT_FNS):
                if kind is None:
                    yield Finding(
                        self.id, path, line,
                        "emit_action() with a computed kind — action "
                        "kinds must be literal so the registry/docs "
                        "coherence is checkable (suppress with the "
                        "contract if intentional)")
                    continue
                action_sites.setdefault(kind, (path, line))
                if kind not in actions:
                    yield Finding(
                        self.id, path, line,
                        f"controller action kind {kind!r} emitted "
                        "here but not declared in "
                        "fleet.controller.ACTIONS")
            for kind, (path, line) in self._calls(
                    project, self.ACTION_SITE_FNS - self.ACTION_EMIT_FNS):
                if kind is None:
                    continue  # reconstruction sites may be computed
                action_sites.setdefault(kind, (path, line))
                if kind not in actions:
                    yield Finding(
                        self.id, path, line,
                        f"controller action kind {kind!r} emitted "
                        "here but not declared in "
                        "fleet.controller.ACTIONS")
            for kind in sorted(actions - set(action_sites)):
                yield Finding(
                    self.id, self.CONTROLLER_PY, 1,
                    f"controller action kind {kind!r} declared in "
                    "ACTIONS but no code emits it")
        # ---- the docs/fleet.md catalogs (events + actions)
        doc = project.doc_text(self.DOC)
        if doc is None:
            yield Finding(self.id, self.DOC, 1,
                          "docs/fleet.md missing — the fleet event "
                          "catalog lives there")
            return
        doc_kinds = set(self.DOC_ROW_RX.findall(doc))
        for kind in sorted(declared):
            if kind not in doc_kinds:
                yield Finding(
                    self.id, self.DOC, 1,
                    f"declared fleet event kind {kind!r} absent from "
                    "the docs/fleet.md event catalog")
        for kind in sorted(actions):
            if kind not in doc_kinds:
                yield Finding(
                    self.id, self.DOC, 1,
                    f"declared controller action kind {kind!r} absent "
                    "from the docs/fleet.md action catalog")
        for kind in sorted(doc_kinds - declared - actions):
            if action_pairs is None:
                yield Finding(
                    self.id, self.DOC, 1,
                    f"docs/fleet.md catalogs event kind {kind!r} but "
                    "fleet.slo.EVENTS does not declare it")
            else:
                yield Finding(
                    self.id, self.DOC, 1,
                    f"docs/fleet.md catalogs kind {kind!r} but "
                    "neither fleet.slo.EVENTS nor "
                    "fleet.controller.ACTIONS declares it")


# =================================================== 11. chaos-coverage

@register
class ChaosCoverageRule(Rule):
    id = "chaos-coverage"
    summary = ("chaos scenario MANIFEST ⇔ faults.SITES ⇔ "
               "docs/resilience.md: every (site, action) pair claimed "
               "by exactly one scenario that exists and is documented")
    rationale = (
        "The chaos campaign's coverage gate is only sound if the "
        "manifest it checks against is itself sound. A fault pair no "
        "scenario claims is a hole campaigns can never exercise — the "
        "injection point exists but nothing drives traffic through "
        "it; a pair claimed twice makes per-scenario sweep ownership "
        "ambiguous; a manifest entry without a scenario class is "
        "coverage the campaign silently skips. chaos/scenarios.py's "
        "MANIFEST is the single source of truth and must stay an "
        "exact partition of faults.SITES.")

    SCENARIOS_PY = "trivy_tpu/chaos/scenarios.py"
    DOC = "docs/resilience.md"
    SECTION_RX = re.compile(r"^#+\s*Chaos campaigns\s*$", re.M)

    def _manifest_line(self, project: Project) -> int:
        node = project._registry_assign(self.SCENARIOS_PY, "MANIFEST")
        return getattr(node, "lineno", 1)

    @staticmethod
    def _scenario_class_names(project: Project) -> set[str]:
        """`name = "<literal>"` class attributes of scenarios.py
        ClassDefs — the campaign's scenario registry keys."""
        pf = project.file(ChaosCoverageRule.SCENARIOS_PY)
        names: set[str] = set()
        if pf is None:
            return names
        for node in pf.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in node.body:
                if (isinstance(sub, ast.Assign)
                        and any(isinstance(t, ast.Name)
                                and t.id == "name"
                                for t in sub.targets)):
                    val = _const_str(sub.value)
                    if val:
                        names.add(val)
        return names

    def check(self, project: Project):
        manifest = getattr(project, "declared_chaos_manifest", None)
        if manifest is None:
            return  # tree has no chaos package
        line = self._manifest_line(project)
        if not manifest:
            yield Finding(
                self.id, self.SCENARIOS_PY, line,
                "chaos.scenarios.MANIFEST is missing or not a pure "
                "literal — the scenario coverage map must be exported "
                "as structured data")
            return
        claimed: dict[tuple[str, str], str] = {}
        for name in sorted(manifest):
            for site, actions in manifest[name]:
                for action in actions:
                    pair = (site, action)
                    if pair in claimed and claimed[pair] != name:
                        yield Finding(
                            self.id, self.SCENARIOS_PY, line,
                            f"fault pair {site}:{action} claimed by "
                            f"both {claimed[pair]!r} and {name!r} — "
                            "the manifest must partition faults.SITES")
                    claimed.setdefault(pair, name)
        declared_pairs = project.declared_fault_sites
        # an empty/missing SITES registry is the fault-site rule's
        # finding, not a reason to call every claimed pair unknown
        if declared_pairs:
            registry = {(site, a) for site, actions in declared_pairs
                        for a in actions}
            for site, action in sorted(registry - set(claimed)):
                yield Finding(
                    self.id, self.SCENARIOS_PY, line,
                    f"fault pair {site}:{action} is declared in "
                    "faults.SITES but no chaos scenario claims it — "
                    "campaigns can never cover it")
            for site, action in sorted(set(claimed) - registry):
                yield Finding(
                    self.id, self.SCENARIOS_PY, line,
                    f"chaos manifest claims fault pair {site}:{action} "
                    "that faults.SITES does not declare")
        class_names = self._scenario_class_names(project)
        if project.file(self.SCENARIOS_PY) is not None:
            for name in sorted(set(manifest) - class_names):
                yield Finding(
                    self.id, self.SCENARIOS_PY, line,
                    f"manifest scenario {name!r} has no scenario "
                    "class (no ClassDef with a literal name = "
                    f"{name!r}) — its pairs are coverage the "
                    "campaign silently skips")
            for name in sorted(class_names - set(manifest)):
                yield Finding(
                    self.id, self.SCENARIOS_PY, line,
                    f"scenario class {name!r} is not in MANIFEST — "
                    "it claims no fault pairs and campaigns never "
                    "run it")
        doc = project.doc_text(self.DOC)
        if doc is None:
            return  # the fault-site rule owns the doc's existence
        m = self.SECTION_RX.search(doc)
        if m is None:
            yield Finding(
                self.id, self.DOC, 1,
                'docs/resilience.md has no "Chaos campaigns" section '
                "— the campaign engine must be documented")
            return
        section = doc[m.end():]
        nxt = re.search(r"^#+ ", section, re.M)
        if nxt is not None:
            section = section[:nxt.start()]
        for name in sorted(manifest):
            if f"`{name}`" not in section:
                yield Finding(
                    self.id, self.DOC, 1,
                    f"chaos scenario {name!r} missing from the "
                    'docs/resilience.md "Chaos campaigns" section '
                    "(expected backticked in the scenario table)")


# ==================================================== 12. usage-field

@register
class UsageFieldRule(Rule):
    id = "usage-field"
    summary = ("usage cost-vector fields: emitted ⇔ usage.FIELDS ⇔ "
               "docs/observability.md 'Cost-vector fields' catalog")
    rationale = (
        "Billing-adjacent data must not drift: a usage.add() of a "
        "field the FIELDS registry does not declare is spend nobody "
        "can interpret, a declared field nothing emits is a catalog "
        "entry operators will query forever and always read zero, and "
        "an undocumented field is a number tenants see on their bill "
        "with no definition behind it. The registry is the single "
        "source of truth and must stay a pure literal so this rule "
        "(and the docs) can read it without importing the tree.")

    USAGE_PY = "trivy_tpu/obs/usage.py"
    DOC = "docs/observability.md"
    SECTION_RX = re.compile(r"^#+\s*Cost-vector fields\s*$", re.M)
    DOC_ROW_RX = re.compile(r"^\|\s*`([a-z0-9_]+)`", re.M)

    def _fields_line(self, project: Project) -> int:
        node = project._registry_assign(self.USAGE_PY, "FIELDS")
        return getattr(node, "lineno", 1)

    @staticmethod
    def _emitted(project: Project):
        """(field, path, line) for every literal usage.add()/add_to()
        call site; add_lanes() call sites anchor the ``lane_s``
        conservation field (attrib hands a whole lane dict over, so
        no literal field name appears there)."""
        for pf in project.files():
            if pf.relpath == UsageFieldRule.USAGE_PY:
                continue  # the registry's own module
            for node in ast.walk(pf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "usage"):
                    continue
                if node.func.attr == "add" and node.args:
                    yield (_const_str(node.args[0]), pf.relpath,
                           node.lineno)
                elif node.func.attr == "add_to" and len(node.args) >= 2:
                    yield (_const_str(node.args[1]), pf.relpath,
                           node.lineno)
                elif node.func.attr == "add_lanes":
                    yield ("lane_s", pf.relpath, node.lineno)

    def check(self, project: Project):
        declared_pairs = getattr(project, "declared_usage_fields", None)
        if declared_pairs is None:
            return  # tree has no usage module
        line = self._fields_line(project)
        if not declared_pairs:
            yield Finding(
                self.id, self.USAGE_PY, line,
                "obs.usage.FIELDS is missing or not a pure literal — "
                "the cost-vector catalog must be exported as "
                "structured data")
            return
        declared = {n for n, _d in declared_pairs}
        emitted: dict[str, tuple[str, int]] = {}
        for field, path, lineno in self._emitted(project):
            if field is None:
                yield Finding(
                    self.id, path, lineno,
                    "usage field name must be a string literal — a "
                    "computed field defeats the catalog check")
                continue
            emitted.setdefault(field, (path, lineno))
            if field not in declared:
                yield Finding(
                    self.id, path, lineno,
                    f"usage field {field!r} emitted but not declared "
                    "in obs.usage.FIELDS")
        for field in sorted(declared - set(emitted)):
            yield Finding(
                self.id, self.USAGE_PY, line,
                f"usage field {field!r} declared in FIELDS but no "
                "code emits it — operators will query it forever and "
                "always read zero")
        doc = project.doc_text(self.DOC)
        if doc is None:
            return  # the metric-name rule owns the doc's existence
        m = self.SECTION_RX.search(doc)
        if m is None:
            yield Finding(
                self.id, self.DOC, 1,
                'docs/observability.md has no "Cost-vector fields" '
                "section — the usage catalog must be documented")
            return
        section = doc[m.end():]
        nxt = re.search(r"^#+ ", section, re.M)
        if nxt is not None:
            section = section[:nxt.start()]
        doc_fields = set(self.DOC_ROW_RX.findall(section))
        for field in sorted(declared - doc_fields):
            yield Finding(
                self.id, self.DOC, 1,
                f"usage field {field!r} missing from the "
                '"Cost-vector fields" table')
        for field in sorted(doc_fields - declared):
            yield Finding(
                self.id, self.DOC, 1,
                f'"Cost-vector fields" table documents {field!r} but '
                "obs.usage.FIELDS does not declare it")


# ----------------------------------------------------------- the driver

def _suppression_for(pf: PyFile | None, finding: Finding):
    """-> ("ok" | "missing-reason" | None)."""
    if pf is None:
        return None
    for ln in (finding.line, finding.line - 1):
        if 1 <= ln <= len(pf.lines):
            m = SUPPRESS_RX.search(pf.lines[ln - 1])
            if m:
                rules = {r.strip() for r in m.group("rules").split(",")}
                if finding.rule in rules:
                    return "ok" if m.group("reason").strip() else \
                        "missing-reason"
    return None


def load_baseline(path: str) -> list[dict]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("findings", [])
    for e in entries:
        if not (e.get("rule") and e.get("path")):
            raise ValueError(
                "baseline entries need at least {rule, path}")
    return entries


def run(project: Project, rule_ids=None, baseline=None):
    """Run rules -> (findings, suppressed).

    ``baseline`` is a list of ``{"rule", "path", "reason"}`` dicts;
    entries without a non-empty reason are reported as findings
    (rule id ``baseline``) rather than honored — a baseline is
    staged debt, not a mute button."""
    baseline = baseline or []
    base_ok = set()
    findings: list[Finding] = []
    suppressed: list[tuple[Finding, str]] = []
    for e in baseline:
        if str(e.get("reason", "")).strip():
            base_ok.add((e["rule"], e["path"]))
        else:
            findings.append(Finding(
                "baseline", e["path"], 0,
                f"baseline entry for [{e['rule']}] has no reason — "
                "baselines record justified debt, not mutes"))
    for rid, cls in sorted(RULES.items()):
        if rule_ids is not None and rid not in rule_ids:
            continue
        for f in cls().check(project):
            sup = _suppression_for(project.file(f.path), f)
            if sup == "ok":
                suppressed.append((f, "inline"))
            elif sup == "missing-reason":
                findings.append(Finding(
                    "suppression", f.path, f.line,
                    f"suppression of [{f.rule}] has no reason — the "
                    "comment is the review trail"))
            elif (f.rule, f.path) in base_ok:
                suppressed.append((f, "baseline"))
            else:
                findings.append(f)
    return findings, suppressed
