"""Span-to-resource-lane bottleneck attribution + slow-scan flight
recorder (docs/observability.md "Attribution & profiling").

Every span the pipeline already emits is classified into a fixed
taxonomy of **resource lanes** and accumulated per scan and fleet-wide,
answering the question the roadmap's north-star bench keeps asking:
*which lane bounds the run* — fetch I/O, host encode, device dispatch,
device wait, host crunch, queue wait, or report rendering.

Two numbers per lane, from one streaming pass over each completed
root trace:

- **busy seconds** — the wall-clock union of that lane's span
  intervals (overlapping spans of one lane count once);
- **critical seconds** — the lane's slice of an exact partition of the
  scan's wall clock: every instant is attributed to the single
  highest-priority lane active at that moment (work lanes outrank
  waits; see ``PRIORITY``), instants with no classified span are
  ``other``.  Critical slices + other == wall, so per-scan lane
  occupancies can never sum past the wall clock.

The taxonomy is machine-checked both ways by the ``span-taxonomy``
lint rule: every span name this module classifies must be emitted by
an instrumented call site under ``trivy_tpu/`` and vice versa, so the
shared vocabulary cannot silently rot.

Wiring: :func:`acquire` installs a completed-root sink into
``obs.tracing`` (refcounted — the RPC server holds it for its
lifetime; ``TRIVY_TPU_ATTRIB=0`` kills it, ``=1`` forces it on for
one-shot CLI runs).  With the sink installed spans collect even while
classic tracing is off, but nothing is buffered beyond the flight
recorder's bounded ring of the N slowest scan traces
(``TRIVY_TPU_FLIGHT_RECORDER_N``), exportable as Chrome trace JSON
from the live server at ``GET /debug/flight`` without ``--trace-export``
having been set at startup.  ``GET /debug/profile`` serves
:func:`Aggregator.snapshot`; ``trivy-tpu profile URL`` renders it.
"""

from __future__ import annotations

import heapq
import os
from collections import deque

from trivy_tpu.analysis.witness import make_lock
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import tracing
from trivy_tpu.obs import usage

# ------------------------------------------------------------ taxonomy

# the fixed resource lanes every classified span accumulates into
LANES = (
    "fetch_io",         # registry/layer/network reads (incl. RPC waits)
    "host_encode",      # query -> tensor encode on the host
    "device_dispatch",  # composing + launching device micro-batches
    "device_wait",      # blocked on device results (shard/screen collect)
    "host_crunch",      # host-side analysis/decode/verify/post-process
    "queue_wait",       # parked behind another lane (scheduler queue,
                        # layer-dedupe singleflight, fetch starvation)
    "report",           # rendering/serializing the finished report
)

# exact span name -> lane (the span-taxonomy lint rule enforces that
# every entry is emitted somewhere under trivy_tpu/ and every literal
# span name emitted there appears here or in SPAN_STRUCTURAL)
SPAN_LANES = {
    "analysis.fetch": "fetch_io",
    "rekor_sbom_discovery": "fetch_io",
    "analysis.walk": "host_crunch",
    "analysis.lane": "host_crunch",
    "analysis.split": "host_crunch",
    "analysis.apply": "host_crunch",
    "apply_layers": "host_crunch",
    "secret_results": "host_crunch",
    "post_hooks": "host_crunch",
    "delta.diff": "host_crunch",
    "pipeline.crunch": "host_crunch",
    "pipeline.finalize": "host_crunch",
    "pipeline.encode": "host_encode",
    "sched.enqueue": "queue_wait",
    "sched.collect": "queue_wait",
    "analysis.await_fetch": "queue_wait",
    "analysis.await_lane": "queue_wait",
    "analysis.dedupe.wait": "queue_wait",
    "sched.batch": "device_dispatch",
    "engine.dispatch": "device_dispatch",
    "engine.shard": "device_wait",
    "engine.host": "device_wait",
    "dcn.merge": "host_crunch",
    "secret.screen": "device_wait",
    "fleet.hedge": "fetch_io",
    "fleet.probe": "fetch_io",
    "fleet.attempt": "fetch_io",
    "report": "report",
}

# structural spans: timed containers whose children carry the lanes —
# classified so the taxonomy is total, but attributed to no lane (their
# un-covered self-time surfaces as `other`)
SPAN_STRUCTURAL = {
    "scan",
    "scan_artifact",
    "driver.scan",
    "inspect",
    "detect",
    "server.scan",
    "fleet",
    "fleet.artifact",
    "monitor.promote",
    "watch.rescore",
    "delta.rematch",
    "fleet.rollout",
    "fleet.control",
}

# dynamic span families (f-string names) -> lane, matched by prefix
SPAN_PREFIX_LANES = (
    ("rpc.", "fetch_io"),
)

# critical-path tie-break, highest first: at any instant the single
# charged lane is the most "actively working" one — work lanes outrank
# waits (a host busy crunching while a fetch is parked is host-bound,
# not fetch-bound), and among waits the device outranks the network
# outranks the queue
PRIORITY = (
    "device_dispatch",
    "host_encode",
    "host_crunch",
    "report",
    "device_wait",
    "fetch_io",
    "queue_wait",
)

# root span names that constitute ONE scan for per-scan records and the
# flight recorder (other roots — watch re-scores, promotes — still
# accumulate into the fleet totals)
SCAN_ROOTS = {"scan", "scan_artifact", "server.scan", "fleet.artifact"}


def classify(name: str) -> str | None:
    """-> lane for a span name, or None (structural/unknown)."""
    lane = SPAN_LANES.get(name)
    if lane is not None:
        return lane
    for prefix, plane in SPAN_PREFIX_LANES:
        if name.startswith(prefix):
            return plane
    return None


# ------------------------------------------------- per-trace attribution

def _merge(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    intervals.sort()
    out: list[tuple[float, float]] = []
    for lo, hi in intervals:
        if out and lo <= out[-1][1]:
            if hi > out[-1][1]:
                out[-1] = (out[-1][0], hi)
        else:
            out.append((lo, hi))
    return out


def attribute_root(root) -> dict:
    """One completed root trace -> per-lane busy/critical seconds.

    Busy = union of the lane's span intervals (clipped to the root's
    window).  Critical = an exact partition of the root window: each
    elementary segment goes to the highest-PRIORITY active lane, the
    uncovered remainder to ``other``.  Guaranteed:
    sum(critical) + other == wall (so lane occupancies never sum past
    the wall clock of the scan)."""
    t0, t1 = root.start, root.start + root.elapsed
    per_lane: dict[str, list[tuple[float, float]]] = {}
    stack = [root]
    while stack:
        s = stack.pop()
        lane = classify(s.name)
        if lane is not None and s is not root:
            lo, hi = max(s.start, t0), min(s.start + s.elapsed, t1)
            if hi > lo:
                per_lane.setdefault(lane, []).append((lo, hi))
        stack.extend(s.children)
    merged = {lane: _merge(iv) for lane, iv in per_lane.items()}
    busy = {lane: sum(hi - lo for lo, hi in iv)
            for lane, iv in merged.items()}

    # elementary-segment sweep for the critical partition. Cuts and
    # every lane's merged interval list are sorted, so one forward
    # pointer per lane keeps the whole sweep linear in the span count
    # — this runs synchronously at every root-span close on the scan
    # thread, so no O(spans^2) rescans of the interval lists
    points = {t0, t1}
    for iv in merged.values():
        for lo, hi in iv:
            points.add(lo)
            points.add(hi)
    cuts = sorted(points)
    crit = dict.fromkeys(merged, 0.0)
    other = 0.0
    active_lanes = [lane for lane in PRIORITY if lane in merged]
    cursor = dict.fromkeys(active_lanes, 0)
    for a, b in zip(cuts, cuts[1:]):
        seg = b - a
        if seg <= 0:
            continue
        for lane in active_lanes:
            iv = merged[lane]
            i = cursor[lane]
            while i < len(iv) and iv[i][1] <= a:
                i += 1
            cursor[lane] = i
            # cuts include every interval endpoint, so covering the
            # segment start covers the whole segment
            if i < len(iv) and iv[i][0] <= a:
                crit[lane] += seg
                break
        else:
            other += seg
    wall = max(root.elapsed, 0.0)
    dominant = "other"
    best = other
    for lane, v in crit.items():
        if v > best:
            dominant, best = lane, v
    return {
        "name": root.name,
        "trace_id": root.trace_id,
        "scan_id": tracing.current_scan_id(),
        "wall_s": wall,
        "busy": busy,
        "crit": crit,
        "other_s": other,
        "dominant": dominant,
    }


# ------------------------------------------------------ flight recorder

def flight_n() -> int:
    """Ring size of the slow-scan flight recorder (0 disables it)."""
    raw = os.environ.get("TRIVY_TPU_FLIGHT_RECORDER_N", "")
    if not raw:
        return 8
    try:
        return max(int(raw), 0)
    except ValueError:
        return 8


#: bounded ring of retained fleet-attempt trace fragments (hedged /
#: failed-over dispatches tagged by the smart client) — kept SEPARATE
#: from the slowest-scan heap so a losing hedge attempt never pollutes
#: the per-scan records, yet stays pullable for cross-replica stitching
FRAGMENT_RING = 32


class FlightRecorder:
    """Bounded ring of the N slowest scan traces seen since the last
    reset — a live server keeps whole trace trees for exactly the scans
    an operator will ask about, exportable as Chrome trace JSON from
    `/debug/flight` without tracing having been enabled at startup.

    Internally a min-heap keyed on wall seconds: a new scan evicts the
    CURRENT FASTEST retained trace once the ring is full, so the ring
    converges on the true top-N slowest.

    A second, separate ring retains fleet-attempt FRAGMENTS: server-
    side trees of hedged/failed-over dispatches (tagged with their
    attempt identity by the smart client). Fragments are not scans —
    the losing attempt of a hedge race must not masquerade as a slow
    scan — but the cross-replica stitcher (fleet/telemetry.py) pulls
    them from `/debug/flight` to rebuild ONE trace per hedged request."""

    def __init__(self):
        self._lock = make_lock("obs.attrib.flight._lock")
        self._heap: list[tuple[float, int, dict, object]] = []
        self._seq = 0
        self._fragments: deque = deque(maxlen=FRAGMENT_RING)

    def offer(self, root, rec: dict) -> None:
        n = flight_n()
        if n <= 0:
            return
        with self._lock:
            self._seq += 1
            entry = (rec["wall_s"], self._seq, rec, root)
            if len(self._heap) < n:
                heapq.heappush(self._heap, entry)
            elif entry[0] > self._heap[0][0]:
                heapq.heapreplace(self._heap, entry)
            # trim if the knob shrank between offers
            while len(self._heap) > n:
                heapq.heappop(self._heap)

    def offer_fragment(self, root, rec: dict) -> None:
        """Retain a fleet-attempt fragment for the stitcher (newest
        FRAGMENT_RING kept; disabled with the recorder itself)."""
        if flight_n() <= 0:
            return
        with self._lock:
            self._fragments.append((rec, root))

    def records(self) -> list[dict]:
        """Retained scan records, slowest first (fragments excluded)."""
        with self._lock:
            entries = sorted(self._heap, reverse=True)
        return [rec for _w, _s, rec, _r in entries]

    def fragment_records(self) -> list[dict]:
        with self._lock:
            return [rec for rec, _r in self._fragments]

    def chrome_doc(self) -> dict:
        """Chrome trace-event JSON of every retained trace (slowest
        first) plus the fleet-attempt fragments, the same shape
        --trace-export writes."""
        with self._lock:
            entries = sorted(self._heap, reverse=True)
            fragments = list(self._fragments)
        flat = []
        for _w, _s, _rec, root in entries:
            stack = [root]
            while stack:
                s = stack.pop()
                flat.append(s)
                stack.extend(s.children)
        for _rec, root in fragments:
            stack = [root]
            while stack:
                s = stack.pop()
                flat.append(s)
                stack.extend(s.children)
        return {"traceEvents": tracing.chrome_events(flat),
                "displayTimeUnit": "ms",
                "flightRecorder": {"n": flight_n(),
                                   "traces": len(entries),
                                   "fragments": len(fragments)}}

    def reset(self) -> None:
        with self._lock:
            self._heap.clear()
            self._fragments.clear()


# ----------------------------------------------------------- aggregator

_RECENT = 64


class Aggregator:
    """Streaming fleet-wide accumulator: every completed root trace is
    attributed once and folded into per-lane totals, a bounded ring of
    recent per-scan records, and the flight recorder."""

    def __init__(self):
        self._lock = make_lock("obs.attrib._lock")
        self.flight = FlightRecorder()
        self._reset_locked()

    def _reset_locked(self) -> None:
        self._busy = dict.fromkeys(LANES, 0.0)
        self._crit = dict.fromkeys(LANES, 0.0)
        self._other = 0.0
        self._wall = 0.0
        self._scans = 0
        self._roots = 0
        self._fragments = 0
        self._recent: deque = deque(maxlen=_RECENT)

    def reset(self) -> None:
        with self._lock:
            self._reset_locked()
        self.flight.reset()

    def observe_root(self, root) -> None:
        """The obs.tracing sink: classify one finished root trace.

        A root carrying a HEDGE attempt tag (``server.scan`` adopted
        from one side of a raced dispatch) is a FRAGMENT of a scan
        whose real root lives on the client: its lanes still fold into
        the fleet totals (the server really did the work), but it is
        not counted as a scan, never enters the per-scan records or
        the slowest-scan ring — a losing hedge attempt must not
        masquerade as an independent slow scan — and is retained in
        the fragment ring for the cross-replica stitcher instead. A
        FAILOVER retry (meta ``failover_attempt``) stays a full scan:
        it is the scan's only server-side record."""
        rec = attribute_root(root)
        is_fragment = (root.name in SCAN_ROOTS
                       and root.meta.get("attempt") is not None)
        is_scan = root.name in SCAN_ROOTS and not is_fragment
        with self._lock:
            self._roots += 1
            self._wall += rec["wall_s"]
            self._other += rec["other_s"]
            for lane, v in rec["busy"].items():
                self._busy[lane] += v
            for lane, v in rec["crit"].items():
                self._crit[lane] += v
            if is_scan:
                self._scans += 1
                self._recent.append(rec)
            if is_fragment:
                self._fragments += 1
        for lane, v in rec["busy"].items():
            if v > 0:
                obs_metrics.ATTRIB_LANE_SECONDS.inc(v, lane=lane,
                                                    kind="busy")
        # conservation hook: the same busy vector the attribution spine
        # just counted is handed to usage metering on this thread — the
        # one that closed the root span, where the request's tenant
        # scope is still ambient — so per-tenant lane-seconds sum to
        # the fleet attribution totals by construction
        usage.add_lanes(rec["busy"])
        for lane, v in rec["crit"].items():
            if v > 0:
                obs_metrics.ATTRIB_LANE_SECONDS.inc(v, lane=lane,
                                                    kind="critical")
        if is_scan:
            self.flight.offer(root, rec)
        elif is_fragment:
            self.flight.offer_fragment(root, rec)

    @staticmethod
    def _round_rec(rec: dict) -> dict:
        return {
            "name": rec["name"],
            "trace_id": rec["trace_id"],
            "scan_id": rec["scan_id"],
            "wall_s": round(rec["wall_s"], 6),
            "busy": {k: round(v, 6) for k, v in rec["busy"].items()},
            "crit": {k: round(v, 6) for k, v in rec["crit"].items()},
            "other_s": round(rec["other_s"], 6),
            "dominant": rec["dominant"],
        }

    def verdict(self) -> str:
        """Roofline-style 'bound by X' verdict over the fleet totals."""
        with self._lock:
            if not self._roots:
                return "no traces observed"
            crit = dict(self._crit)
            other = self._other
            wall = self._wall
        lane = max(crit, key=crit.get)  # LANES order breaks ties
        if other >= crit[lane]:
            share = other / wall if wall else 0.0
            return (f"bound by untracked time ({share:.0%} of wall "
                    "outside classified spans)")
        share = crit[lane] / wall if wall else 0.0
        return f"bound by {lane} ({share:.0%} of the critical path)"

    def snapshot(self) -> dict:
        """The /debug/profile document (JSON-safe)."""
        with self._lock:
            lanes = {
                lane: {
                    "busy_s": round(self._busy[lane], 6),
                    "crit_s": round(self._crit[lane], 6),
                    "crit_share": round(
                        self._crit[lane] / self._wall, 4)
                    if self._wall else 0.0,
                }
                for lane in LANES
            }
            doc = {
                "enabled": enabled(),
                "scans": self._scans,
                "roots": self._roots,
                "fragments": self._fragments,
                "wall_s": round(self._wall, 6),
                "other_s": round(self._other, 6),
                "lanes": lanes,
                "recent": [self._round_rec(r) for r in self._recent],
            }
        doc["verdict"] = self.verdict()
        doc["flight"] = {
            "n": flight_n(),
            "slowest": [
                {"name": r["name"], "trace_id": r["trace_id"],
                 "scan_id": r["scan_id"],
                 "wall_s": round(r["wall_s"], 6),
                 "dominant": r["dominant"]}
                for r in self.flight.records()
            ],
        }
        return doc


AGG = Aggregator()

# --------------------------------------------------------- installation

_refs = 0
_refs_lock = make_lock("obs.attrib._refs_lock")


def _kill_switched() -> bool:
    return os.environ.get("TRIVY_TPU_ATTRIB", "") in ("0", "false")


def enabled() -> bool:
    return tracing._sink is not None


def acquire() -> bool:
    """Refcounted enable: install the attribution sink (a no-op under
    the TRIVY_TPU_ATTRIB=0 kill switch). The RPC server holds one ref
    for its lifetime; pair every acquire with a release()."""
    if _kill_switched():
        return False
    global _refs
    with _refs_lock:
        _refs += 1
        tracing.set_sink(AGG.observe_root)
    return True


def release() -> None:
    global _refs
    with _refs_lock:
        if _refs > 0:
            _refs -= 1
        if _refs == 0 and not _env_forced():
            tracing.set_sink(None)


def _env_forced() -> bool:
    """TRIVY_TPU_ATTRIB=1 keeps attribution on for one-shot CLI runs
    with no server holding a ref."""
    raw = os.environ.get("TRIVY_TPU_ATTRIB", "")
    return raw not in ("", "0", "false")


if _env_forced():  # opt-in for CLI scans: TRIVY_TPU_ATTRIB=1
    tracing.set_sink(AGG.observe_root)
