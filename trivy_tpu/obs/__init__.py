"""Unified observability spine (docs/observability.md).

- `obs.metrics`: thread-safe labeled Counter/Gauge/Histogram registry
  with Prometheus text exposition; `obs.metrics.REGISTRY` holds the
  process-wide spine metrics.
- `obs.tracing`: contextvars-based distributed tracer — spans keep
  parentage across worker threads and over the RPC boundary
  (X-Trivy-Trace), export as Chrome trace-event JSON, and feed
  trace_id/span_id/scan_id into log records.
- `obs.attrib`: span-to-resource-lane bottleneck attribution + the
  slow-scan flight recorder (served at /debug/profile, /debug/flight;
  `trivy-tpu profile`).
- `obs.phase(...)`: the one-liner scan instrumentation point — a trace
  span AND a `trivy_tpu_scan_phase_seconds{phase=...}` observation from
  the same clock, so the trace tree, the histogram, and bench.py
  --phase-json all tell the same story. When a trace is live, the
  observation carries the trace id as an OpenMetrics exemplar — a p99
  bucket links to the exact trace that landed there.
"""

from __future__ import annotations

import contextlib
import time

from trivy_tpu.obs import metrics, tracing
from trivy_tpu.obs import attrib  # noqa: F401 — TRIVY_TPU_ATTRIB=1 self-installs

__all__ = ["metrics", "tracing", "attrib", "phase"]


@contextlib.contextmanager
def phase(span_name: str, phase: str | None = None, **meta):
    """Trace span + per-phase latency histogram in one breath. The
    histogram label defaults to the span name; pass `phase=` when the
    metric catalog name differs (e.g. span "apply_layers" is the
    "cache" phase)."""
    t0 = time.perf_counter()
    trace_id = ""
    try:
        with tracing.span(span_name, **meta) as s:
            if s is not None:
                trace_id = s.trace_id
            yield s
    finally:
        metrics.SCAN_PHASE_SECONDS.observe(
            time.perf_counter() - t0, exemplar=trace_id or None,
            phase=phase or span_name)
