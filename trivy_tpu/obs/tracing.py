"""Context-propagating distributed tracing for the scan spine.

Rebuilt from the thread-local `utils/trace.py` seed on `contextvars`:

- Spans carry 128-bit trace ids and 64-bit span ids; children inherit
  the trace id and record their parent's span id, so worker threads
  (`utils/pipeline.py` adopts the submitting context) and fleet lanes
  attach to the submitting scan's span instead of becoming orphaned
  roots.
- The current (trace_id, span_id) propagates over the RPC boundary via
  the `X-Trivy-Trace` header: the client injects it per request, the
  server adopts it as the parent of its handler span, and because ids
  are shared, a remote scan renders as ONE stitched tree (`render()`
  grafts any collected root under the collected span it names as
  parent — in-process client/server tests see the full picture; across
  processes the ids still join via logs and exports).
- `export_chrome(path)` writes Chrome trace-event JSON ("traceEvents"
  with `ph: "X"` complete events) viewable in Perfetto / chrome://tracing.
- A scan id (one per scan_artifact / fleet artifact) rides a second
  contextvar; `log_fields()` hands trace_id/span_id/scan_id to log.py
  so every log line joins the trace.
- `TRIVY_TPU_SLOW_SPAN_MS` logs any span exceeding the threshold even
  when tracing is off (spans then time themselves but collect nothing).

Enabled via --trace / --trace-export (CLI) or TRIVY_TPU_TRACE=1; the
JAX profiler dump is written when TRIVY_TPU_JAX_TRACE_DIR is set.

Usage:
    with trace.span("scan"):
        with trace.span("inspect"): ...
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading

from trivy_tpu.analysis.witness import make_lock
import time
from dataclasses import dataclass, field

TRACE_HEADER = "X-Trivy-Trace"

_enabled = os.environ.get("TRIVY_TPU_TRACE", "") not in ("", "0", "false")


def _env_slow_ms() -> float | None:
    raw = os.environ.get("TRIVY_TPU_SLOW_SPAN_MS", "")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


_slow_ms: float | None = _env_slow_ms()


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def set_slow_span_ms(ms: float | None) -> None:
    """Override the TRIVY_TPU_SLOW_SPAN_MS threshold (None disables)."""
    global _slow_ms
    _slow_ms = ms


@dataclass
class Span:
    name: str
    trace_id: str = ""
    span_id: str = ""
    parent_id: str = ""
    start: float = 0.0      # perf_counter, for elapsed
    start_ts: float = 0.0   # epoch seconds, for exports
    elapsed: float = 0.0
    tid: int = 0
    children: list["Span"] = field(default_factory=list)
    meta: dict = field(default_factory=dict)


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


# current span + scan id are contextvars: worker threads start from an
# empty context, so nothing leaks between threads, and adopt()/attach()
# copy a captured context in explicitly where propagation is wanted
_current: contextvars.ContextVar[Span | None] = contextvars.ContextVar(
    "trivy_tpu_current_span", default=None)
_scan_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "trivy_tpu_scan_id", default="")
# remote parentage adopted from an incoming X-Trivy-Trace header: the
# next root span opened in this context joins the caller's trace
_remote_link: contextvars.ContextVar[tuple[str, str] | None] = \
    contextvars.ContextVar("trivy_tpu_remote_link", default=None)
# fleet attempt identity (attempt index, endpoint index, kind): set by
# the smart client around hedged/failed-over dispatches so the
# outgoing X-Trivy-Trace header tags WHICH attempt a server-side trace
# fragment belongs to — the cross-replica stitcher joins fragments by
# this tag (docs/observability.md "Fleet observability"). kind "hedge"
# marks a raced duplicate (the server-side tree is a FRAGMENT of one
# scan); kind "failover" marks a sequential retry whose tree is the
# scan's only server-side record and still counts as a scan.
_attempt_tag: contextvars.ContextVar[tuple[int, int, str] | None] = \
    contextvars.ContextVar("trivy_tpu_attempt_tag", default=None)

# finished root spans; generation guards reset() against spans still
# closing on other threads (their append is simply dropped)
_roots: list[Span] = []
_roots_lock = make_lock("obs.tracing._roots_lock")
_generation = 0

# the buffered-root ring bound: a long-running server with tracing on
# (--trace-export, TRIVY_TPU_TRACE=1) used to grow _roots without limit
# until exit; past this many buffered roots the OLDEST trace is dropped
# and counted in trivy_tpu_trace_spans_dropped_total (the export file
# carries the drop count, so a truncated trace is never mistaken for a
# complete one)
MAX_BUFFERED_ROOTS = 4096
_dropped = 0  # spans dropped since the last reset(); guarded by _roots_lock

# completed-root sink (obs.attrib): when set, spans collect and every
# finished ROOT trace is handed to the sink even while classic tracing
# is off — the attribution aggregator and flight recorder see whole
# trees without buffering anything in _roots
_sink = None


def set_sink(fn) -> None:
    """Install (or clear, fn=None) the completed-root-trace sink.
    Owned by obs.attrib — use attrib.acquire()/release() instead of
    calling this directly."""
    global _sink
    _sink = fn


def _span_count(root: Span) -> int:
    n = 0
    stack = [root]
    while stack:
        s = stack.pop()
        n += 1
        stack.extend(s.children)
    return n


def dropped_spans() -> int:
    """Spans evicted from the bounded root buffer since the last
    reset() (mirrored in trivy_tpu_trace_spans_dropped_total)."""
    with _roots_lock:
        return _dropped


class _Noop:
    """Reusable no-op context manager: the disabled-tracing fast path
    allocates nothing per span."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


def span(name: str, **meta):
    if not _enabled and _slow_ms is None and _sink is None:
        return _NOOP
    return _span_cm(name, meta)


@contextlib.contextmanager
def _span_cm(name: str, meta: dict):
    sink = _sink
    collect = _enabled or sink is not None
    slow = _slow_ms
    s = Span(name=name, meta=meta, tid=threading.get_ident())
    token = None
    is_root = False
    gen = _generation
    if collect:
        parent = _current.get()
        if parent is not None:
            s.trace_id = parent.trace_id
            s.parent_id = parent.span_id
            parent.children.append(s)  # GIL-atomic append
        else:
            is_root = True
            link = _remote_link.get()
            if link is not None:
                # adopted remote parentage: still collected as a local
                # root; render() stitches it under the caller's span
                # when that span was collected in this process
                s.trace_id, s.parent_id = link
            else:
                s.trace_id = _new_trace_id()
        s.span_id = _new_span_id()
        token = _current.set(s)
    s.start_ts = time.time()
    s.start = time.perf_counter()
    try:
        yield s
    finally:
        s.elapsed = time.perf_counter() - s.start
        if collect:
            _current.reset(token)
            if is_root:
                if _enabled:
                    evicted = None
                    with _roots_lock:
                        if gen == _generation:  # reset() since open: drop
                            _roots.append(s)
                            if len(_roots) > MAX_BUFFERED_ROOTS:
                                evicted = _roots.pop(0)
                    if evicted is not None:
                        # count the evicted tree OUTSIDE the lock (a
                        # large trace walk must not stall concurrent
                        # span closes), once for both sinks
                        n = _span_count(evicted)
                        global _dropped
                        with _roots_lock:
                            _dropped += n
                        _count_dropped(n)
                if sink is not None:
                    try:
                        sink(s)
                    except Exception:
                        # a broken profiler sink must never break the
                        # scan that produced the trace
                        pass
        if slow is not None and s.elapsed * 1000.0 >= slow:
            _log_slow(s)


def _count_dropped(n: int) -> None:
    # lazy import: metrics never imports tracing, but the package
    # __init__ imports both and the counter is only needed on the rare
    # eviction path
    from trivy_tpu.obs import metrics as _metrics

    _metrics.TRACE_SPANS_DROPPED.inc(n)


def _log_slow(s: Span) -> None:
    from trivy_tpu.log import logger  # lazy: log.py lazily imports us

    kv = {"ms": round(s.elapsed * 1000.0, 1)}
    if s.trace_id:
        kv["trace_id"] = s.trace_id
        kv["span_id"] = s.span_id
    logger("trace").warn(f"slow span: {s.name}", **kv)


def add_meta(**meta) -> None:
    s = _current.get()
    if _enabled and s is not None:
        s.meta.update(meta)


def current() -> Span | None:
    """The innermost open span of this context (None when tracing is
    off or no span is open)."""
    return _current.get()


def current_scan_id() -> str:
    return _scan_id.get()


def log_fields() -> dict | None:
    """trace_id/span_id/scan_id for log correlation (only the fields
    that are set; None when there is nothing to report)."""
    s = _current.get()
    sid = _scan_id.get()
    if s is None and not sid:
        return None
    out: dict = {}
    if s is not None:
        out["trace_id"] = s.trace_id
        out["span_id"] = s.span_id
    if sid:
        out["scan_id"] = sid
    return out


# ------------------------------------------------------- cross-thread

def capture():
    """Snapshot the ambient trace context (current span + scan id) in
    the submitting thread; hand the result to adopt() inside a worker
    thread so its spans attach to the submitting scan instead of
    becoming orphaned roots. Cheap: two contextvar reads."""
    s = _current.get()
    sid = _scan_id.get()
    link = _remote_link.get()
    if s is None and not sid and link is None:
        return None
    return (s, sid, link)


@contextlib.contextmanager
def adopt(captured):
    """Install a capture()d context in this thread for the duration."""
    if captured is None:
        yield
        return
    s, sid, link = captured
    tokens = []
    if s is not None:
        tokens.append((_current, _current.set(s)))
    if sid:
        tokens.append((_scan_id, _scan_id.set(sid)))
    if link is not None:
        tokens.append((_remote_link, _remote_link.set(link)))
    try:
        yield
    finally:
        for var, token in reversed(tokens):
            var.reset(token)


@contextlib.contextmanager
def scan_scope(scan_id: str | None = None, force: bool = False):
    """Make a scan id ambient for log correlation. An id already in
    scope is kept unless `force` (fleet lanes force one per artifact;
    the scanner then inherits it)."""
    if scan_id is None:
        if _scan_id.get() and not force:
            yield _scan_id.get()
            return
        from trivy_tpu.utils import uuid as uuid_util

        scan_id = uuid_util.new()
    token = _scan_id.set(scan_id)
    try:
        yield scan_id
    finally:
        _scan_id.reset(token)


# --------------------------------------------------------- RPC boundary

def inject_headers(headers: dict) -> None:
    """Client side: stamp the current span's identity into the outgoing
    request so the server's spans join this trace. Under an
    :func:`attempt_scope` the header additionally carries
    ``-<attempt>.<endpoint>`` so the server-side fragment is
    attributable to ONE dispatch of a hedged/failed-over request."""
    s = _current.get()
    if (_enabled or _sink is not None) and s is not None:
        value = f"{s.trace_id}-{s.span_id}"
        tag = _attempt_tag.get()
        if tag is not None:
            value += f"-{tag[0]}.{tag[1]}"
            if tag[2] == "failover":
                value += ".f"
        headers[TRACE_HEADER] = value


@contextlib.contextmanager
def attempt_scope(attempt: int, endpoint: int, kind: str = "hedge"):
    """Tag every request injected inside this scope with its fleet
    dispatch identity (attempt index + endpoint index). The smart
    client opens one scope per hedged (kind="hedge") or failed-over
    (kind="failover") dispatch; plain single-dispatch requests stay
    untagged (byte-identical header)."""
    token = _attempt_tag.set((int(attempt), int(endpoint), kind))
    try:
        yield
    finally:
        _attempt_tag.reset(token)


def current_attempt_tag() -> tuple[int, int, str] | None:
    """The ambient fleet dispatch identity, or None outside an
    attempt_scope (the RPC client stamps it onto its span meta so the
    stitched cross-replica trace shows which attempt each client-side
    round trip belonged to)."""
    return _attempt_tag.get()


def parse_trace_header(value: str | None) -> tuple[str, str] | None:
    """'<32-hex trace>-<16-hex span>[-<attempt>.<endpoint>]' ->
    (trace_id, parent_span_id). The optional third segment (a fleet
    attempt tag) is parsed separately by :func:`parse_attempt_tag`."""
    if not value:
        return None
    parts = value.split("-")
    if len(parts) < 2 or not parts[0] or not parts[1]:
        return None
    trace_id, span_id = parts[0], parts[1]
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return trace_id, span_id


def parse_attempt_tag(value: str | None) -> tuple[int, int, str] | None:
    """The '<attempt>.<endpoint>[.f]' segment of an extended trace
    header -> (attempt_index, endpoint_index, kind) where kind is
    "failover" for the ``.f`` suffix and "hedge" otherwise, or None
    when the header is the legacy two-part form (or malformed — never
    an error: tagging only enriches, correctness never depends on
    it)."""
    if not value:
        return None
    parts = value.split("-")
    if len(parts) < 3:
        return None
    fields = parts[2].split(".")
    if len(fields) < 2:
        return None
    try:
        attempt, endpoint = int(fields[0]), int(fields[1])
    except ValueError:
        return None
    kind = "failover" if fields[2:3] == ["f"] else "hedge"
    return attempt, endpoint, kind


@contextlib.contextmanager
def server_span(name: str, header: str | None, **meta):
    """Server side: open a handler span whose parent is the caller's
    span from the X-Trivy-Trace header (fresh root when absent)."""
    link = parse_trace_header(header)
    token = _remote_link.set(link) if link is not None else None
    try:
        with span(name, **meta) as s:
            yield s
    finally:
        if token is not None:
            _remote_link.reset(token)


# ------------------------------------------------------------ lifecycle

def reset() -> None:
    """Drop every collected span, process-wide. Safe to call from any
    thread while spans are open elsewhere (their eventual close is
    discarded by the generation guard) and idempotent when tracing is
    disabled."""
    global _generation, _dropped
    with _roots_lock:
        _generation += 1
        _roots.clear()
        _dropped = 0


def _stitched_roots() -> tuple[list[Span], dict[str, list[Span]]]:
    """Snapshot of collected roots, with roots that name a collected
    span as parent grafted under it (the client RPC span adopts the
    server handler span). Non-destructive: the graft lives in the
    returned extra-children map, not in Span.children."""
    with _roots_lock:
        roots = list(_roots)
    by_id: dict[str, Span] = {}

    def index(s: Span):
        by_id[s.span_id] = s
        for c in s.children:
            index(c)

    for r in roots:
        index(r)
    extra: dict[str, list[Span]] = {}
    top: list[Span] = []
    for r in roots:
        parent = by_id.get(r.parent_id) if r.parent_id else None
        if parent is not None and parent is not r:
            extra.setdefault(parent.span_id, []).append(r)
        else:
            top.append(r)
    return top, extra


def render(out=None) -> str:
    """Render collected spans as an indented tree with timings."""
    lines: list[str] = []
    top, extra = _stitched_roots()

    def walk(s: Span, depth: int):
        extras = "".join(f" {k}={v}" for k, v in s.meta.items())
        lines.append(f"{'  ' * depth}{s.name:<{28 - 2 * depth}} "
                     f"{s.elapsed * 1000:9.1f} ms{extras}")
        for c in s.children:
            walk(c, depth + 1)
        for c in extra.get(s.span_id, ()):
            walk(c, depth + 1)

    for root in top:
        walk(root, 0)
    text = "\n".join(lines)
    if out is not None and text:
        out.write("-- trace " + "-" * 42 + "\n" + text + "\n")
    return text


def spans() -> list[Span]:
    """Flat list of every collected span (roots first, then children)."""
    out: list[Span] = []

    def walk(s: Span):
        out.append(s)
        for c in s.children:
            walk(c)

    with _roots_lock:
        roots = list(_roots)
    for r in roots:
        walk(r)
    return out


def timings() -> dict[str, float]:
    """Aggregate elapsed seconds per span name across the collection —
    the per-phase breakdown bench.py --phase-json dumps."""
    agg: dict[str, float] = {}
    for s in spans():
        agg[s.name] = agg.get(s.name, 0.0) + s.elapsed
    return {k: round(v, 6) for k, v in agg.items()}


def chrome_events(span_list: list[Span] | None = None) -> list[dict]:
    """Chrome trace-event 'complete' (ph=X) events for every collected
    span (or an explicit span list — the flight recorder exports its
    retained traces this way); timestamps in microseconds since epoch."""
    events = []
    for s in (spans() if span_list is None else span_list):
        args = {"trace_id": s.trace_id, "span_id": s.span_id}
        if s.parent_id:
            args["parent_id"] = s.parent_id
        args.update({k: str(v) for k, v in s.meta.items()})
        events.append({
            "name": s.name,
            "ph": "X",
            "ts": round(s.start_ts * 1e6, 1),
            "dur": round(s.elapsed * 1e6, 1),
            "pid": os.getpid(),
            "tid": s.tid,
            "cat": "trivy_tpu",
            "args": args,
        })
    return events


def export_chrome(path: str) -> int:
    """Write the collected spans as Chrome trace-event JSON (open in
    Perfetto / chrome://tracing). Returns the number of events."""
    events = chrome_events()
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           # bounded-buffer honesty: spans evicted by the root ring
           # since the last reset — non-zero means this file is a
           # truncated window, not the whole run
           "spansDropped": dropped_spans()}
    # lint: allow[atomic-write] user-requested --trace-export artifact, not program state
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return len(events)


@contextlib.contextmanager
def jax_profile():
    """Capture a JAX profiler trace when TRIVY_TPU_JAX_TRACE_DIR is set
    (viewable with tensorboard/xprof)."""
    trace_dir = os.environ.get("TRIVY_TPU_JAX_TRACE_DIR", "")
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
