"""Labeled metrics registry with Prometheus text exposition.

One `Registry` holds `Counter` / `Gauge` / `Histogram` metrics, each with
a fixed label-name tuple and a bounded number of label-value series (a
runaway label set raises `CardinalityError` instead of silently eating
RSS). All mutation and the exposition render share ONE re-entrant lock
per registry, so `render()` is a consistent snapshot — concurrent scans
cannot produce torn reads of related counters, and multi-field updates
(e.g. the server's scans_total + scan_seconds_sum) can be grouped under
`registry.locked()`.

Two scopes exist by convention:

- `REGISTRY` (module-level): process-wide spine metrics — scan-phase
  latency, RPC client round-trips, retries, breaker state, degraded
  activations, fault-injector fires, cache corruption.
- per-service registries: the RPC server's `Metrics` keeps its own so a
  fresh `Server` starts from zero (tests spin several per process); its
  /metrics response concatenates both scopes.

The exposition writer emits `# HELP` / `# TYPE` for every registered
metric (even before the first sample) in the Prometheus text format
0.0.4 the `/metrics` endpoint advertises.
"""

from __future__ import annotations

import threading
import time

from trivy_tpu.analysis.witness import make_lock
from typing import Callable, Iterable

# Fixed default latency buckets (seconds): micro-phases up to the
# 60 s north-star crawl budget.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0,
)

DEFAULT_MAX_SERIES = 256


class MetricError(ValueError):
    """Metric misuse: bad labels, type clash, duplicate registration."""


class CardinalityError(MetricError):
    """A metric grew more label-value series than its bound allows."""


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render as integers,
    the rest in shortest-round-trip-ish form (0.75 -> "0.75")."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return format(v, ".10g")


def _escape(value: str) -> str:
    return (value.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _labels_text(names: tuple[str, ...], values: tuple[str, ...],
                 extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [f'{n}="{_escape(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Metric:
    """Common series bookkeeping; subclasses define the sample shape."""

    kind = "untyped"

    def __init__(self, registry: "Registry", name: str, help_text: str,
                 labels: tuple[str, ...], max_series: int,
                 collapse_label: tuple[str, int] | None = None):
        self.registry = registry
        self.name = name
        self.help = help_text
        self.label_names = labels
        self.max_series = max_series
        self.collapse_label = collapse_label
        self._collapse_seen: set[str] = set()
        self._series: dict[tuple[str, ...], object] = {}

    def _materialize_unlabeled(self) -> None:
        # an unlabeled metric exposes its zero sample immediately (the
        # hand-rolled server Metrics always rendered "name 0"); labeled
        # metrics stay empty until a label set is first used
        if not self.label_names:
            self._series[()] = self._new_state()

    def _key(self, labels: dict) -> tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise MetricError(
                f"{self.name}: got labels {sorted(labels)!r}, declared "
                f"{sorted(self.label_names)!r}")
        return tuple(str(labels[n]) for n in self.label_names)

    def _collapse(self, key: tuple[str, ...],
                  register: bool = True) -> tuple[str, ...]:
        """Top-N-collapse policy: once `collapse_label=(name, N)` has
        seen N distinct values for that label, every new value is
        rewritten to "other" instead of growing a fresh series — an
        unbounded public label (e.g. tenant) can then never trip
        CardinalityError. No collapse_label (the default) leaves the
        key — and the legacy exposition bytes — untouched.
        `register=False` applies the rewrite without admitting a new
        value (reads must not consume top-N slots)."""
        if self.collapse_label is None:
            return key
        lname, n = self.collapse_label
        try:
            i = self.label_names.index(lname)
        except ValueError:
            return key
        v = key[i]
        if v == "other" or v in self._collapse_seen:
            return key
        if len(self._collapse_seen) >= n:
            return key[:i] + ("other",) + key[i + 1:]
        if register:
            self._collapse_seen.add(v)
        return key

    def _slot(self, labels: dict) -> tuple[str, ...]:
        """Get-or-create the series state for a label set; returns the
        series key (the one label-validation pass per update). Caller
        holds the registry lock."""
        key = self._collapse(self._key(labels))
        if key not in self._series:
            if len(self._series) >= self.max_series:
                raise CardinalityError(
                    f"{self.name}: more than {self.max_series} label "
                    f"sets (runaway label values? latest: {key!r})")
            self._series[key] = self._new_state()
        return key

    def _new_state(self):
        raise NotImplementedError

    def clear(self) -> None:
        with self.registry._lock:
            self._series.clear()
            self._collapse_seen.clear()

    # rendering -------------------------------------------------------

    def _om_family(self) -> tuple[str, str]:
        """(family name, type) for the OpenMetrics metadata lines.
        OpenMetrics names a counter FAMILY without the `_total` suffix
        (samples keep it); a counter whose name cannot be suffixed that
        way (legacy `*_seconds_sum`) degrades to `unknown`, which has
        no naming constraints — the sample names themselves never
        change in either exposition."""
        if self.kind == "counter":
            if self.name.endswith("_total"):
                return self.name[: -len("_total")], "counter"
            return self.name, "unknown"
        return self.name, self.kind

    def _render(self, out: list[str], om: bool = False) -> None:
        if om:
            family, kind = self._om_family()
            out.append(f"# HELP {family} {self.help}")
            out.append(f"# TYPE {family} {kind}")
        else:
            out.append(f"# HELP {self.name} {self.help}")
            out.append(f"# TYPE {self.name} {self.kind}")
        for key in sorted(self._series):
            self._render_series(out, key, self._series[key], om=om)

    def _render_series(self, out: list[str], key, state,
                       om: bool = False) -> None:
        out.append(
            f"{self.name}{_labels_text(self.label_names, key)} "
            f"{_fmt(state)}")


class Counter(_Metric):
    kind = "counter"

    def _new_state(self) -> float:
        return 0.0

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise MetricError(f"{self.name}: counters only go up")
        with self.registry._lock:
            self._series[self._slot(labels)] += amount

    def value(self, **labels) -> float:
        with self.registry._lock:
            key = self._collapse(self._key(labels), register=False)
            return float(self._series.get(key, 0.0))


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._fn: Callable[[], float] | None = None

    def _new_state(self) -> float:
        return 0.0

    def set(self, value: float, **labels) -> None:
        with self.registry._lock:
            self._series[self._slot(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self.registry._lock:
            self._series[self._slot(labels)] += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Evaluate `fn` at render time (unlabeled gauges only) — for
        values derived from ambient state, e.g. DB generation age."""
        if self.label_names:
            raise MetricError(
                f"{self.name}: set_function needs an unlabeled gauge")
        self._fn = fn

    def value(self, **labels) -> float:
        with self.registry._lock:
            if self._fn is not None:
                return float(self._fn())
            return float(self._series.get(self._key(labels), 0.0))

    def _render(self, out: list[str], om: bool = False) -> None:
        if self._fn is not None:
            try:
                val = float(self._fn())
            except Exception:
                return  # a broken callback must not break /metrics
            out.append(f"# HELP {self.name} {self.help}")
            out.append(f"# TYPE {self.name} {self.kind}")
            out.append(f"{self.name} {_fmt(val)}")
            return
        super()._render(out, om=om)


class _HistState:
    __slots__ = ("counts", "total", "count", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # cumulative at render, raw here
        self.total = 0.0
        self.count = 0
        # per-raw-bucket (trace_id, value, epoch_ts) — the last traced
        # observation that landed in each bucket; only materialized
        # once an exemplar is actually recorded, rendered only in the
        # OpenMetrics exposition (the 0.0.4 bytes never change)
        self.exemplars: list | None = None


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, name, help_text, labels, max_series,
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help_text, labels, max_series)
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise MetricError(f"{name}: histogram needs >= 1 bucket")
        if len(set(bounds)) != len(bounds):
            raise MetricError(f"{name}: duplicate bucket bounds")
        self.buckets = tuple(bounds)

    def _new_state(self) -> _HistState:
        return _HistState(len(self.buckets) + 1)  # +1 for +Inf

    def observe(self, value: float, exemplar: str | None = None,
                **labels) -> None:
        """Record one observation. `exemplar` is an optional trace id:
        the OpenMetrics exposition links the bucket this value lands in
        to that trace (`... # {trace_id="…"} value ts`), so a p99
        bucket names the exact scan that put it there."""
        value = float(value)
        with self.registry._lock:
            state: _HistState = self._series[self._slot(labels)]  # type: ignore[assignment]
            i = 0
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    break
            else:
                i = len(self.buckets)
            state.counts[i] += 1
            state.total += value
            state.count += 1
            if exemplar:
                if state.exemplars is None:
                    state.exemplars = [None] * len(state.counts)
                state.exemplars[i] = (str(exemplar), value, time.time())

    def snapshot(self, **labels) -> tuple[list[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count)."""
        with self.registry._lock:
            state = self._series.get(self._key(labels))
            if state is None:
                return [0] * (len(self.buckets) + 1), 0.0, 0
            cum, running = [], 0
            for c in state.counts:
                running += c
                cum.append(running)
            return cum, state.total, state.count

    @staticmethod
    def _exemplar_text(state: _HistState, i: int, om: bool) -> str:
        if not om or state.exemplars is None or state.exemplars[i] is None:
            return ""
        trace_id, value, ts = state.exemplars[i]
        return (f' # {{trace_id="{_escape(trace_id)}"}} '
                f"{_fmt(value)} {ts:.3f}")

    def _render_series(self, out: list[str], key,
                       state: _HistState, om: bool = False) -> None:
        running = 0
        for i, (bound, c) in enumerate(zip(self.buckets, state.counts)):
            running += c
            out.append(
                f"{self.name}_bucket"
                f"{_labels_text(self.label_names, key, (('le', _fmt(bound)),))}"
                f" {running}" + self._exemplar_text(state, i, om))
        running += state.counts[-1]
        out.append(
            f"{self.name}_bucket"
            f"{_labels_text(self.label_names, key, (('le', '+Inf'),))}"
            f" {running}"
            + self._exemplar_text(state, len(self.buckets), om))
        lbl = _labels_text(self.label_names, key)
        out.append(f"{self.name}_sum{lbl} {_fmt(state.total)}")
        out.append(f"{self.name}_count{lbl} {state.count}")


class Registry:
    """A named, typed metric namespace with one lock for everything."""

    def __init__(self):
        # RLock: multi-metric updates group under locked() while each
        # single inc stays safe on its own
        self._lock = make_lock("obs.metrics._lock", threading.RLock())
        self._metrics: dict[str, _Metric] = {}

    def locked(self):
        """Hold the registry lock across several updates so a concurrent
        render can't observe them half-applied."""
        return self._lock

    def _register(self, cls, name: str, help_text: str,
                  labels: tuple[str, ...], max_series: int,
                  **kwargs) -> _Metric:
        labels = tuple(labels)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.label_names != labels):
                    raise MetricError(
                        f"metric {name!r} re-registered with a different "
                        "type or label set")
                return existing
            m = cls(self, name, help_text, labels, max_series, **kwargs)
            m._materialize_unlabeled()
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_text: str,
                labels: tuple[str, ...] = (),
                max_series: int = DEFAULT_MAX_SERIES,
                collapse_label: tuple[str, int] | None = None) -> Counter:
        return self._register(Counter, name, help_text, labels, max_series,
                              collapse_label=collapse_label)

    def gauge(self, name: str, help_text: str,
              labels: tuple[str, ...] = (),
              max_series: int = DEFAULT_MAX_SERIES,
              collapse_label: tuple[str, int] | None = None) -> Gauge:
        return self._register(Gauge, name, help_text, labels, max_series,
                              collapse_label=collapse_label)

    def histogram(self, name: str, help_text: str,
                  labels: tuple[str, ...] = (),
                  buckets: Iterable[float] = DEFAULT_BUCKETS,
                  max_series: int = DEFAULT_MAX_SERIES) -> Histogram:
        return self._register(Histogram, name, help_text, labels,
                              max_series, buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> bytes:
        """Prometheus text exposition 0.0.4, generated under ONE lock
        acquisition: the response is a consistent point-in-time snapshot
        even while scans are incrementing counters concurrently."""
        out: list[str] = []
        with self._lock:
            for name in self._metrics:  # registration order is stable
                self._metrics[name]._render(out)
        return ("\n".join(out) + "\n").encode()

    def render_openmetrics(self, eof: bool = True) -> bytes:
        """OpenMetrics-flavored exposition: the same series as
        :meth:`render` plus trace-id **exemplars** on histogram buckets
        and the `# EOF` terminator. Served from `/metrics` only under
        `Accept: application/openmetrics-text` content negotiation —
        the default 0.0.4 bytes never change (golden-tested).
        `eof=False` lets a caller concatenate several registries into
        one exposition with a single terminator."""
        out: list[str] = []
        with self._lock:
            for name in self._metrics:
                self._metrics[name]._render(out, om=True)
        text = "\n".join(out) + "\n"
        if eof:
            text += "# EOF\n"
        return text.encode()


# ---------------------------------------------------------------- spine

REGISTRY = Registry()

SCAN_PHASE_SECONDS = REGISTRY.histogram(
    "trivy_tpu_scan_phase_seconds",
    "Wall-clock seconds per scan phase (inspect/cache/detect/secret/report)",
    labels=("phase",))
RPC_CLIENT_SECONDS = REGISTRY.histogram(
    "trivy_tpu_rpc_client_seconds",
    "RPC client round-trip seconds per attempt, by twirp method",
    labels=("method",))
RETRY_ATTEMPTS = REGISTRY.counter(
    "trivy_tpu_retry_attempts_total",
    "RPC retry attempts (excludes each call's first attempt)",
    labels=("method",))
DEGRADED_TOTAL = REGISTRY.counter(
    "trivy_tpu_degraded_total",
    "Degraded-mode activations by component "
    "(driver=local fallback scan, cache=local-only mirror, "
    "engine=host-oracle after device loss, "
    "secret=host scanner after a device-screen failure)",
    labels=("component",))
FAULT_FIRES = REGISTRY.counter(
    "trivy_tpu_fault_injections_total",
    "Fault-injector rule firings by configured site and action",
    labels=("site", "action"))
BREAKER_STATE = REGISTRY.gauge(
    "trivy_tpu_breaker_state",
    "Circuit breaker state (0=closed, 1=half-open, 2=open)",
    labels=("name",))
BREAKER_TRANSITIONS = REGISTRY.counter(
    "trivy_tpu_breaker_transitions_total",
    "Circuit breaker transitions into each state",
    labels=("name", "state"))
CACHE_CORRUPT = REGISTRY.counter(
    "trivy_tpu_cache_corrupt_total",
    "Corrupt cache entries evicted (self-healing reads)")
COMPILE_CACHE_HITS = REGISTRY.counter(
    "trivy_tpu_compile_cache_hits_total",
    "Compiled advisory-DB tensor sets loaded from the persistent cache "
    "(warm start skipped a full recompile)")
COMPILE_CACHE_MISSES = REGISTRY.counter(
    "trivy_tpu_compile_cache_misses_total",
    "Compiled-DB cache lookups that fell back to a full recompile "
    "(absent, parameter mismatch, or corrupt-quarantined entry)")
PIPELINE_OCCUPANCY = REGISTRY.gauge(
    "trivy_tpu_pipeline_occupancy",
    "Fraction of the last pipelined crawl's wall-clock x stages the "
    "executor's stages were busy (1.0 = encode/device/rescreen fully "
    "overlapped; ~1/3 = serial)")
ANALYSIS_PIPELINE_OCCUPANCY = REGISTRY.gauge(
    "trivy_tpu_analysis_pipeline_occupancy",
    "Fraction of the last layer-analysis pipeline's wall-clock x lanes "
    "the fetch/walk stages were busy (1.0 = fetch of layer N+1 fully "
    "overlapped with analysis of layer N; ~0.5 = serial)")
ANALYSIS_LANE_BUSY = REGISTRY.gauge(
    "trivy_tpu_analysis_lane_busy",
    "Per-lane busy fraction of the last multi-lane layer-analysis run "
    "(lane k's walk seconds / scan wall seconds; lane counts are "
    "clamped to 32 so the label set stays bounded)",
    labels=("lane",), max_series=40)
LAYERS_ANALYZED = REGISTRY.counter(
    "trivy_tpu_layers_analyzed_total",
    "Container layers actually walked+analyzed (cache misses that this "
    "process led)")
LAYER_DEDUPE_HITS = REGISTRY.counter(
    "trivy_tpu_layer_dedupe_hits_total",
    "Layers satisfied without analysis: content-addressed blob-cache "
    "hits plus singleflight followers that reused a concurrent scan's "
    "completed analysis")
LAYER_DEDUPE_INFLIGHT_WAITS = REGISTRY.counter(
    "trivy_tpu_layer_dedupe_inflight_waits_total",
    "Times a scan waited on another scan's in-flight analysis of the "
    "same layer instead of analyzing it itself (in-process singleflight "
    "and the server-side MissingBlobs gate)")
SCHED_BATCH_ROWS = REGISTRY.histogram(
    "trivy_tpu_sched_batch_rows",
    "Package-query rows per coalesced match-scheduler micro-batch",
    buckets=(64, 256, 1024, 4096, 16384, 65536, 262144))
SCHED_COALESCED = REGISTRY.histogram(
    "trivy_tpu_sched_coalesced_requests",
    "Distinct scan requests coalesced into one scheduler micro-batch",
    buckets=(1, 2, 4, 8, 16, 32, 64))
SCHED_QUEUE_DEPTH = REGISTRY.gauge(
    "trivy_tpu_sched_queue_depth",
    "Scan requests waiting in the match-scheduler submission queue")
SCHED_WAIT_SECONDS = REGISTRY.histogram(
    "trivy_tpu_sched_wait_seconds",
    "Queue wait from scheduler submission to first micro-batch dispatch",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 1.0, 5.0))
MESH_SHAPE = REGISTRY.gauge(
    "trivy_tpu_mesh_shape",
    "Serving-mesh topology by axis (axis=data: query-parallel groups, "
    "axis=db: advisory shards — GLOBAL across hosts on the distributed "
    "MeshDB, axis=hosts: DCN processes); absent/0 = single-chip path",
    labels=("axis",))
MESH_SHARD_DISPATCH_SECONDS = REGISTRY.histogram(
    "trivy_tpu_mesh_shard_dispatch_seconds",
    "Per-shard dispatch+collect wall seconds of the mesh match path "
    "(includes retries and the host fallback of a degraded shard)",
    labels=("shard",),
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             1.0, 5.0))
MESH_SHARD_RETRIES = REGISTRY.counter(
    "trivy_tpu_mesh_shard_retries_total",
    "Mesh shard dispatches retried after a shard-local failure "
    "(before any degradation)",
    labels=("shard",))
MESH_SHARD_DEGRADATIONS = REGISTRY.counter(
    "trivy_tpu_mesh_shard_degradations_total",
    "Mesh shards degraded to the host oracle after retries were "
    "exhausted or the shard's device was lost (zero finding diff; the "
    "healthy shards keep serving on-device)",
    labels=("shard",))
MESH_RERESOLVES = REGISTRY.counter(
    "trivy_tpu_mesh_reresolves_total",
    "Explicit control-plane mesh recoveries (the fleet controller's "
    "mesh_reresolve action): scope=shard re-residented degraded local "
    "shard slices on their devices, scope=host re-partitioned the "
    "distributed MeshDB over surviving DCN hosts",
    labels=("scope",))
DCN_HOST_DISPATCH_SECONDS = REGISTRY.histogram(
    "trivy_tpu_dcn_host_dispatch_seconds",
    "Per-remote-host dispatch+collect wall seconds of the distributed "
    "MeshDB (the cross-host wait, incl. retries; a degraded host's "
    "mask recompute is in the merge, not here)",
    labels=("host",),
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             1.0, 5.0, 30.0))
DCN_HOST_DEGRADATIONS = REGISTRY.counter(
    "trivy_tpu_dcn_host_degradations_total",
    "Remote hosts whose whole advisory slice degraded to the "
    "coordinator's bit-identical host mask (worker death, transport "
    "timeout, or injected engine.host fault; surviving hosts keep "
    "serving on-device, zero finding diff)",
    labels=("host",))
DCN_MERGE_SECONDS = REGISTRY.histogram(
    "trivy_tpu_dcn_merge_seconds",
    "Coordinator-side merge of per-host shard bitmaps into the global "
    "mask stack the host-merge decoder consumes (unpack + degraded-"
    "host mask recompute)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 1.0))
DELTA_DIFF_SECONDS = REGISTRY.histogram(
    "trivy_tpu_delta_diff_seconds",
    "Advisory-delta diff duration on a DB generation promote "
    "(fingerprint load + touched-key computation)")
DELTA_REMATCH_SECONDS = REGISTRY.histogram(
    "trivy_tpu_delta_rematch_seconds",
    "Delta re-score duration: affected artifacts re-matched through "
    "the engine's micro-batch path after a generation promote")
DELTA_TOUCHED_KEYS = REGISTRY.gauge(
    "trivy_tpu_delta_touched_keys",
    "Advisory (space, name) keys whose content changed in the most "
    "recent generation promote's delta diff")
DELTA_REMATCHED = REGISTRY.counter(
    "trivy_tpu_delta_rematched_artifacts_total",
    "Journaled artifacts re-matched by delta re-scores (incremental "
    "passes count only the affected subset)")
DELTA_FULL_RESCANS = REGISTRY.counter(
    "trivy_tpu_delta_full_rescans_total",
    "Delta re-scores that degraded to re-matching every indexed "
    "artifact, by reason (schema change, missing fingerprints, "
    "injected fault, degraded index, threshold, verify mismatch)",
    labels=("reason",))
DELTA_EVENTS = REGISTRY.counter(
    "trivy_tpu_delta_events_total",
    "Finding edges emitted by delta re-scores (kind=introduced: new "
    "finding on a frozen artifact; kind=resolved: finding retracted "
    "by the new advisory generation)",
    labels=("kind",))
DELTA_SHEDS = REGISTRY.counter(
    "trivy_tpu_delta_sheds_total",
    "Delta re-scores shed or deferred: wall-time budget expired "
    "mid-sweep, or a promote landed while a re-score was running "
    "(queued, not stacked)")
SECRET_PROBE_DEVICE = REGISTRY.gauge(
    "trivy_tpu_secret_probe_device",
    "Hybrid secret probe verdict: 1 = the device anchor screen's "
    "share-weighted time beat the host path (hybrid keeps its device "
    "share), 0 = host-only; absent until the one-shot probe runs")
SECRET_PROBE_MBPS = REGISTRY.gauge(
    "trivy_tpu_secret_probe_mb_per_s",
    "Hybrid secret probe throughput by path (path=device: anchor "
    "screen on the accelerator; path=host: native-AC host scan) on "
    "the probe corpus",
    labels=("path",))
SECRET_DEVICE_SHARE = REGISTRY.gauge(
    "trivy_tpu_secret_device_share",
    "Byte fraction of the last hybrid secret scan actually handed to "
    "the device screen (0 = the probe or a device failure routed "
    "everything to the host path)")
SECRET_STREAM_FILES = REGISTRY.counter(
    "trivy_tpu_secret_stream_files_total",
    "Files scanned through the streaming chunked secret path "
    "(size over the whole-file threshold; byte-identical findings)")
SECRET_STREAM_BYTES = REGISTRY.counter(
    "trivy_tpu_secret_stream_bytes_total",
    "Bytes consumed by the streaming chunked secret path")
SECRET_NFA_CACHE_HITS = REGISTRY.counter(
    "trivy_tpu_secret_nfa_cache_hits_total",
    "Compiled secret-NFA programs loaded from the persistent "
    "compiled-artifact cache (warm start skipped rule compilation)")
SECRET_NFA_CACHE_MISSES = REGISTRY.counter(
    "trivy_tpu_secret_nfa_cache_misses_total",
    "Compiled secret-NFA cache lookups that fell back to compiling "
    "the ruleset (absent, version mismatch, or corrupt-quarantined)")
SECRET_SCHED_BATCH_CHUNKS = REGISTRY.histogram(
    "trivy_tpu_secret_sched_batch_chunks",
    "16 KiB device chunks per coalesced secret anchor-screen "
    "micro-batch (the packed super-buffer the kernel scans at once)",
    buckets=(16, 64, 256, 1024, 4096, 16384))
SECRET_SCHED_COALESCED = REGISTRY.histogram(
    "trivy_tpu_secret_sched_coalesced_requests",
    "Distinct concurrent scans coalesced into one secret anchor-"
    "screen micro-batch",
    buckets=(1, 2, 4, 8, 16, 32))
TRACE_SPANS_DROPPED = REGISTRY.counter(
    "trivy_tpu_trace_spans_dropped_total",
    "Collected trace spans evicted by the bounded root-trace buffer "
    "(a long-running server with tracing on keeps the newest "
    "MAX_BUFFERED_ROOTS traces; the Chrome export notes this count)")
FLEET_REQUESTS = REGISTRY.counter(
    "trivy_tpu_fleet_requests_total",
    "Requests dispatched per fleet endpoint (the smart client's "
    "load-balanced + hedged dispatches; endpoint = index within the "
    "set, stable across membership changes)",
    labels=("endpoint",))
FLEET_FAILOVERS = REGISTRY.counter(
    "trivy_tpu_fleet_failovers_total",
    "Requests retried on a different replica after a transport-level "
    "failure on the first choice")
FLEET_HEDGES = REGISTRY.counter(
    "trivy_tpu_fleet_hedges_total",
    "Hedged scan dispatches by outcome: won (the hedge's response was "
    "used), lost (the primary answered first after all), denied (the "
    "hedge budget refused to fire one)",
    labels=("outcome",))
FLEET_ENDPOINT_HEALTH = REGISTRY.gauge(
    "trivy_tpu_fleet_endpoint_healthy",
    "Per-endpoint health from the /readyz JSON prober (1 ready, "
    "0 not ready/unreachable/removed)",
    labels=("endpoint",))
FLEET_REPLICA_HEALTHY = REGISTRY.gauge(
    "trivy_tpu_fleet_replica_healthy",
    "Per-endpoint ROUTABLE verdict after a health-prober pass: 1 = the "
    "picker will route to this replica (ready AND its circuit breaker "
    "is not open), 0 = skipped (not ready, unreachable, or breaker "
    "open) — the raw /readyz verdict alone is "
    "trivy_tpu_fleet_endpoint_healthy",
    labels=("endpoint",))
FLEET_PROBE_SECONDS = REGISTRY.histogram(
    "trivy_tpu_fleet_probe_seconds",
    "Wall seconds per background /readyz health probe, by endpoint — "
    "a replica whose probe latency is an outlier vs the fleet median "
    "is flagged as replica skew in the fleet event log",
    labels=("endpoint",),
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             1.0, 5.0))
FLEET_EVENTS = REGISTRY.counter(
    "trivy_tpu_fleet_events_total",
    "Fleet ops events emitted into the event bus by kind (the durable "
    "journal + /events tail carry the full records — docs/fleet.md "
    "'Event catalog')",
    labels=("kind",))
FLEET_DEDUPE_CLAIMS = REGISTRY.counter(
    "trivy_tpu_fleet_dedupe_claims_total",
    "Distributed (redis-backed) layer-claim outcomes across the "
    "replica set: leader (this server's client analyzes), follower "
    "(parked on another server's in-flight analysis), expired "
    "(took over a dead leader's claim), reclaim (waiter timeout "
    "takeover)",
    labels=("outcome",))
FLEET_ROLLOUTS = REGISTRY.counter(
    "trivy_tpu_fleet_rollouts_total",
    "Coordinated advisory-DB rollouts by outcome (completed, "
    "rolled_back, noop)",
    labels=("outcome",))
FLEET_ROLLOUT_STAGE_SECONDS = REGISTRY.histogram(
    "trivy_tpu_fleet_rollout_stage_seconds",
    "Wall seconds per rollout stage (plan, canary, probe, roll, "
    "rescore, rollback) — the sum is the fleet's refresh window, vs "
    "the reference's full-fleet quiesce",
    labels=("stage",))
CONTROLLER_TICKS = REGISTRY.counter(
    "trivy_tpu_fleet_controller_ticks_total",
    "Fleet-controller control passes (observe -> reconcile -> decide "
    "-> act), including passes that decided nothing — liveness signal "
    "for the self-driving loop (docs/fleet.md 'Self-driving fleet')")
CONTROLLER_ACTIONS = REGISTRY.counter(
    "trivy_tpu_fleet_controller_actions_total",
    "Fleet-controller actions by kind (the fleet.controller.ACTIONS "
    "vocabulary) and outcome (applied, dry_run, reconciled, dropped, "
    "failed) — each also journaled and emitted as a "
    "controller_action ops event",
    labels=("kind", "outcome"))
CONTROLLER_REPLICAS = REGISTRY.gauge(
    "trivy_tpu_fleet_controller_replicas",
    "Replica count the fleet controller observed on its latest pass — "
    "the autoscaler's actual, to compare against the min/max policy "
    "bounds")
ATTRIB_LANE_SECONDS = REGISTRY.counter(
    "trivy_tpu_attrib_lane_seconds_total",
    "Resource-lane attribution seconds accumulated from completed "
    "scan traces (kind=busy: wall-clock union the lane's spans "
    "covered; kind=critical: the lane's slice of the per-scan "
    "critical-path partition) — docs/observability.md "
    "'Attribution & profiling'",
    labels=("lane", "kind"))


def _tenant_top_n() -> int:
    """Tenant-label collapse bound (TRIVY_TPU_USAGE_TOP_N, read once
    at import): the tenant label is attacker-controlled on a public
    server, so every tenant metric collapses past this many distinct
    values instead of risking CardinalityError."""
    import os
    try:
        return max(1, int(os.environ.get("TRIVY_TPU_USAGE_TOP_N", "")
                          or 64))
    except ValueError:
        return 64


TENANT_SCANS = REGISTRY.counter(
    "trivy_tpu_tenant_scans_total",
    "Scan RPCs served to completion per tenant (tenant = 16-hex-char "
    "SHA-256 prefix of the auth token; 'anonymous' = no token, "
    "'other' = beyond the TRIVY_TPU_USAGE_TOP_N collapse bound) — "
    "docs/observability.md 'Usage metering'",
    labels=("tenant",),
    collapse_label=("tenant", _tenant_top_n()))
TENANT_SHEDS = REGISTRY.counter(
    "trivy_tpu_tenant_sheds_total",
    "Requests shed with 503 per tenant (overload, deadline expiry, "
    "draining) — shed demand is metered so overload cannot hide a "
    "tenant's load",
    labels=("tenant",),
    collapse_label=("tenant", _tenant_top_n()))
TENANT_QUERIES = REGISTRY.counter(
    "trivy_tpu_tenant_queries_total",
    "Rows submitted to the match/secret schedulers per tenant",
    labels=("tenant",),
    collapse_label=("tenant", _tenant_top_n()))
TENANT_ROWS_MATCHED = REGISTRY.counter(
    "trivy_tpu_tenant_rows_matched_total",
    "Device advisory rows matched per tenant",
    labels=("tenant",),
    collapse_label=("tenant", _tenant_top_n()))
TENANT_WIRE_BYTES = REGISTRY.counter(
    "trivy_tpu_tenant_wire_bytes_total",
    "Bytes on the RPC wire per tenant and direction (post-gzip; the "
    "pre-compression payload bytes live in the /debug/usage cost "
    "vector)",
    labels=("tenant", "direction"),
    collapse_label=("tenant", _tenant_top_n()))
TENANT_LANE_SECONDS = REGISTRY.counter(
    "trivy_tpu_tenant_lane_seconds_total",
    "Attribution-lane busy seconds per tenant — conservation "
    "invariant: summed over tenants this equals "
    "trivy_tpu_attrib_lane_seconds_total{kind='busy'} per lane "
    "(machine-asserted by /debug/usage and bench.py --usage)",
    labels=("tenant", "lane"),
    collapse_label=("tenant", _tenant_top_n()))
QOS_QUEUE_SHEDS = REGISTRY.counter(
    "trivy_tpu_qos_queue_sheds_total",
    "Scheduler submissions shed at a tenant's queue-depth cap "
    "(TRIVY_TPU_QOS_TENANT_QUEUE) — the per-tenant slice of the "
    "sheds cost-vector field, so a greedy tenant's rejected demand "
    "is visible separately from global overload",
    labels=("tenant",),
    collapse_label=("tenant", _tenant_top_n()))
QOS_ACTIVE_TENANTS = REGISTRY.gauge(
    "trivy_tpu_qos_active_tenants",
    "Distinct tenants with queued work in the last match-scheduler "
    "batch compose (the fair-share width of the current interleave)")
WIRE_REQUESTS = REGISTRY.counter(
    "trivy_tpu_wire_requests_total",
    "RPC bodies by negotiated wire format (json | columnar) and "
    "direction (out = request sent by this client, in = request "
    "served by this server) — docs/performance.md 'Binary columnar "
    "wire'",
    labels=("format", "direction"))
WIRE_FALLBACKS = REGISTRY.counter(
    "trivy_tpu_wire_fallbacks_total",
    "Columnar-to-JSON fallbacks by reason (unlearn = 4xx from a "
    "replica not advertising the capability — rollback handling; "
    "corrupt = frame checksum reject; error = columnar wire error "
    "after its one retry; drop = injected renegotiate)",
    labels=("reason",))
WIRE_FRAMES = REGISTRY.counter(
    "trivy_tpu_wire_frames_total",
    "Columnar frames encoded/decoded by direction (out/in); the "
    "streaming scan response counts one frame per result table",
    labels=("direction",))
