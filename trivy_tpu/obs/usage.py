"""Per-tenant usage metering: who is spending the fleet's seconds,
bytes, and device rows (docs/observability.md "Usage metering").

Every scan accrues a per-request **cost vector** — attribution-lane
busy seconds (the obs.attrib taxonomy), device rows matched, queries
submitted, layers fetched/analyzed/deduped, bytes over the RPC wire
(pre/post gzip, both directions), cache hits/misses, secret MB
screened, queue-wait seconds, and shed outcomes — keyed by a tenant id
derived from the auth token (hashed, never logged raw; requests with
no token land in the ``anonymous`` bucket).

The accrual scope is a contextvar that follows the scan across the
scheduler, fanal pipeline, secret lane, and mesh dispatch exactly the
way tracing capture/adopt does: the RPC server opens a scope per
request, the scheduler captures it per pending request and re-adopts
it around batch dispatch, and the fanal fetch lane adopts it on its
worker thread.  ``add()`` with no ambient scope is a no-op costing one
contextvar read, which is also the whole disabled (TRIVY_TPU_USAGE=0)
fast path — guarded <2% of scan wall by bench.py --usage.

Load-bearing invariant — **conservation**: the per-tenant lane-second
sums equal the fleet attribution totals
(trivy_tpu_attrib_lane_seconds_total{kind="busy"}), because
obs.attrib's aggregator hands every observed root's busy vector to
``add_lanes`` on the same thread that closed the root span; spans that
close outside any request scope (client-side RPCs, background work)
accrue to ``anonymous``, so overload and unattributed work cannot hide
a tenant's demand.  ``snapshot()`` machine-checks the invariant and
/debug/usage serves it.

Aggregates live in a bounded top-N registry (tenants beyond
TRIVY_TPU_USAGE_TOP_N collapse into ``other`` — the same cardinality
policy the tenant spine metrics enforce via ``collapse_label``), are
optionally journaled per interval over durability/appendlog
(torn-tail-tolerant replay, compaction), and are federated across
replicas by fleet.telemetry / the ``trivy-tpu usage`` CLI.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import os
import time

from trivy_tpu.analysis.witness import make_lock
from trivy_tpu.obs import metrics as obs_metrics

# The cost-vector field catalog.  Pure literal: the usage-field lint
# rule parses this registry and cross-checks it against every
# usage.add()/add_to() call site and the docs/observability.md
# "Cost-vector fields" table, so a field cannot be emitted, dropped,
# or documented on its own.
FIELDS = (
    ("scans", "scan RPCs served to completion"),
    ("sheds", "requests shed with 503 (overload, deadline, draining)"),
    ("queries", "rows submitted to the scheduler (match + screen)"),
    ("rows_matched", "device advisory rows matched"),
    ("layers_fetched", "layer blobs fetched by the fanal pipeline"),
    ("layers_analyzed", "layer blobs walked by analyzers"),
    ("layers_deduped", "layer fetches avoided by the dedupe gate"),
    ("bytes_in", "request payload bytes after transport decoding"),
    ("bytes_out", "response payload bytes before transport encoding"),
    ("wire_bytes_in", "request bytes on the wire (post-gzip)"),
    ("wire_bytes_out", "response bytes on the wire (post-gzip)"),
    ("cache_hits", "cache blobs already present at MissingBlobs"),
    ("cache_misses", "cache blobs absent at MissingBlobs (pre-dedupe)"),
    ("secret_mb", "megabytes screened by the secret scanner"),
    ("queue_wait_s", "seconds queued in the scheduler before dispatch"),
    ("lane_s", "attribution-lane busy seconds (conservation field)"),
)

_FIELD_NAMES = frozenset(name for name, _doc in FIELDS)

ANONYMOUS = "anonymous"
OTHER = "other"

_DEF_TOP_N = 64
_DEF_INTERVAL_S = 60.0
_JOURNAL_COMPACT_EVERY = 256

_scope: contextvars.ContextVar["UsageScope | None"] = contextvars.ContextVar(
    "trivy_tpu_usage_scope", default=None)


def enabled() -> bool:
    """TRIVY_TPU_USAGE=0 is the kill switch: no scopes are created, so
    every accrual call short-circuits on the ambient-scope read."""
    return os.environ.get("TRIVY_TPU_USAGE", "") not in ("0", "false")


def top_n() -> int:
    try:
        return max(1, int(os.environ.get("TRIVY_TPU_USAGE_TOP_N", "")
                          or _DEF_TOP_N))
    except ValueError:
        return _DEF_TOP_N


def tenant_id(token: str | None) -> str:
    """Stable tenant key for an auth token: 16 hex chars of SHA-256.
    The raw token is never logged, journaled, or exported — only this
    hash appears in metrics, /debug/usage, and the journal."""
    if not token:
        return ANONYMOUS
    return "t-" + hashlib.sha256(token.encode()).hexdigest()[:16]


class UsageScope:
    """One request's accumulating cost vector.  Thread-safe: the fanal
    fetch lane and scheduler accrue from worker threads while the
    handler thread owns the scope."""

    __slots__ = ("tenant", "fields", "lanes", "_lock")

    def __init__(self, tenant: str):
        self.tenant = tenant
        self.fields: dict[str, float] = {}
        self.lanes: dict[str, float] = {}
        self._lock = make_lock("obs.usage.scope._lock")

    def _add(self, field: str, amount: float) -> None:
        with self._lock:
            self.fields[field] = self.fields.get(field, 0.0) + amount

    def _add_lanes(self, busy: dict) -> None:
        with self._lock:
            for lane, v in busy.items():
                if v > 0:
                    self.lanes[lane] = self.lanes.get(lane, 0.0) + v


# ------------------------------------------------------------ accrual


def ambient() -> UsageScope | None:
    """The scope the current context accrues to (None = unmetered)."""
    return _scope.get()


def add(field: str, amount: float = 1.0) -> None:
    """Accrue `amount` to the ambient scope; no-op (one contextvar
    read) when the context is unmetered or metering is disabled."""
    s = _scope.get()
    if s is None:
        return
    s._add(field, amount)


def add_to(scope: UsageScope | None, field: str, amount: float = 1.0) -> None:
    """Accrue to a captured scope from another thread (the scheduler's
    per-pending queue-wait accounting)."""
    if scope is None:
        return
    scope._add(field, amount)


def add_lanes(busy: dict) -> None:
    """Fold one observed root span's per-lane busy seconds — called by
    obs.attrib on the thread that closed the root, where the request's
    scope is still ambient.  Rootless-context spans accrue straight to
    the ``anonymous`` bucket so conservation holds by construction."""
    if not busy or not enabled():
        return
    s = _scope.get()
    if s is not None:
        s._add_lanes(busy)
        return
    USAGE.fold_lanes(ANONYMOUS, busy)


def capture() -> UsageScope | None:
    """Snapshot the ambient scope for adoption on another thread —
    the usage twin of tracing.capture()."""
    return _scope.get()


@contextlib.contextmanager
def adopt(scope: UsageScope | None):
    """Re-establish a captured scope on the current thread."""
    if scope is None:
        yield
        return
    token = _scope.set(scope)
    try:
        yield
    finally:
        _scope.reset(token)


@contextlib.contextmanager
def scope(tenant: str):
    """Open a request scope for `tenant` (a tenant_id() hash).  On
    exit the accumulated cost vector folds into the process registry
    and the trivy_tpu_tenant_* spine metrics.  A no-op yielding None
    when TRIVY_TPU_USAGE=0."""
    if not enabled():
        yield None
        return
    s = UsageScope(tenant)
    token = _scope.set(s)
    try:
        yield s
    finally:
        _scope.reset(token)
        USAGE.fold(s)


# ----------------------------------------------------------- registry


def _merge(dst: dict, src: dict) -> None:
    for k, v in src.items():
        dst[k] = dst.get(k, 0.0) + v


class UsageRegistry:
    """Bounded per-tenant aggregate store.  Beyond top_n() distinct
    tenants new arrivals collapse into ``other`` instead of tripping
    the CardinalityError a public server cannot afford; the tenant
    spine metrics apply the same policy via collapse_label."""

    def __init__(self):
        self._lock = make_lock("obs.usage._lock")
        self._tenants: dict[str, dict] = {}
        self._journal = None
        self._journal_path = None
        self._journal_next_t = 0.0

    # -- folding ----------------------------------------------------

    def _collapse(self, tenant: str) -> str:
        if tenant in self._tenants or tenant == OTHER:
            return tenant
        if len(self._tenants) >= top_n():
            return OTHER
        return tenant

    def fold(self, s: UsageScope) -> None:
        with s._lock:
            fields = dict(s.fields)
            lanes = dict(s.lanes)
        with self._lock:
            tenant = self._collapse(s.tenant)
            rec = self._tenants.setdefault(tenant,
                                           {"fields": {}, "lanes": {}})
            _merge(rec["fields"], fields)
            _merge(rec["lanes"], lanes)
        self._export(tenant, fields, lanes)
        self._journal_tick()

    def fold_lanes(self, tenant: str, busy: dict) -> None:
        lanes = {k: v for k, v in busy.items() if v > 0}
        if not lanes:
            return
        with self._lock:
            tenant = self._collapse(tenant)
            rec = self._tenants.setdefault(tenant,
                                           {"fields": {}, "lanes": {}})
            _merge(rec["lanes"], lanes)
        self._export(tenant, {}, lanes)
        self._journal_tick()

    def _export(self, tenant: str, fields: dict, lanes: dict) -> None:
        """Mirror a fold into the trivy_tpu_tenant_* spine metrics
        (outside self._lock: the metrics registry has its own)."""
        m = obs_metrics
        if fields.get("scans"):
            m.TENANT_SCANS.inc(fields["scans"], tenant=tenant)
        if fields.get("sheds"):
            m.TENANT_SHEDS.inc(fields["sheds"], tenant=tenant)
        if fields.get("queries"):
            m.TENANT_QUERIES.inc(fields["queries"], tenant=tenant)
        if fields.get("rows_matched"):
            m.TENANT_ROWS_MATCHED.inc(fields["rows_matched"],
                                      tenant=tenant)
        if fields.get("wire_bytes_in"):
            m.TENANT_WIRE_BYTES.inc(fields["wire_bytes_in"],
                                    tenant=tenant, direction="in")
        if fields.get("wire_bytes_out"):
            m.TENANT_WIRE_BYTES.inc(fields["wire_bytes_out"],
                                    tenant=tenant, direction="out")
        for lane, v in lanes.items():
            m.TENANT_LANE_SECONDS.inc(v, tenant=tenant, lane=lane)

    # -- snapshot / conservation ------------------------------------

    def snapshot(self) -> dict:
        """Per-tenant table + fleet totals + the machine-checked
        conservation comparison against the attribution spine."""
        with self._lock:
            tenants = {t: {"fields": dict(r["fields"]),
                           "lanes": dict(r["lanes"])}
                       for t, r in self._tenants.items()}
        totals = {"fields": {}, "lanes": {}}
        for rec in tenants.values():
            _merge(totals["fields"], rec["fields"])
            _merge(totals["lanes"], rec["lanes"])
        from trivy_tpu.obs import attrib  # import cycle: attrib -> usage
        lane_busy = {}
        for lane in attrib.LANES:
            v = obs_metrics.ATTRIB_LANE_SECONDS.value(lane=lane,
                                                      kind="busy")
            if v:
                lane_busy[lane] = v
        tenant_lane_s = sum(totals["lanes"].values())
        attrib_lane_s = sum(lane_busy.values())
        diff = abs(tenant_lane_s - attrib_lane_s)
        tol = 1e-6 + 1e-9 * max(tenant_lane_s, attrib_lane_s)
        return {
            "enabled": enabled(),
            "top_n": top_n(),
            "tenants": tenants,
            "totals": totals,
            "conservation": {
                "tenant_lane_s": tenant_lane_s,
                "attrib_lane_s": attrib_lane_s,
                "attrib_lanes": lane_busy,
                "diff_s": diff,
                "ok": diff <= tol,
            },
        }

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()

    # -- journal ----------------------------------------------------

    def _journal_interval(self) -> float:
        try:
            return float(os.environ.get("TRIVY_TPU_USAGE_INTERVAL_S", "")
                         or _DEF_INTERVAL_S)
        except ValueError:
            return _DEF_INTERVAL_S

    def _journal_open(self, path: str):
        from trivy_tpu.durability.appendlog import AppendLog, AppendLogError
        header = {"log": "usage-journal", "version": 1}
        try:
            if os.path.exists(path):
                log, records = AppendLog.replay(path)
                self._adopt_journal_records(records)
                return log
            return AppendLog.create(path, header)
        except AppendLogError:
            try:
                log, records = AppendLog.salvage(path, header)
                self._adopt_journal_records(records)
                return log
            except AppendLogError:
                return None

    def _adopt_journal_records(self, records: list[dict]) -> None:
        """Journal records are cumulative snapshots: the last durable
        one wins (torn tails were already truncated by replay).
        Caller holds self._lock (_journal_open runs under the
        _journal_tick lock; the lock is not re-entrant)."""
        last = None
        for rec in records:
            if rec.get("kind") == "usage":
                last = rec
        if last is None:
            return
        for t, r in (last.get("tenants") or {}).items():
            slot = self._tenants.setdefault(
                t, {"fields": {}, "lanes": {}})
            _merge(slot["fields"], r.get("fields") or {})
            _merge(slot["lanes"], r.get("lanes") or {})

    def _journal_tick(self) -> None:
        path = os.environ.get("TRIVY_TPU_USAGE_JOURNAL", "")
        if not path:
            return
        now = time.monotonic()
        with self._lock:
            if path != self._journal_path:
                self._journal_path = path
                self._journal = self._journal_open(path)
                self._journal_next_t = 0.0
            if self._journal is None or now < self._journal_next_t:
                return
            self._journal_next_t = now + self._journal_interval()
            rec = {"kind": "usage",
                   "tenants": {t: {"fields": dict(r["fields"]),
                                   "lanes": dict(r["lanes"])}
                               for t, r in self._tenants.items()}}
            journal = self._journal
        from trivy_tpu.durability.appendlog import AppendLogError
        try:
            journal.append(rec)
            if journal.records_written > _JOURNAL_COMPACT_EVERY:
                journal.rewrite([rec])
        except AppendLogError:
            pass  # journaling is best-effort; metering must not fail scans

    def journal_sync(self) -> None:
        """Force a journal snapshot now (shutdown hook / tests)."""
        with self._lock:
            self._journal_next_t = 0.0
        self._journal_tick()

    def journal_close(self) -> None:
        with self._lock:
            if self._journal is not None:
                self._journal.close()
            self._journal = None
            self._journal_path = None


def replay_journal(path: str) -> dict:
    """Load the last durable usage snapshot from a journal file —
    the `trivy-tpu usage --journal PATH` data source."""
    from trivy_tpu.durability.appendlog import AppendLog
    log, records = AppendLog.replay(path)
    log.close()
    last: dict = {"kind": "usage", "tenants": {}}
    for rec in records:
        if rec.get("kind") == "usage":
            last = rec
    return {"tenants": last.get("tenants") or {}}


USAGE = UsageRegistry()
