"""Watch mode: DB promote → delta re-score → introduced/resolved events.

Two consumers share the machinery:

- **Server** (`MonitorService`): attached to a `ScanService` when the
  server runs with `--monitor-index`.  Completed scans record their
  inventory/baseline; after every successful advisory-DB hot swap the
  service re-scores in a background thread (one at a time — a promote
  landing mid-re-score is queued, never stacked), publishes events to a
  bounded ring served at ``GET /monitor/events?since=N``, and logs each
  event as a trace-correlated JSON-able record.
- **CLI** (`watch_local` / `watch_remote`): ``trivy-tpu watch`` either
  polls a DB root + local index directly (emitting events as JSON
  lines on stdout) or tails a server's event ring.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from trivy_tpu.analysis.witness import make_lock
from trivy_tpu.log import logger
from trivy_tpu.monitor import rematch as rematch_mod
from trivy_tpu.monitor.delta import compute_delta
from trivy_tpu.monitor.index import MonitorIndex, MonitorIndexError
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import tracing

_log = logger("monitor.watch")

EVENT_RING = 4096


def budget_s() -> float | None:
    """TRIVY_TPU_DELTA_BUDGET_S bounds one re-score's wall time (the
    deadline budget of the monitor path; unset = unbounded)."""
    raw = os.environ.get("TRIVY_TPU_DELTA_BUDGET_S", "")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        _log.warn("malformed TRIVY_TPU_DELTA_BUDGET_S; ignoring",
                  value=raw)
        return None


def open_index(path: str, journal_path: str | None = None
               ) -> MonitorIndex:
    """Open an index; a corrupt one rebuilds from the fleet journal
    when available, else moves aside and starts fresh."""
    try:
        return MonitorIndex.open(path)
    except MonitorIndexError as e:
        if journal_path and os.path.exists(journal_path):
            _log.warn("monitor index unusable; rebuilding from journal",
                      path=path, err=str(e))
            return MonitorIndex.rebuild_from_journal(path, journal_path)
        return MonitorIndex.open_or_reset(path)


class MonitorService:
    """Server-side monitor: scan recording + promote-triggered
    re-scoring + the event ring behind ``/monitor/events``."""

    def __init__(self, index_path: str, engine_fn, db_path: str,
                 scheduler=None, journal_path: str | None = None):
        self.index = open_index(index_path, journal_path)
        self._engine_fn = engine_fn
        self._scheduler = scheduler
        self.db_path = db_path
        self._lock = make_lock("monitor.watch._lock")
        self._events: collections.deque = collections.deque(
            maxlen=EVENT_RING)
        self._seq = 0
        self._running = False
        self._pending = None  # (old_digest, db, new_digest) queued promote
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------ scans

    def record_scan(self, artifact_id: str, cap,
                    db_digest: str | None = None) -> None:
        """Index one completed scan's capture (inventory + engine-level
        finding baseline, stamped with the generation it was matched
        against). Never fails the scan: append errors degrade the
        index (next re-score goes full)."""
        self.index.update(artifact_id, cap.packages, cap.findings,
                          db_digest=db_digest)

    # ---------------------------------------------------------- promote

    def on_promote(self, old_digest: str | None, db,
                   new_digest: str | None,
                   params_changed: str | None = None) -> bool:
        """Hot-swap hook: schedule the delta re-score in the background.
        A promote landing while one is running replaces any queued one
        (only the LATEST generation matters — intermediate deltas are
        subsumed because the planner diffs from the index's stored
        baseline digest, not from the interrupted attempt)."""
        with self._lock:
            if self._running:
                self._pending = (old_digest, db, new_digest,
                                 params_changed)
                obs_metrics.DELTA_SHEDS.inc()
                _log.info("re-score already running; promote queued",
                          new=new_digest)
                return False
            self._running = True
        ctx = tracing.capture()

        def _bg():
            with tracing.adopt(ctx):
                self._rescore_loop(old_digest, db, new_digest,
                                   params_changed)

        t = threading.Thread(target=_bg, name="ttpu-monitor", daemon=True)
        t.start()
        with self._lock:
            self._threads = [th for th in self._threads
                             if th.is_alive()] + [t]
        return True

    def _rescore_loop(self, old_digest, db, new_digest,
                      params_changed) -> None:
        while True:
            try:
                self.rescore_now(old_digest, db, new_digest,
                                 params_changed)
            except Exception as exc:
                _log.warn("delta re-score failed; index state not "
                          "advanced (next promote re-plans)",
                          err=str(exc))
            with self._lock:
                if self._pending is None:
                    self._running = False
                    return
                old_digest, db, new_digest, params_changed = \
                    self._pending
                self._pending = None

    def rescore_now(self, old_digest, db, new_digest,
                    params_changed=None):
        """Synchronous re-score (the background loop and tests)."""
        # scan_scope assigns the correlation id the emitted events and
        # this re-score's log lines share (works with tracing off)
        with tracing.scan_scope(force=True), \
                tracing.span("monitor.promote", db=new_digest or ""):
            plan = compute_delta(self.db_path, old_digest, db,
                                 new_digest=new_digest,
                                 params_changed=params_changed)
            engine = self._engine_fn()
            if self._scheduler is not None:
                from trivy_tpu.sched.scheduler import SchedEngine

                # the re-match sweep joins the shared micro-batch
                # stream, interleaving with live scans under the
                # scheduler's fairness rules instead of monopolizing
                # the device
                engine = SchedEngine(engine, self._scheduler)
            return rematch_mod.rescore(engine, self.index, plan,
                                       budget_s=budget_s(),
                                       on_event=self._emit)

    # ----------------------------------------------------------- events

    def _emit(self, ev: dict) -> None:
        with self._lock:
            self._seq += 1
            self._events.append((self._seq, ev))
        _log.info("monitor event", **ev)

    def events_since(self, since: int) -> tuple[int, list[dict]]:
        """-> (next cursor, events with seq > since). The ring is
        bounded: a slow consumer that falls more than EVENT_RING events
        behind misses the overwritten ones (the cursor jump tells it)."""
        with self._lock:
            out = [ev for seq, ev in self._events if seq > since]
            return self._seq, out

    def close(self) -> None:
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=10.0)
        self.index.close()


# ------------------------------------------------------------- CLI loops

def emit_line(fh, doc: dict) -> None:
    fh.write(json.dumps(doc, sort_keys=True) + "\n")
    fh.flush()


def watch_local(db_path: str, index: MonitorIndex, engine_factory,
                out_fh, interval_s: float = 60.0, once: bool = False,
                verify: bool | None = None, stop_event=None) -> int:
    """Poll `db_path` for generation changes; on change, delta-re-score
    the local index and emit events as JSON lines on `out_fh`.

    `engine_factory` is a zero-arg callable returning a freshly built
    MatchEngine over the CURRENT on-disk DB (cli/run.new_engine under
    the parsed args). Returns 0 (loop ended / --once complete)."""
    from trivy_tpu.tensorize import cache as compile_cache

    while True:
        digest = compile_cache.db_digest(db_path)
        if digest is not None and digest != index.db_digest:
            with tracing.scan_scope(force=True), \
                    tracing.span("watch.rescore", db=digest):
                engine = engine_factory()
                plan = compute_delta(db_path, index.db_digest, engine.db,
                                     new_digest=digest)
                report = rematch_mod.rescore(
                    engine, index, plan, budget_s=budget_s(),
                    verify=verify,
                    on_event=lambda ev: emit_line(out_fh, ev))
            emit_line(out_fh, {
                "event": "rescore", "db_digest": digest,
                "full": report.full, "reason": report.reason,
                "rematched": report.rematched,
                "indexed": report.total_indexed,
                "introduced": report.introduced,
                "resolved": report.resolved, "shed": report.shed,
                "duration_s": round(report.duration_s, 3),
            })
        if once:
            return 0
        if stop_event is not None and stop_event.wait(interval_s):
            return 0
        if stop_event is None:
            time.sleep(interval_s)


def watch_remote(server: str, out_fh, token: str | None = None,
                 interval_s: float = 2.0, once: bool = False,
                 stop_event=None) -> int:
    """Tail a server's /monitor/events ring, printing each event as a
    JSON line.  Survives server restarts (the cursor resets when the
    server's sequence does)."""
    import urllib.request

    cursor = 0
    base = server.rstrip("/")
    while True:
        url = f"{base}/monitor/events?since={cursor}"
        req = urllib.request.Request(url)
        if token:
            req.add_header("Trivy-Token", token)
        try:
            with urllib.request.urlopen(req, timeout=10.0) as resp:
                doc = json.loads(resp.read())
            nxt = int(doc.get("next", cursor))
            if nxt < cursor:
                cursor = 0  # server restarted; resync from its start
            else:
                cursor = nxt
            for ev in doc.get("events") or []:
                emit_line(out_fh, ev)
        except OSError as exc:
            _log.warn("watch: server unreachable; retrying",
                      server=base, err=str(exc))
        if once:
            return 0
        if stop_event is not None and stop_event.wait(interval_s):
            return 0
        if stop_event is None:
            time.sleep(interval_s)
