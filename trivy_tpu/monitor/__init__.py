"""Continuous monitoring: advisory-delta incremental re-matching
(docs/monitoring.md).

An hourly trivy-db refresh used to mean re-scanning every journaled
artifact from scratch, even though a typical advisory delta touches a
tiny fraction of (space, name) keys.  This subsystem turns a DB
generation promote into a seconds-scale fleet re-score:

- `delta`   — diff two generations' advisory-key fingerprint tables
  (persisted next to the compiled-DB cache) into a touched-key set,
  with "everything touched" fallbacks on schema/format/window changes;
- `index`   — a durable inverted (space, name) → artifact index +
  per-artifact match state, journal-style append log next to the scan
  journal (crash-safe, torn-tail tolerant, rebuildable);
- `capture` — a zero-cost-when-off tap that records each scan's
  package inventory and engine-level findings into the index;
- `rematch` — re-match ONLY the affected artifacts through
  `MatchEngine.submit()` micro-batches, emit introduced/resolved
  events, provably byte-identical to a from-scratch re-match;
- `watch`   — the `trivy-tpu watch` loop and the server-side monitor
  service hooked into the DB hot swap.

`TRIVY_TPU_MONITOR=0` is the kill switch: scans stop recording index
state and promotes stop triggering re-scores.
"""

from __future__ import annotations

import os


def enabled() -> bool:
    """TRIVY_TPU_MONITOR=0 disables the monitor subsystem entirely."""
    return os.environ.get("TRIVY_TPU_MONITOR", "1") != "0"


from trivy_tpu.monitor.capture import capture_scan, tap  # noqa: E402
from trivy_tpu.monitor.delta import DeltaPlan, compute_delta  # noqa: E402
from trivy_tpu.monitor.index import (  # noqa: E402
    MonitorIndex,
    MonitorIndexError,
)
from trivy_tpu.monitor.rematch import RescoreReport, rescore  # noqa: E402

__all__ = [
    "DeltaPlan",
    "MonitorIndex",
    "MonitorIndexError",
    "RescoreReport",
    "capture_scan",
    "compute_delta",
    "enabled",
    "rescore",
    "tap",
]
