"""Delta re-scoring: touched keys ∩ index → re-match → events.

The correctness contract (asserted by tests, the fault matrix and
``bench.py bench_delta``): after a re-score, the index's stored finding
state is byte-identical to re-matching EVERY indexed artifact from
scratch against the new engine.  The incremental path may only skip an
artifact when none of its (space, name) keys are touched — and an
untouched key's advisory content is digest-identical across the two
generations, so its match results cannot differ (delta.py).  Every
fault rung (``monitor.rematch`` drop/error, a degraded index) widens
the re-match set up to "everything", never narrows it.

Events are the observable product: one JSON-able dict per finding edge
(introduced / resolved), deterministic order, trace-correlated via the
ambient span.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import tracing
from trivy_tpu.resilience import faults

_log = logger("monitor.rematch")

FAULT_SITE = "monitor.rematch"

# rows per submit() micro-batch of the re-match sweep (matches the
# match scheduler's default micro-batch target)
BATCH_ROWS = 65536


def verify_enabled() -> bool:
    """TRIVY_TPU_DELTA_VERIFY=1: every re-score cross-checks itself
    against a from-scratch full re-match (double work; CI / paranoia)."""
    return os.environ.get("TRIVY_TPU_DELTA_VERIFY", "") == "1"


@dataclass
class RescoreReport:
    db_digest: str | None
    full: bool
    reason: str
    rematched: int = 0
    total_indexed: int = 0
    introduced: int = 0
    resolved: int = 0
    events: list[dict] = field(default_factory=list)
    shed: bool = False           # budget expired before completion
    verified: bool | None = None  # None = verify pass not run
    duration_s: float = 0.0


def _queries_of(packages: list[tuple]) -> list:
    from trivy_tpu.detector.engine import PkgQuery

    return [PkgQuery(s, n, v, sch) for s, n, v, sch in packages]


# the ONE finding-identity definition (see its docstring): re-exported
# here because the re-scoring code and its tests read it from this
# module
from trivy_tpu.detector.engine import finding_keys  # noqa: E402


def full_findings(engine, index) -> dict[str, set[tuple]]:
    """From-scratch oracle: every indexed artifact re-matched against
    `engine` (the zero-diff reference the incremental path is asserted
    against)."""
    ids = index.artifacts()
    out: dict[str, set[tuple]] = {}
    for batch in _batched(index, ids):
        lists = [_queries_of(index.packages_of(a)) for a in batch]
        res_lists = engine.submit(lists)
        advs = engine.cdb.advisories
        for aid, rl in zip(batch, res_lists):
            out[aid] = finding_keys(advs, rl)
    return out


def _batched(index, ids: list[str]):
    """Group artifact ids so each group's total query rows stay near
    BATCH_ROWS — one submit() micro-batch per group."""
    group: list[str] = []
    rows = 0
    for aid in ids:
        n = len(index.packages_of(aid))
        if group and rows + n > BATCH_ROWS:
            yield group
            group, rows = [], 0
        group.append(aid)
        rows += n
    if group:
        yield group


def _event(kind: str, aid: str, key: tuple, db_digest,
           ids: dict) -> dict:
    space, name, version, scheme, vuln_id = key
    ev = {"event": kind, "artifact": aid, "space": space, "name": name,
          "version": version, "scheme": scheme, "vuln_id": vuln_id,
          "db_digest": db_digest}
    ev.update(ids)
    return ev


def rescore(engine, index, plan, budget_s: float | None = None,
            verify: bool | None = None, on_event=None) -> RescoreReport:
    """Apply a DeltaPlan: re-match the affected artifacts through
    `engine.submit()` micro-batches, emit introduced/resolved events,
    advance the index's stored state to the new generation.

    `engine` may be a bare MatchEngine or the server's SchedEngine
    facade (then the re-match batches coalesce with live scans).
    `budget_s` bounds wall time; on expiry the remaining artifacts are
    left un-advanced (``shed=True``) and the state digest is NOT moved,
    so the next attempt re-plans from the same baseline.  `verify`
    (default TRIVY_TPU_DELTA_VERIFY) re-matches everything afterwards
    and asserts the incremental state equals it."""
    t0 = time.monotonic()
    full = plan.full
    reason = plan.reason
    # fault ladder: drop/error degrade the plan to a full re-score (more
    # work, same answer); delay stalls; kill crashes (replay recovers)
    rules = faults.fire(FAULT_SITE)
    faults.check_kill(FAULT_SITE, rules=rules)
    for r in rules:
        if r.action == "delay":
            time.sleep(r.param if r.param is not None else 0.002)
        elif r.action in ("drop", "error"):
            if not full:
                full = True
                reason = f"fault-{r.action}"
                obs_metrics.DELTA_FULL_RESCANS.inc(reason=reason)
    if index.degraded and not full:
        # a durable append failed earlier: stored baselines may be
        # stale in unknown ways — re-baseline everything
        full = True
        reason = "index-degraded"
        obs_metrics.DELTA_FULL_RESCANS.inc(reason=reason)
    report = RescoreReport(plan.new_digest, full, reason,
                           total_indexed=len(index.artifacts()))
    if verify is None:
        verify = verify_enabled()
    # trace correlation: the ambient span's trace id when tracing is
    # collecting, plus the scan id (which scan_scope assigns even with
    # tracing off — the same ids the JSON log lines carry)
    ids: dict = {}
    span = tracing.current()
    if span is not None and span.trace_id:
        ids["trace_id"] = span.trace_id
    scan_id = tracing.current_scan_id()
    if scan_id:
        ids["scan_id"] = scan_id
    with tracing.span("delta.rematch", full=full,
                      touched=len(plan.touched)):
        if full:
            aids = index.artifacts()
        else:
            aids = index.affected(plan.touched)
        advs = engine.cdb.advisories
        deadline = None if budget_s is None else t0 + budget_s
        completed = True
        for batch in _batched(index, aids):
            if deadline is not None and time.monotonic() > deadline:
                completed = False
                report.shed = True
                obs_metrics.DELTA_SHEDS.inc()
                _log.warn("re-score budget expired; state not advanced",
                          done=report.rematched, remaining=len(aids)
                          - report.rematched)
                break
            # snapshot-then-CAS: a live scan re-recording an artifact
            # mid-sweep must win over this sweep's computation from the
            # PRE-scan inventory (update_if refuses when the record
            # moved, and no events fire for a refused write)
            pkg_snap = {a: index.packages_of(a) for a in batch}
            fnd_snap = {a: index.findings_of(a) for a in batch}
            lists = [_queries_of(pkg_snap[a]) for a in batch]
            res_lists = engine.submit(lists)
            for aid, rl in zip(batch, res_lists):
                new_keys = finding_keys(advs, rl)
                old_keys = fnd_snap[aid]
                # every processed artifact re-stamps onto the new
                # generation (the replay staleness check keys on it),
                # and a degraded log regains a trusted copy
                if not index.update_if(aid, pkg_snap[aid], old_keys,
                                       new_keys,
                                       db_digest=plan.new_digest):
                    continue
                report.rematched += 1
                if old_keys is None or new_keys == old_keys:
                    # fresh/rebuilt record adopts its baseline silently
                    continue
                for k in sorted(new_keys - old_keys):
                    ev = _event("introduced", aid, k,
                                plan.new_digest, ids)
                    report.events.append(ev)
                    report.introduced += 1
                    if on_event is not None:
                        on_event(ev)
                for k in sorted(old_keys - new_keys):
                    ev = _event("resolved", aid, k,
                                plan.new_digest, ids)
                    report.events.append(ev)
                    report.resolved += 1
                    if on_event is not None:
                        on_event(ev)
        if completed:
            if full:
                # every record was re-baselined above: the durable log
                # holds a trusted copy again (a set_state append failure
                # below re-flags degraded and the next re-score goes
                # full once more)
                index.degraded = ""
            # the transition record: untouched artifacts keep their old
            # stamps, and the replay chain proves their baselines carry
            # to the new generation (index.py _baseline_carries)
            index.set_state(plan.new_digest, window=index.window,
                            prev=plan.old_digest,
                            touched=None if full else plan.touched)
            index.compact()
    obs_metrics.DELTA_REMATCHED.inc(report.rematched)
    obs_metrics.DELTA_EVENTS.inc(report.introduced, kind="introduced")
    obs_metrics.DELTA_EVENTS.inc(report.resolved, kind="resolved")
    report.duration_s = time.monotonic() - t0
    obs_metrics.DELTA_REMATCH_SECONDS.observe(report.duration_s)
    if verify and completed:
        oracle = full_findings(engine, index)
        diff = sum(
            1 for aid in oracle
            if (index.findings_of(aid) or set()) != oracle[aid])
        report.verified = diff == 0
        if diff:
            _log.error("delta re-score diverged from full re-match; "
                       "re-baselining", artifacts=diff)
            obs_metrics.DELTA_FULL_RESCANS.inc(reason="verify-mismatch")
            for aid, keys in oracle.items():
                index.update(aid, index.packages_of(aid), keys,
                             db_digest=plan.new_digest)
    _log.info("delta re-score complete", full=full, reason=reason,
              rematched=report.rematched, indexed=report.total_indexed,
              introduced=report.introduced, resolved=report.resolved,
              shed=report.shed,
              duration_s=round(report.duration_s, 3))
    return report
