"""Compiled-DB delta diff: old generation vs new → touched advisory keys.

Both sides load cheaply: `tensorize.cache.save_keymap` persists a
per-(space, name) content-fingerprint table next to each generation's
compiled tensor entry, so a promote-time diff reads two small gzipped
tables instead of two full advisory DBs.  When the old table is gone
(pruned, pre-monitor generation) the old generation directory itself is
tried; when that is gone too — or schema / fingerprint-format / window
parameters changed — the plan degrades to "everything touched", which
re-matches every indexed artifact.  Every fallback rung is *more* work,
never a wrong answer (docs/monitoring.md).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import tracing

_log = logger("monitor.delta")

# above this touched-key fraction an incremental pass stops paying for
# itself (the index intersection + per-artifact bookkeeping approaches
# the cost of just re-matching everything)
DEFAULT_FULL_THRESHOLD = 0.5


def full_threshold() -> float:
    raw = os.environ.get("TRIVY_TPU_DELTA_FULL_THRESHOLD", "")
    try:
        return float(raw) if raw else DEFAULT_FULL_THRESHOLD
    except ValueError:
        _log.warn("malformed TRIVY_TPU_DELTA_FULL_THRESHOLD; using "
                  "default", value=raw)
        return DEFAULT_FULL_THRESHOLD


@dataclass
class DeltaPlan:
    """What a promote means for the fleet: which advisory keys moved.

    ``full=True`` → the touched set could not be (cheaply and provably)
    bounded; re-match everything.  ``full=False`` with an empty
    ``touched`` set → a no-op promote (same content digest)."""

    old_digest: str | None
    new_digest: str | None
    full: bool = False
    reason: str = ""  # why full (empty for an incremental plan)
    touched: frozenset = field(default_factory=frozenset)
    n_keys: int = 0  # size of the new generation's key table


def compute_delta(db_path: str, old_digest: str | None, new_db,
                  new_digest: str | None = None,
                  params_changed: str | None = None) -> DeltaPlan:
    """Diff the `old_digest` generation against `new_db` (the already
    loaded candidate) → DeltaPlan.  `params_changed` names a
    non-content reason to distrust the diff (window params, fingerprint
    format) and forces a full plan."""
    from trivy_tpu.tensorize import cache as compile_cache

    t0 = time.perf_counter()
    with tracing.span("delta.diff", old=old_digest or "",
                      new=new_digest or ""):
        plan = _compute(db_path, old_digest, new_db, new_digest,
                        params_changed, compile_cache)
    obs_metrics.DELTA_DIFF_SECONDS.observe(time.perf_counter() - t0)
    if plan.full:
        obs_metrics.DELTA_FULL_RESCANS.inc(reason=plan.reason or "unknown")
        _log.warn("advisory delta fell back to a full re-score",
                  reason=plan.reason, old=plan.old_digest,
                  new=plan.new_digest)
    else:
        obs_metrics.DELTA_TOUCHED_KEYS.set(len(plan.touched))
        _log.info("advisory delta computed", touched=len(plan.touched),
                  keys=plan.n_keys, old=plan.old_digest,
                  new=plan.new_digest,
                  diff_s=round(time.perf_counter() - t0, 3))
    return plan


def _compute(db_path: str, old_digest: str | None, new_db,
             new_digest: str | None, params_changed: str | None,
             compile_cache) -> DeltaPlan:
    new_digest = new_digest or compile_cache.db_digest(db_path)

    def full(reason: str) -> DeltaPlan:
        return DeltaPlan(old_digest, new_digest, full=True, reason=reason)

    if params_changed:
        return full(params_changed)
    if new_digest is None:
        return full("new-digest-unavailable")
    if old_digest is None:
        return full("no-baseline-generation")
    if old_digest == new_digest:
        # same content: nothing moved, nothing to re-match
        return DeltaPlan(old_digest, new_digest)
    # the new side: persist-then-load keeps one canonical computation
    compile_cache.save_keymap(db_path, new_db, digest=new_digest)
    new_map = compile_cache.load_keymap(db_path, new_digest)
    if new_map is None:
        # cache disabled/unwritable: compute in memory, still exact
        new_map = {"schema": new_db.meta.version,
                   "keys": compile_cache.advisory_fingerprints(new_db)}
    old_map = compile_cache.load_keymap(db_path, old_digest)
    if old_map is None:
        old_map = _fingerprints_from_generation(db_path, old_digest,
                                                compile_cache)
    if old_map is None:
        return full("old-fingerprints-unavailable")
    if old_map.get("schema") != new_map.get("schema"):
        return full("schema-version-changed")
    old_keys, new_keys = old_map["keys"], new_map["keys"]
    touched = {k for k in old_keys.keys() | new_keys.keys()
               if old_keys.get(k) != new_keys.get(k)}
    n_keys = max(len(new_keys), 1)
    if len(touched) / n_keys > full_threshold():
        return full("touched-fraction-above-threshold")
    return DeltaPlan(old_digest, new_digest,
                     touched=frozenset(touched), n_keys=len(new_keys))


def _fingerprints_from_generation(db_path: str, old_digest: str,
                                  compile_cache):
    """Fallback old side: the previous generation directory is usually
    still installed under generations/ — load it and fingerprint in
    memory.  None when the bytes are gone (→ full re-score)."""
    if not old_digest.startswith("sha256-"):
        return None
    from trivy_tpu.db import generations
    from trivy_tpu.db.store import AdvisoryDB

    gen_dir = os.path.join(generations.generations_root(db_path),
                           old_digest)
    if not os.path.isdir(gen_dir):
        return None
    try:
        old_db = AdvisoryDB.load(gen_dir)
    except Exception as exc:
        _log.warn("previous generation unreadable for delta diff",
                  path=gen_dir, err=str(exc))
        return None
    _log.info("fingerprinting previous generation from disk (no cached "
              "keymap)", path=gen_dir)
    return {"schema": old_db.meta.version,
            "keys": compile_cache.advisory_fingerprints(old_db)}
