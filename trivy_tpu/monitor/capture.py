"""Scan-time capture of package inventory + engine-level findings.

The monitor's unit of truth is the match layer: per artifact, the
exact `PkgQuery` set its detectors submitted and the engine-level
finding keys those queries produced.  Capturing at the engine handle
(rather than re-deriving from rendered reports) keeps the index's
inventory byte-exact with what a re-match will submit — the zero-diff
guarantee depends on it.

Zero cost when off: `tap()` returns the engine handle unchanged unless
an ambient `capture_scan()` scope is active on this context (the scan's
own thread; fleet lanes and server request threads each carry their
own contextvar)."""

from __future__ import annotations

import contextlib
import contextvars

_collector: contextvars.ContextVar = contextvars.ContextVar(
    "trivy_tpu_monitor_capture", default=None)


class ScanCapture:
    """Accumulates one scan's (space, name, version, scheme) package
    tuples and (…, vuln_id) finding tuples across its detect calls."""

    __slots__ = ("packages", "findings")

    def __init__(self):
        self.packages: set[tuple] = set()
        self.findings: set[tuple] = set()


@contextlib.contextmanager
def capture_scan():
    """Scope under which `tap()`-wrapped engine handles record every
    detect() call's queries and finding keys."""
    cap = ScanCapture()
    token = _collector.set(cap)
    try:
        yield cap
    finally:
        _collector.reset(token)


def current():
    """The ambient ScanCapture (None outside a capture_scan scope) —
    snapshot it in a submitting thread, adopt() it in the worker (the
    tracing.capture/adopt idiom for thread handoffs)."""
    return _collector.get()


@contextlib.contextmanager
def adopt(cap):
    """Install a current()-snapshotted capture in this thread."""
    if cap is None:
        yield
        return
    token = _collector.set(cap)
    try:
        yield
    finally:
        _collector.reset(token)


class _TapEngine:
    """Engine-handle wrapper recording detect() traffic into the
    ambient ScanCapture; everything else reads through."""

    __slots__ = ("_engine",)

    def __init__(self, engine):
        self._engine = engine

    def detect(self, queries: list) -> list:
        from trivy_tpu.detector.engine import finding_keys

        results = self._engine.detect(queries)
        cap = _collector.get()
        if cap is not None:
            for r in results:
                cap.packages.add(r.query.key)
            cap.findings |= finding_keys(
                self._engine.cdb.advisories, results)
        return results

    def __getattr__(self, name: str):
        return getattr(self._engine, name)


def tap(engine_handle):
    """Wrap `engine_handle` for capture when a capture_scan() scope is
    active; otherwise hand it back untouched (the common path)."""
    if _collector.get() is None:
        return engine_handle
    return _TapEngine(engine_handle)
