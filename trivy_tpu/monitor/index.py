"""Durable inverted package→artifact index (docs/monitoring.md).

One JSONL append log (durability.appendlog) persisted next to the scan
journal.  Records after the header:

    {"kind": "artifact", "id": t,
     "packages": [[space, name, version, scheme], ...],
     "findings": [[space, name, version, scheme, vuln_id], ...] | null,
     "db": <generation digest the findings were matched against> | null,
     "digest": "sha256:..."}          last-write-wins per artifact id
    {"kind": "remove", "id": t}       artifact dropped from monitoring
    {"kind": "state", "db_digest": d, "window": w|null,
     "prev": d_old|null,
     "touched": [[space, name], ...] | null}
                                      a completed re-score's transition:
                                      the generation the index is now
                                      baselined at, which one it came
                                      from, and which advisory keys that
                                      delta touched (null = everything)

In memory the records expand into (a) per-artifact package inventory +
finding baseline and (b) the inverted (space, name) → {artifact ids}
map a delta plan intersects.  Every artifact record is digest-sealed
(like journal `done` records): a bit-flipped record is dropped at
replay and the artifact falls back to its previous valid record — the
monitor then re-baselines it rather than diffing against garbage.

Fault site ``monitor.index`` fires per append: `kill` crashes before
the write, `torn-write`/`bitflip` mangle it (caught at replay),
`error` raises (the caller marks the index degraded → next re-score
goes full), `drop` silently loses the record (an undetected lost
write; replay simply yields the older state, against which delta and
full re-scoring still agree — never a wrong answer).

The per-record ``db`` stamp closes the lost-write coherence hole: if a
``state`` record reached the disk while some artifact's update did
not (a dropped append, a crash between the two), the replayed log
would otherwise pair the new generation's state digest with an old
generation's finding baseline — and an incremental re-score would
trust it.  At replay, an artifact stamped with an older generation
keeps its baseline only when the recorded transition chain from its
stamp to the final state digest exists and touches NONE of its
(space, name) keys — by the delta invariant (docs/monitoring.md)
such a baseline is identical at both ends.  Any gap in the chain, a
full ("touched everything") transition, or an intersection nulls the
baseline, which forces the artifact into the next re-score's
re-baseline set: more work, never a stale answer.
"""

from __future__ import annotations

import hashlib
import json
import os

from trivy_tpu.analysis.witness import make_lock
from trivy_tpu.durability.appendlog import AppendLog, AppendLogError
from trivy_tpu.log import logger

_log = logger("monitor.index")

FAULT_SITE = "monitor.index"
INDEX_VERSION = 1


class MonitorIndexError(Exception):
    pass


# a touched-key set larger than this is persisted as "everything" in
# the state record (the replay chain then re-baselines conservatively
# instead of the log carrying megabytes of key lists per promote)
MAX_TOUCHED_PERSIST = 4096


def _seal(rec: dict) -> str:
    body = {k: v for k, v in rec.items() if k != "digest"}
    return "sha256:" + hashlib.sha256(
        json.dumps(body, sort_keys=True,
                   separators=(",", ":")).encode()).hexdigest()


class MonitorIndex:
    """Writer + replayer for one monitor index file."""

    def __init__(self, log: AppendLog):
        self._log = log
        self._lock = make_lock("monitor.index._lock")
        # id -> {"packages": [tuple4...], "findings": set[tuple5]|None}
        self._artifacts: dict[str, dict] = {}
        self._inverted: dict[tuple[str, str], set[str]] = {}
        self.db_digest: str | None = None
        self.window = None
        # completed re-score transitions, in record order (replay only):
        # (prev_digest, new_digest, frozenset of touched keys | None)
        self._transitions: list[tuple] = []
        # non-empty when a durable append failed: the stored state may
        # be stale in unknown ways, so the next re-score goes full and
        # re-baselines every artifact (clearing this on success)
        self.degraded: str = ""

    # ------------------------------------------------------------ open

    @property
    def path(self) -> str:
        return self._log.path

    @classmethod
    def open(cls, path: str) -> "MonitorIndex":
        """Open (creating if missing) and replay. Raises
        MonitorIndexError when the file exists but is unusable — the
        caller decides between `rebuild_from_journal` and
        `open_or_reset`."""
        if not os.path.exists(path):
            log = AppendLog.create(
                path, {"v": INDEX_VERSION, "purpose": "monitor-index"},
                fault_site=FAULT_SITE)
            return cls(log)
        try:
            log, records = AppendLog.replay(path, fault_site=FAULT_SITE)
        except AppendLogError as e:
            raise MonitorIndexError(str(e))
        if log.header.get("v") != INDEX_VERSION:
            log.close()
            raise MonitorIndexError(
                f"monitor index {path} is version {log.header.get('v')}, "
                f"this build writes v{INDEX_VERSION}")
        idx = cls(log)
        last_stamp = None
        for rec in records:
            idx._apply(rec)
            if rec.get("kind") == "artifact" and rec.get("db"):
                last_stamp = rec["db"]
        if idx.db_digest is None:
            # no re-score ever recorded a state: adopt the generation
            # the most recent scan was matched against, so a fleet
            # scanned under X and watched later still gets its X→Y
            # delta instead of a silent re-baseline
            idx.db_digest = last_stamp
        # lost-write coherence (module docstring): a baseline stamped
        # with an older generation survives only when the recorded
        # transition chain from its stamp to the final state exists and
        # touches none of its keys — anything else re-baselines
        stale = 0
        for a in idx._artifacts.values():
            if a["findings"] is None or a["db"] == idx.db_digest:
                continue
            if not idx._baseline_carries(a):
                a["findings"] = None
                stale += 1
        if stale:
            _log.info("monitor index baselines from another generation "
                      "will re-baseline on the next re-score",
                      count=stale)
        idx._rebuild_inverted()
        return idx

    @classmethod
    def open_or_reset(cls, path: str) -> "MonitorIndex":
        """Open; on corruption move the bad file aside and start fresh
        (scan-side callers: records repopulate as scans complete)."""
        try:
            return cls.open(path)
        except MonitorIndexError as e:
            dest = path + ".corrupt"
            n = 0
            while os.path.exists(dest):
                n += 1
                dest = f"{path}.corrupt.{n}"
            os.rename(path, dest)
            _log.warn("monitor index unusable; moved aside and starting "
                      "fresh", path=path, moved_to=dest, err=str(e))
            return cls.open(path)

    @classmethod
    def rebuild_from_journal(cls, path: str,
                             journal_path: str) -> "MonitorIndex":
        """Rebuild a missing/corrupt index from a fleet scan journal's
        embedded reports.  Package inventories are reconstructed from
        each report's result package lists (full only under
        ``--list-all-pkgs``); findings are NOT trusted across the
        rebuild — every rebuilt artifact carries a null baseline, so
        its first re-score re-baselines silently instead of emitting
        events diffed against a lossy reconstruction."""
        from trivy_tpu.durability.journal import ScanJournal

        if os.path.exists(path):
            dest = path + ".corrupt"
            n = 0
            while os.path.exists(dest):
                n += 1
                dest = f"{path}.corrupt.{n}"
            os.rename(path, dest)
            _log.warn("rebuilding monitor index from journal; old file "
                      "moved aside", path=path, moved_to=dest)
        j = ScanJournal.resume(journal_path)
        try:
            idx = cls.open(path)
            for target, doc in j.done.items():
                pkgs = packages_from_report(doc)
                if pkgs:
                    idx.update(target, pkgs, None)
            _log.info("monitor index rebuilt from journal",
                      path=path, journal=journal_path,
                      artifacts=len(idx._artifacts))
            return idx
        finally:
            j.close()

    # ------------------------------------------------------------ state

    def _apply(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "artifact" and rec.get("id"):
            if _seal(rec) != rec.get("digest"):
                _log.warn("monitor index record failed digest check; "
                          "dropped", id=rec.get("id"))
                return
            f = rec.get("findings")
            self._artifacts[rec["id"]] = {
                "packages": [tuple(p) for p in rec.get("packages") or []],
                "findings": (None if f is None
                             else {tuple(x) for x in f}),
                "db": rec.get("db"),
            }
        elif kind == "remove" and rec.get("id"):
            self._artifacts.pop(rec["id"], None)
        elif kind == "state":
            t = rec.get("touched")
            self._transitions.append(
                (rec.get("prev"), rec.get("db_digest"),
                 None if t is None else frozenset(
                     (s, n) for s, n in t)))
            self.db_digest = rec.get("db_digest")
            self.window = rec.get("window")

    def _baseline_carries(self, a: dict) -> bool:
        """Is a baseline stamped at a["db"] still exact at the final
        state digest?  True iff a recorded transition chain leads from
        the stamp to the final digest and its accumulated touched keys
        avoid every one of the artifact's (space, name) keys."""
        cur = a["db"]
        acc: set = set()
        for prev, new, touched in self._transitions:
            if cur == self.db_digest:
                break
            if prev != cur:
                continue
            if touched is None:  # full re-score: everything moved
                return False
            acc |= touched
            cur = new
        if cur != self.db_digest:
            return False  # no chain (interrupted re-score, lost state)
        return not any((p[0], p[1]) in acc for p in a["packages"])

    def _rebuild_inverted(self) -> None:
        inv: dict[tuple[str, str], set[str]] = {}
        for aid, a in self._artifacts.items():
            for p in a["packages"]:
                inv.setdefault((p[0], p[1]), set()).add(aid)
        self._inverted = inv

    # ------------------------------------------------------------ write

    def _append(self, rec: dict) -> None:
        try:
            self._log.append(rec)
        except AppendLogError as e:
            # the scan (or re-score) goes on; the monitor stops trusting
            # incremental state until a full re-score rewrites it
            self.degraded = f"index append failed: {e}"
            _log.warn("monitor index append failed; delta re-scoring "
                      "degraded to full until re-baselined", err=str(e))

    def update(self, artifact_id: str, packages, findings,
               db_digest: str | None = None) -> None:
        """Record one artifact's inventory + finding baseline.
        `packages`: iterable of (space, name, version, scheme) tuples;
        `findings`: iterable of (space, name, version, scheme, vuln_id)
        tuples, or None for "no baseline yet" (first re-score
        re-baselines without emitting events); `db_digest`: the
        generation the findings were matched against — a replay drops
        baselines whose stamp disagrees with the final state record."""
        pkgs = sorted({tuple(p) for p in packages})
        fnds = None if findings is None else sorted(
            {tuple(f) for f in findings})
        with self._lock:
            self._update_locked(artifact_id, pkgs, fnds, db_digest)

    def _update_locked(self, artifact_id: str, pkgs: list[tuple],
                       fnds, db_digest: str | None) -> None:
        rec = {"kind": "artifact", "id": artifact_id,
               "packages": [list(p) for p in pkgs],
               "findings": None if fnds is None else [list(f)
                                                      for f in fnds],
               "db": db_digest}
        rec["digest"] = _seal(rec)
        prev = self._artifacts.get(artifact_id)
        self._append(rec)
        if prev:
            for p in prev["packages"]:
                s = self._inverted.get((p[0], p[1]))
                if s:
                    s.discard(artifact_id)
        self._artifacts[artifact_id] = {
            "packages": pkgs,
            "findings": None if fnds is None else set(fnds),
            "db": db_digest,
        }
        for p in pkgs:
            self._inverted.setdefault((p[0], p[1]),
                                      set()).add(artifact_id)

    def update_if(self, artifact_id: str, expected_packages,
                  expected_findings, findings,
                  db_digest: str | None = None) -> bool:
        """Compare-and-swap for the re-score sweep: write `findings`
        only if the artifact's record still matches the (packages,
        findings) snapshot the sweep computed from.  False = a live
        scan re-recorded the artifact mid-sweep — its fresher record
        wins and the sweep's stale computation is discarded."""
        exp_pkgs = sorted({tuple(p) for p in expected_packages})
        exp_fnds = None if expected_findings is None else {
            tuple(f) for f in expected_findings}
        fnds = None if findings is None else sorted(
            {tuple(f) for f in findings})
        with self._lock:  # check + write under ONE acquisition
            a = self._artifacts.get(artifact_id)
            if a is None or a["packages"] != exp_pkgs \
                    or a["findings"] != exp_fnds:
                return False
            self._update_locked(artifact_id, exp_pkgs, fnds, db_digest)
        return True

    def remove(self, artifact_id: str) -> None:
        with self._lock:
            a = self._artifacts.pop(artifact_id, None)
            if a is None:
                return
            self._append({"kind": "remove", "id": artifact_id})
            for p in a["packages"]:
                s = self._inverted.get((p[0], p[1]))
                if s:
                    s.discard(artifact_id)

    def set_state(self, db_digest: str | None, window=None,
                  prev: str | None = None, touched=None) -> None:
        """Record a completed re-score transition.  `touched` is the
        delta's touched-key iterable (None = everything / unknown);
        oversized sets persist as None — conservative, never stale."""
        if touched is not None:
            touched = sorted({(k[0], k[1]) for k in touched})
            if len(touched) > MAX_TOUCHED_PERSIST:
                touched = None
        with self._lock:
            self._append({"kind": "state", "db_digest": db_digest,
                          "window": window, "prev": prev,
                          "touched": (None if touched is None else
                                      [list(k) for k in touched])})
            # mirror the transition in memory so compact() can judge
            # baseline carry exactly the way a later replay would
            self._transitions.append(
                (prev, db_digest,
                 None if touched is None else frozenset(touched)))
            self.db_digest = db_digest
            self.window = window

    def compact(self, slack: int = 3) -> None:
        """Rewrite the log when appends outnumber live records by
        `slack`x (every re-score appends changed artifacts; without
        this the log grows with advisory churn forever). `slack=0`
        forces the rewrite."""
        with self._lock:
            live = len(self._artifacts) + 1
            if slack and self._log.records_written <= max(
                    slack * live, 16):
                return
            records: list[dict] = []
            for aid in sorted(self._artifacts):
                a = self._artifacts[aid]
                # chain collapse: a baseline that provably carries to
                # the current state digest is re-stamped onto it (the
                # carry proof IS "identical at both ends"); anything
                # else is nulled — the compacted log holds exactly one
                # state record, so old stamps could never re-verify
                stamp, fnds = a["db"], a["findings"]
                if fnds is not None and stamp != self.db_digest:
                    if self._baseline_carries(a):
                        stamp = self.db_digest
                    else:
                        fnds = None
                    a["db"], a["findings"] = stamp, fnds
                rec = {"kind": "artifact", "id": aid,
                       "packages": [list(p) for p in a["packages"]],
                       "findings": (None if fnds is None else
                                    [list(f) for f in sorted(fnds)]),
                       "db": stamp}
                rec["digest"] = _seal(rec)
                records.append(rec)
            records.append({"kind": "state", "db_digest": self.db_digest,
                            "window": self.window, "prev": None,
                            "touched": None})
            try:
                self._log.rewrite(records)
            except (AppendLogError, OSError) as e:
                # the previous log survives (atomic rewrite), but the
                # handle is closed: degrade like any append failure —
                # the next re-score goes full and re-baselines
                self.degraded = f"index compaction failed: {e}"
                _log.warn("monitor index compaction failed; degraded "
                          "to full re-score", err=str(e))
                return
            self._transitions = [(None, self.db_digest, None)]
            _log.info("monitor index compacted", path=self.path,
                      artifacts=len(self._artifacts))

    # ------------------------------------------------------------- read

    def artifacts(self) -> list[str]:
        with self._lock:
            return sorted(self._artifacts)

    def packages_of(self, artifact_id: str) -> list[tuple]:
        with self._lock:
            a = self._artifacts.get(artifact_id)
            return list(a["packages"]) if a else []

    def findings_of(self, artifact_id: str):
        """set of finding tuples, or None (no baseline)."""
        with self._lock:
            a = self._artifacts.get(artifact_id)
            if a is None or a["findings"] is None:
                return None
            return set(a["findings"])

    def affected(self, touched) -> list[str]:
        """Artifact ids whose inventory intersects the touched key set,
        plus every artifact with no finding baseline yet (those must
        re-baseline whenever a re-score runs)."""
        with self._lock:
            out: set[str] = set()
            inv = self._inverted
            if len(touched) <= len(inv):
                for key in touched:
                    out |= inv.get(key, set())
            else:
                for key, ids in inv.items():
                    if key in touched:
                        out |= ids
            for aid, a in self._artifacts.items():
                if a["findings"] is None:
                    out.add(aid)
            return sorted(out)

    def close(self) -> None:
        self._log.close()

    def __enter__(self) -> "MonitorIndex":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------- report rebuild

def packages_from_report(doc: dict) -> list[tuple]:
    """Best-effort package inventory from an embedded fleet-journal
    report document (rebuild path). Language results map their type to
    the "eco::" query space; OS results reconstruct the space from the
    report's OS metadata. Packages whose space/scheme cannot be
    resolved are skipped — a rebuilt artifact re-baselines on first
    re-score anyway, so a lossy inventory only narrows which deltas
    re-match it, never which findings it reports."""
    from trivy_tpu.detector.ospkg import DISTROS, bucket_for
    from trivy_tpu.versioning import ECOSYSTEM_SCHEME

    out: set[tuple] = set()
    meta_os = ((doc.get("Metadata") or {}).get("OS") or {})
    family = meta_os.get("Family") or ""
    os_name = meta_os.get("Name") or ""
    cfg = DISTROS.get(family)
    for res in doc.get("Results") or []:
        rclass = res.get("Class")
        rtype = res.get("Type") or ""
        for p in res.get("Packages") or []:
            name = p.get("Name")
            version = p.get("Version") or ""
            if p.get("Release"):
                version = f"{version}-{p['Release']}"
            if p.get("Epoch"):
                version = f"{p['Epoch']}:{version}"
            if not name or not version:
                continue
            if rclass == "lang-pkgs":
                scheme = ECOSYSTEM_SCHEME.get(rtype)
                if scheme:
                    out.add((f"{rtype}::", name, version, scheme))
            elif rclass == "os-pkgs" and cfg is not None:
                src = p.get("SrcName") or name
                src_ver = p.get("SrcVersion") or p.get("Version") or ""
                if p.get("SrcRelease"):
                    src_ver = f"{src_ver}-{p['SrcRelease']}"
                if p.get("SrcEpoch"):
                    src_ver = f"{p['SrcEpoch']}:{src_ver}"
                out.add((bucket_for(family, os_name), src,
                         src_ver or version, cfg.scheme))
    return sorted(out)
