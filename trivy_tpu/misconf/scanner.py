"""Misconfiguration scan façade (reference pkg/misconf/scanner.go):
file-type detection -> per-type parse -> check evaluation ->
Misconfiguration with PASS/FAIL entries, cause line ranges and code
snippets."""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from trivy_tpu.iac import detection
from trivy_tpu.iac.check import Cause, Check
from trivy_tpu.iac.ignore import is_ignored, parse_ignores
from trivy_tpu.types.artifact import Misconfiguration
from trivy_tpu.types.report import (
    CauseMetadata,
    Code,
    DetectedMisconfiguration,
    Line,
)


@dataclass
class DockerfileCtx:
    path: str = ""
    dockerfile: object = None


@dataclass
class K8sCtx:
    path: str = ""
    resource: dict = field(default_factory=dict)

    @property
    def pod_spec(self):
        from trivy_tpu.iac.parsers.yamlconf import k8s_pod_spec

        return k8s_pod_spec(self.resource)

    @property
    def containers(self):
        from trivy_tpu.iac.parsers.yamlconf import k8s_containers

        return k8s_containers(self.resource)


@dataclass
class CloudCtx:
    path: str = ""
    cloud_resources: list = field(default_factory=list)


def _contexts(file_type: str, path: str, content: bytes) -> list:
    if file_type == detection.DOCKERFILE:
        from trivy_tpu.iac.parsers.dockerfile import parse_dockerfile

        return [DockerfileCtx(path=path,
                              dockerfile=parse_dockerfile(content))]
    if file_type in (detection.KUBERNETES, detection.HELM):
        from trivy_tpu.iac.parsers.yamlconf import (
            k8s_resources,
            parse_config,
        )

        content = _strip_helm(content) if file_type == detection.HELM \
            else content
        docs = parse_config(content)
        return [K8sCtx(path=path, resource=r)
                for r in k8s_resources(docs)]
    if file_type == detection.TERRAFORM:
        # single-file entry: evaluate as a one-file module so expressions
        # (locals, functions, interpolations) still resolve
        from trivy_tpu.iac.checks.cloud import adapt_terraform
        from trivy_tpu.iac.terraform import ModuleLoader, evaluate_module

        dirname = os.path.dirname(path)
        loader = ModuleLoader({path: content})
        ev = evaluate_module({path: content}, dirname, loader)
        return [CloudCtx(path=path,
                         cloud_resources=adapt_terraform(ev.blocks))]
    if file_type == detection.CLOUDFORMATION:
        from trivy_tpu.iac.checks.cloud import adapt_cloudformation
        from trivy_tpu.iac.parsers.yamlconf import (
            cfn_resources,
            parse_config,
        )

        docs = parse_config(content)
        return [CloudCtx(path=path,
                         cloud_resources=adapt_cloudformation(
                             cfn_resources(docs)))]
    if file_type == detection.TERRAFORM_PLAN:
        import json as _json

        from trivy_tpu.iac.checks.cloud import adapt_terraform_plan

        try:
            doc = _json.loads(content)
        except ValueError:
            return []
        return [CloudCtx(path=path,
                         cloud_resources=adapt_terraform_plan(doc))]
    if file_type == detection.AZURE_ARM:
        import json as _json

        from trivy_tpu.iac.arm import evaluate_template
        from trivy_tpu.iac.checks.azure import adapt_arm

        try:
            doc = _json.loads(content)
        except ValueError:
            return []
        # resolve [parameters()/variables()/...] expressions, expand
        # copy loops, flatten nested deployments before adapting
        # (reference pkg/iac/scanners/azure/arm + resolver)
        return [CloudCtx(path=path,
                         cloud_resources=adapt_arm(
                             evaluate_template(doc)))]
    return []


def _strip_helm(content: bytes) -> bytes:
    """Best-effort: drop {{ ... }} actions so the YAML parses
    (reference renders charts via helm engine; full render is out of
    scope for template-only scans)."""
    import re

    text = content.decode("utf-8", "replace")
    text = re.sub(r"\{\{.*?\}\}", "", text, flags=re.S)
    return text.encode()


def _snippet(content: bytes, start: int, end: int) -> Code:
    lines = content.decode("utf-8", "replace").splitlines()
    out = []
    end = min(max(end, start), len(lines))
    for n in range(max(start, 1), end + 1):
        if n > len(lines):
            break
        out.append(Line(
            number=n, content=lines[n - 1],
            is_cause=True,
            first_cause=(n == start), last_cause=(n == end),
        ))
    return Code(lines=out)


def _to_detected(chk: Check, file_type: str, cause: Cause | None,
                 content: bytes, status: str) -> DetectedMisconfiguration:
    md = CauseMetadata(provider=chk.provider, service=chk.service)
    message = chk.title
    if cause is not None:
        md.resource = cause.resource
        md.start_line = cause.start_line
        md.end_line = max(cause.end_line, cause.start_line)
        if cause.start_line:
            md.code = _snippet(content, cause.start_line, md.end_line)
        message = cause.message or chk.title
    ns = chk.namespace
    if ns == "builtin":
        ns = f"builtin.{chk.provider}.{chk.service}".rstrip(".")
    return DetectedMisconfiguration(
        type=file_type, id=chk.id, avd_id=chk.avd_id, title=chk.title,
        description=chk.description, message=message,
        namespace=ns,
        query=f"data.{ns}.deny", resolution=chk.resolution,
        severity=chk.severity, primary_url=chk.url,
        references=[chk.url] if chk.url else [], status=status,
        cause_metadata=md,
    )


def scan_terraform_modules(
        files: dict[str, bytes]) -> list[Misconfiguration]:
    """Directory-aware terraform scan: evaluate each ROOT module (child
    modules expand inline through their `source` dirs, reference
    pkg/iac/scanners/terraform), then run the checks over the evaluated
    resources, attributing findings to each resource's source file."""
    from trivy_tpu.iac.checks.cloud import adapt_terraform
    from trivy_tpu.iac.engine import active
    from trivy_tpu.iac.terraform import (
        ModuleLoader,
        evaluate_module,
        module_dirs,
    )

    tf_files = {p: c for p, c in files.items()
                if p.endswith((".tf", ".tf.json"))}
    if not tf_files:
        return []
    loader = ModuleLoader(tf_files)
    # adapter context is scoped to the ROOT module tree that produced
    # each block (reference modules.GetResourcesByType spans one root +
    # its children, not sibling roots — an account default in stack A
    # must not suppress findings in unrelated stack B). A file shared
    # by several roots gets one context PER instantiating root, each
    # carrying only that root's blocks; _run_checks dedupes identical
    # causes across them.
    per_file_ctxs: dict[str, list] = {}
    for d in module_dirs(tf_files, loader=loader):
        ev = evaluate_module(loader.tf_files(d), d, loader)
        by_file: dict[str, list] = {}
        for blk in ev.blocks:
            by_file.setdefault(blk.src_path, []).append(blk)
        for path, blks in by_file.items():
            per_file_ctxs.setdefault(path, []).append(CloudCtx(
                path=path,
                cloud_resources=adapt_terraform(
                    blks, scan_blocks=ev.blocks)))
    out: list[Misconfiguration] = []
    for path in sorted(per_file_ctxs):
        content = files.get(path, b"")
        misconf = _run_checks(detection.TERRAFORM, path,
                              per_file_ctxs[path], content)
        if misconf.failures or misconf.successes:
            out.append(misconf)
    return out


def _run_checks(ftype: str, path: str, ctxs: list,
                content: bytes) -> Misconfiguration:
    """Run every active check for `ftype` over the contexts, apply
    `#trivy:ignore` / `#tfsec:ignore` comments (incl. parameterized and
    above-block forms), and collect FAIL/PASS findings."""
    from trivy_tpu.utils import clock

    ignores = parse_ignores(content)
    today = clock.now().date()
    # line-range -> resolved attrs, so above-block and parameterized
    # ignores can bind to the resource a cause sits in
    spans = [(r.start_line, r.end_line, r.attrs)
             for ctx in ctxs
             for r in getattr(ctx, "cloud_resources", ())]

    def _enclosing(c: Cause):
        for s, e, attrs in spans:
            if s and s <= c.start_line <= max(e, s):
                return s, attrs
        return 0, None

    misconf = Misconfiguration(file_type=ftype, file_path=path)
    from trivy_tpu.iac.engine import active

    for chk in active().checks_for(ftype):
        causes: list[Cause] = []
        for ctx in ctxs:
            try:
                causes.extend(chk.run(ctx))
            except Exception:
                continue  # a broken check must not kill the scan
        kept = []
        seen: set[tuple] = set()
        for c in causes:
            # a file shared by several root modules is checked once per
            # instantiating root: identical causes collapse to one
            key = (c.message, c.resource, c.start_line, c.end_line)
            if key in seen:
                continue
            seen.add(key)
            res_start, attrs = _enclosing(c)
            if not is_ignored(ignores, chk.id, chk.avd_id,
                              c.start_line, c.end_line,
                              resource_start=res_start, attrs=attrs,
                              today=today):
                kept.append(c)
        causes = kept
        if causes:
            for c in causes:
                misconf.failures.append(
                    _to_detected(chk, ftype, c, content, "FAIL"))
        else:
            misconf.successes.append(
                _to_detected(chk, ftype, None, content, "PASS"))
    return misconf


def scan_config(path: str, content: bytes,
                file_type: str | None = None) -> Misconfiguration | None:
    """-> Misconfiguration (successes + failures) or None if the file is
    not a recognized config type."""
    ftype = file_type or detection.detect(path, content)
    if ftype is None or ftype in (detection.YAML, detection.JSON):
        return None  # plain data files: nothing to check (yet)
    ctxs = _contexts(ftype, path, content)
    if not ctxs:
        return None
    return _run_checks(ftype, path, ctxs, content)
