"""Misconfiguration -> report Result shaping (reference
pkg/scanner/local/scan.go:371 misconfsToResults)."""

from __future__ import annotations

from trivy_tpu.types.artifact import Misconfiguration
from trivy_tpu.types.enums import ResultClass
from trivy_tpu.types.report import MisconfSummary, Result


def to_result(misconf: Misconfiguration) -> Result | None:
    if not misconf.successes and not misconf.failures:
        return None
    return Result(
        target=misconf.file_path,
        result_class=ResultClass.CONFIG,
        type=misconf.file_type,
        misconf_summary=MisconfSummary(
            successes=len(misconf.successes),
            failures=len(misconf.failures),
        ),
        misconfigurations=sorted(
            list(misconf.failures) + list(misconf.successes),
            key=lambda m: m.sort_key(),
        ),
    )
