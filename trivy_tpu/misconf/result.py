"""Misconfiguration -> report Result shaping (reference
pkg/scanner/local/scan.go:371 misconfsToResults)."""

from __future__ import annotations

from trivy_tpu.types.artifact import Misconfiguration
from trivy_tpu.types.enums import ResultClass
from trivy_tpu.types.report import (
    DetectedMisconfiguration,
    MisconfSummary,
    Result,
)
from trivy_tpu.types.serde import from_dict


def _rebuild(items) -> list[DetectedMisconfiguration]:
    # Misconfiguration.successes/failures are untyped lists, so entries
    # come back as plain dicts after a cache round-trip
    return [
        m if isinstance(m, DetectedMisconfiguration)
        else from_dict(DetectedMisconfiguration, m)
        for m in items
    ]


def to_result(misconf: Misconfiguration) -> Result | None:
    misconf.successes = _rebuild(misconf.successes)
    misconf.failures = _rebuild(misconf.failures)
    if not misconf.successes and not misconf.failures:
        return None
    return Result(
        target=misconf.file_path,
        result_class=ResultClass.CONFIG,
        type=misconf.file_type,
        misconf_summary=MisconfSummary(
            successes=len(misconf.successes),
            failures=len(misconf.failures),
        ),
        misconfigurations=sorted(
            list(misconf.failures) + list(misconf.successes),
            key=lambda m: m.sort_key(),
        ),
    )
