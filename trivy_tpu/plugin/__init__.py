from trivy_tpu.plugin.manager import (  # noqa: F401
    Plugin,
    PluginError,
    PluginManager,
)
