"""Subprocess plugin system (reference pkg/plugin):

- a plugin is a directory under <cache>/plugin/<name>/ with a
  `plugin.yaml` manifest {name, version, summary, platforms:
  [{selector: {os, arch}, uri, bin}]} (plugin.go:23-54)
- install from a local directory, a zip archive, or a URL
  (manager.go:99 install sources; the OCI source is network-gated)
- `trivy-tpu <plugin-name> args…` and `trivy-tpu plugin run` execute
  the selected platform binary as a subprocess (plugin.go:101)
"""

from __future__ import annotations

import os
import platform as _platform
import re
import shutil
import stat
import subprocess
import sys
import urllib.request
import zipfile
from dataclasses import dataclass, field

import yaml

from trivy_tpu.durability import atomic_write
from trivy_tpu.log import logger

_log = logger("plugin")


class PluginError(Exception):
    pass


@dataclass
class Platform:
    os: str = ""
    arch: str = ""
    uri: str = ""
    bin: str = ""


@dataclass
class Plugin:
    name: str = ""
    version: str = ""
    repository: str = ""
    summary: str = ""
    description: str = ""
    platforms: list[Platform] = field(default_factory=list)
    dir: str = ""

    @classmethod
    def from_manifest(cls, path: str) -> "Plugin":
        with open(path, "rb") as f:
            doc = yaml.safe_load(f) or {}
        plats = []
        for p in doc.get("platforms") or []:
            sel = p.get("selector") or {}
            plats.append(Platform(
                os=sel.get("os", ""), arch=sel.get("arch", ""),
                uri=p.get("uri", ""), bin=p.get("bin", "")))
        return cls(
            name=doc.get("name", ""),
            version=str(doc.get("version", "")),
            repository=doc.get("repository", ""),
            summary=doc.get("summary", "") or doc.get("usage", ""),
            description=doc.get("description", ""),
            platforms=plats,
            dir=os.path.dirname(path),
        )

    def select_platform(self) -> Platform:
        """First platform whose selector matches this host; empty
        selector fields are wildcards (reference plugin.go selector)."""
        host_os = sys.platform.replace("linux2", "linux")
        if host_os.startswith("linux"):
            host_os = "linux"
        host_arch = _platform.machine().lower()
        host_arch = {"x86_64": "amd64", "aarch64": "arm64"}.get(
            host_arch, host_arch)
        for p in self.platforms:
            if p.os and p.os != host_os:
                continue
            if p.arch and p.arch != host_arch:
                continue
            return p
        raise PluginError(
            f"plugin {self.name!r} does not support {host_os}/{host_arch}")

    def run(self, args: list[str], stdin=None) -> int:
        plat = self.select_platform()
        bin_path = os.path.join(self.dir, plat.bin)
        if not os.path.exists(bin_path):
            raise PluginError(f"plugin binary missing: {bin_path}")
        st = os.stat(bin_path)
        if not st.st_mode & stat.S_IXUSR:
            os.chmod(bin_path, st.st_mode | stat.S_IXUSR)
        proc = subprocess.run([bin_path, *args], stdin=stdin)
        return proc.returncode


_SAFE_NAME = re.compile(r"^[A-Za-z0-9._-]+$")


class PluginManager:
    def __init__(self, cache_dir: str):
        self.root = os.path.join(cache_dir, "plugin")

    def _dir(self, name: str) -> str:
        # the name may come from an untrusted zip/URL manifest; a name like
        # "../../target" would rmtree/copytree outside the plugin root
        if not _SAFE_NAME.match(name) or name in (".", ".."):
            raise PluginError(f"invalid plugin name {name!r}")
        return os.path.join(self.root, name)

    # ------------------------------------------------------------- list

    def list(self) -> list[Plugin]:
        out = []
        if not os.path.isdir(self.root):
            return out
        for name in sorted(os.listdir(self.root)):
            manifest = os.path.join(self.root, name, "plugin.yaml")
            if os.path.exists(manifest):
                try:
                    out.append(Plugin.from_manifest(manifest))
                except Exception as e:
                    _log.warn("bad plugin manifest", plugin=name, err=str(e))
        return out

    def get(self, name: str) -> Plugin | None:
        manifest = os.path.join(self._dir(name), "plugin.yaml")
        if not os.path.exists(manifest):
            return None
        return Plugin.from_manifest(manifest)

    # ---------------------------------------------------------- install

    DEFAULT_INDEX_URL = ("https://aquasecurity.github.io/"
                         "trivy-plugin-index/v1/index.yaml")

    @property
    def index_path(self) -> str:
        return os.path.join(self.root, "index.yaml")

    def update_index(self, url: str = "") -> None:
        """Download the plugin index (reference manager.go index.yaml)."""
        url = url or self.DEFAULT_INDEX_URL
        with urllib.request.urlopen(url, timeout=60) as resp:
            data = resp.read()
        os.makedirs(self.root, exist_ok=True)
        atomic_write(self.index_path, data)
        _log.info("plugin index updated", url=url)

    def index(self) -> list[dict]:
        """Cached index entries: [{name, repository, summary, ...}]."""
        if not os.path.exists(self.index_path):
            return []
        with open(self.index_path, encoding="utf-8") as f:
            doc = yaml.safe_load(f) or {}
        return doc.get("plugins") or []

    def search(self, keyword: str = "") -> list[dict]:
        kw = keyword.lower()
        return [p for p in self.index()
                if kw in (p.get("name", "") + p.get("summary", "")).lower()]

    def _resolve_index_name(self, name: str) -> str:
        """Bare plugin name -> its repository via the cached index
        (reference tryIndex, manager.go:101)."""
        for p in self.index():
            if p.get("name") == name and p.get("repository"):
                _log.info("plugin found in the index", name=name,
                          repository=p["repository"])
                return p["repository"]
        return name

    def install(self, source: str, insecure: bool = False) -> Plugin:
        """source: local dir with plugin.yaml, local .zip, http(s) URL to
        a zip, an OCI reference (registry/repo:tag), or a bare index name
        (reference manager.go:99)."""
        if os.path.isdir(source):
            return self._install_dir(source)
        if source.endswith(".zip") and os.path.exists(source):
            return self._install_zip(source)
        if source.startswith(("http://", "https://")):
            with urllib.request.urlopen(source, timeout=120) as resp:
                data = resp.read()
            tmp = os.path.join(self.root, ".download.zip")
            os.makedirs(self.root, exist_ok=True)
            # lint: allow[atomic-write] transient download buffer, consumed and unlinked in this call
            with open(tmp, "wb") as f:
                f.write(data)
            try:
                return self._install_zip(tmp)
            finally:
                os.unlink(tmp)
        if re.fullmatch(r"[A-Za-z0-9][A-Za-z0-9._-]*", source):
            source = self._resolve_index_name(source)
        if "/" in source:  # OCI reference
            return self._install_oci(source, insecure=insecure)
        raise PluginError(
            f"unsupported plugin source {source!r} "
            "(local dir, .zip, http(s) URL, OCI ref, or index name)")

    def _install_oci(self, ref: str, insecure: bool = False) -> Plugin:
        """Pull a plugin OCI artifact: every tar(.gz) layer unpacks into
        the staging dir, which must yield a plugin.yaml."""
        from trivy_tpu.artifact.image_source import (
            RegistryClient,
            SourceError,
            parse_reference,
        )

        registry, repo, tag, digest = parse_reference(ref)
        tmp = os.path.join(self.root, ".oci-unpack")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            client = RegistryClient(registry, insecure=insecure)
            try:
                manifest, _ = client.manifest(repo, digest or tag)
            except SourceError as e:
                raise PluginError(f"plugin OCI manifest {ref}: {e}")
            import gzip as _gzip
            import io
            import tarfile

            for layer in manifest.get("layers") or []:
                try:
                    data = client.blob(repo, layer.get("digest", ""))
                except SourceError as e:
                    raise PluginError(f"plugin OCI blob {ref}: {e}")
                if data[:2] == b"\x1f\x8b":
                    data = _gzip.decompress(data)
                if not tarfile.is_tarfile(io.BytesIO(data)):
                    continue
                with tarfile.open(fileobj=io.BytesIO(data)) as tf:
                    try:
                        # the "data" filter rejects absolute paths, ..
                        # traversal and escaping links member-by-member
                        tf.extractall(tmp, filter="data")
                    except tarfile.TarError as e:
                        raise PluginError(
                            f"unsafe path in plugin layer: {e}")
            return self._install_dir(tmp)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def _install_dir(self, source: str) -> Plugin:
        manifest = os.path.join(source, "plugin.yaml")
        if not os.path.exists(manifest):
            raise PluginError(f"no plugin.yaml in {source}")
        plugin = Plugin.from_manifest(manifest)
        if not plugin.name:
            raise PluginError("plugin manifest has no name")
        dest = self._dir(plugin.name)
        if os.path.exists(dest):
            shutil.rmtree(dest)
        shutil.copytree(source, dest)
        plugin.dir = dest
        _log.info("installed plugin", name=plugin.name,
                  version=plugin.version)
        return plugin

    def _install_zip(self, source: str) -> Plugin:
        tmp = os.path.join(self.root, ".unpack")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            with zipfile.ZipFile(source) as zf:
                for info in zf.infolist():
                    # zip-slip guard
                    dest = os.path.realpath(os.path.join(tmp, info.filename))
                    if not dest.startswith(os.path.realpath(tmp) + os.sep):
                        raise PluginError(
                            f"unsafe path in plugin zip: {info.filename}")
                zf.extractall(tmp)
            # manifest may live at the top or in a single subdirectory
            root = tmp
            if not os.path.exists(os.path.join(root, "plugin.yaml")):
                entries = [e for e in os.listdir(root)
                           if os.path.isdir(os.path.join(root, e))]
                if len(entries) == 1:
                    root = os.path.join(root, entries[0])
            return self._install_dir(root)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def uninstall(self, name: str) -> bool:
        dest = self._dir(name)
        if not os.path.exists(dest):
            return False
        shutil.rmtree(dest)
        _log.info("uninstalled plugin", name=name)
        return True

    # --------------------------------------------------------------- run

    def run(self, name: str, args: list[str], stdin=None) -> int:
        plugin = self.get(name)
        if plugin is None:
            raise PluginError(f"plugin {name!r} is not installed")
        return plugin.run(args, stdin=stdin)
