"""Post-handlers mutating BlobInfo after analysis
(reference pkg/fanal/handler/sysfile/filter.go:54): drop language packages
whose files were installed by the OS package manager — they're already
covered by the OS package scan."""

from __future__ import annotations

from trivy_tpu.fanal.analyzer import AnalysisResult

# app types exempt from the system-file filter (reference filter.go:
# these are looked up per-file, not per-project)
_EXEMPT_TYPES = {"node-pkg", "python-pkg", "gemspec", "jar", "conda-pkg"}


def system_file_filter(result: AnalysisResult) -> None:
    installed = set(result.system_installed_files)
    if not installed:
        return
    kept = []
    for app in result.applications:
        path = app.file_path
        if app.type in _EXEMPT_TYPES and path:
            # filter individual packages by their own path
            app.packages = [
                p for p in app.packages
                if (p.file_path or path) not in installed
                and "/" + (p.file_path or path) not in installed
            ]
            if app.packages:
                kept.append(app)
            continue
        if path and (path in installed or "/" + path in installed):
            continue
        kept.append(app)
    result.applications = kept
