"""Analyzer framework (reference pkg/fanal/analyzer/analyzer.go).

- Analyzers register into a global registry (analyzer.go:26-27); an
  AnalyzerGroup is built per scan honoring disabled types (analyzer.go:321)
- per-file analyzers get (path, content); post-analyzers get a virtual
  filesystem of just their required files (analyzer.go:475-515)
- results merge into one AnalysisResult per blob (analyzer.go:251-301)
- analyzer versions feed cache keys (analyzer.go:385)

Host-side design difference from the reference: instead of a goroutine per
(file x analyzer), files are walked serially/thread-pooled and matching is
dispatched by path — the heavy parallelism belongs to the device batches,
not the host (SURVEY.md §2.10).
"""

from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass, field
from typing import Callable

from trivy_tpu.log import logger
from trivy_tpu.types.artifact import (
    Application,
    BlobInfo,
    CustomResource,
    LicenseFile,
    OS,
    PackageInfo,
    Repository,
    Secret,
)

_log = logger("analyzer")


@dataclass
class AnalysisInput:
    """One file presented to an analyzer."""

    path: str  # path inside the artifact (no leading slash)
    content: bytes | None = None
    size: int = 0
    mode: int = 0
    # opener for lazy/large files
    open: Callable[[], bytes] | None = None

    def read(self) -> bytes:
        if self.content is None and self.open is not None:
            self.content = self.open()
        return self.content or b""


@dataclass
class AnalysisResult:
    os: OS = field(default_factory=OS)
    repository: Repository | None = None
    package_infos: list[PackageInfo] = field(default_factory=list)
    applications: list[Application] = field(default_factory=list)
    secrets: list[Secret] = field(default_factory=list)
    licenses: list[LicenseFile] = field(default_factory=list)
    system_installed_files: list[str] = field(default_factory=list)
    custom_resources: list[CustomResource] = field(default_factory=list)
    misconfigurations: list = field(default_factory=list)
    build_info: object | None = None
    digests: dict = field(default_factory=dict)

    def merge(self, other: "AnalysisResult | None") -> None:
        if other is None:
            return
        self.os = self.os.merge(other.os)
        if other.repository is not None:
            self.repository = other.repository
        self.package_infos.extend(other.package_infos)
        self.applications.extend(other.applications)
        self.secrets.extend(other.secrets)
        self.licenses.extend(other.licenses)
        self.system_installed_files.extend(other.system_installed_files)
        self.custom_resources.extend(other.custom_resources)
        self.misconfigurations.extend(other.misconfigurations)
        if other.build_info is not None:
            bi, obi = self.build_info, other.build_info
            if bi is None:
                self.build_info = obi
            else:  # merge fields (content manifest + dockerfile analyzers)
                bi.content_sets = bi.content_sets or obi.content_sets
                bi.nvr = bi.nvr or obi.nvr
                bi.arch = bi.arch or obi.arch
        self.digests.update(other.digests)

    def to_blob(self) -> BlobInfo:
        blob = BlobInfo()
        blob.os = self.os
        blob.repository = self.repository
        blob.package_infos = sorted(
            self.package_infos, key=lambda p: p.file_path
        )
        blob.applications = sorted(
            self.applications, key=lambda a: (a.type, a.file_path)
        )
        blob.secrets = sorted(self.secrets, key=lambda s: s.file_path)
        blob.licenses = sorted(self.licenses, key=lambda l: (l.file_path, l.package_name))
        blob.misconfigurations = self.misconfigurations
        blob.custom_resources = self.custom_resources
        blob.build_info = self.build_info
        blob.digests = dict(sorted(self.digests.items()))
        return blob


class Analyzer:
    """Base per-file analyzer."""

    type: str = ""
    version: int = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        raise NotImplementedError

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        raise NotImplementedError


class PostAnalyzer(Analyzer):
    """Analyzer over a set of collected files (virtual FS): lockfile
    parsers that need sibling files, license classifiers, etc."""

    def post_analyze(self, files: dict[str, AnalysisInput]) -> AnalysisResult | None:
        raise NotImplementedError


_ANALYZERS: list[Analyzer] = []
_POST_ANALYZERS: list[PostAnalyzer] = []


def register(a) -> Analyzer:
    """Register an analyzer instance (or class, instantiated here)."""
    _ANALYZERS.append(a() if isinstance(a, type) else a)
    return a


def register_post(a) -> PostAnalyzer:
    _POST_ANALYZERS.append(a() if isinstance(a, type) else a)
    return a


def unregister(a) -> None:
    """Remove a dynamically registered analyzer (module system)."""
    for reg in (_ANALYZERS, _POST_ANALYZERS):
        if a in reg:
            reg.remove(a)


# analyzer type groups (reference pkg/fanal/analyzer/const.go:150-258)
TYPE_OSES = {
    "os-release", "alpine", "amazon", "debian", "photon", "redhat-base",
    "suse", "ubuntu", "ubuntu-esm", "apk", "dpkg", "dpkg-license", "rpm",
    "rpmqa", "apk-repo",
}
TYPE_INDIVIDUAL_PKGS = {
    "gemspec", "node-pkg", "python-pkg", "gobinary", "rustbinary", "jar",
    "conda-pkg", "composer-vendor",
}
# exactly the reference's TypeLockfiles (const.go:211-231): cargo,
# nuget, dotnet-core, packages-props, bun, and julia are NOT in it and
# stay enabled for rootfs/image scans
TYPE_LOCKFILES = {
    "bundler", "npm", "yarn", "pnpm", "pip", "pipenv", "poetry", "uv",
    "gomod", "composer", "pom", "gradle",
    "sbt", "conan", "pub",
    "hex", "swift", "cocoapods", "conda-environment",
}


def _pattern_required(a, path: str, size: int, mode: int) -> bool:
    """required() widened by --file-patterns regexes attached to the
    analyzer copy (reference analyzer.go:321-377 filePatterns)."""
    pats = getattr(a, "extra_patterns", None)
    if pats and any(p.search(path) for p in pats):
        return True
    return a.required(path, size, mode)


@dataclass
class AnalyzerGroup:
    """The set of analyzers active for one scan."""

    analyzers: list[Analyzer]
    post_analyzers: list[PostAnalyzer]

    @classmethod
    def build(
        cls,
        disabled_types: set[str] | None = None,
        enabled_types: set[str] | None = None,
        file_patterns: list[str] | None = None,
        helm_overrides: dict | None = None,
    ) -> "AnalyzerGroup":
        """file_patterns: `analyzer-type:regex` entries (reference
        analyzer.go:321-377 filePatterns) — a file whose path matches the
        regex is fed to that analyzer even if required() declines it."""
        import re as _re

        disabled = disabled_types or set()
        patterns: dict[str, list] = {}
        for entry in file_patterns or []:
            atype, _, pat = entry.partition(":")
            if not pat:
                raise ValueError(
                    f"invalid file pattern {entry!r} (want type:regex)")
            patterns.setdefault(atype, []).append(_re.compile(pat))

        def keep(a: Analyzer) -> bool:
            if a.type in disabled:
                return False
            if enabled_types is not None and a.type not in enabled_types:
                return False
            return True

        # --file-patterns may name an IaC FILE type (dockerfile:...,
        # kubernetes:...); those route to the config analyzer with a
        # detection override (reference: the dockerfile analyzer is its
        # own type, here one config analyzer owns all IaC types)
        iac_types = {"dockerfile", "kubernetes", "terraform",
                     "cloudformation", "terraformplan", "helm",
                     "azure-arm", "yaml", "json"}
        iac_type_pats = [(rx, atype) for atype, rxs in patterns.items()
                         if atype in iac_types for rx in rxs]

        def wrap(a):
            pats = list(patterns.get(a.type) or [])
            type_pats = iac_type_pats if a.type == "config" else []
            if type_pats:
                pats.extend(rx for rx, _t in type_pats)
            overrides = helm_overrides if a.type == "config" else None
            if not pats and not type_pats and not overrides:
                return a
            import copy

            a2 = copy.copy(a)
            if pats:
                a2.extra_patterns = pats
            if type_pats:
                a2.iac_type_patterns = type_pats
            if overrides:
                a2.helm_overrides = overrides
            return a2

        return cls(
            analyzers=[wrap(a) for a in _ANALYZERS if keep(a)],
            post_analyzers=[wrap(a) for a in _POST_ANALYZERS if keep(a)],
        )

    def versions(self) -> dict[str, int]:
        out = {}
        for a in self.analyzers + self.post_analyzers:
            out[a.type] = a.version
        return dict(sorted(out.items()))

    def analyze_file(self, result: AnalysisResult, inp: AnalysisInput,
                     post_files: dict) -> None:
        for a in self.analyzers:
            try:
                if not _pattern_required(a, inp.path, inp.size, inp.mode):
                    continue
                result.merge(a.analyze(inp))
            except Exception as e:  # analyzer bugs must not kill the scan
                _log.debug("analyzer failed", analyzer=a.type,
                           path=inp.path, err=str(e))
        for pa in self.post_analyzers:
            try:
                if _pattern_required(pa, inp.path, inp.size, inp.mode):
                    inp.read()
                    post_files.setdefault(pa.type, {})[inp.path] = inp
            except Exception as e:
                _log.debug("post-analyzer required() failed",
                           analyzer=pa.type, path=inp.path, err=str(e))

    def post_analyze(self, result: AnalysisResult, post_files: dict) -> None:
        for pa in self.post_analyzers:
            files = post_files.get(pa.type)
            if not files:
                continue
            try:
                result.merge(pa.post_analyze(files))
            except Exception as e:
                _log.warn("post-analyzer failed", analyzer=pa.type, err=str(e))


def matches_any(path: str, patterns: list[str]) -> bool:
    base = os.path.basename(path)
    for pat in patterns:
        if fnmatch.fnmatch(path, pat) or fnmatch.fnmatch(base, pat):
            return True
    return False
