"""Pipelined, dedupe-aware layer-analysis executor (docs/performance.md
"Analysis pipeline & layer dedupe").

Two mechanisms attack the artifact-analysis share of the north-star
budget (BASELINE.md arithmetic: matching is ~2 s, layer inspection is
the rest):

1. **Prefetch pipeline** — ``run_layer_pipeline`` overlaps the I/O-bound
   fetch+decode of layer N+1 with the CPU-bound walk/analyze of layer N
   (the PR 4 double-buffered coordinator/crunch-lane idiom applied to
   the fanal stage). One fetch lane reads layer streams out of the image
   source sequentially — tar/daemon/registry handles are not required to
   be thread-safe, so exactly one thread ever touches them — while the
   coordinator (the scanning thread) analyzes in layer order, so results
   are byte-identical to the serial path by construction. Depth-bounded:
   at most ``prefetch_depth`` layers are materialized ahead.

2. **Content-addressed cross-image dedupe** — layer cache keys are
   already content addressed (diffID x analyzer versions, cache_key),
   so a base layer shared by every debian/alpine image hits the blob
   cache after its first analysis. ``LayerSingleflight`` closes the
   remaining window: two *concurrent* scans (fleet lanes, concurrent
   in-process server scans) that both miss the cache on the same blob_id
   coordinate so exactly one analyzes it; the rest wait on the completed
   BlobInfo document and replay it into their own cache handle when it
   differs from the leader's. The same registry, in TTL mode, gates the
   RPC server's MissingBlobs endpoint so concurrent *remote* clients
   sharing the server cache dedupe too (rpc/server.py).

``TRIVY_TPU_ANALYSIS_PIPELINE=0`` disables both and restores the serial
per-layer path byte-identically (artifact/image.py keeps the legacy loop
verbatim behind the switch).

3. **Multi-lane walk** — ``run_layer_lanes`` generalizes the pair into
   one in-order fetch lane feeding N walk lanes (``--parallel N`` /
   ``TRIVY_TPU_ANALYSIS_WORKERS``, reference-parity default 5 matching
   pkg/parallel/pipeline.go). Per-layer analysis is independent, so
   lanes split+analyze distinct layers concurrently — mostly inside
   GIL-dropping native/numpy code (ops/splitter.py, the vectorized
   analyzers) — while the coordinator applies every BlobInfo document
   strictly in layer order: cache writes, singleflight publishes and
   journal records happen exactly as the serial path would emit them,
   so the output is byte-identical by construction at any lane count.
   ``workers<=1`` IS the PR 6 two-stage pipeline, code path and all.

Fault site ``analysis.fetch`` (resilience/faults.py grammar): ``delay``
sleeps in the fetch lane, ``drop`` discards the fetched stream and
refetches (a lost prefetch is recomputed — results unchanged), ``error``
fails the fetch once and the layer is refetched from scratch (two
consecutive injected errors fail the scan), ``kill`` crashes for the
SIGKILL-and-resume matrix.

Fault site ``analysis.lane`` mirrors the ladder at the walk stage:
``delay`` sleeps in the lane, ``drop`` discards the analyzed document
and recomputes it from the already-split members (results unchanged),
``error`` fails the lane analysis once and it is recomputed (two
consecutive injected errors fail the scan), ``kill`` crashes mid-walk
for the SIGKILL-and-resume matrix.
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading

from trivy_tpu.analysis.witness import make_lock
import time

from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import tracing
from trivy_tpu.obs import usage
from trivy_tpu.resilience import faults

_log = logger("fanal.pipeline")

FETCH_SITE = "analysis.fetch"
LANE_SITE = "analysis.lane"

#: reference parity: pkg/parallel/pipeline.go runs 5 workers by default
DEFAULT_WORKERS = 5
#: per-lane occupancy gauge cardinality bound (and the hard lane cap)
MAX_WORKERS = 32

# a server-side MissingBlobs claim with no PutBlob after this long is
# presumed dead (client crashed mid-analysis) and may be re-claimed
SERVER_CLAIM_TTL_S = 300.0
# total seconds one MissingBlobs request spends waiting on other
# clients' in-flight layers before telling the caller to analyze them
SERVER_WAIT_BUDGET_S = 10.0
# in-process leaders always finish (try/finally), so this is a hang
# guard, not a tuning knob
_INPROC_WAIT_S = 600.0


class AnalysisFetchError(Exception):
    """A layer fetch failed (injected or real); the layer is refetched
    once before the scan fails."""


class AnalysisLaneError(Exception):
    """A lane analysis failed (injected or real); the document is
    recomputed once from the split members before the scan fails."""


def enabled() -> bool:
    """The ``TRIVY_TPU_ANALYSIS_PIPELINE`` kill switch (default on)."""
    return os.environ.get("TRIVY_TPU_ANALYSIS_PIPELINE", "1") != "0"


def prefetch_depth() -> int:
    raw = os.environ.get("TRIVY_TPU_ANALYSIS_PREFETCH")
    if raw:
        try:
            return max(int(raw), 1)
        except ValueError:
            _log.warn("bad TRIVY_TPU_ANALYSIS_PREFETCH; using default",
                      value=raw)
    return 2


def analysis_workers(requested: int | None = None) -> int:
    """Walk-lane count: ``TRIVY_TPU_ANALYSIS_WORKERS`` overrides the
    caller's ``--parallel`` value; malformed values warn-and-default
    like ``TRIVY_TPU_ANALYSIS_PREFETCH``. Clamped to [1, MAX_WORKERS]
    (the per-lane gauge's cardinality bound)."""
    n = requested if requested is not None else DEFAULT_WORKERS
    raw = os.environ.get("TRIVY_TPU_ANALYSIS_WORKERS")
    if raw:
        try:
            n = int(raw)
        except ValueError:
            _log.warn("bad TRIVY_TPU_ANALYSIS_WORKERS; using default",
                      value=raw)
    return max(1, min(n, MAX_WORKERS))


# ------------------------------------------------------------ singleflight


class _Slot:
    """One in-flight layer analysis other scans can wait on."""

    __slots__ = ("event", "doc", "ok", "src_cache", "created", "done",
                 "holder")

    def __init__(self, src_cache, holder=None):
        self.event = threading.Event()
        self.doc: dict | None = None
        self.ok = False
        self.src_cache = src_cache  # leader's cache handle (may be None)
        self.created = time.monotonic()
        self.done = False
        self.holder = holder        # opaque claimant identity (server
        #                             gate: the scan's trace id)


class LayerSingleflight:
    """blob_id-keyed in-flight registry: first claimer leads, the rest
    wait on the leader's completed blob document.

    Two modes share the implementation:

    - in-process (``ttl_s=None``): leaders are code paths with a
      try/finally around :meth:`finish`, so slots always resolve;
    - server gate (``ttl_s`` set): leaders are remote clients that may
      die between MissingBlobs and PutBlob, so a stale claim expires
      and the next claimer takes over.
    """

    def __init__(self, ttl_s: float | None = None):
        self._lock = make_lock("fanal.pipeline._lock")
        self._inflight: dict[str, _Slot] = {}
        self.ttl_s = ttl_s

    def claim(self, blob_id: str, src_cache=None,
              holder=None) -> tuple[_Slot, bool]:
        """-> (slot, is_leader). Leaders MUST eventually call
        :meth:`finish` on their slot (TTL mode excepted). A non-None
        ``holder`` matching the live claim's holder re-leads instead of
        waiting — a retried RPC (lost response, resent request) must
        not park on its own first attempt's claim, which nobody else
        will ever complete."""
        now = time.monotonic()
        with self._lock:
            slot = self._inflight.get(blob_id)
            if slot is not None and self.ttl_s is not None \
                    and now - slot.created > self.ttl_s:
                # presumed-dead leader: release its waiters (they
                # re-probe and analyze) and take the claim over
                slot.done = True
                slot.event.set()
                slot = None
            if slot is not None and holder is not None \
                    and slot.holder == holder:
                slot.created = now  # idempotent re-claim extends TTL
                return slot, True
            if slot is None:
                if self.ttl_s is not None and len(self._inflight) > 1024:
                    self._sweep_expired(now)
                slot = _Slot(src_cache, holder=holder)
                self._inflight[blob_id] = slot
                return slot, True
            return slot, False

    def _sweep_expired(self, now: float) -> None:
        # caller holds the lock; TTL mode only
        for bid in [b for b, s in self._inflight.items()
                    if now - s.created > self.ttl_s]:
            s = self._inflight.pop(bid)
            s.event.set()

    def finish(self, blob_id: str, slot: _Slot, doc: dict | None = None,
               ok: bool = False) -> None:
        """Resolve a claim (idempotent). ``ok=True`` publishes ``doc``
        to waiters; ``ok=False`` sends them back to claim()."""
        with self._lock:
            if slot.done:
                return
            slot.done = True
            if self._inflight.get(blob_id) is slot:
                del self._inflight[blob_id]
        slot.doc = doc
        slot.ok = ok
        slot.event.set()

    def reclaim(self, blob_id: str, holder=None) -> None:
        """Forcibly take over a claim whose holder is presumed dead
        (a waiter timed out on it). The stale slot's waiters are
        released (they re-probe and analyze); the fresh claim carries a
        fresh TTL and resolves at the new holder's completion, so later
        callers park on a live analysis instead of the ghost."""
        with self._lock:
            old = self._inflight.get(blob_id)
            if old is not None:
                old.done = True
                old.event.set()
            self._inflight[blob_id] = _Slot(None, holder=holder)

    def complete(self, blob_id: str) -> None:
        """Server-gate completion: a PutBlob for ``blob_id`` landed in
        the shared cache, so any slot resolves successfully (no doc —
        waiters re-probe the now-populated cache)."""
        with self._lock:
            slot = self._inflight.pop(blob_id, None)
        if slot is not None:
            slot.done = True
            slot.ok = True
            slot.event.set()

    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)


#: process-wide registry for in-process scans (fleet lanes, concurrent
#: library/server-embedded scans) — content-addressed blob_ids make
#: cross-scan sharing safe by construction
SINGLEFLIGHT = LayerSingleflight()


# ------------------------------------------------------- journal hook


class JournalHook:
    """Per-layer journal wiring a fleet lane installs around its scan:
    ``on_layer(blob_id)`` records a completed layer analysis in the
    fleet journal; ``precompleted`` is the blob_id set replayed from a
    resumed journal (those layers sit in the cache already and are
    skipped — and counted — instead of re-analyzed)."""

    def __init__(self, on_layer=None, precompleted: set[str] | None = None):
        self.on_layer = on_layer
        self.precompleted = precompleted or set()

    def layer_done(self, blob_id: str) -> None:
        if self.on_layer is not None:
            self.on_layer(blob_id)


# module-global, not a contextvar: the hook must reach the scan worker
# threads _scan_with_timeout spawns (fresh contexts), and a fleet has
# exactly one journal shared by every lane anyway
_JOURNAL_HOOK: JournalHook | None = None


@contextlib.contextmanager
def journal_scope(on_layer=None, precompleted: set[str] | None = None):
    """Install the fleet-wide layer journal for the duration of a fleet
    run (cli/fleet.py wraps run_pipeline in this)."""
    global _JOURNAL_HOOK
    prev = _JOURNAL_HOOK
    _JOURNAL_HOOK = JournalHook(on_layer, precompleted)
    try:
        yield
    finally:
        _JOURNAL_HOOK = prev


def journal_hook() -> JournalHook | None:
    return _JOURNAL_HOOK


# --------------------------------------------------------- fetch stage


def _close_quietly(obj) -> None:
    """Discarded layer streams may be real OS files (containerd content
    store); a discard must not leak the descriptor."""
    close = getattr(obj, "close", None)
    if close is not None:
        with contextlib.suppress(Exception):
            close()


def fetch_guarded(fetch):
    """Run ``fetch()`` under the ``analysis.fetch`` fault site. ``drop``
    discards the fetched stream and refetches; ``error`` raises
    AnalysisFetchError (the pipeline retries the whole fetch once);
    ``delay`` sleeps; ``kill`` dies (SIGKILL / raise-mode)."""
    rules = faults.fire(FETCH_SITE)
    faults.check_kill(FETCH_SITE, rules=rules)
    drop = err = False
    for r in rules:
        if r.action == "delay":
            time.sleep(r.param if r.param is not None else 0.05)
        elif r.action == "drop":
            drop = True
        elif r.action == "error":
            err = True
    data = fetch()
    if err:
        _close_quietly(data)
        raise AnalysisFetchError("injected analysis.fetch error")
    if drop:
        _close_quietly(data)
        data = fetch()  # the prefetched stream was lost; fetch again
    return data


def fetch_with_retry(fetch):
    try:
        return fetch_guarded(fetch)
    except AnalysisFetchError as e:
        _log.warn("layer fetch failed; refetching once", err=str(e))
        return fetch_guarded(fetch)


# ---------------------------------------------------------- lane stage


def lane_guarded(work):
    """Run ``work()`` (the analyzer pass over already-split members)
    under the ``analysis.lane`` fault site. ``work`` must be a pure
    recomputation — it consumes no stream — so ``drop`` discards the
    document and recomputes it, ``error`` raises AnalysisLaneError
    (the lane retries the analysis once), ``delay`` sleeps in the
    lane, ``kill`` dies (SIGKILL / raise-mode)."""
    rules = faults.fire(LANE_SITE)
    faults.check_kill(LANE_SITE, rules=rules)
    drop = err = False
    for r in rules:
        if r.action == "delay":
            time.sleep(r.param if r.param is not None else 0.05)
        elif r.action == "drop":
            drop = True
        elif r.action == "error":
            err = True
    if err:
        raise AnalysisLaneError("injected analysis.lane error")
    doc = work()
    if drop:
        doc = work()  # the analyzed document was lost; recompute
    return doc


def lane_with_retry(work):
    try:
        return lane_guarded(work)
    except AnalysisLaneError as e:
        _log.warn("lane analysis failed; recomputing once", err=str(e))
        return lane_guarded(work)


# ------------------------------------------------------------ pipeline


class _Stop(Exception):
    pass


def run_layer_pipeline(items: list, fetch, process,
                       depth: int | None = None) -> dict:
    """Overlap ``fetch(item)`` (fetch lane) with ``process(item,
    payload)`` (calling thread, strict item order).

    ``fetch`` must be the only code touching the image source while the
    pipeline runs (the lane serializes all fetches on one thread).
    Returns stage-busy stats and publishes the
    ``trivy_tpu_analysis_pipeline_occupancy`` gauge.
    """
    depth = depth or prefetch_depth()
    stats = {"layers": len(items), "fetch_busy_s": 0.0,
             "walk_busy_s": 0.0, "wall_s": 0.0, "occupancy": 0.0}
    if not items:
        return stats
    wall0 = time.perf_counter()

    if len(items) == 1:
        # nothing to overlap: fetch inline (same fault probes, no lane)
        t0 = time.perf_counter()
        with tracing.span(FETCH_SITE, layers=1):
            payload = fetch_with_retry(lambda: fetch(items[0]))
        usage.add("layers_fetched")
        stats["fetch_busy_s"] = time.perf_counter() - t0
        t0 = time.perf_counter()
        with tracing.span("analysis.walk", layers=1):
            process(items[0], payload)
        usage.add("layers_analyzed")
        stats["walk_busy_s"] = time.perf_counter() - t0
    else:
        out: queue.Queue = queue.Queue(maxsize=max(depth - 1, 1))
        stop = threading.Event()
        trace_ctx = tracing.capture()
        usage_ctx = usage.capture()

        def fetch_lane():
            with tracing.adopt(trace_ctx), usage.adopt(usage_ctx):
                for item in items:
                    if stop.is_set():
                        return
                    t0 = time.perf_counter()
                    try:
                        with tracing.span(FETCH_SITE):
                            payload = fetch_with_retry(lambda: fetch(item))
                    except BaseException as exc:  # lint: allow[bare-except] delivered to the analyzing thread in layer order
                        stats["fetch_busy_s"] += time.perf_counter() - t0
                        _put_interruptible(out, (item, exc, True), stop)
                        return
                    usage.add("layers_fetched")
                    stats["fetch_busy_s"] += time.perf_counter() - t0
                    if not _put_interruptible(out, (item, payload, False),
                                              stop):
                        _close_quietly(payload)  # coordinator aborted
                        return

        lane = threading.Thread(target=fetch_lane, daemon=True,
                                name="ttpu-layer-fetch")
        lane.start()

        def next_payload():
            # never a bare blocking get: a lane that died without
            # enqueuing (failure outside its guarded fetch) must not
            # wedge the scan — and the singleflight claims it holds —
            # forever
            while True:
                try:
                    return out.get(timeout=1.0)
                except queue.Empty:
                    if not lane.is_alive():
                        raise RuntimeError(
                            "layer fetch lane died without a result")

        try:
            for _ in items:
                # queue_wait attribution lane: the analyzing thread
                # starving on the fetch lane (fetch-bound crawls show
                # up here, not in analysis.walk)
                with tracing.span("analysis.await_fetch"):
                    item, payload, is_err = next_payload()
                if is_err:
                    raise payload
                t0 = time.perf_counter()
                with tracing.span("analysis.walk"):
                    process(item, payload)
                usage.add("layers_analyzed")
                stats["walk_busy_s"] += time.perf_counter() - t0
        finally:
            stop.set()

            def drain():
                with contextlib.suppress(queue.Empty):
                    while True:  # unblock a lane stuck on put(); close
                        _it, payload, is_err = out.get_nowait()  # orphans
                        if not is_err:
                            _close_quietly(payload)

            drain()
            lane.join(timeout=30.0)
            if lane.is_alive():
                # a wedged fetch (stalled registry/daemon read): the
                # caller will close the image source under it — the
                # lane's fault handler swallows the resulting error,
                # but say why the source teardown may log noise
                _log.warn("layer fetch lane still running at abort; "
                          "a stalled fetch will be abandoned")
            # a put that was already past its stop check can land
            # between the first drain and the lane exiting
            drain()

    wall = max(time.perf_counter() - wall0, 1e-9)
    stats["wall_s"] = wall
    stats["occupancy"] = min(
        (stats["fetch_busy_s"] + stats["walk_busy_s"]) / (2 * wall), 1.0)
    obs_metrics.ANALYSIS_PIPELINE_OCCUPANCY.set(stats["occupancy"])
    return stats


def run_layer_lanes(items: list, fetch, walk, apply,
                    depth: int | None = None, workers: int = 1) -> dict:
    """Multi-lane layer executor: one in-order fetch lane feeds
    ``workers`` walk lanes running ``walk(item, payload) -> doc``
    concurrently; the calling thread applies every document strictly in
    item order via ``apply(item, doc)``.

    Ordering invariant: ``apply`` — cache writes, singleflight
    publishes, journal records, counters — runs only on the calling
    thread and only for item k after items 0..k-1 were applied, so the
    externally visible effects are exactly the serial sequence and the
    output is byte-identical by construction at any lane count. Errors
    (fetch or walk) surface at their item's position in that order.

    ``workers<=1`` (or a single item) delegates to
    :func:`run_layer_pipeline` with ``walk``+``apply`` composed — the
    PR 6 two-stage pipeline, same code path, same spans.
    """
    workers = max(1, min(int(workers), MAX_WORKERS))
    if workers <= 1 or len(items) <= 1:
        return run_layer_pipeline(
            items, fetch,
            lambda item, payload: apply(item, walk(item, payload)),
            depth=depth)

    # each lane needs a layer in hand plus one in the queue to stay
    # busy; a caller-set prefetch depth still wins when larger
    depth = depth or max(prefetch_depth(), workers + 1)
    n_lanes = min(workers, len(items))
    stats = {"layers": len(items), "fetch_busy_s": 0.0,
             "walk_busy_s": 0.0, "apply_busy_s": 0.0, "wall_s": 0.0,
             "occupancy": 0.0, "workers": n_lanes,
             "lane_busy_s": [0.0] * n_lanes}
    wall0 = time.perf_counter()

    dispatch: queue.Queue = queue.Queue(maxsize=max(depth - 1, n_lanes))
    stop = threading.Event()
    trace_ctx = tracing.capture()
    usage_ctx = usage.capture()

    cond = threading.Condition()
    results: dict[int, tuple[object, bool]] = {}
    active = [0]  # walks in flight, guarded by cond

    def deliver(seq: int, value, is_err: bool) -> None:
        with cond:
            results[seq] = (value, is_err)
            cond.notify_all()

    def fetch_lane():
        with tracing.adopt(trace_ctx), usage.adopt(usage_ctx):
            for seq, item in enumerate(items):
                if stop.is_set():
                    return
                t0 = time.perf_counter()
                try:
                    with tracing.span(FETCH_SITE):
                        payload = fetch_with_retry(lambda: fetch(item))
                except BaseException as exc:  # lint: allow[bare-except] delivered to the coordinator at this layer's position
                    stats["fetch_busy_s"] += time.perf_counter() - t0
                    deliver(seq, exc, True)
                    return
                usage.add("layers_fetched")
                stats["fetch_busy_s"] += time.perf_counter() - t0
                if not _put_interruptible(dispatch, (seq, item, payload),
                                          stop):
                    _close_quietly(payload)  # coordinator aborted
                    return

    def walk_lane(lane_id: int):
        with tracing.adopt(trace_ctx), usage.adopt(usage_ctx):
            while not stop.is_set():
                try:
                    task = dispatch.get(timeout=1.0)
                except queue.Empty:
                    continue
                if task is None:
                    return
                seq, item, payload = task
                with cond:
                    active[0] += 1
                t0 = time.perf_counter()
                try:
                    with tracing.span(LANE_SITE, lane=lane_id):
                        doc = walk(item, payload)
                except BaseException as exc:  # lint: allow[bare-except] surfaces at this layer's position in apply order
                    deliver(seq, exc, True)
                else:
                    usage.add("layers_analyzed")
                    deliver(seq, doc, False)
                finally:
                    stats["lane_busy_s"][lane_id] += \
                        time.perf_counter() - t0
                    with cond:
                        active[0] -= 1
                        cond.notify_all()

    fetcher = threading.Thread(target=fetch_lane, daemon=True,
                               name="ttpu-layer-fetch")
    lanes = [threading.Thread(target=walk_lane, args=(k,), daemon=True,
                              name=f"ttpu-analysis-lane-{k}")
             for k in range(n_lanes)]
    fetcher.start()
    for t in lanes:
        t.start()

    def wait_result(seq: int):
        # never a bare blocking wait: lanes that died without
        # delivering (failure outside their guarded stages) must not
        # wedge the scan — and the singleflight claims it holds
        with cond:
            while seq not in results:
                cond.wait(timeout=1.0)
                if seq in results:
                    break
                if (not fetcher.is_alive() and dispatch.empty()
                        and active[0] == 0):
                    raise RuntimeError(
                        "analysis lanes died without a result")
            return results.pop(seq)

    def drain():
        with contextlib.suppress(queue.Empty):
            while True:  # unblock a fetch stuck on put(); close orphans
                task = dispatch.get_nowait()
                if task is not None:
                    _close_quietly(task[2])

    try:
        for seq, item in enumerate(items):
            # queue_wait attribution lane: the coordinator starving on
            # the walk lanes (fetch- or walk-bound crawls show up here)
            with tracing.span("analysis.await_lane"):
                value, is_err = wait_result(seq)
            if is_err:
                raise value
            t0 = time.perf_counter()
            with tracing.span("analysis.apply"):
                apply(item, value)
            stats["apply_busy_s"] += time.perf_counter() - t0
    finally:
        stop.set()
        drain()
        for _ in lanes:  # wake lanes parked on get() immediately
            with contextlib.suppress(queue.Full):
                dispatch.put_nowait(None)
        fetcher.join(timeout=30.0)
        for t in lanes:
            t.join(timeout=30.0)
        if fetcher.is_alive() or any(t.is_alive() for t in lanes):
            _log.warn("analysis lanes still running at abort; a "
                      "stalled fetch/walk will be abandoned")
        drain()

    wall = max(time.perf_counter() - wall0, 1e-9)
    stats["wall_s"] = wall
    stats["walk_busy_s"] = sum(stats["lane_busy_s"])
    busy = (stats["fetch_busy_s"] + stats["walk_busy_s"]
            + stats["apply_busy_s"])
    stats["occupancy"] = min(busy / ((2 + n_lanes) * wall), 1.0)
    obs_metrics.ANALYSIS_PIPELINE_OCCUPANCY.set(stats["occupancy"])
    for k in range(n_lanes):
        obs_metrics.ANALYSIS_LANE_BUSY.set(
            min(stats["lane_busy_s"][k] / wall, 1.0), lane=str(k))
    return stats


def _put_interruptible(q: queue.Queue, obj, stop: threading.Event) -> bool:
    """Bounded put that gives up when the coordinator aborted (its
    finally-drain empties the queue, so one-second polls suffice)."""
    while not stop.is_set():
        try:
            q.put(obj, timeout=1.0)
            return True
        except queue.Full:
            continue
    return False
