"""Layer squashing: merge per-layer BlobInfos bottom-up into one
ArtifactDetail (reference pkg/fanal/applier/docker.go:95-256).

Semantics preserved:
- whiteouts/opaque dirs delete earlier layers' entries by path prefix
- per-(path, type) entries: the highest layer wins
- secrets merge per file across layers keeping layer attribution
  (docker.go:297-316)
- origin-layer attribution for packages found in lower layers
- dpkg license files merge into their packages
- "individual package" types (node-pkg, python-pkg, gemspec, jar) aggregate
  into one application per type (docker.go:268-293)
"""

from __future__ import annotations

import hashlib

from trivy_tpu.types.artifact import (
    Application,
    ArtifactDetail,
    BlobInfo,
    Layer,
    OS,
    Secret,
)
from trivy_tpu.utils.purl import purl_for_package

# aggregation targets (reference pkg/fanal/types TypeIndividualPkgs)
AGGREGATE_TYPES = {"node-pkg", "python-pkg", "gemspec", "jar", "conda-pkg"}


def pkg_uid(file_path: str, pkg) -> str:
    h = hashlib.blake2b(digest_size=8)
    h.update(
        f"{file_path}\x00{pkg.name}\x00{pkg.version}\x00{pkg.release}"
        f"\x00{pkg.epoch}\x00{pkg.arch}\x00{pkg.file_path}".encode()
    )
    return h.hexdigest()


class _PathMap:
    """Flat path->value map with prefix deletion (stands in for the
    reference's nested map; paths are keys, whiteouts delete by prefix)."""

    def __init__(self):
        self.entries: dict[tuple[str, ...], object] = {}

    def set(self, path: str, type_key: str, value) -> None:
        self.entries[tuple(path.split("/")) + (type_key,)] = value

    def delete_prefix(self, path: str) -> None:
        prefix = tuple(p for p in path.split("/") if p != "")
        for k in [k for k in self.entries if k[: len(prefix)] == prefix]:
            del self.entries[k]

    def walk(self):
        # insertion order is layer order; sort for stable output like the
        # reference's sorted nested-map walk
        for k in sorted(self.entries):
            yield self.entries[k]


def apply_layers(layers: list[BlobInfo]) -> ArtifactDetail:
    path_map = _PathMap()
    secrets: dict[str, Secret] = {}
    merged = ArtifactDetail()

    for layer in layers:
        for opq in layer.opaque_dirs:
            path_map.delete_prefix(opq.rstrip("/"))
        for wh in layer.whiteout_files:
            path_map.delete_prefix(wh)

        merged.os = merged.os.merge(layer.os)
        if layer.repository is not None:
            merged.repository = layer.repository
        if layer.build_info is not None:
            merged.build_info = layer.build_info  # last layer wins
        merged.digests.update(layer.digests)

        for pkg_info in layer.package_infos:
            path_map.set(pkg_info.file_path, "type:ospkg", pkg_info)
        for app in layer.applications:
            path_map.set(app.file_path, f"type:{app.type}", app)
        for misconf in layer.misconfigurations:
            path_map.set(misconf.file_path, "type:config", misconf)
        for secret in layer.secrets:
            _merge_secret(
                secrets, secret,
                Layer(layer.digest, layer.diff_id, layer.created_by),
            )
        for lic in layer.licenses:
            lic.layer = Layer(layer.digest, layer.diff_id)
            path_map.set(lic.file_path, f"type:license,{lic.type}", lic)
        for cr in layer.custom_resources:
            cr.layer = Layer(layer.digest, layer.diff_id)
            path_map.set(cr.file_path, f"custom:{cr.type}", cr)

    from trivy_tpu.types.artifact import (
        CustomResource,
        LicenseFile,
        Misconfiguration,
        PackageInfo,
    )

    for value in path_map.walk():
        if isinstance(value, PackageInfo):
            merged.packages.extend(value.packages)
        elif isinstance(value, Application):
            merged.applications.append(value)
        elif isinstance(value, Misconfiguration):
            merged.misconfigurations.append(value)
        elif isinstance(value, LicenseFile):
            merged.licenses.append(value)
        elif isinstance(value, CustomResource):
            merged.custom_resources.append(value)

    merged.secrets = [secrets[k] for k in sorted(secrets)]

    # dpkg licenses merge into packages (docker.go:191-206)
    dpkg_licenses: dict[str, list[str]] = {}
    kept_licenses = []
    for lic in merged.licenses:
        if lic.type == "dpkg":
            dpkg_licenses[lic.package_name] = [f.name for f in lic.findings]
        else:
            kept_licenses.append(lic)
    merged.licenses = kept_licenses

    for pkg in merged.packages:
        if merged.build_info is not None:
            pkg.build_info = merged.build_info
        if not pkg.layer.digest and not pkg.layer.diff_id:
            origin = _lookup_origin_pkg(pkg, layers)
            if origin is not None:
                pkg.layer = Layer(origin[0], origin[1])
                if origin[2]:
                    pkg.installed_files = origin[2]
        if merged.os.family and not pkg.identifier.purl:
            pkg.identifier.purl = _os_purl(merged.os, pkg)
        pkg.identifier.uid = pkg_uid("", pkg)
        if pkg.name in dpkg_licenses:
            pkg.licenses = dpkg_licenses[pkg.name]

    for app in merged.applications:
        for pkg in app.packages:
            if not pkg.layer.digest and not pkg.layer.diff_id:
                origin = _lookup_origin_lib(app.file_path, pkg, layers)
                if origin is not None:
                    pkg.layer = Layer(origin[0], origin[1])
            if not pkg.identifier.purl:
                pkg.identifier.purl = purl_for_package(
                    "lang", app.type, pkg.name, pkg.version
                )
            pkg.identifier.uid = pkg_uid(app.file_path, pkg)

    _aggregate(merged)
    return merged


def _merge_secret(secrets: dict, secret: Secret, layer: Layer) -> None:
    """Secret merge keeps per-layer findings with attribution
    (reference docker.go:297-316)."""
    existing = secrets.get(secret.file_path)
    for f in secret.findings:
        f.layer = layer
    if existing is None:
        secrets[secret.file_path] = secret
    else:
        existing.findings = secret.findings  # upper layer wins per file


def _lookup_origin_pkg(pkg, layers):
    for layer in layers:
        for pi in layer.package_infos:
            for p in pi.packages:
                if (p.name, p.version, p.release) == (
                    pkg.name, pkg.version, pkg.release,
                ):
                    return layer.digest, layer.diff_id, p.installed_files
    return None


def _lookup_origin_lib(file_path, pkg, layers):
    for layer in layers:
        for app in layer.applications:
            if app.file_path != file_path:
                continue
            for p in app.packages:
                if (p.name, p.version) == (pkg.name, pkg.version):
                    return layer.digest, layer.diff_id
    return None


def _os_purl(os_info: OS, pkg) -> str:
    family_type = {
        "alpine": "apk", "chainguard": "apk", "wolfi": "apk",
        "minimos": "apk",
        "debian": "deb", "ubuntu": "deb", "echo": "deb",
    }.get(os_info.family, "rpm")
    from trivy_tpu.utils.purl import PackageURL

    qualifiers = {}
    if pkg.arch:
        qualifiers["arch"] = pkg.arch
    if pkg.epoch:
        qualifiers["epoch"] = str(pkg.epoch)
    qualifiers["distro"] = f"{os_info.family}-{os_info.name}"
    version = pkg.version
    if pkg.release:
        version += f"-{pkg.release}"
    return str(PackageURL(
        type=family_type, namespace=os_info.family, name=pkg.name,
        version=version, qualifiers=qualifiers,
    ))


def _aggregate(merged: ArtifactDetail) -> None:
    """Aggregate individual-package apps into one per type
    (reference docker.go:268-293)."""
    aggregated: dict[str, Application] = {}
    kept = []
    for app in merged.applications:
        if app.type in AGGREGATE_TYPES:
            agg = aggregated.setdefault(app.type, Application(type=app.type))
            agg.packages.extend(app.packages)
        else:
            kept.append(app)
    merged.applications = kept + [aggregated[t] for t in sorted(aggregated)]
