"""Read-only ext4 filesystem parser (reference pkg/fanal/vm/filesystem +
the go-ext4 library the reference walks VM images with).

Pure-Python, seek-based: superblock → group descriptors → inode table →
extent tree (or classic block map) → directory entries.  Supports the
features a default `mkfs.ext4` enables: 64bit, flex_bg, extents,
filetype, huge_file; classic indirect block maps for ext2/3-style
images; fast symlinks; htree directories (interior nodes read as the
fake linear dirents they are laid out as).
"""

from __future__ import annotations

import stat
import struct
from dataclasses import dataclass
from typing import BinaryIO, Iterator

EXT4_MAGIC = 0xEF53
EXTENTS_FL = 0x80000
INLINE_DATA_FL = 0x10000000
ROOT_INO = 2

INCOMPAT_64BIT = 0x80


class Ext4Error(Exception):
    pass


@dataclass
class Superblock:
    block_size: int
    blocks_per_group: int
    inodes_per_group: int
    inode_size: int
    first_data_block: int
    desc_size: int
    inodes_count: int


@dataclass
class Inode:
    ino: int
    mode: int
    size: int
    flags: int
    block: bytes  # raw 60-byte i_block area

    @property
    def is_dir(self) -> bool:
        return stat.S_ISDIR(self.mode)

    @property
    def is_file(self) -> bool:
        return stat.S_ISREG(self.mode)

    @property
    def is_symlink(self) -> bool:
        return stat.S_ISLNK(self.mode)


@dataclass
class DirEntry:
    name: str
    ino: int
    file_type: int  # 1=file 2=dir 7=symlink (when filetype feature on)


class Ext4:
    """fh must be a seekable binary file positioned anywhere; `offset`
    is the byte offset of the filesystem inside it (partition start)."""

    def __init__(self, fh: BinaryIO, offset: int = 0):
        self.fh = fh
        self.offset = offset
        self.sb = self._read_superblock()
        self._group_desc_cache: dict[int, int] = {}

    # ------------------------------------------------------------ probe

    @staticmethod
    def probe(fh: BinaryIO, offset: int = 0) -> bool:
        try:
            fh.seek(offset + 1024 + 56)
            magic = struct.unpack("<H", fh.read(2))[0]
            return magic == EXT4_MAGIC
        except (OSError, struct.error):
            return False

    # ----------------------------------------------------------- layout

    def _read_at(self, off: int, size: int) -> bytes:
        self.fh.seek(self.offset + off)
        data = self.fh.read(size)
        if len(data) != size:
            raise Ext4Error(f"short read at {off}")
        return data

    def _read_block(self, block: int) -> bytes:
        return self._read_at(block * self.sb.block_size, self.sb.block_size)

    def _read_superblock(self) -> Superblock:
        raw = self._read_at(1024, 1024)
        magic = struct.unpack_from("<H", raw, 56)[0]
        if magic != EXT4_MAGIC:
            raise Ext4Error("not an ext4 filesystem (bad magic)")
        log_block_size = struct.unpack_from("<I", raw, 24)[0]
        feature_incompat = struct.unpack_from("<I", raw, 96)[0]
        desc_size = 32
        if feature_incompat & INCOMPAT_64BIT:
            desc_size = struct.unpack_from("<H", raw, 254)[0] or 64
        return Superblock(
            block_size=1024 << log_block_size,
            blocks_per_group=struct.unpack_from("<I", raw, 32)[0],
            inodes_per_group=struct.unpack_from("<I", raw, 40)[0],
            inode_size=struct.unpack_from("<H", raw, 88)[0] or 128,
            first_data_block=struct.unpack_from("<I", raw, 20)[0],
            desc_size=desc_size,
            inodes_count=struct.unpack_from("<I", raw, 0)[0],
        )

    def _inode_table_block(self, group: int) -> int:
        if group in self._group_desc_cache:
            return self._group_desc_cache[group]
        gd_start = (self.sb.first_data_block + 1) * self.sb.block_size
        raw = self._read_at(gd_start + group * self.sb.desc_size,
                            self.sb.desc_size)
        lo = struct.unpack_from("<I", raw, 8)[0]
        hi = struct.unpack_from("<I", raw, 40)[0] \
            if self.sb.desc_size >= 64 else 0
        block = (hi << 32) | lo
        self._group_desc_cache[group] = block
        return block

    def inode(self, ino: int) -> Inode:
        if not 1 <= ino <= self.sb.inodes_count:
            raise Ext4Error(f"inode {ino} out of range")
        group, index = divmod(ino - 1, self.sb.inodes_per_group)
        table = self._inode_table_block(group)
        off = table * self.sb.block_size + index * self.sb.inode_size
        raw = self._read_at(off, self.sb.inode_size)
        size_lo = struct.unpack_from("<I", raw, 4)[0]
        size_hi = struct.unpack_from("<I", raw, 108)[0] \
            if self.sb.inode_size > 108 else 0
        return Inode(
            ino=ino,
            mode=struct.unpack_from("<H", raw, 0)[0],
            size=(size_hi << 32) | size_lo,
            flags=struct.unpack_from("<I", raw, 32)[0],
            block=raw[40:100],
        )

    # ------------------------------------------------------- file data

    def _extent_blocks(self, node_raw: bytes) -> Iterator[tuple[int, int, int]]:
        """Yield (logical_block, physical_block, count) from an extent
        tree node, recursing through index nodes."""
        magic, entries, _max, depth = struct.unpack_from("<HHHH", node_raw, 0)
        if magic != 0xF30A:
            raise Ext4Error("bad extent magic")
        if depth == 0:
            for i in range(entries):
                off = 12 + i * 12
                ee_block, ee_len, hi, lo = struct.unpack_from(
                    "<IHHI", node_raw, off)
                if ee_len > 32768:
                    # unwritten extent: allocated but uninitialized — reads
                    # as zeros, so treat it as a hole rather than exposing
                    # whatever stale bytes sit on disk
                    continue
                yield ee_block, (hi << 32) | lo, ee_len
        else:
            for i in range(entries):
                off = 12 + i * 12
                _ei_block, leaf_lo, leaf_hi, _ = struct.unpack_from(
                    "<IIHH", node_raw, off)
                child = (leaf_hi << 32) | leaf_lo
                yield from self._extent_blocks(self._read_block(child))

    def _classic_blocks(self, inode: Inode,
                        n_blocks: int) -> Iterator[int]:
        """ext2/3-style direct + (double/triple) indirect block map."""
        ids = struct.unpack("<15I", inode.block)
        per = self.sb.block_size // 4
        emitted = 0

        def emit(block_id):
            nonlocal emitted
            emitted += 1
            return block_id

        for b in ids[:12]:
            if emitted >= n_blocks:
                return
            yield emit(b)

        def indirect(block_id, level):
            nonlocal emitted
            if block_id == 0:
                # sparse hole covering the whole subtree
                for _ in range(per ** level):
                    if emitted >= n_blocks:
                        return
                    yield emit(0)
                return
            table = struct.unpack(f"<{per}I", self._read_block(block_id))
            for entry in table:
                if emitted >= n_blocks:
                    return
                if level == 1:
                    yield emit(entry)
                else:
                    yield from indirect(entry, level - 1)

        for level, b in enumerate(ids[12:15], start=1):
            if emitted >= n_blocks:
                return
            yield from indirect(b, level)

    def read_file(self, inode: Inode, limit: int | None = None) -> bytes:
        size = inode.size if limit is None else min(inode.size, limit)
        if inode.flags & INLINE_DATA_FL:
            return inode.block[:size]
        bs = self.sb.block_size
        n_blocks = (inode.size + bs - 1) // bs
        out = bytearray()
        if inode.flags & EXTENTS_FL:
            chunks: dict[int, tuple[int, int]] = {}
            for logical, physical, count in self._extent_blocks(inode.block):
                chunks[logical] = (physical, count)
            pos = 0
            while pos < n_blocks and len(out) < size:
                if pos in chunks:
                    physical, count = chunks[pos]
                    want = min(count, n_blocks - pos)
                    out += self._read_at(physical * bs, want * bs)
                    pos += want
                else:
                    # hole: find next mapped logical block
                    nxt = min((l for l in chunks if l > pos),
                              default=n_blocks)
                    out += b"\x00" * ((nxt - pos) * bs)
                    pos = nxt
        else:
            for b in self._classic_blocks(inode, n_blocks):
                if len(out) >= size:
                    break
                out += b"\x00" * bs if b == 0 else self._read_block(b)
        return bytes(out[:size])

    def read_symlink(self, inode: Inode) -> str:
        if inode.size < 60 and not inode.flags & EXTENTS_FL:
            return inode.block[:inode.size].decode("utf-8", "replace")
        return self.read_file(inode).decode("utf-8", "replace")

    # ------------------------------------------------------ directories

    def read_dir(self, inode: Inode) -> list[DirEntry]:
        data = self.read_file(inode)
        out = []
        off = 0
        while off + 8 <= len(data):
            ino, rec_len, name_len, ftype = struct.unpack_from(
                "<IHBB", data, off)
            if rec_len < 8:
                break
            if ino != 0 and name_len:
                name = data[off + 8:off + 8 + name_len].decode(
                    "utf-8", "replace")
                if name not in (".", ".."):
                    out.append(DirEntry(name=name, ino=ino, file_type=ftype))
            off += rec_len
        return out

    def walk(self, max_file_size: int | None = None
             ) -> Iterator[tuple[str, Inode]]:
        """Yield (path, inode) for every regular file, DFS from root."""
        seen: set[int] = set()
        stack: list[tuple[str, int]] = [("", ROOT_INO)]
        while stack:
            prefix, ino = stack.pop()
            if ino in seen:
                continue
            seen.add(ino)
            try:
                node = self.inode(ino)
                entries = self.read_dir(node)
            except Ext4Error:
                continue
            for e in sorted(entries, key=lambda d: d.name, reverse=True):
                path = f"{prefix}/{e.name}" if prefix else e.name
                try:
                    child = self.inode(e.ino)
                except Ext4Error:
                    continue
                if child.is_dir:
                    stack.append((path, e.ino))
                elif child.is_file:
                    yield path, child
