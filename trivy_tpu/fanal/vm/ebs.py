"""EBS snapshot streaming disk source (reference pkg/fanal/artifact/vm/
{ebs,ami}.go): scan an EBS snapshot (or the snapshot backing an AMI)
without downloading the whole image — the EBS direct APIs serve 512 KiB
blocks on demand, and the filesystem readers only touch the blocks the
walk actually needs.

The AWS client is injectable: production uses boto3 ("ebs" + "ec2"
clients) when it is importable; tests inject fakes. Targets:
  ebs:snap-xxxx   scan the snapshot directly
  ami:ami-xxxx    resolve the AMI's root device snapshot first
"""

from __future__ import annotations

import io
from collections import OrderedDict

from trivy_tpu.log import logger

_log = logger("ebs")

DEFAULT_BLOCK_SIZE = 512 * 1024
CACHE_BLOCKS = 64  # ~32 MiB with default block size


class EBSError(Exception):
    pass


class EBSDisk(io.RawIOBase):
    """Seekable read-only view over an EBS snapshot.

    `client` must provide the two EBS direct APIs used here (boto3's
    "ebs" client does):
      list_snapshot_blocks(SnapshotId=..., [NextToken=...]) ->
        {"Blocks": [{"BlockIndex": int, "BlockToken": str}],
         "BlockSize": int, "VolumeSize": int(GiB), "NextToken": str?}
      get_snapshot_block(SnapshotId=..., BlockIndex=..., BlockToken=...)
        -> {"BlockData": readable stream}
    Unlisted blocks are holes (read as zeros). Fetched blocks go through
    a small LRU — filesystem walks revisit metadata blocks constantly.
    """

    def __init__(self, client, snapshot_id: str):
        self.client = client
        self.snapshot_id = snapshot_id
        self.pos = 0
        self.block_size = DEFAULT_BLOCK_SIZE
        self.volume_bytes = 0
        self._tokens: dict[int, str] = {}
        self._cache: OrderedDict[int, bytes] = OrderedDict()
        self._zero = b""
        self._load_block_map()

    def _load_block_map(self) -> None:
        token = None
        while True:
            kwargs = {"SnapshotId": self.snapshot_id}
            if token:
                kwargs["NextToken"] = token
            try:
                resp = self.client.list_snapshot_blocks(**kwargs)
            except Exception as e:  # boto3 raises service-specific types
                raise EBSError(
                    f"cannot list blocks of {self.snapshot_id}: {e}"
                ) from e
            self.block_size = resp.get("BlockSize") or self.block_size
            vol_gib = resp.get("VolumeSize") or 0
            self.volume_bytes = vol_gib * (1 << 30)
            for b in resp.get("Blocks") or []:
                self._tokens[int(b["BlockIndex"])] = b["BlockToken"]
            token = resp.get("NextToken")
            if not token:
                break
        if not self.volume_bytes and self._tokens:
            self.volume_bytes = (max(self._tokens) + 1) * self.block_size
        _log.info("EBS snapshot block map loaded",
                  snapshot=self.snapshot_id, blocks=len(self._tokens),
                  block_size=self.block_size)

    def _block(self, index: int) -> bytes:
        token = self._tokens.get(index)
        if token is None:
            # hole: shared zero buffer, never cached — sparse snapshots
            # would otherwise evict network-fetched blocks from the LRU
            if len(self._zero) != self.block_size:
                self._zero = b"\x00" * self.block_size
            return self._zero
        cached = self._cache.get(index)
        if cached is not None:
            self._cache.move_to_end(index)
            return cached
        try:
            resp = self.client.get_snapshot_block(
                SnapshotId=self.snapshot_id, BlockIndex=index,
                BlockToken=token)
        except Exception as e:
            raise EBSError(
                f"cannot fetch block {index} of {self.snapshot_id}: "
                f"{e}") from e
        body = resp["BlockData"]
        data = body.read() if hasattr(body, "read") else bytes(body)
        if len(data) < self.block_size:
            data += b"\x00" * (self.block_size - len(data))
        self._cache[index] = data
        if len(self._cache) > CACHE_BLOCKS:
            self._cache.popitem(last=False)
        return data

    # ------------------------------------------------------------ file API

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, off: int, whence: int = 0) -> int:
        if whence == 0:
            self.pos = off
        elif whence == 1:
            self.pos += off
        else:
            self.pos = self.volume_bytes + off
        return self.pos

    def tell(self) -> int:
        return self.pos

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self.volume_bytes - self.pos
        n = max(0, min(n, self.volume_bytes - self.pos))
        out = bytearray()
        while n > 0:
            index, within = divmod(self.pos, self.block_size)
            take = min(n, self.block_size - within)
            out += self._block(index)[within:within + take]
            self.pos += take
            n -= take
        return bytes(out)


def resolve_ami(ec2_client, ami_id: str) -> str:
    """AMI id -> snapshot id of its root EBS device (reference
    vm/ami.go: DescribeImages -> BlockDeviceMappings)."""
    try:
        resp = ec2_client.describe_images(ImageIds=[ami_id])
    except Exception as e:
        raise EBSError(f"cannot describe {ami_id}: {e}") from e
    images = resp.get("Images") or []
    if not images:
        raise EBSError(f"AMI not found: {ami_id}")
    image = images[0]
    root = image.get("RootDeviceName")
    mappings = image.get("BlockDeviceMappings") or []
    for m in mappings:
        ebs = m.get("Ebs") or {}
        if not ebs.get("SnapshotId"):
            continue
        if root is None or m.get("DeviceName") == root:
            return ebs["SnapshotId"]
    for m in mappings:  # no mapping matched the root device name
        ebs = m.get("Ebs") or {}
        if ebs.get("SnapshotId"):
            return ebs["SnapshotId"]
    raise EBSError(f"AMI {ami_id} has no EBS-backed device")


def open_ebs_target(target: str, client_factory=None):
    """'ebs:snap-…' or 'ami:ami-…' -> EBSDisk.

    `client_factory(service_name)` returns an AWS client; defaults to
    boto3 (gated import — the scanner works without it for every
    non-EBS target)."""
    if client_factory is None:
        try:
            import boto3  # type: ignore
        except ImportError as e:
            raise EBSError(
                "boto3 is required for ebs:/ami: targets (pip install "
                "boto3, plus AWS credentials in the environment)") from e

        def client_factory(name):
            return boto3.client(name)

    kind, _, ident = target.partition(":")
    if kind == "ami":
        ident = resolve_ami(client_factory("ec2"), ident)
    return EBSDisk(client_factory("ebs"), ident)
