"""VM disk containers (reference pkg/fanal/vm/disk + vm/disk/vmdk.go):
raw images, MBR/GPT partition tables, and monolithic-sparse VMDK.

`open_disk(path)` returns a seekable file-like view of the flat disk;
`find_filesystems(fh)` probes the whole disk and every partition for a
supported filesystem and yields (name, byte_offset).
"""

from __future__ import annotations

import io
import struct
from typing import BinaryIO, Iterator

from trivy_tpu.fanal.vm.ext4 import Ext4

SECTOR = 512


class DiskError(Exception):
    pass


# ------------------------------------------------------------- VMDK


VMDK_MAGIC = b"KDMV"


class SparseVMDK(io.RawIOBase):
    """Seekable view over a monolithic-sparse VMDK extent
    (reference vm/disk/vmdk.go; format: VMware Virtual Disk Format 5.0
    sparse extent — header, grain directory, grain tables)."""

    def __init__(self, fh: BinaryIO):
        self.fh = fh
        fh.seek(0)
        hdr = fh.read(512)
        if hdr[:4] != VMDK_MAGIC:
            raise DiskError("not a VMDK sparse extent")
        (self.version, self.flags, capacity, grain_size, _desc_off,
         _desc_size, gtes_per_gt, _rgd_off, gd_off, _overhead) = \
            struct.unpack_from("<IIQQQQIQQQ", hdr, 4)
        self.capacity = capacity * SECTOR          # bytes
        self.grain_size = grain_size * SECTOR      # bytes per grain
        self.gtes_per_gt = gtes_per_gt
        # load grain directory + tables once (small for test-size disks)
        n_grains = capacity // grain_size
        n_tables = (n_grains + gtes_per_gt - 1) // gtes_per_gt
        fh.seek(gd_off * SECTOR)
        gd = struct.unpack(f"<{n_tables}I", fh.read(4 * n_tables))
        self.grain_map: list[int] = []
        for gt_sector in gd:
            if gt_sector == 0:
                self.grain_map.extend([0] * gtes_per_gt)
                continue
            fh.seek(gt_sector * SECTOR)
            self.grain_map.extend(
                struct.unpack(f"<{gtes_per_gt}I",
                              fh.read(4 * gtes_per_gt)))
        self.pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, off: int, whence: int = 0) -> int:
        if whence == 0:
            self.pos = off
        elif whence == 1:
            self.pos += off
        else:
            self.pos = self.capacity + off
        return self.pos

    def tell(self) -> int:
        return self.pos

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self.capacity - self.pos
        n = max(0, min(n, self.capacity - self.pos))
        out = bytearray()
        while n > 0:
            grain, within = divmod(self.pos, self.grain_size)
            take = min(n, self.grain_size - within)
            sector = self.grain_map[grain] \
                if grain < len(self.grain_map) else 0
            if sector == 0:
                out += b"\x00" * take
            else:
                self.fh.seek(sector * SECTOR + within)
                out += self.fh.read(take)
            self.pos += take
            n -= take
        return bytes(out)


def open_disk(path: str) -> BinaryIO:
    """Open a VM image; sparse VMDK gets a flattening wrapper, anything
    else is treated as a raw/flat image."""
    fh = open(path, "rb")
    magic = fh.read(4)
    fh.seek(0)
    if magic == VMDK_MAGIC:
        return SparseVMDK(fh)
    if magic == b"QFI\xfb":
        fh.close()
        raise DiskError("qcow2 images are not supported; convert with "
                        "`qemu-img convert` to raw first")
    return fh


# -------------------------------------------------------- partitions


def _mbr_partitions(fh: BinaryIO) -> Iterator[tuple[int, int]]:
    """-> (start_byte, type) for primary MBR partitions."""
    fh.seek(0)
    mbr = fh.read(512)
    if len(mbr) < 512 or mbr[510:512] != b"\x55\xaa":
        return
    for i in range(4):
        entry = mbr[446 + 16 * i:446 + 16 * (i + 1)]
        ptype = entry[4]
        lba = struct.unpack_from("<I", entry, 8)[0]
        if ptype and lba:
            yield lba * SECTOR, ptype


def _gpt_partitions(fh: BinaryIO) -> Iterator[int]:
    fh.seek(SECTOR)
    hdr = fh.read(92)
    if hdr[:8] != b"EFI PART":
        return
    part_lba = struct.unpack_from("<Q", hdr, 72)[0]
    n_parts = struct.unpack_from("<I", hdr, 80)[0]
    entry_size = struct.unpack_from("<I", hdr, 84)[0]
    fh.seek(part_lba * SECTOR)
    table = fh.read(n_parts * entry_size)
    for i in range(n_parts):
        entry = table[i * entry_size:(i + 1) * entry_size]
        if len(entry) < 48 or entry[:16] == b"\x00" * 16:
            continue
        first_lba = struct.unpack_from("<Q", entry, 32)[0]
        if first_lba:
            yield first_lba * SECTOR


def find_filesystems(fh: BinaryIO) -> list[tuple[str, int]]:
    """Probe the whole disk and each partition: -> [(fstype, offset)]."""
    out: list[tuple[str, int]] = []
    candidates: list[int] = [0]
    for off in _gpt_partitions(fh):
        candidates.append(off)
    if len(candidates) == 1:  # no GPT; try MBR (0xEE = protective GPT)
        for off, ptype in _mbr_partitions(fh):
            if ptype != 0xEE:
                candidates.append(off)
    for off in candidates:
        if Ext4.probe(fh, off):
            out.append(("ext4", off))
        elif _probe_xfs(fh, off):
            out.append(("xfs", off))
    return out


def _probe_xfs(fh: BinaryIO, offset: int) -> bool:
    try:
        fh.seek(offset)
        return fh.read(4) == b"XFSB"
    except OSError:
        return False
