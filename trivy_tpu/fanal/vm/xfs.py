"""Read-only XFS filesystem parser (reference pkg/fanal/vm/filesystem
walks xfs via the masahiro331/go-xfs-filesystem library; this is an
independent implementation of the on-disk format).

Pure-Python, seek-based, big-endian throughout. Supports what `mkfs.xfs`
defaults produce (v5: CRC-enabled metadata, ftype dirents, dinode v3)
plus v4 layouts: shortform/local directories, extent-format files and
directories (block and leaf/node forms — leaf metadata lives past the
32 GiB logical boundary and is simply not walked), B+tree extent maps
for heavily fragmented files, inline and remote symlinks. CRCs are not
verified — this is a scanner, not a repair tool.

Interface mirrors vm/ext4.py: probe(fh, offset), walk() yielding
(path, Inode), read_file(inode), read_symlink(inode).
"""

from __future__ import annotations

import stat
import struct
from dataclasses import dataclass
from typing import BinaryIO, Iterator

XFS_MAGIC = b"XFSB"
DINODE_MAGIC = 0x494E  # "IN"

# di_format values
FMT_DEV = 0
FMT_LOCAL = 1
FMT_EXTENTS = 2
FMT_BTREE = 3

INCOMPAT_FTYPE = 0x1

# directory data block magics
DIR_MAGIC_BLOCK = (b"XD2B", b"XDB3")  # single-block form (has tail)
DIR_MAGIC_DATA = (b"XD2D", b"XDD3")  # data blocks of leaf/node dirs
BMAP_MAGIC = (b"BMAP", b"BMA3")  # long-form bmbt nodes
SYMLINK_MAGIC = b"XSLM"

# leaf/free dir blocks live at logical byte offset >= 32 GiB
DIR_LEAF_OFFSET = 32 * 1024 ** 3


class XfsError(Exception):
    pass


@dataclass
class Superblock:
    block_size: int
    agblocks: int
    agcount: int
    inode_size: int
    inopblock: int
    inopblog: int
    agblklog: int
    dirblklog: int
    rootino: int
    version: int
    ftype: bool


@dataclass
class Inode:
    ino: int
    mode: int
    size: int
    format: int
    nextents: int
    fork: bytes  # raw data-fork bytes

    @property
    def is_dir(self) -> bool:
        return stat.S_ISDIR(self.mode)

    @property
    def is_file(self) -> bool:
        return stat.S_ISREG(self.mode)

    @property
    def is_symlink(self) -> bool:
        return stat.S_ISLNK(self.mode)


@dataclass
class DirEntry:
    name: str
    ino: int


class Xfs:
    """fh must be a seekable binary file; `offset` is the byte offset of
    the filesystem inside it (partition start)."""

    def __init__(self, fh: BinaryIO, offset: int = 0):
        self.fh = fh
        self.offset = offset
        self.sb = self._read_superblock()

    # ------------------------------------------------------------ probe

    @staticmethod
    def probe(fh: BinaryIO, offset: int = 0) -> bool:
        try:
            fh.seek(offset)
            return fh.read(4) == XFS_MAGIC
        except OSError:
            return False

    # ----------------------------------------------------------- layout

    def _read_at(self, off: int, size: int) -> bytes:
        self.fh.seek(self.offset + off)
        data = self.fh.read(size)
        if len(data) != size:
            raise XfsError(f"short read at {off}")
        return data

    def _read_superblock(self) -> Superblock:
        raw = self._read_at(0, 264)
        if raw[:4] != XFS_MAGIC:
            raise XfsError("not an XFS filesystem (bad magic)")
        versionnum = struct.unpack_from(">H", raw, 100)[0]
        version = versionnum & 0xF
        features_incompat = struct.unpack_from(">I", raw, 216)[0] \
            if version == 5 else 0
        # v4 keeps ftype in features2 (XFS_SB_VERSION2_FTYPE 0x200)
        features2 = struct.unpack_from(">I", raw, 200)[0]
        block_size = struct.unpack_from(">I", raw, 4)[0]
        dirblklog = raw[192]
        # untrusted images: directory block size drives allocations in
        # read_dir; real XFS caps it at 64 KiB (mkfs -n size=)
        if not 512 <= block_size <= 65536:
            raise XfsError(f"implausible block size {block_size}")
        if dirblklog > 7 or (block_size << dirblklog) > (1 << 16):
            raise XfsError(f"implausible dirblklog {dirblklog}")
        return Superblock(
            block_size=block_size,
            rootino=struct.unpack_from(">Q", raw, 56)[0],
            agblocks=struct.unpack_from(">I", raw, 84)[0],
            agcount=struct.unpack_from(">I", raw, 88)[0],
            inode_size=struct.unpack_from(">H", raw, 104)[0],
            inopblock=struct.unpack_from(">H", raw, 106)[0],
            inopblog=raw[123],
            agblklog=raw[124],
            dirblklog=dirblklog,
            version=version,
            ftype=bool(features_incompat & INCOMPAT_FTYPE)
            or bool(version == 4 and features2 & 0x200),
        )

    def _fsblock_byte(self, fsbno: int) -> int:
        """Absolute fsblock number -> byte offset (AG-relative encoding:
        high bits AG number, low sb_agblklog bits block-in-AG)."""
        agno = fsbno >> self.sb.agblklog
        agbno = fsbno & ((1 << self.sb.agblklog) - 1)
        if agno >= self.sb.agcount:
            raise XfsError(f"fsblock {fsbno} beyond AG count")
        return (agno * self.sb.agblocks + agbno) * self.sb.block_size

    def inode(self, ino: int) -> Inode:
        sb = self.sb
        agino_bits = sb.agblklog + sb.inopblog
        agno = ino >> agino_bits
        agino = ino & ((1 << agino_bits) - 1)
        agbno = agino >> sb.inopblog
        idx = agino & (sb.inopblock - 1)
        if agno >= sb.agcount:
            raise XfsError(f"inode {ino} beyond AG count")
        byte = (agno * sb.agblocks + agbno) * sb.block_size \
            + idx * sb.inode_size
        raw = self._read_at(byte, sb.inode_size)
        if struct.unpack_from(">H", raw, 0)[0] != DINODE_MAGIC:
            raise XfsError(f"bad inode magic for ino {ino}")
        version = raw[4]
        fork_off = 176 if version >= 3 else 100
        # di_forkoff (in 8-byte units) bounds the data fork when an
        # attribute fork follows it
        forkoff = raw[82]
        fork_end = fork_off + forkoff * 8 if forkoff else sb.inode_size
        return Inode(
            ino=ino,
            mode=struct.unpack_from(">H", raw, 2)[0],
            format=raw[5],
            size=struct.unpack_from(">Q", raw, 56)[0],
            nextents=struct.unpack_from(">I", raw, 76)[0],
            fork=raw[fork_off:fork_end],
        )

    # ------------------------------------------------------- extent maps

    @staticmethod
    def _unpack_extent(rec: bytes) -> tuple[int, int, int, int]:
        """16-byte packed bmbt record -> (startoff, startblock, count,
        unwritten_flag)."""
        l0, l1 = struct.unpack(">QQ", rec)
        flag = l0 >> 63
        startoff = (l0 >> 9) & ((1 << 54) - 1)
        startblock = ((l0 & 0x1FF) << 43) | (l1 >> 21)
        count = l1 & ((1 << 21) - 1)
        return startoff, startblock, count, flag

    def _extents(self, inode: Inode) -> list[tuple[int, int, int]]:
        """-> [(logical_block, physical_fsblock, count)], holes omitted;
        unwritten extents read as zeros so they are treated as holes."""
        out: list[tuple[int, int, int]] = []
        if inode.format == FMT_EXTENTS:
            for i in range(inode.nextents):
                rec = inode.fork[i * 16:(i + 1) * 16]
                if len(rec) < 16:
                    break
                off, blk, cnt, flag = self._unpack_extent(rec)
                if not flag:
                    out.append((off, blk, cnt))
        elif inode.format == FMT_BTREE:
            out.extend(self._btree_extents(inode.fork))
        return out

    def _btree_extents(self, fork: bytes) -> Iterator[tuple[int, int, int]]:
        """Walk the bmbt rooted in the inode fork (bmdr block: level,
        numrecs, keys, then pointers at the fixed maxrecs offset)."""
        level, numrecs = struct.unpack_from(">HH", fork, 0)
        if level == 0:
            raise XfsError("bmdr root with level 0")
        # real bmbt depth caps at XFS_BTREE_MAXLEVELS (9); a crafted root
        # level near 2^16 with a level-consistent block chain would
        # otherwise recurse past Python's frame limit
        if level > 16:
            raise XfsError("bmdr root level implausible")
        maxrecs = (len(fork) - 4) // 16
        ptr_base = 4 + maxrecs * 8
        # untrusted images: visited-set rejects pointer cycles, and
        # _btree_block enforces the strictly-decreasing level so a crafted
        # on-disk level field cannot drive unbounded recursion
        seen: set[int] = set()
        for i in range(numrecs):
            ptr = struct.unpack_from(">Q", fork, ptr_base + i * 8)[0]
            yield from self._btree_block(ptr, level - 1, seen)

    def _btree_block(self, fsbno: int, expect_level: int,
                     seen: set[int]) -> Iterator[tuple[int, int, int]]:
        if fsbno in seen:
            raise XfsError("bmbt pointer cycle")
        seen.add(fsbno)
        raw = self._read_at(self._fsblock_byte(fsbno), self.sb.block_size)
        if raw[:4] not in BMAP_MAGIC:
            raise XfsError("bad bmbt block magic")
        level, numrecs = struct.unpack_from(">HH", raw, 4)
        if level != expect_level:
            raise XfsError("bmbt level mismatch")
        hdr = 72 if raw[:4] == b"BMA3" else 24
        if level == 0:
            for i in range(numrecs):
                off, blk, cnt, flag = self._unpack_extent(
                    raw[hdr + i * 16: hdr + (i + 1) * 16])
                if not flag:
                    yield off, blk, cnt
        else:
            maxrecs = (self.sb.block_size - hdr) // 16
            ptr_base = hdr + maxrecs * 8
            for i in range(numrecs):
                ptr = struct.unpack_from(">Q", raw, ptr_base + i * 8)[0]
                yield from self._btree_block(ptr, level - 1, seen)

    # ------------------------------------------------------- file data

    def read_file(self, inode: Inode, limit: int | None = None) -> bytes:
        size = inode.size if limit is None else min(inode.size, limit)
        if inode.format == FMT_LOCAL:
            return bytes(inode.fork[:size])
        bs = self.sb.block_size
        out = bytearray(size)
        for logical, physical, count in self._extents(inode):
            start = logical * bs
            if start >= size:
                continue
            want = min(count * bs, size - start)
            data = self._read_at(self._fsblock_byte(physical), want)
            out[start:start + want] = data
        return bytes(out)

    def read_symlink(self, inode: Inode) -> str:
        if inode.format == FMT_LOCAL:
            return inode.fork[:inode.size].decode("utf-8", "replace")
        # remote symlink: v5 blocks carry a 56-byte XSLM header each.
        # Symlink targets cap at PATH_MAX; don't trust a crafted
        # size/extent map to drive larger reads.
        size = min(inode.size, 4096)
        raw = bytearray()
        bs = self.sb.block_size
        for _logical, physical, count in self._extents(inode):
            for c in range(count):
                if len(raw) >= size:
                    break
                blk = self._read_at(self._fsblock_byte(physical + c), bs)
                raw += blk[56:] if blk[:4] == SYMLINK_MAGIC else blk
        return bytes(raw[:size]).decode("utf-8", "replace")

    # ------------------------------------------------------ directories

    # untrusted images: bound per-directory work so a crafted extent map
    # (logical offsets just below the 32 GiB leaf boundary, or 2^21-block
    # extents) cannot force multi-GiB allocations
    MAX_DIR_BLOCKS = 65536  # 256 MiB of directory data at 4 KiB blocks

    def read_dir(self, inode: Inode) -> list[DirEntry]:
        if inode.format == FMT_LOCAL:
            return self._read_sf_dir(inode.fork)
        out: list[DirEntry] = []
        bs = self.sb.block_size
        blk_per_dirblk = 1 << self.sb.dirblklog
        # assemble directory blocks sparsely: dirblock index -> buffer
        # (dirblocks can span extents when dirblklog > 0)
        dirblocks: dict[int, bytearray] = {}
        for logical, physical, count in self._extents(inode):
            if logical * bs >= DIR_LEAF_OFFSET:
                continue  # leaf/freeindex metadata, not entries
            for c in range(count):
                lblock = logical + c
                dindex, within = divmod(lblock, blk_per_dirblk)
                buf = dirblocks.get(dindex)
                if buf is None:
                    if len(dirblocks) >= self.MAX_DIR_BLOCKS:
                        raise XfsError("directory too large")
                    buf = dirblocks[dindex] = \
                        bytearray(bs * blk_per_dirblk)
                data = self._read_at(
                    self._fsblock_byte(physical + c), bs)
                buf[within * bs:(within + 1) * bs] = data
        for dindex in sorted(dirblocks):
            out.extend(self._parse_dir_block(bytes(dirblocks[dindex])))
        return out

    def _read_sf_dir(self, fork: bytes) -> list[DirEntry]:
        """Shortform directory packed directly in the inode fork."""
        if len(fork) < 2:
            return []
        count, i8count = fork[0], fork[1]
        n = count or i8count
        ino_len = 8 if i8count else 4
        pos = 2 + ino_len  # header parent inumber
        out: list[DirEntry] = []
        for _ in range(n):
            if pos + 3 > len(fork):
                break
            namelen = fork[pos]
            pos += 3  # namelen + 2-byte offset tag
            name = fork[pos:pos + namelen].decode("utf-8", "replace")
            pos += namelen
            if self.sb.ftype:
                pos += 1
            if pos + ino_len > len(fork):
                break
            ino = int.from_bytes(fork[pos:pos + ino_len], "big")
            pos += ino_len
            out.append(DirEntry(name=name, ino=ino))
        return out

    def _parse_dir_block(self, blk: bytes) -> list[DirEntry]:
        """One directory data block (block or data form) -> entries."""
        magic = blk[:4]
        if magic in DIR_MAGIC_BLOCK:
            hdr = 64 if magic == b"XDB3" else 16
            # block form: leaf array + tail at the end bound the entries
            count, _stale = struct.unpack_from(">II", blk, len(blk) - 8)
            end = len(blk) - 8 - count * 8
        elif magic in DIR_MAGIC_DATA:
            hdr = 64 if magic == b"XDD3" else 16
            end = len(blk)
        else:
            return []
        out: list[DirEntry] = []
        pos = hdr
        while pos + 8 <= end:
            if blk[pos:pos + 2] == b"\xff\xff":  # unused entry
                length = struct.unpack_from(">H", blk, pos + 2)[0]
                if length < 8:
                    break
                pos += length
                continue
            ino = struct.unpack_from(">Q", blk, pos)[0]
            namelen = blk[pos + 8]
            name = blk[pos + 9:pos + 9 + namelen].decode("utf-8", "replace")
            entry_len = 8 + 1 + namelen + (1 if self.sb.ftype else 0) + 2
            entry_len = (entry_len + 7) & ~7
            if namelen == 0:
                break
            if name not in (".", ".."):
                out.append(DirEntry(name=name, ino=ino))
            pos += entry_len
        return out

    # ------------------------------------------------------------- walk

    def walk(self, max_file_size: int | None = None
             ) -> Iterator[tuple[str, Inode]]:
        """Yield (path, inode) for every regular file, DFS from root."""
        seen: set[int] = set()
        stack: list[tuple[str, int]] = [("", self.sb.rootino)]
        while stack:
            prefix, ino = stack.pop()
            if ino in seen:
                continue
            seen.add(ino)
            try:
                node = self.inode(ino)
                entries = self.read_dir(node)
            except XfsError:
                continue
            for e in sorted(entries, key=lambda d: d.name, reverse=True):
                path = f"{prefix}/{e.name}" if prefix else e.name
                try:
                    child = self.inode(e.ino)
                except XfsError:
                    continue
                if child.is_dir:
                    stack.append((path, e.ino))
                elif child.is_file:
                    yield path, child
