"""File-tree walkers (reference pkg/fanal/walker):
- FSWalker: directory traversal with skip globs (fs.go:25)
- LayerTarWalker: container layer tars with whiteout/opaque-dir handling
  (tar.go:35-60: ".wh." prefix files delete, ".wh..wh..opq" marks opaque)
"""

from __future__ import annotations

import os
import stat
import tarfile
from dataclasses import dataclass, field
from typing import Iterator

from trivy_tpu.fanal.analyzer import AnalysisInput, matches_any
from trivy_tpu.log import logger

_log = logger("walker")

# never walked (reference walker.go defaultSkipDirs)
DEFAULT_SKIP_DIRS = [".git", "**/.git", "proc", "sys", "dev"]

MAX_FILE_SIZE = 200 * 1024 * 1024  # hard cap on single-file reads


@dataclass
class FSWalker:
    skip_files: list[str] = field(default_factory=list)
    skip_dirs: list[str] = field(default_factory=list)
    only_dirs: list[str] = field(default_factory=list)

    def walk(self, root: str) -> Iterator[AnalysisInput]:
        root = os.path.abspath(root)
        skip_dirs = list(self.skip_dirs) + DEFAULT_SKIP_DIRS
        for dirpath, dirnames, filenames in os.walk(root):
            rel_dir = os.path.relpath(dirpath, root)
            rel_dir = "" if rel_dir == "." else rel_dir.replace(os.sep, "/")
            # prune skipped dirs
            keep = []
            for d in dirnames:
                rel = f"{rel_dir}/{d}" if rel_dir else d
                if matches_any(rel, skip_dirs) or matches_any(d, skip_dirs):
                    continue
                keep.append(d)
            dirnames[:] = sorted(keep)
            for fname in sorted(filenames):
                rel = f"{rel_dir}/{fname}" if rel_dir else fname
                if matches_any(rel, self.skip_files):
                    continue
                full = os.path.join(dirpath, fname)
                try:
                    st = os.lstat(full)
                except OSError:
                    continue
                if not stat.S_ISREG(st.st_mode):
                    continue
                if st.st_size > MAX_FILE_SIZE:
                    _log.debug("skipping oversized file", path=rel,
                               size=st.st_size)
                    continue
                yield AnalysisInput(
                    path=rel,
                    size=st.st_size,
                    mode=st.st_mode,
                    open=lambda p=full: open(p, "rb").read(),
                )


@dataclass
class LayerFile:
    input: AnalysisInput | None = None
    whiteout: str | None = None  # path deleted by this layer
    opaque_dir: str | None = None


def _collect(members) -> tuple[list[AnalysisInput], list[str], list[str]]:
    """Shared whiteout/opaque/size classification over an in-order
    iterable of ``(name, is_reg, size, mode, read)`` member records —
    the one place the layer-walk semantics live, whether the records
    came from tarfile or the native splitter."""
    files: list[AnalysisInput] = []
    opaque_dirs: list[str] = []
    whiteout_files: list[str] = []
    for name, is_reg, size, mode, read in members:
        # strip only a leading "./", not dots of root-level dotfiles
        name = name.removeprefix("./").lstrip("/")
        if not name:
            continue
        base = os.path.basename(name)
        dirn = os.path.dirname(name)
        if base == ".wh..wh..opq":
            opaque_dirs.append(dirn)
            continue
        if base.startswith(".wh."):
            whiteout_files.append(
                os.path.join(dirn, base[len(".wh."):]).replace(os.sep, "/")
            )
            continue
        if not is_reg:
            continue
        if size > MAX_FILE_SIZE:
            continue
        content = read()
        if content is None:
            continue
        files.append(AnalysisInput(
            path=name, content=content, size=size, mode=mode,
        ))
    return files, opaque_dirs, whiteout_files


def walk_layer_tar(tar_src) -> tuple[list[AnalysisInput], list[str], list[str]]:
    """-> (files, opaque_dirs, whiteout_files). Accepts layer bytes, a
    path, or a readable file-like object (reference walker/tar.go).

    The native streaming splitter (ops/splitter.py) handles the fast
    path: incremental gunzip + tar framing with the GIL released. It
    declines anything outside tarfile's exact semantics, replaying the
    consumed bytes so the pure-Python walk below re-reads the layer
    from the start — results can never diverge from the tarfile path.

    The file-like form opens in tarfile *stream* mode (``r|*``), which
    gunzips compressed layers incrementally: peak RSS is one tar member
    plus the source stream, never a full decompressed layer copy. The
    walk below already consumes members strictly in order, which is the
    only constraint stream mode adds."""
    from trivy_tpu.ops import splitter

    if splitter.enabled() and splitter.available():
        members, tar_src = splitter.try_split(tar_src, MAX_FILE_SIZE)
        if members is not None:
            return _collect(members)

    if isinstance(tar_src, (bytes, bytearray)):
        import io

        tf = tarfile.open(fileobj=io.BytesIO(tar_src))
    elif hasattr(tar_src, "read"):
        tf = tarfile.open(fileobj=tar_src, mode="r|*")
    else:
        tf = tarfile.open(tar_src)

    def gen():
        for member in tf:
            yield (member.name, member.isreg(), member.size, member.mode,
                   lambda m=member: (lambda f: f.read() if f is not None
                                     else None)(tf.extractfile(m)))

    with tf:
        return _collect(gen())
