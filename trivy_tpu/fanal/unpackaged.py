"""Unpackaged-binary SBOM discovery (reference
pkg/fanal/handler/unpackaged/unpackaged.go): for executables not owned
by any package manager, look up their sha256 digest in rekor; when a
cosign SBOM attestation exists, decode it and attach the packages as an
application at the binary's path.  Enabled with `--sbom-sources rekor`."""

from __future__ import annotations

from trivy_tpu.attestation import parse_statement, unwrap_cosign_predicate
from trivy_tpu.attestation.rekor import MAX_GET_ENTRIES, Client, RekorError
from trivy_tpu.log import logger

_log = logger("unpackaged")


def discover_sboms(detail, rekor_url: str) -> int:
    """Mutates detail.applications with rekor-attested SBOMs for
    detail.digests entries.  Returns the number of binaries resolved."""
    import json

    from trivy_tpu.sbom.decode import decode_sbom_bytes

    if not detail.digests:
        return 0
    client = Client(rekor_url)
    resolved = 0
    for path, digest in sorted(detail.digests.items()):
        hash_ = digest.removeprefix("sha256:")
        try:
            ids = client.search(f"sha256:{hash_}")
            if not ids:
                continue
            entries = client.get_entries(ids[:MAX_GET_ENTRIES])
        except RekorError as e:
            _log.debug("rekor lookup failed", path=path, err=str(e))
            continue
        for entry in entries:
            try:
                statement = parse_statement(entry.statement)
                inner = unwrap_cosign_predicate(statement)
                if isinstance(inner, str):
                    inner = json.loads(inner)
                blob, _meta = decode_sbom_bytes(
                    json.dumps(inner).encode())
            except (ValueError, TypeError) as e:
                _log.debug("attestation decode failed", path=path,
                           err=str(e))
                continue
            for app in blob.applications:
                app.file_path = app.file_path or path
                detail.applications.append(app)
            if blob.applications:
                resolved += 1
                _log.info("unpackaged binary resolved via rekor",
                          path=path,
                          apps=len(blob.applications))
                break
    return resolved
