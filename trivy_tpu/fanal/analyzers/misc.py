"""Remaining special-purpose analyzers (reference pkg/fanal/analyzer):

- rpmqa: CBL-Mariner distroless rpm manifest (pkg/rpm/rpmqa.go)
- buildinfo: Red Hat content manifests + buildinfo Dockerfiles
  (buildinfo/{content_manifest,dockerfile}.go)
- executable: sha256 digests of unpackaged binaries for rekor SBOM
  discovery (executable/executable.go)
- sbom: SBOM documents shipped inside images, e.g. Bitnami
  (sbom/sbom.go)
"""

from __future__ import annotations

import json
import re
import stat

from trivy_tpu.fanal.analyzer import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register,
)
from trivy_tpu.log import logger
from trivy_tpu.types.artifact import BuildInfo, Package, PackageInfo

_log = logger("analyzer")


@register
class RpmqaAnalyzer(Analyzer):
    """var/lib/rpmmanifest/container-manifest-2: `rpm -qa --qf` dump
    with 10 tab-separated fields (reference rpmqa.go:28-78)."""

    type = "rpmqa"
    version = 1

    _FILES = ("var/lib/rpmmanifest/container-manifest-2",)

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path in self._FILES

    def analyze(self, inp: AnalysisInput):
        pkgs = []
        for line in inp.read().decode("utf-8", "replace").splitlines():
            fields = line.split("\t")
            if len(fields) != 10:
                continue
            name, ver_rel, arch, src_rpm = (
                fields[0], fields[1], fields[7], fields[9])
            version, _, release = ver_rel.partition("-")
            src_name, src_ver, src_rel = _parse_source_rpm(src_rpm)
            pkgs.append(Package(
                id=f"{name}@{ver_rel}",
                name=name, version=version, release=release, arch=arch,
                src_name=src_name or name,
                src_version=src_ver or version,
                src_release=src_rel or release,
            ))
        if not pkgs:
            return None
        res = AnalysisResult()
        res.package_infos = [PackageInfo(file_path=inp.path, packages=pkgs)]
        return res


def _parse_source_rpm(src: str) -> tuple[str, str, str]:
    """name-version-release.src.rpm -> (name, version, release)."""
    if not src or src == "(none)":
        return "", "", ""
    base = src.removesuffix(".src.rpm")
    m = re.match(r"(.+)-([^-]+)-([^-]+)$", base)
    if not m:
        return "", "", ""
    return m.group(1), m.group(2), m.group(3)


@register
class ContentManifestAnalyzer(Analyzer):
    """root/buildinfo/content_manifests/*.json -> BuildInfo.content_sets
    (reference buildinfo/content_manifest.go)."""

    type = "redhat-content-manifest"
    version = 1

    _DIRS = ("root/buildinfo/content_manifests/", "usr/share/buildinfo/")

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        d, _, f = path.rpartition("/")
        return (d + "/") in self._DIRS and f.endswith(".json")

    def analyze(self, inp: AnalysisInput):
        try:
            doc = json.loads(inp.read())
        except ValueError:
            return None
        sets = doc.get("content_sets") or []
        if not sets:
            return None
        res = AnalysisResult()
        res.build_info = BuildInfo(content_sets=list(sets))
        return res


_NVR_VERSION_RE = re.compile(r"-(\d[^-]*-\d[^-]*)$")


@register
class RedHatDockerfileAnalyzer(Analyzer):
    """root/buildinfo/Dockerfile-<name>-<version>-<release>: NVR from
    the filename + labels (reference buildinfo/dockerfile.go)."""

    type = "redhat-dockerfile"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        d, _, f = path.rpartition("/")
        return d == "root/buildinfo" and f.startswith("Dockerfile")

    def analyze(self, inp: AnalysisInput):
        text = inp.read().decode("utf-8", "replace")
        component = arch = ""
        for m in re.finditer(
                r'^\s*LABEL\s+(.+?)(?<!\\)$',
                text, re.M | re.S):
            for key, value in re.findall(
                    r'([\w.\-]+)=("?[^"\s]+"?|"[^"]*")', m.group(1)):
                key = key.lower()
                value = value.strip('"')
                if key in ("com.redhat.component", "bzcomponent"):
                    component = value
                elif key == "architecture":
                    arch = value
        if not component or not arch:
            return None
        m = _NVR_VERSION_RE.search(inp.path.rpartition("/")[2])
        version = m.group(1) if m else ""
        res = AnalysisResult()
        res.build_info = BuildInfo(
            nvr=f"{component}-{version}" if version else component,
            arch=arch)
        return res


_ELF_MAGICS = (b"\x7fELF", b"MZ", b"\xcf\xfa\xed\xfe", b"\xfe\xed\xfa\xcf",
               b"\xca\xfe\xba\xbe")


@register
class ExecutableAnalyzer(Analyzer):
    """sha256 digests of executable binaries not managed by any package
    manager, so the unpackaged handler can look up SBOM attestations in
    rekor (reference executable/executable.go)."""

    type = "executable"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        if size < 64 or size > 512 * 1024 * 1024:
            return False
        return bool(mode & (stat.S_IXUSR | stat.S_IXGRP | stat.S_IXOTH))

    def analyze(self, inp: AnalysisInput):
        import hashlib

        content = inp.read()
        if content[:4][:2] not in (m[:2] for m in _ELF_MAGICS) and \
                not any(content.startswith(m) for m in _ELF_MAGICS):
            return None
        res = AnalysisResult()
        res.digests = {
            inp.path: "sha256:" + hashlib.sha256(content).hexdigest()}
        return res


@register
class SbomAnalyzer(Analyzer):
    """SBOM documents found inside artifacts (reference sbom/sbom.go):
    *.spdx(.json) / *.cdx(.json) decode into packages/applications;
    Bitnami app dirs get their file paths rewritten so components
    resolve to the shipped location."""

    type = "sbom"
    version = 1

    _SUFFIXES = (".spdx", ".spdx.json", ".cdx", ".cdx.json")

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        base = path.rpartition("/")[2]
        return base.endswith(self._SUFFIXES) or (
            base.startswith(".spdx-") and path.startswith("opt/bitnami/"))

    def analyze(self, inp: AnalysisInput):
        from trivy_tpu.sbom.decode import decode_sbom_bytes

        try:
            blob, _meta = decode_sbom_bytes(inp.read())
        except ValueError as e:
            _log.debug("in-image SBOM decode failed", path=inp.path,
                       err=str(e))
            return None
        res = AnalysisResult()
        res.package_infos = blob.package_infos
        res.applications = blob.applications
        if inp.path.startswith("opt/bitnami/"):
            app_dir = inp.path.rpartition("/")[0]
            for app in res.applications:
                for pkg in app.packages:
                    if not pkg.file_path:
                        pkg.file_path = app_dir
        for app in res.applications:
            if not app.file_path:
                app.file_path = inp.path
        return res
