"""rpm package DB analyzer (reference pkg/fanal/analyzer/pkg/rpm/ via
knqyf263/go-rpmdb): reads the rpmdb in its sqlite (rpmdb.sqlite, modern
Fedora/RHEL9+) or BerkeleyDB-hash (Packages, RHEL<=8/CentOS) formats and
parses the stored rpm header blobs.

Header blob layout (rpm tag data as stored in the DB, no lead/signature):
  [index_len:u32][data_len:u32] then index_len 16-byte entries
  (tag:u32, type:u32, offset:u32, count:u32) then the data section.
"""

from __future__ import annotations

import os
import re
import sqlite3
import struct
import tempfile

from trivy_tpu.fanal.analyzer import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register,
)
from trivy_tpu.log import logger
from trivy_tpu.types.artifact import Package, PackageInfo

_log = logger("rpm")

RPMDB_PATHS = {
    "usr/lib/sysimage/rpm/rpmdb.sqlite",
    "var/lib/rpm/rpmdb.sqlite",
    "usr/lib/sysimage/rpm/Packages",
    "var/lib/rpm/Packages",
    "usr/lib/sysimage/rpm/Packages.db",
    "var/lib/rpm/Packages.db",
}

# rpm tags
_T_NAME = 1000
_T_VERSION = 1001
_T_RELEASE = 1002
_T_EPOCH = 1003
_T_ARCH = 1022
_T_VENDOR = 1011
_T_LICENSE = 1014
_T_SOURCERPM = 1044
_T_DIRINDEXES = 1116
_T_BASENAMES = 1117
_T_DIRNAMES = 1118
_T_PROVIDENAME = 1047
_T_REQUIRENAME = 1049
_T_MODULARITYLABEL = 5096

_STRING_TYPES = (6, 8, 9)


def parse_header_blob(blob: bytes) -> dict[int, object] | None:
    if len(blob) < 8:
        return None
    il, dl = struct.unpack(">II", blob[:8])
    if il <= 0 or il > 100000 or dl <= 0 or dl > len(blob):
        return None
    idx_end = 8 + 16 * il
    if idx_end + dl > len(blob) + 8:  # loose sanity
        if idx_end > len(blob):
            return None
    data = blob[idx_end:]
    out: dict[int, object] = {}
    for i in range(il):
        tag, typ, off, count = struct.unpack_from(">IIII", blob, 8 + 16 * i)
        if off >= len(data):
            continue
        try:
            if typ in (6, 9):  # STRING / I18N (first value)
                end = data.index(b"\x00", off)
                out[tag] = data[off:end].decode("utf-8", "replace")
            elif typ == 8:  # STRING_ARRAY
                vals = []
                p = off
                for _ in range(count):
                    end = data.index(b"\x00", p)
                    vals.append(data[p:end].decode("utf-8", "replace"))
                    p = end + 1
                out[tag] = vals
            elif typ == 4:  # INT32
                out[tag] = list(struct.unpack_from(f">{count}i", data, off))
            elif typ == 3:  # INT16
                out[tag] = list(struct.unpack_from(f">{count}h", data, off))
            elif typ == 5:  # INT64
                out[tag] = list(struct.unpack_from(f">{count}q", data, off))
        except (ValueError, struct.error):
            continue
    return out if _T_NAME in out else None


_SRC_RPM = re.compile(r"^(?P<name>.+)-(?P<ver>[^-]+)-(?P<rel>[^-]+)\.src\.rpm$")


def header_to_package(h: dict[int, object]) -> Package | None:
    name = h.get(_T_NAME)
    version = h.get(_T_VERSION)
    if not name or not version:
        return None
    pkg = Package(
        name=str(name),
        version=str(version),
        release=str(h.get(_T_RELEASE, "") or ""),
        arch=str(h.get(_T_ARCH, "") or ""),
        maintainer=str(h.get(_T_VENDOR, "") or ""),
        modularity_label=str(h.get(_T_MODULARITYLABEL, "") or ""),
    )
    epoch = h.get(_T_EPOCH)
    if isinstance(epoch, list) and epoch:
        pkg.epoch = int(epoch[0])
        pkg.src_epoch = pkg.epoch
    lic = h.get(_T_LICENSE)
    if lic:
        pkg.licenses = [str(lic)]
    srpm = h.get(_T_SOURCERPM)
    if srpm and srpm != "(none)":
        m = _SRC_RPM.match(str(srpm))
        if m:
            pkg.src_name = m.group("name")
            pkg.src_version = m.group("ver")
            pkg.src_release = m.group("rel")
    if not pkg.src_name:
        pkg.src_name = pkg.name
        pkg.src_version = pkg.version
        pkg.src_release = pkg.release
    # installed files from dirnames/dirindexes/basenames
    dirs = h.get(_T_DIRNAMES) or []
    idxs = h.get(_T_DIRINDEXES) or []
    bases = h.get(_T_BASENAMES) or []
    if dirs and bases and len(idxs) == len(bases):
        files = []
        for di, base in zip(idxs, bases):
            if 0 <= di < len(dirs):
                files.append(f"{dirs[di]}{base}")
        pkg.installed_files = files
    pkg.id = f"{pkg.name}@{pkg.full_version()}"
    return pkg


# ------------------------------------------------------------- backends


def read_sqlite_rpmdb(content: bytes) -> list[bytes]:
    with tempfile.NamedTemporaryFile(suffix=".sqlite", delete=False) as f:
        f.write(content)
        path = f.name
    try:
        con = sqlite3.connect(path)
        try:
            rows = con.execute("SELECT blob FROM Packages").fetchall()
            return [r[0] for r in rows]
        finally:
            con.close()
    finally:
        os.unlink(path)


def read_bdb_rpmdb(content: bytes) -> list[bytes]:
    """Minimal BerkeleyDB hash reader: walks every page, collects inline
    (H_KEYDATA) and overflow (H_OFFPAGE) data values."""
    if len(content) < 512:
        return []
    magic = struct.unpack_from("<I", content, 12)[0]
    if magic != 0x061561:  # DB_HASHMAGIC little-endian
        be = struct.unpack_from(">I", content, 12)[0]
        if be != 0x061561:
            return []
    pagesize = struct.unpack_from("<I", content, 20)[0]
    if pagesize < 512 or pagesize > 65536:
        return []
    n_pages = len(content) // pagesize
    blobs: list[bytes] = []

    def read_overflow(pgno: int) -> bytes:
        out = bytearray()
        seen = set()
        while pgno and pgno not in seen and pgno < n_pages:
            seen.add(pgno)
            base = pgno * pagesize
            next_pgno = struct.unpack_from("<I", content, base + 16)[0]
            hf_offset = struct.unpack_from("<H", content, base + 22)[0]
            out += content[base + 26: base + 26 + hf_offset]
            pgno = next_pgno
        return bytes(out)

    for pgno in range(1, n_pages):
        base = pgno * pagesize
        ptype = content[base + 25]
        if ptype != 8 and ptype != 13:  # P_HASH(8 old)/P_HASH(13 unsorted)
            continue
        n_entries = struct.unpack_from("<H", content, base + 20)[0]
        if n_entries == 0 or n_entries > pagesize // 2:
            continue
        offsets = struct.unpack_from(f"<{n_entries}H", content, base + 26)
        # entries alternate key/data; data entries are odd indices
        for i in range(1, n_entries, 2):
            off = offsets[i]
            if off >= pagesize:
                continue
            etype = content[base + off]
            if etype == 1:  # H_KEYDATA
                end = offsets[i - 1] if i >= 1 and offsets[i - 1] > off else pagesize
                blobs.append(content[base + off + 1: base + end])
            elif etype == 3:  # H_OFFPAGE
                ov_pgno = struct.unpack_from("<I", content, base + off + 4)[0]
                blobs.append(read_overflow(ov_pgno))
    return blobs


def read_rpmdb(path: str, content: bytes) -> list[Package]:
    if path.endswith("rpmdb.sqlite"):
        raw = read_sqlite_rpmdb(content)
    elif path.endswith("Packages"):
        raw = read_bdb_rpmdb(content)
    else:
        _log.debug("unsupported rpmdb flavor", path=path)
        return []
    pkgs = []
    for blob in raw:
        h = parse_header_blob(blob)
        if h is None:
            continue
        pkg = header_to_package(h)
        if pkg is not None:
            pkgs.append(pkg)
    return pkgs


@register
class RpmAnalyzer(Analyzer):
    type = "rpm"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path in RPMDB_PATHS

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        pkgs = read_rpmdb(inp.path, inp.read())
        if not pkgs:
            return None
        installed = [f for p in pkgs for f in p.installed_files]
        res = AnalysisResult()
        res.package_infos = [PackageInfo(file_path=inp.path, packages=pkgs)]
        res.system_installed_files = installed
        return res
