"""OS detection analyzers (reference pkg/fanal/analyzer/os/*):
/etc/os-release (+usr/lib), alpine-release, debian_version,
redhat/centos/oracle/rocky/alma release files, apk repositories."""

from __future__ import annotations

import re

from trivy_tpu.fanal.analyzer import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register,
)
from trivy_tpu.types.artifact import OS, Repository

# os-release ID= -> family (reference analyzer/os/release/release.go)
_ID_FAMILY = {
    "alpine": "alpine",
    "opensuse-leap": "opensuse-leap",
    "opensuse-tumbleweed": "opensuse-tumbleweed",
    "opensuse": "opensuse",
    "sles": "suse linux enterprise server",
    "sle-micro": "suse linux enterprise micro",
    "amzn": "amazon",
    "ol": "oracle",
    "fedora": "fedora",
    "rhel": "redhat",
    "centos": "centos",
    "rocky": "rocky",
    "almalinux": "alma",
    "mariner": "cbl-mariner",
    "azurelinux": "azurelinux",
    "wolfi": "wolfi",
    "chainguard": "chainguard",
    "minimos": "minimos",
    "photon": "photon",
    "debian": "debian",
    "ubuntu": "ubuntu",
    "echo": "echo",
}


def _parse_os_release(text: str) -> dict[str, str]:
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        k, _, v = line.partition("=")
        out[k.strip()] = v.strip().strip('"').strip("'")
    return out


@register
class OSReleaseAnalyzer(Analyzer):
    type = "os-release"
    version = 1

    PATHS = ("etc/os-release", "usr/lib/os-release")

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path in self.PATHS

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        kv = _parse_os_release(inp.read().decode("utf-8", "replace"))
        family = _ID_FAMILY.get(kv.get("ID", "").lower())
        if family is None:
            return None
        version = kv.get("VERSION_ID", "")
        if not version and family in ("wolfi", "chainguard", "minimos",
                                      "opensuse-tumbleweed", "echo"):
            version = kv.get("BUILD_ID", "")  # rolling
        res = AnalysisResult()
        res.os = OS(family=family, name=version)
        return res


@register
class AlpineReleaseAnalyzer(Analyzer):
    type = "alpine"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path == "etc/alpine-release"

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        version = inp.read().decode("utf-8", "replace").strip()
        if not version:
            return None
        res = AnalysisResult()
        res.os = OS(family="alpine", name=version)
        return res


@register
class DebianVersionAnalyzer(Analyzer):
    type = "debian"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path == "etc/debian_version"

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        version = inp.read().decode("utf-8", "replace").strip()
        if not version or "/" in version:  # "bookworm/sid" -> not a release
            return None
        res = AnalysisResult()
        res.os = OS(family="debian", name=version)
        return res


_RH_RELEASE = re.compile(r"(?P<name>.+) release (?P<ver>[\d.]+)")

_RH_FILES = {
    "etc/redhat-release": None,  # name decides
    "etc/centos-release": "centos",
    "etc/rocky-release": "rocky",
    "etc/almalinux-release": "alma",
    "etc/oracle-release": "oracle",
    "etc/fedora-release": "fedora",
    "etc/system-release": None,
    "usr/lib/fedora-release": "fedora",
}


@register
class RedHatBaseAnalyzer(Analyzer):
    type = "redhat-base"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path in _RH_FILES

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        text = inp.read().decode("utf-8", "replace").strip()
        m = _RH_RELEASE.match(text)
        if not m:
            return None
        family = _RH_FILES.get(inp.path)
        if family is None:
            name = m.group("name").lower()
            if "centos" in name:
                family = "centos"
            elif "rocky" in name:
                family = "rocky"
            elif "alma" in name:
                family = "alma"
            elif "oracle" in name:
                family = "oracle"
            elif "fedora" in name:
                family = "fedora"
            elif "amazon" in name:
                family = "amazon"
            else:
                family = "redhat"
        res = AnalysisResult()
        res.os = OS(family=family, name=m.group("ver"))
        return res


@register
class ApkRepoAnalyzer(Analyzer):
    """Alpine repository release detection from /etc/apk/repositories
    (reference analyzer/repo/apk.go): lets the detector use the repo
    stream (e.g. edge) over the os-release version."""

    type = "apk-repo"
    version = 1

    _RX = re.compile(
        r"https?://.*/alpine/(?P<ver>v\d+\.\d+|edge|latest-stable)/"
    )

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path == "etc/apk/repositories"

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        newest = None
        for line in inp.read().decode("utf-8", "replace").splitlines():
            m = self._RX.search(line.strip())
            if not m:
                continue
            ver = m.group("ver").lstrip("v")
            if ver == "latest-stable":
                continue
            if newest is None or _repo_newer(ver, newest):
                newest = ver
        if newest is None:
            return None
        res = AnalysisResult()
        res.repository = Repository(family="alpine", release=newest)
        return res


def _repo_newer(a: str, b: str) -> bool:
    if a == "edge":
        return True
    if b == "edge":
        return False
    try:
        pa = tuple(int(x) for x in a.split("."))
        pb = tuple(int(x) for x in b.split("."))
        return pa > pb
    except ValueError:
        return False
