"""apk installed-package DB analyzer
(reference pkg/fanal/analyzer/pkg/apk/apk.go): parses
lib/apk/db/installed — blocks of single-letter fields:
P name, V version, A arch, L license, o origin (source pkg), m maintainer,
F directory, R file-in-directory, D/p dependencies/provides."""

from __future__ import annotations

from trivy_tpu.fanal.analyzer import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    register,
)
from trivy_tpu.types.artifact import Package, PackageInfo

DB_PATH = "lib/apk/db/installed"


def parse_apk_installed(text: str):
    pkgs: list[Package] = []
    installed_files: list[str] = []
    provides: dict[str, str] = {}  # provided name -> pkg id
    cur: Package | None = None
    cur_dir = ""
    cur_files: list[str] = []
    depends: dict[str, list[str]] = {}

    def flush():
        nonlocal cur, cur_files
        if cur is not None and not cur.empty:
            cur.id = f"{cur.name}@{cur.version}"
            cur.installed_files = cur_files
            pkgs.append(cur)
        cur, cur_files = None, []

    for line in text.splitlines():
        if not line.strip():
            flush()
            cur_dir = ""
            continue
        if len(line) < 2 or line[1] != ":":
            continue
        tag, value = line[0], line[2:]
        if tag == "P":
            flush()
            cur = Package(name=value)
        elif cur is None:
            continue
        elif tag == "V":
            cur.version = value
        elif tag == "A":
            cur.arch = value
        elif tag == "L" and value:
            cur.licenses = [value]
        elif tag == "o":
            cur.src_name = value
        elif tag == "m":
            cur.maintainer = value
        elif tag == "F":
            cur_dir = value
        elif tag == "R":
            path = f"{cur_dir}/{value}" if cur_dir else value
            cur_files.append(path)
            installed_files.append(path)
        elif tag == "p":
            for prov in value.split():
                provides[prov.split("=")[0]] = cur.name
        elif tag == "D":
            depends[cur.name] = value.split()
    flush()

    for p in pkgs:
        if not p.src_name:
            p.src_name = p.name
        p.src_version = p.version
        # split version-release for reporting; matching uses the full string
        if "-r" in p.version:
            v, _, r = p.version.rpartition("-")
            if r.startswith("r") and r[1:].isdigit():
                p.version, p.release = v, r
                p.src_version, p.src_release = v, r
    # resolve dependencies to package ids
    name_to_id = {p.name: p.id for p in pkgs}
    for p in pkgs:
        deps = []
        for d in depends.get(p.name, []):
            d = d.split("=")[0].split("<")[0].split(">")[0].split("~")[0]
            if d.startswith("!"):
                continue
            target = name_to_id.get(d) or name_to_id.get(provides.get(d, ""))
            if target and target != p.id:
                deps.append(target)
        p.depends_on = sorted(set(deps))
    return pkgs, installed_files


@register
class ApkAnalyzer(Analyzer):
    type = "apk"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path == DB_PATH

    def analyze(self, inp: AnalysisInput) -> AnalysisResult | None:
        pkgs, installed = parse_apk_installed(
            inp.read().decode("utf-8", "replace")
        )
        if not pkgs:
            return None
        res = AnalysisResult()
        res.package_infos = [PackageInfo(file_path=inp.path, packages=pkgs)]
        res.system_installed_files = installed
        return res
