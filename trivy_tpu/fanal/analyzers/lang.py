"""Language analyzers: declarative wiring of the dependency parsers into
the analyzer registry (reference pkg/fanal/analyzer/language/*: mostly thin
wrappers over pkg/dependency/parser via language.Analyze)."""

from __future__ import annotations

import os
import re
import stat

from trivy_tpu.fanal.analyzer import (
    AnalysisInput,
    AnalysisResult,
    Analyzer,
    PostAnalyzer,
    register,
    register_post,
)
from trivy_tpu.parsers import golang, java_pom, misc_lang, nodejs
from trivy_tpu.parsers import python as pyparse
from trivy_tpu.types.artifact import Application


def _app(app_type: str, path: str, pkgs) -> AnalysisResult | None:
    # version-less packages are unmatchable noise EXCEPT the graph root
    # (go.mod main module): VEX product reachability needs it
    pkgs = [p for p in pkgs
            if p and (not p.empty
                      or getattr(p, "relationship", "") == "root")]
    if not pkgs:
        return None
    res = AnalysisResult()
    res.applications = [Application(type=app_type, file_path=path, packages=pkgs)]
    return res


class _LockfileAnalyzer(PostAnalyzer):
    """One lockfile filename -> one application."""

    app_type = ""
    filenames: tuple = ()
    parser = None

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return os.path.basename(path) in self.filenames

    def _accepts(self, path: str) -> bool:
        # post_files buckets are keyed by analyzer type; two analyzers
        # sharing a type must not run on each other's files.  Files routed
        # in via --file-patterns are accepted too.
        if os.path.basename(path) in self.filenames:
            return True
        return any(p.search(path)
                   for p in getattr(self, "extra_patterns", ()))

    def post_analyze(self, files: dict[str, AnalysisInput]):
        res = AnalysisResult()
        for path, inp in sorted(files.items()):
            if not self._accepts(path):
                continue
            got = _app(self.app_type, path, type(self).parser(inp.read()))
            res.merge(got)
        return res


def _lockfile(app_type: str, filenames: tuple, parser,
              atype: str = "") -> None:
    atype = atype or app_type
    cls = type(
        f"{atype.title()}Analyzer",
        (_LockfileAnalyzer,),
        {"type": atype, "app_type": app_type, "filenames": filenames,
         "parser": staticmethod(parser)},
    )
    register_post(cls())


_lockfile("npm", ("package-lock.json",), nodejs.parse_package_lock)
_lockfile("yarn", ("yarn.lock",), nodejs.parse_yarn_lock)
_lockfile("pnpm", ("pnpm-lock.yaml",), nodejs.parse_pnpm_lock)
_lockfile("pip", ("requirements.txt",), pyparse.parse_requirements)
_lockfile("pipenv", ("Pipfile.lock",), pyparse.parse_pipfile_lock)
# poetry gets its own analyzer below (pyproject.toml supplements the lock)
_lockfile("uv", ("uv.lock",), pyparse.parse_uv_lock)
_lockfile("julia", ("Manifest.toml",), misc_lang.parse_julia_manifest)
_lockfile("nuget", ("packages.config",),
          misc_lang.parse_nuget_packages_config, atype="nuget-config")
_lockfile("nuget", ("Directory.Packages.props",),
          misc_lang.parse_nuget_packages_props, atype="packages-props")
_lockfile("pom", ("pom.xml",), java_pom.parse_pom)
_lockfile("cargo", ("Cargo.lock",), misc_lang.parse_cargo_lock)
_lockfile("composer", ("composer.lock",), misc_lang.parse_composer_lock)
_lockfile("bundler", ("Gemfile.lock",), misc_lang.parse_gemfile_lock)
_lockfile("gradle", ("gradle.lockfile",),
          misc_lang.parse_gradle_lockfile)
_lockfile("sbt", ("build.sbt.lock",), misc_lang.parse_sbt_lockfile)
_lockfile("nuget", ("packages.lock.json",), misc_lang.parse_nuget_lock)
_lockfile("pub", ("pubspec.lock",), misc_lang.parse_pubspec_lock)
_lockfile("hex", ("mix.lock",), misc_lang.parse_mix_lock)
_lockfile("cocoapods", ("Podfile.lock",), misc_lang.parse_podfile_lock)
_lockfile("swift", ("Package.resolved",), misc_lang.parse_swift_resolved)
_lockfile("conan", ("conan.lock",), misc_lang.parse_conan_lock)
_lockfile("conda-environment", ("environment.yml", "environment.yaml"),
          misc_lang.parse_conda_environment)


@register_post
class PoetryAnalyzer(PostAnalyzer):
    """poetry.lock + sibling pyproject.toml: the lockfile lists every
    package; pyproject marks which are direct deps and which belong to
    dev groups (reference pkg/fanal/analyzer/language/python/poetry)."""

    type = "poetry"
    version = 2
    app_type = "poetry"

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return os.path.basename(path) in ("poetry.lock", "pyproject.toml")

    def post_analyze(self, files):
        res = AnalysisResult()
        by_dir: dict[str, dict[str, AnalysisInput]] = {}
        for path, inp in files.items():
            by_dir.setdefault(os.path.dirname(path), {})[
                os.path.basename(path)] = inp
        for d, group in sorted(by_dir.items()):
            if "poetry.lock" not in group:
                continue
            pkgs = pyparse.parse_poetry_lock(group["poetry.lock"].read())
            if "pyproject.toml" in group:
                try:
                    proj = pyparse.parse_pyproject(group["pyproject.toml"].read())
                except Exception:
                    proj = None
                if proj:
                    direct = proj["dependencies"]
                    dev = set().union(*proj["groups"].values()) \
                        if proj["groups"] else set()
                    for p in pkgs:
                        norm = pyparse._norm_name(p.name)
                        if norm in direct:
                            p.relationship = "direct"
                        elif norm in dev:
                            p.relationship = "direct"
                            p.dev = True
                        else:
                            p.relationship = "indirect"
                            p.indirect = True
            res.merge(_app("poetry", group["poetry.lock"].path, pkgs))
        return res


@register_post
class DotnetDepsAnalyzer(PostAnalyzer):
    type = "dotnet-core"
    version = 1
    app_type = "dotnet-core"

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path.endswith(".deps.json")

    def post_analyze(self, files):
        res = AnalysisResult()
        for path, inp in sorted(files.items()):
            res.merge(_app(self.app_type, path,
                           misc_lang.parse_deps_json(inp.read())))
        return res


@register_post
class GoModAnalyzer(PostAnalyzer):
    """go.mod (+ go.sum supplement when go.mod predates go 1.17, whose
    lockfiles list no indirect deps — reference
    pkg/fanal/analyzer/language/golang/mod)."""

    type = "gomod"
    version = 2
    app_type = "gomod"

    _GO_DIRECTIVE = re.compile(rb"^go\s+(\d+)\.(\d+)", re.M)

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return os.path.basename(path) in ("go.mod", "go.sum")

    def post_analyze(self, files):
        res = AnalysisResult()
        by_dir: dict[str, dict[str, AnalysisInput]] = {}
        for path, inp in files.items():
            by_dir.setdefault(os.path.dirname(path), {})[
                os.path.basename(path)] = inp
        for d, group in sorted(by_dir.items()):
            if "go.mod" not in group:
                continue
            mod_content = group["go.mod"].read()
            pkgs = golang.parse_go_mod(mod_content)
            m = self._GO_DIRECTIVE.search(mod_content)
            pre117 = m is None or (int(m.group(1)), int(m.group(2))) < (1, 17)
            if pre117 and "go.sum" in group:
                have = {p.name for p in pkgs}
                for p in golang.parse_go_sum(group["go.sum"].read()):
                    if p.name not in have:
                        p.indirect = True
                        p.relationship = "indirect"
                        pkgs.append(p)
            res.merge(_app("gomod", group["go.mod"].path, pkgs))
        return res


# ------------------------------------------------- individual packages


@register
class NodePkgAnalyzer(Analyzer):
    """node_modules/**/package.json -> installed node packages."""

    type = "node-pkg"
    version = 1

    _RX = re.compile(r"(^|/)node_modules/(@[^/]+/)?[^/]+/package\.json$")

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return bool(self._RX.search(path))

    def analyze(self, inp: AnalysisInput):
        pkg = nodejs.parse_package_json(inp.read())
        if pkg is None:
            return None
        pkg.file_path = inp.path
        return _app("node-pkg", inp.path, [pkg])


@register
class PythonPkgAnalyzer(Analyzer):
    """site-packages dist-info/egg-info -> installed python packages."""

    type = "python-pkg"
    version = 1

    _RX = re.compile(r"\.(dist-info/METADATA|egg-info/PKG-INFO|egg-info)$")

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return bool(self._RX.search(path))

    def analyze(self, inp: AnalysisInput):
        pkg = pyparse.parse_dist_metadata(inp.read())
        if pkg is None:
            return None
        pkg.file_path = inp.path
        return _app("python-pkg", inp.path, [pkg])


@register
class GemspecAnalyzer(Analyzer):
    type = "gemspec"
    version = 1

    _RX = re.compile(r"specifications/.+\.gemspec$")

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return bool(self._RX.search(path))

    def analyze(self, inp: AnalysisInput):
        pkg = misc_lang.parse_gemspec(inp.read())
        if pkg is None:
            return None
        pkg.file_path = inp.path
        return _app("gemspec", inp.path, [pkg])


@register
class JarAnalyzer(Analyzer):
    type = "jar"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path.endswith((".jar", ".war", ".ear", ".par"))

    def analyze(self, inp: AnalysisInput):
        return _app("jar", inp.path, misc_lang.parse_jar(inp.read(), inp.path))


@register
class ComposerVendorAnalyzer(Analyzer):
    """vendor/composer/installed.json (reference
    analyzer/language/php/composer vendor analyzer; same entry shape as
    composer.lock, parsed by the shared parser)."""

    type = "composer-vendor"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return os.path.basename(path) == "installed.json"

    def analyze(self, inp: AnalysisInput):
        try:
            pkgs = misc_lang.parse_composer_lock(inp.read())
        except ValueError:
            return None
        return _app("composer-vendor", inp.path, pkgs)


@register
class CondaPkgAnalyzer(Analyzer):
    type = "conda-pkg"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return "conda-meta/" in path and path.endswith(".json")

    def analyze(self, inp: AnalysisInput):
        pkg = misc_lang.parse_conda_meta(inp.read())
        if pkg is None:
            return None
        pkg.file_path = inp.path
        return _app("conda-pkg", inp.path, [pkg])


def _looks_like_executable(path: str, size: int, mode: int,
                           extra_exts: tuple = ()) -> bool:
    """Candidate filter shared by the binary analyzers: plausible size,
    executable bit (when mode is known), extension-less or a known
    binary extension."""
    if size < 1024 or size > 200 * 1024 * 1024:
        return False
    if not (mode & (stat.S_IXUSR | stat.S_IXGRP | stat.S_IXOTH)) and mode:
        return False
    base = os.path.basename(path)
    return "." not in base or base.endswith((".bin", ".exe") + extra_exts)


_BINARY_MAGICS = (b"\x7fELF", b"MZ\x90\x00", b"\xcf\xfa\xed\xfe",
                  b"\xfe\xed\xfa\xcf")


@register
class WordPressAnalyzer(Analyzer):
    """wp-includes/version.php -> wordpress core version (reference
    analyzer/language/php/wordpress)."""

    type = "wordpress"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return path.endswith("wp-includes/version.php")

    def analyze(self, inp: AnalysisInput):
        pkg = misc_lang.parse_wordpress_version(inp.read())
        if pkg is None:
            return None
        pkg.file_path = inp.path
        return _app("wordpress", inp.path, [pkg])


@register
class RustBinaryAnalyzer(Analyzer):
    """Executables with a cargo-auditable dependency list embedded
    (reference analyzer/language/rust/binary)."""

    type = "rustbinary"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return _looks_like_executable(path, size, mode)

    def analyze(self, inp: AnalysisInput):
        content = inp.read()
        if content[:4] not in _BINARY_MAGICS:
            return None
        if b"cargo" not in content and b"rustc" not in content:
            return None
        pkgs = misc_lang.parse_rust_binary(content)
        return _app("rustbinary", inp.path, pkgs)


@register
class GoBinaryAnalyzer(Analyzer):
    """Executable ELF/PE/Mach-O files with embedded Go build info
    (reference analyzer/language/golang/binary)."""

    type = "gobinary"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return _looks_like_executable(path, size, mode, (".test",))

    def analyze(self, inp: AnalysisInput):
        content = inp.read()
        if content[:4] not in _BINARY_MAGICS:
            return None
        pkgs = golang.parse_go_binary(content)
        return _app("gobinary", inp.path, pkgs)
