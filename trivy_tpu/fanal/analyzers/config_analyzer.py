"""Config (IaC) analyzer: detects config files during the walk and runs
the misconfiguration engine over them (reference
pkg/fanal/analyzer/config/* post-analyzers -> pkg/misconf.Scanner)."""

from __future__ import annotations

import os

from trivy_tpu.fanal.analyzer import (
    AnalysisInput,
    AnalysisResult,
    PostAnalyzer,
    register_post,
)
from trivy_tpu.iac import detection

_MAX_CONFIG_SIZE = 5 * 1024 * 1024

_CANDIDATE_EXT = (".yaml", ".yml", ".json", ".tf", ".tf.json", ".tpl")


def _looks_like_config(path: str) -> bool:
    name = os.path.basename(path).lower()
    if detection._DOCKERFILE_NAME.search(name):
        return True
    return name.endswith(_CANDIDATE_EXT) or name == "chart.yaml"


@register_post
class ConfigAnalyzer(PostAnalyzer):
    type = "config"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        if size > _MAX_CONFIG_SIZE:
            return False
        return _looks_like_config(path)

    def post_analyze(self, files: dict[str, AnalysisInput]):
        from trivy_tpu.misconf.scanner import scan_config

        res = AnalysisResult()
        for path, inp in sorted(files.items()):
            misconf = scan_config(path, inp.read())
            if misconf is not None and (
                misconf.failures or misconf.successes
            ):
                res.misconfigurations.append(misconf)
        return res
