"""Config (IaC) analyzer: detects config files during the walk and runs
the misconfiguration engine over them (reference
pkg/fanal/analyzer/config/* post-analyzers -> pkg/misconf.Scanner)."""

from __future__ import annotations

import os

from trivy_tpu.fanal.analyzer import (
    AnalysisInput,
    AnalysisResult,
    PostAnalyzer,
    register_post,
)
from trivy_tpu.iac import detection

_MAX_CONFIG_SIZE = 5 * 1024 * 1024

_CANDIDATE_EXT = (".yaml", ".yml", ".json", ".tf", ".tf.json", ".tpl")
_CHART_ARCHIVE_EXT = (".tgz", ".tar.gz")


def _looks_like_config(path: str) -> bool:
    name = os.path.basename(path).lower()
    if detection._DOCKERFILE_NAME.search(name):
        return True
    return name.endswith(_CANDIDATE_EXT + _CHART_ARCHIVE_EXT) \
        or name == "chart.yaml"


def _strip_helm_hooks(rendered: bytes) -> bytes | None:
    """Blank out rendered docs carrying a helm.sh/hook annotation (test/
    install hooks are not cluster resources; the reference's helm scan
    output omits them). Kept docs stay byte-identical at their original
    line offsets — dropped docs become blank lines — so finding line
    numbers still point into the rendered template. None when nothing
    scannable remains."""
    if b"helm.sh/hook" not in rendered:
        return rendered
    import yaml

    text = rendered.decode("utf-8", "replace")
    lines = text.splitlines(keepends=True)
    # document chunks split on '---' separator lines
    chunks: list[tuple[int, int]] = []
    start = 0
    for i, line in enumerate(lines):
        # document separators sit at column 0; an indented literal
        # '---' inside a block scalar is NOT a separator
        if line.rstrip() == "---" and line[:1] == "-":
            chunks.append((start, i))
            start = i + 1
    chunks.append((start, len(lines)))

    def is_hook(chunk_text: str) -> bool:
        if "helm.sh/hook" not in chunk_text:
            return False
        try:
            doc = yaml.safe_load(chunk_text)
        except yaml.YAMLError:
            return False
        if not isinstance(doc, dict):
            return False
        ann = (doc.get("metadata") or {}).get("annotations") or {}
        return any(str(k).startswith("helm.sh/hook") for k in ann)

    kept_any = False
    out_lines = list(lines)
    for lo, hi in chunks:
        chunk = "".join(lines[lo:hi])
        if is_hook(chunk):
            for i in range(lo, hi):
                out_lines[i] = "\n"
        elif chunk.strip():
            kept_any = True
    if not kept_any:
        return None
    return "".join(out_lines).encode()


def _render_chart_archive(data: bytes,
                          overrides: dict | None) -> list[tuple[str, bytes]]:
    """Packaged helm chart (.tgz) -> rendered (chart-relative path,
    yaml) pairs; empty when the archive holds no chart."""
    import gzip
    import io
    import tarfile

    from trivy_tpu.iac.helm import render_chart

    members: dict[str, bytes] = {}
    total = 0
    try:
        with tarfile.open(fileobj=io.BytesIO(data), mode="r:*") as tar:
            for m in tar.getmembers():
                if not m.isfile() or m.size > _MAX_CONFIG_SIZE:
                    continue
                # untrusted archives: bound member count and total
                # decompressed bytes (a tiny gzip can expand hugely)
                if len(members) >= 4096 or total > 64 * 1024 * 1024:
                    break
                f = tar.extractfile(m)
                if f is not None:
                    name = m.name
                    while name.startswith("./"):
                        name = name[2:]
                    data_m = f.read(_MAX_CONFIG_SIZE + 1)
                    if len(data_m) > _MAX_CONFIG_SIZE:
                        continue  # lied about size
                    members[name] = data_m
                    total += len(data_m)
    except (tarfile.TarError, gzip.BadGzipFile, OSError, EOFError):
        return []
    # the chart lives under a top-level directory inside the archive
    roots = {p.split("/", 1)[0] for p in members
             if p.endswith("/Chart.yaml") and p.count("/") == 1}
    out: list[tuple[str, bytes]] = []
    for root in sorted(roots):
        chart_files = {
            p[len(root) + 1:]: c for p, c in members.items()
            if p.startswith(root + "/")
        }
        out.extend(render_chart(chart_files, overrides))
    return out


@register_post
class ConfigAnalyzer(PostAnalyzer):
    type = "config"
    version = 1
    # --helm-set / --helm-values for this scan; set on a per-group copy
    # by AnalyzerGroup.build (never mutated on the registry singleton, so
    # concurrent scans in one process cannot leak overrides)
    helm_overrides: dict | None = None

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        if size > _MAX_CONFIG_SIZE:
            return False
        return _looks_like_config(path)

    def post_analyze(self, files: dict[str, AnalysisInput]):
        from trivy_tpu.iac.helm import find_chart_roots, render_chart
        from trivy_tpu.misconf.scanner import scan_config

        res = AnalysisResult()
        # helm charts render as a unit (reference scans the helm-engine
        # output, not raw templates); rendered docs scan as kubernetes
        roots = find_chart_roots(files)
        in_chart: set[str] = set()
        for root in roots:
            prefix = root + "/" if root else ""
            chart_files = {
                p[len(prefix):]: files[p].read()
                for p in files if p.startswith(prefix)
            }
            if not chart_files:
                continue
            # only files the helm engine consumes are chart-owned; other
            # configs living under the chart dir (Dockerfile, *.tf, ...)
            # still scan individually
            in_chart.update(
                prefix + rel for rel in chart_files
                if rel in ("Chart.yaml", "values.yaml", "values.yml")
                or rel.startswith("templates/")
            )
            for rel_path, rendered in render_chart(chart_files,
                                                   self.helm_overrides):
                rendered = _strip_helm_hooks(rendered)
                if rendered is None:
                    continue
                full = prefix + rel_path
                misconf = scan_config(full, rendered,
                                      file_type=detection.KUBERNETES)
                if misconf is not None and (misconf.failures
                                            or misconf.successes):
                    misconf.file_type = detection.HELM
                    for d in misconf.failures + misconf.successes:
                        d.type = detection.HELM
                    res.misconfigurations.append(misconf)

        # packaged charts (*.tgz) render in place; targets keep the
        # archive path prefix (reference: "chart.tar.gz:templates/x")
        for path, inp in sorted(files.items()):
            if not path.lower().endswith(_CHART_ARCHIVE_EXT):
                continue
            in_chart.add(path)
            for rel_path, rendered in _render_chart_archive(
                    inp.read(), self.helm_overrides):
                rendered = _strip_helm_hooks(rendered)
                if rendered is None:
                    continue
                misconf = scan_config(f"{path}:{rel_path}", rendered,
                                      file_type=detection.KUBERNETES)
                if misconf is not None and (misconf.failures
                                            or misconf.successes):
                    misconf.file_type = detection.HELM
                    for d in misconf.failures + misconf.successes:
                        d.type = detection.HELM
                    res.misconfigurations.append(misconf)
        # terraform evaluates per MODULE directory (variables, locals,
        # child modules span files), not per file
        from trivy_tpu.misconf.scanner import scan_terraform_modules

        tf_paths = {p for p in files
                    if p.endswith((".tf", ".tf.json")) and p not in in_chart}
        if tf_paths:
            res.misconfigurations.extend(scan_terraform_modules(
                {p: files[p].read() for p in tf_paths}))

        type_pats = getattr(self, "iac_type_patterns", [])
        for path, inp in sorted(files.items()):
            if path in in_chart or path in tf_paths:
                continue
            forced = None
            for rx, ftype in type_pats:
                if rx.search(path):
                    forced = ftype
                    break
            misconf = scan_config(path, inp.read(), file_type=forced)
            if misconf is not None and (
                misconf.failures or misconf.successes
            ):
                res.misconfigurations.append(misconf)
        return res
