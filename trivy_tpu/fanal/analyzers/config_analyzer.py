"""Config (IaC) analyzer: detects config files during the walk and runs
the misconfiguration engine over them (reference
pkg/fanal/analyzer/config/* post-analyzers -> pkg/misconf.Scanner)."""

from __future__ import annotations

import os

from trivy_tpu.fanal.analyzer import (
    AnalysisInput,
    AnalysisResult,
    PostAnalyzer,
    register_post,
)
from trivy_tpu.iac import detection

_MAX_CONFIG_SIZE = 5 * 1024 * 1024

_CANDIDATE_EXT = (".yaml", ".yml", ".json", ".tf", ".tf.json", ".tpl")


def _looks_like_config(path: str) -> bool:
    name = os.path.basename(path).lower()
    if detection._DOCKERFILE_NAME.search(name):
        return True
    return name.endswith(_CANDIDATE_EXT) or name == "chart.yaml"


@register_post
class ConfigAnalyzer(PostAnalyzer):
    type = "config"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        if size > _MAX_CONFIG_SIZE:
            return False
        return _looks_like_config(path)

    def post_analyze(self, files: dict[str, AnalysisInput]):
        from trivy_tpu.iac.helm import find_chart_roots, render_chart
        from trivy_tpu.misconf.scanner import scan_config

        res = AnalysisResult()
        # helm charts render as a unit (reference scans the helm-engine
        # output, not raw templates); rendered docs scan as kubernetes
        roots = find_chart_roots(files)
        in_chart: set[str] = set()
        for root in roots:
            prefix = root + "/" if root else ""
            chart_files = {
                p[len(prefix):]: files[p].read()
                for p in files if p.startswith(prefix)
            }
            if not chart_files:
                continue
            # only files the helm engine consumes are chart-owned; other
            # configs living under the chart dir (Dockerfile, *.tf, ...)
            # still scan individually
            in_chart.update(
                prefix + rel for rel in chart_files
                if rel in ("Chart.yaml", "values.yaml", "values.yml")
                or rel.startswith("templates/")
            )
            for rel_path, rendered in render_chart(chart_files):
                full = prefix + rel_path
                misconf = scan_config(full, rendered,
                                      file_type=detection.KUBERNETES)
                if misconf is not None and (misconf.failures
                                            or misconf.successes):
                    misconf.file_type = detection.HELM
                    for d in misconf.failures + misconf.successes:
                        d.type = detection.HELM
                    res.misconfigurations.append(misconf)
        # terraform evaluates per MODULE directory (variables, locals,
        # child modules span files), not per file
        from trivy_tpu.misconf.scanner import scan_terraform_modules

        tf_paths = {p for p in files
                    if p.endswith((".tf", ".tf.json")) and p not in in_chart}
        if tf_paths:
            res.misconfigurations.extend(scan_terraform_modules(
                {p: files[p].read() for p in tf_paths}))

        for path, inp in sorted(files.items()):
            if path in in_chart or path in tf_paths:
                continue
            misconf = scan_config(path, inp.read())
            if misconf is not None and (
                misconf.failures or misconf.successes
            ):
                res.misconfigurations.append(misconf)
        return res
