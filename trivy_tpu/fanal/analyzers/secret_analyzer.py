"""Secret analyzer (reference pkg/fanal/analyzer/secret/secret.go), as a
BATCH post-analyzer: files are collected during the walk and scanned in one
device keyword-prefilter pass + host regex on candidates, instead of the
reference's per-file loop."""

from __future__ import annotations

import os

from trivy_tpu.fanal.analyzer import (
    AnalysisInput,
    AnalysisResult,
    PostAnalyzer,
    register_post,
)
from trivy_tpu.log import logger
from trivy_tpu.ops.secret_nfa import KERNEL_VERSION
from trivy_tpu.secret.scanner import SecretConfig, SecretScanner

_log = logger("secret")

WARN_SIZE = 10 * 1024 * 1024  # reference secret.go:110

_SKIP_DIRS = ("node_modules/.cache/", ".git/", "usr/share/doc/")
_SKIP_FILES = {"go.sum", "package-lock.json", "yarn.lock", "pnpm-lock.yaml",
               "Pipfile.lock", "poetry.lock", "Cargo.lock", "composer.lock"}

# module-level toggle set by the CLI (--no-tpu). "hybrid" splits the
# corpus: device batches dispatch first (async), then the host AC path
# scans the rest while the chip computes — the fastest wall-clock
# configuration measured on tunneled v5e (threads were 2x slower; see
# SecretScanner._scan_files_hybrid)
USE_DEVICE = "hybrid"


# bump on host-side semantic changes (rules, scanner behavior); the
# kernel component below covers device-screen changes
_ANALYZER_BASE = 1


@register_post
class SecretAnalyzer(PostAnalyzer):
    type = "secret"
    # the cache key must change when EITHER the host scanner or the
    # device screen's semantics do (reference invalidates on analyzer
    # version, cache/key.go; here the "analyzer" includes the anchor
    # kernel — SURVEY hard part 4)
    version = _ANALYZER_BASE * 1000 + KERNEL_VERSION

    def __init__(self, config_path: str | None = None):
        self._scanner = None
        self._config_path = config_path

    @property
    def scanner(self) -> SecretScanner:
        if self._scanner is None:
            cfg = None
            if self._config_path and os.path.exists(self._config_path):
                cfg = SecretConfig.load(self._config_path)
            self._scanner = SecretScanner(cfg)
        return self._scanner

    def configure(self, config_path: str | None) -> None:
        if config_path == self._config_path and self._scanner is not None:
            return  # unchanged config keeps the warm scanner (and its
            # scheduler thread + uploaded device bank) across scans
        if self._scanner is not None:
            self._scanner.close()  # stop the secret-lane scheduler
            # thread so a re-config can't leak one per scan
        self._config_path = config_path
        self._scanner = None

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        if os.path.basename(path) in _SKIP_FILES:
            return False
        # never scan the secret config itself: only the root-level file
        # named like the config (reference secret.go:175 compares
        # base(configPath) to the walked relative path)
        if self._config_path and \
                os.path.basename(self._config_path) == path:
            return False
        if any(s in path for s in _SKIP_DIRS):
            return False
        if self.scanner.skip_file(path):
            return False
        if size > WARN_SIZE:
            # the reference warns here and scans anyway (secret.go:110);
            # scan_files routes files over the threshold through the
            # streaming chunked path (byte-identical findings, bounded
            # window memory — docs/secrets.md), so no warn-and-punt
            _log.debug("large file takes the streaming secret path",
                       path=path, size=size)
        return True

    def post_analyze(self, files: dict[str, AnalysisInput]) -> AnalysisResult | None:
        batch = [(path, inp.read()) for path, inp in sorted(files.items())]
        secrets = self.scanner.scan_files(batch, use_device=USE_DEVICE)
        if not secrets:
            return None
        res = AnalysisResult()
        res.secrets = secrets
        return res
