"""dpkg status DB analyzer (reference pkg/fanal/analyzer/pkg/dpkg/):
- var/lib/dpkg/status and var/lib/dpkg/status.d/* stanzas
- var/lib/dpkg/info/*.list -> per-package installed files
- dpkg copyright files -> package licenses (analyzer/pkg/dpkg/copyright)
"""

from __future__ import annotations

import os
import re

from trivy_tpu.fanal.analyzer import (
    AnalysisInput,
    AnalysisResult,
    PostAnalyzer,
    register_post,
)
from trivy_tpu.types.artifact import (
    LicenseFile,
    LicenseFinding,
    Package,
    PackageInfo,
)

_SRC_RX = re.compile(r"^(?P<name>[^\s(]+)(?:\s+\((?P<ver>[^)]+)\))?$")


def _parse_version(pkg: Package, ver: str, into_src: bool) -> None:
    epoch = 0
    if ":" in ver:
        e, _, rest = ver.partition(":")
        if e.isdigit():
            epoch, ver = int(e), rest
    version, release = ver, ""
    if "-" in ver:
        version, _, release = ver.rpartition("-")
    if into_src:
        pkg.src_epoch, pkg.src_version, pkg.src_release = epoch, version, release
    else:
        pkg.epoch, pkg.version, pkg.release = epoch, version, release


def parse_dpkg_status(text: str) -> list[Package]:
    pkgs: list[Package] = []
    for stanza in re.split(r"\n\s*\n", text):
        fields: dict[str, str] = {}
        key = None
        for line in stanza.splitlines():
            if line[:1] in (" ", "\t"):
                if key:
                    fields[key] += "\n" + line.strip()
                continue
            if ":" not in line:
                continue
            key, _, val = line.partition(":")
            fields[key.strip()] = val.strip()
        name = fields.get("Package", "")
        version = fields.get("Version", "")
        if not name or not version:
            continue
        status = fields.get("Status", "")
        if status and "installed" not in status.split():
            continue
        pkg = Package(name=name, arch=fields.get("Architecture", ""),
                      maintainer=fields.get("Maintainer", ""))
        _parse_version(pkg, version, into_src=False)
        src = fields.get("Source", "")
        if src:
            m = _SRC_RX.match(src)
            if m:
                pkg.src_name = m.group("name")
                if m.group("ver"):
                    _parse_version(pkg, m.group("ver"), into_src=True)
        if not pkg.src_name:
            pkg.src_name = pkg.name
        if not pkg.src_version:
            pkg.src_epoch = pkg.epoch
            pkg.src_version = pkg.version
            pkg.src_release = pkg.release
        pkg.id = f"{pkg.name}@{pkg.full_version()}"
        dep = fields.get("Depends", "") + "," + fields.get("Pre-Depends", "")
        raw_deps = []
        for d in dep.split(","):
            d = d.strip().split(" ")[0].split(":")[0]
            if d:
                raw_deps.append(d)
        pkg.depends_on = raw_deps  # resolved to ids after all stanzas
        pkgs.append(pkg)
    # resolve dependency names to ids
    by_name = {p.name: p.id for p in pkgs}
    for p in pkgs:
        p.depends_on = sorted(
            {by_name[d] for d in p.depends_on if d in by_name and by_name[d] != p.id}
        )
    return pkgs


_COMMON_LICENSES = [
    "Apache-2.0", "Artistic-2.0", "BSD-2-Clause", "BSD-3-Clause",
    "BSD-4-Clause", "GFDL-1.2", "GFDL-1.3", "GPL-1.0", "GPL-2.0",
    "GPL-3.0", "LGPL-2.0", "LGPL-2.1", "LGPL-3.0", "MPL-1.1", "MPL-2.0",
    "CC0-1.0", "MIT", "ISC", "Zlib",
]


def parse_copyright(text: str) -> list[str]:
    """Extract license names from a Debian machine-readable copyright file
    (License: lines) or by common-license heuristics
    (reference analyzer/pkg/dpkg/copyright.go)."""
    out: list[str] = []
    for line in text.splitlines():
        if line.startswith("License:"):
            name = line.split(":", 1)[1].strip()
            if name and name not in out:
                out.append(name)
    if not out:
        for lic in _COMMON_LICENSES:
            token = lic.replace("-", " ").split(" ")[0].lower()
            if re.search(rf"/usr/share/common-licenses/{re.escape(lic)}", text) or (
                token in ("mit", "isc", "zlib")
                and re.search(rf"\b{token}\b license", text, re.I)
            ):
                if lic not in out:
                    out.append(lic)
    return out


@register_post
class DpkgAnalyzer(PostAnalyzer):
    type = "dpkg"
    version = 1

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        if path == "var/lib/dpkg/status":
            return True
        if path.startswith("var/lib/dpkg/status.d/") and not path.endswith(".md5sums"):
            return True
        if path.startswith("var/lib/dpkg/info/") and path.endswith(".list"):
            return True
        return False

    def post_analyze(self, files: dict[str, AnalysisInput]) -> AnalysisResult | None:
        res = AnalysisResult()
        # installed-files lists keyed by package name (info/<pkg>[:arch].list)
        listed: dict[str, list[str]] = {}
        for path, inp in files.items():
            if path.startswith("var/lib/dpkg/info/"):
                base = os.path.basename(path)[: -len(".list")]
                name = base.split(":")[0]
                file_list = [
                    l.strip() for l in inp.read().decode("utf-8", "replace").splitlines()
                    if l.strip() and l.strip() != "/."
                ]
                listed[name] = file_list
                res.system_installed_files.extend(file_list)
        for path, inp in sorted(files.items()):
            if path.startswith("var/lib/dpkg/info/"):
                continue
            pkgs = parse_dpkg_status(inp.read().decode("utf-8", "replace"))
            if not pkgs:
                continue
            for p in pkgs:
                if p.name in listed:
                    p.installed_files = listed[p.name]
            res.package_infos.append(PackageInfo(file_path=path, packages=pkgs))
        return res if res.package_infos or res.system_installed_files else None


@register_post
class DpkgLicenseAnalyzer(PostAnalyzer):
    type = "dpkg-license"
    version = 1

    _RX = re.compile(r"^usr/share/doc/(?P<pkg>[^/]+)/copyright$")

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        return bool(self._RX.match(path))

    def post_analyze(self, files: dict[str, AnalysisInput]) -> AnalysisResult | None:
        res = AnalysisResult()
        for path, inp in sorted(files.items()):
            m = self._RX.match(path)
            licenses = parse_copyright(inp.read().decode("utf-8", "replace"))
            if not licenses:
                continue
            res.licenses.append(LicenseFile(
                type="dpkg",
                file_path=path,
                package_name=m.group("pkg"),
                findings=[LicenseFinding(name=n) for n in licenses],
            ))
        return res if res.licenses else None
