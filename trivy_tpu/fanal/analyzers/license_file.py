"""Loose license-file analyzer (reference
pkg/fanal/analyzer/licensing/license.go): classify LICENSE/COPYING/
NOTICE-style files, and — in license-full mode — headers of ordinary
source files, into LicenseFile findings."""

from __future__ import annotations

import os

from trivy_tpu.fanal.analyzer import AnalysisInput, AnalysisResult, Analyzer, register
from trivy_tpu.licensing import classifier

_LICENSE_NAMES = {
    "license", "licence", "copying", "copyright", "eula", "notice",
    "patents", "unlicense", "unlicence",
}
_TEXT_EXTS = {"", ".txt", ".md", ".rst", ".html"}

# license-full mode additionally scans source files for license headers
_SOURCE_EXTS = {
    ".c", ".cc", ".cpp", ".h", ".hpp", ".go", ".py", ".js", ".ts", ".java",
    ".rb", ".rs", ".php", ".cs", ".swift", ".kt", ".scala", ".sh",
}

_MAX_SIZE = 1 << 20  # classify only reasonably sized text files


@register
class LicenseFileAnalyzer(Analyzer):
    type = "license-file"
    version = 1

    # class attrs toggled per scan by cli.run._select_scanner when
    # --license-full is set (same pattern as secret_analyzer.USE_DEVICE)
    full = False
    confidence_level = 0.75

    def required(self, path: str, size: int = 0, mode: int = 0) -> bool:
        if size > _MAX_SIZE:
            return False
        base = os.path.basename(path).lower()
        stem, ext = os.path.splitext(base)
        if ext in _TEXT_EXTS and (stem in _LICENSE_NAMES
                                  or base in _LICENSE_NAMES):
            return True
        # e.g. LICENSE-MIT.txt, LICENSE.Apache-2.0 — but not source files
        # like license-checker.py (tooling, not license text)
        if ext not in _SOURCE_EXTS and \
                any(stem.startswith(n + "-") or stem.startswith(n + ".")
                    for n in ("license", "licence", "copying")):
            return True
        if self.full and ext in _SOURCE_EXTS:
            return True
        return False

    def analyze(self, inp: AnalysisInput):
        content = inp.read()
        if b"\x00" in content[:512]:  # binary
            return None
        lf = classifier.classify(inp.path, content, self.confidence_level)
        if lf is None:
            return None
        res = AnalysisResult()
        res.licenses = [lf]
        return res
