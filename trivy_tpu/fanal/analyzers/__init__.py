"""Built-in analyzers. Importing this package registers everything
(reference pkg/fanal/analyzer/all)."""

from trivy_tpu.fanal.analyzers import (  # noqa: F401
    config_analyzer,
    lang,
    license_file,
    misc,
    os_release,
    pkg_apk,
    pkg_dpkg,
    pkg_rpm,
    secret_analyzer,
)
