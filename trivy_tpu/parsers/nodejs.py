"""Node.js parsers (reference pkg/dependency/parser/nodejs/{npm,yarn,pnpm,
packagejson}): package-lock.json v1/v2+, yarn.lock v1/berry,
pnpm-lock.yaml, and node_modules package.json."""

from __future__ import annotations

import json
import os
import re

from trivy_tpu.types.artifact import Location, Package


def _mk(name: str, version: str, dev: bool = False,
        indirect: bool = False) -> Package:
    return Package(
        id=f"{name}@{version}", name=name, version=version, dev=dev,
        relationship="indirect" if indirect else "direct",
        indirect=indirect,
    )


def parse_package_lock(content: bytes) -> list[Package]:
    doc = json.loads(content)
    out: dict[str, Package] = {}
    if "packages" in doc:  # lockfile v2/v3
        for path, meta in doc["packages"].items():
            if not path.startswith("node_modules/"):
                continue  # root/workspace entries
            name = meta.get("name") or path.split("node_modules/")[-1]
            version = meta.get("version", "")
            if not version:
                continue
            indirect = "node_modules/" in path[len("node_modules/"):]
            pkg = _mk(name, version, dev=bool(meta.get("dev")),
                      indirect=indirect)
            deps = list((meta.get("dependencies") or {}).keys())
            pkg.depends_on = deps
            out.setdefault(pkg.id, pkg)
    else:  # v1: nested dependencies tree
        def walk(deps: dict, depth: int):
            for name, meta in (deps or {}).items():
                version = meta.get("version", "")
                if not version:
                    continue
                pkg = _mk(name, version, dev=bool(meta.get("dev")),
                          indirect=depth > 0)
                pkg.depends_on = list((meta.get("requires") or {}).keys())
                out.setdefault(pkg.id, pkg)
                walk(meta.get("dependencies") or {}, depth + 1)

        walk(doc.get("dependencies") or {}, 0)
    pkgs = list(out.values())
    by_name = {p.name: p.id for p in pkgs}
    for p in pkgs:
        p.depends_on = sorted(
            {by_name[d] for d in p.depends_on if d in by_name}
        )
    return sorted(pkgs, key=lambda p: p.id)


_YARN_HEADER = re.compile(
    r'^"?(?P<name>(?:@[^/@"]+/)?[^/@"]+)@(?:npm:)?[^"]*"?(?:, *"?.*)?:$'
)
_YARN_VERSION = re.compile(r'^ {2}version:? "?(?P<v>[^"\s]+)"?$')


def _parse_yarn_lines(lines) -> list[Package]:
    """State machine over (line_no, text) pairs; lines that can't
    change the state (blank, comment, non-version body) may be
    pre-filtered out by the caller."""
    out: dict[str, Package] = {}
    cur_name = None
    cur_line = 0
    for i, line in lines:
        if not line or line.startswith("#"):
            continue
        if not line.startswith(" "):
            m = _YARN_HEADER.match(line.rstrip())
            cur_name = m.group("name") if m else None
            cur_line = i
            continue
        if cur_name:
            m = _YARN_VERSION.match(line.rstrip())
            if m:
                pkg = _mk(cur_name, m.group("v"))
                pkg.locations = [Location(cur_line, i)]
                out.setdefault(pkg.id, pkg)
                cur_name = None
    return sorted(out.values(), key=lambda p: p.id)


# ASCII control bytes that str.splitlines treats as line boundaries
# beyond \n / \r\n / \r — their presence routes a document to the
# scalar tokenizer so line numbering stays byte-for-byte equal
_EXOTIC_BREAKS = (b"\x0b", b"\x0c", b"\x1c", b"\x1d", b"\x1e")

_YARN_VECTOR_MIN = 4096


def _yarn_lines_vectorized(content: bytes):
    """Tokenize a yarn.lock with one numpy pass: find line boundaries
    from the raw bytes, then classify each line by its first byte so
    only header candidates and `  version` lines — the only lines that
    can move the parser's state — are sliced and regex-matched.
    ASCII-only (byte offsets == char offsets, and none of the unicode
    line separators can appear); returns None when the document needs
    the scalar path."""
    if not content.isascii() or any(b in content for b in _EXOTIC_BREAKS):
        return None
    import numpy as np

    buf = np.frombuffer(content, dtype=np.uint8)
    n = buf.size
    term = np.flatnonzero((buf == 0x0A) | (buf == 0x0D))
    # the \n of a \r\n pair terminates nothing on its own
    lone = ~((buf[term] == 0x0A) & (term > 0)
             & (buf[np.maximum(term - 1, 0)] == 0x0D))
    ends = term[lone]
    nxt = ends + 1 + ((buf[ends] == 0x0D)
                      & (ends + 1 < n)
                      & (buf[np.minimum(ends + 1, n - 1)] == 0x0A))
    starts = np.concatenate((np.zeros(1, dtype=ends.dtype), nxt))
    if starts.size and starts[-1] >= n:        # trailing terminator:
        starts = starts[:-1]                   # no final empty line
    else:
        ends = np.concatenate((ends, np.array([n], dtype=ends.dtype)))
    if not starts.size:
        return []

    lens = ends - starts
    first = buf[np.minimum(starts, n - 1)]
    headers = (lens > 0) & (first != 0x23) & (first != 0x20)
    versions = lens >= 9
    if versions.any():
        v = np.flatnonzero(versions)
        probe = np.frombuffer(b"  version", dtype=np.uint8)
        for k, ch in enumerate(probe):
            v = v[buf[starts[v] + k] == ch]
            if not v.size:
                break
        versions = np.zeros_like(versions)
        versions[v] = True
    keep = np.flatnonzero(headers | versions)
    # one whole-document decode + str slices with python ints: the
    # slice loop dominates once the boundary scan is vectorized
    text = content.decode("ascii")
    return [(i + 1, text[s:e])
            for i, s, e in zip(keep.tolist(), starts[keep].tolist(),
                               ends[keep].tolist())]


def parse_yarn_lock(content: bytes) -> list[Package]:
    if (len(content) >= _YARN_VECTOR_MIN
            and os.environ.get("TRIVY_TPU_VECTOR_ANALYZERS", "1") != "0"):
        lines = _yarn_lines_vectorized(content)
        if lines is not None:
            return _parse_yarn_lines(lines)
    return _parse_yarn_lines(
        enumerate(content.decode("utf-8", "replace").splitlines(), 1))


def parse_pnpm_lock(content: bytes) -> list[Package]:
    import yaml

    doc = yaml.safe_load(content) or {}
    out: dict[str, Package] = {}
    ver = str(doc.get("lockfileVersion", "5"))
    direct: set[str] = set()
    importers = doc.get("importers") or {".": doc}
    for imp in importers.values():
        for sec in ("dependencies", "devDependencies", "optionalDependencies"):
            for name, spec in (imp.get(sec) or {}).items():
                v = spec.get("version", "") if isinstance(spec, dict) else str(spec)
                direct.add(f"{name}@{v.split('(')[0]}")
    for key, meta in (doc.get("packages") or {}).items():
        # v5: "/name/1.0.0" or "/@scope/name/1.0.0"; v6+: "/name@1.0.0";
        # v9 keys live under "snapshots"/"packages" as "name@1.0.0"
        k = key.lstrip("/")
        name = version = ""
        if "@" in k and not k.startswith("@") and ver >= "6":
            name, _, version = k.rpartition("@")
        elif k.startswith("@") and k.count("@") >= 2 and ver >= "6":
            name, _, version = k.rpartition("@")
        else:
            parts = k.rsplit("/", 1)
            if len(parts) == 2:
                name, version = parts
        version = version.split("(")[0]
        if not name or not version:
            continue
        dev = bool(meta.get("dev")) if isinstance(meta, dict) else False
        pid = f"{name}@{version}"
        pkg = _mk(name, version, dev=dev, indirect=pid not in direct)
        out.setdefault(pkg.id, pkg)
    return sorted(out.values(), key=lambda p: p.id)


def parse_package_json(content: bytes) -> Package | None:
    """One installed node_modules/<pkg>/package.json -> node-pkg."""
    try:
        doc = json.loads(content)
    except json.JSONDecodeError:
        return None
    name, version = doc.get("name"), doc.get("version")
    if not name or not version:
        return None
    pkg = _mk(str(name), str(version))
    lic = doc.get("license")
    if isinstance(lic, dict):
        lic = lic.get("type")
    if isinstance(lic, str) and lic:
        pkg.licenses = [lic]
    return pkg
