"""Node.js parsers (reference pkg/dependency/parser/nodejs/{npm,yarn,pnpm,
packagejson}): package-lock.json v1/v2+, yarn.lock v1/berry,
pnpm-lock.yaml, and node_modules package.json."""

from __future__ import annotations

import json
import re

from trivy_tpu.types.artifact import Location, Package


def _mk(name: str, version: str, dev: bool = False,
        indirect: bool = False) -> Package:
    return Package(
        id=f"{name}@{version}", name=name, version=version, dev=dev,
        relationship="indirect" if indirect else "direct",
        indirect=indirect,
    )


def parse_package_lock(content: bytes) -> list[Package]:
    doc = json.loads(content)
    out: dict[str, Package] = {}
    if "packages" in doc:  # lockfile v2/v3
        for path, meta in doc["packages"].items():
            if not path.startswith("node_modules/"):
                continue  # root/workspace entries
            name = meta.get("name") or path.split("node_modules/")[-1]
            version = meta.get("version", "")
            if not version:
                continue
            indirect = "node_modules/" in path[len("node_modules/"):]
            pkg = _mk(name, version, dev=bool(meta.get("dev")),
                      indirect=indirect)
            deps = list((meta.get("dependencies") or {}).keys())
            pkg.depends_on = deps
            out.setdefault(pkg.id, pkg)
    else:  # v1: nested dependencies tree
        def walk(deps: dict, depth: int):
            for name, meta in (deps or {}).items():
                version = meta.get("version", "")
                if not version:
                    continue
                pkg = _mk(name, version, dev=bool(meta.get("dev")),
                          indirect=depth > 0)
                pkg.depends_on = list((meta.get("requires") or {}).keys())
                out.setdefault(pkg.id, pkg)
                walk(meta.get("dependencies") or {}, depth + 1)

        walk(doc.get("dependencies") or {}, 0)
    pkgs = list(out.values())
    by_name = {p.name: p.id for p in pkgs}
    for p in pkgs:
        p.depends_on = sorted(
            {by_name[d] for d in p.depends_on if d in by_name}
        )
    return sorted(pkgs, key=lambda p: p.id)


_YARN_HEADER = re.compile(
    r'^"?(?P<name>(?:@[^/@"]+/)?[^/@"]+)@(?:npm:)?[^"]*"?(?:, *"?.*)?:$'
)
_YARN_VERSION = re.compile(r'^ {2}version:? "?(?P<v>[^"\s]+)"?$')


def parse_yarn_lock(content: bytes) -> list[Package]:
    out: dict[str, Package] = {}
    cur_name = None
    cur_line = 0
    for i, line in enumerate(content.decode("utf-8", "replace").splitlines(), 1):
        if not line or line.startswith("#"):
            continue
        if not line.startswith(" "):
            m = _YARN_HEADER.match(line.rstrip())
            cur_name = m.group("name") if m else None
            cur_line = i
            continue
        if cur_name:
            m = _YARN_VERSION.match(line.rstrip())
            if m:
                pkg = _mk(cur_name, m.group("v"))
                pkg.locations = [Location(cur_line, i)]
                out.setdefault(pkg.id, pkg)
                cur_name = None
    return sorted(out.values(), key=lambda p: p.id)


def parse_pnpm_lock(content: bytes) -> list[Package]:
    import yaml

    doc = yaml.safe_load(content) or {}
    out: dict[str, Package] = {}
    ver = str(doc.get("lockfileVersion", "5"))
    direct: set[str] = set()
    importers = doc.get("importers") or {".": doc}
    for imp in importers.values():
        for sec in ("dependencies", "devDependencies", "optionalDependencies"):
            for name, spec in (imp.get(sec) or {}).items():
                v = spec.get("version", "") if isinstance(spec, dict) else str(spec)
                direct.add(f"{name}@{v.split('(')[0]}")
    for key, meta in (doc.get("packages") or {}).items():
        # v5: "/name/1.0.0" or "/@scope/name/1.0.0"; v6+: "/name@1.0.0";
        # v9 keys live under "snapshots"/"packages" as "name@1.0.0"
        k = key.lstrip("/")
        name = version = ""
        if "@" in k and not k.startswith("@") and ver >= "6":
            name, _, version = k.rpartition("@")
        elif k.startswith("@") and k.count("@") >= 2 and ver >= "6":
            name, _, version = k.rpartition("@")
        else:
            parts = k.rsplit("/", 1)
            if len(parts) == 2:
                name, version = parts
        version = version.split("(")[0]
        if not name or not version:
            continue
        dev = bool(meta.get("dev")) if isinstance(meta, dict) else False
        pid = f"{name}@{version}"
        pkg = _mk(name, version, dev=dev, indirect=pid not in direct)
        out.setdefault(pkg.id, pkg)
    return sorted(out.values(), key=lambda p: p.id)


def parse_package_json(content: bytes) -> Package | None:
    """One installed node_modules/<pkg>/package.json -> node-pkg."""
    try:
        doc = json.loads(content)
    except json.JSONDecodeError:
        return None
    name, version = doc.get("name"), doc.get("version")
    if not name or not version:
        return None
    pkg = _mk(str(name), str(version))
    lic = doc.get("license")
    if isinstance(lic, dict):
        lic = lic.get("type")
    if isinstance(lic, str) and lic:
        pkg.licenses = [lic]
    return pkg
