"""Maven pom.xml parser (reference pkg/dependency/parser/java/pom):
property interpolation (${...} incl. project.* and parent-inherited
values), dependencyManagement version resolution, and dependency
extraction. Offline only — no remote-repository resolution; versions
that stay unresolved after interpolation are dropped, mirroring the
reference's offline mode."""

from __future__ import annotations

import re
import xml.etree.ElementTree as ET

from trivy_tpu.types.artifact import Package

_PROP_RX = re.compile(r"\$\{([^}]+)\}")


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _to_dict(elem) -> dict:
    out = {}
    for child in elem:
        out.setdefault(_strip_ns(child.tag), []).append(child)
    return out


def _text(elem, name: str) -> str:
    for child in elem:
        if _strip_ns(child.tag) == name:
            return (child.text or "").strip()
    return ""


def _interpolate(value: str, props: dict[str, str], depth: int = 0) -> str:
    if not value or "${" not in value or depth > 8:
        return value

    def repl(m):
        return props.get(m.group(1), m.group(0))

    new = _PROP_RX.sub(repl, value)
    if new != value:
        return _interpolate(new, props, depth + 1)
    return new


def parse_pom(content: bytes) -> list[Package]:
    """-> [project artifact] + its dependencies (resolvable versions only)."""
    try:
        root = ET.fromstring(content)
    except ET.ParseError:
        return []
    if _strip_ns(root.tag) != "project":
        return []

    parent = None
    for child in root:
        if _strip_ns(child.tag) == "parent":
            parent = child
            break

    group = _text(root, "groupId") or (parent is not None and _text(parent, "groupId")) or ""
    artifact = _text(root, "artifactId")
    version = _text(root, "version") or (parent is not None and _text(parent, "version")) or ""

    # property table: <properties>, project.* built-ins, parent echoes
    props: dict[str, str] = {}
    for child in root:
        if _strip_ns(child.tag) == "properties":
            for p in child:
                props[_strip_ns(p.tag)] = (p.text or "").strip()
    props.setdefault("project.groupId", group or "")
    props.setdefault("project.version", version or "")
    props.setdefault("project.artifactId", artifact or "")
    props.setdefault("pom.groupId", group or "")
    props.setdefault("pom.version", version or "")
    if parent is not None:
        props.setdefault("project.parent.groupId", _text(parent, "groupId"))
        props.setdefault("project.parent.version", _text(parent, "version"))

    group = _interpolate(group, props)
    version = _interpolate(version, props)

    # dependencyManagement pins: (group:artifact) -> version
    managed: dict[str, str] = {}
    for dm in root.iter():
        if _strip_ns(dm.tag) != "dependencyManagement":
            continue
        for dep in dm.iter():
            if _strip_ns(dep.tag) != "dependency":
                continue
            g = _interpolate(_text(dep, "groupId"), props)
            a = _interpolate(_text(dep, "artifactId"), props)
            v = _interpolate(_text(dep, "version"), props)
            if g and a and v and "${" not in v:
                managed[f"{g}:{a}"] = v

    out: list[Package] = []
    if group and artifact and version and "${" not in version:
        out.append(Package(
            id=f"{group}:{artifact}@{version}",
            name=f"{group}:{artifact}", version=version,
        ))

    seen = set()
    deps_root = None
    for child in root:
        if _strip_ns(child.tag) == "dependencies":
            deps_root = child
            break
    if deps_root is None:
        return out
    for dep in deps_root:
        if _strip_ns(dep.tag) != "dependency":
            continue
        g = _interpolate(_text(dep, "groupId"), props)
        a = _interpolate(_text(dep, "artifactId"), props)
        v = _interpolate(_text(dep, "version"), props)
        scope = _text(dep, "scope")
        if scope in ("test", "provided", "system"):
            continue
        if not v:
            v = managed.get(f"{g}:{a}", "")
        if not (g and a and v) or "${" in v or "${" in g or "${" in a:
            continue
        name = f"{g}:{a}"
        if name in seen:
            continue
        seen.add(name)
        out.append(Package(id=f"{name}@{v}", name=name, version=v))
    return out
