"""Python parsers (reference pkg/dependency/parser/python/*):
requirements.txt, Pipfile.lock, poetry.lock, uv.lock, and installed
dist-info/egg-info METADATA."""

from __future__ import annotations

import json
import re

from trivy_tpu.types.artifact import Location, Package


def _mk(name: str, version: str, **kw) -> Package:
    return Package(id=f"{name}@{version}", name=name, version=version, **kw)


_REQ_RX = re.compile(
    r"^(?P<name>[A-Za-z0-9._-]+)\s*(?:\[[^\]]*\])?\s*==\s*(?P<ver>[^;#\s\\]+)"
)


def parse_requirements(content: bytes) -> list[Package]:
    """Only pinned (==) requirements are packages (reference
    parser/python/pip: non-pinned lines are skipped)."""
    out = []
    for i, line in enumerate(content.decode("utf-8", "replace").splitlines(), 1):
        line = line.strip()
        if not line or line.startswith(("#", "-")):
            continue
        m = _REQ_RX.match(line)
        if not m:
            continue
        ver = m.group("ver").strip()
        # skip environment-marker-only or wildcard pins
        if ver.endswith(".*"):
            continue
        pkg = _mk(m.group("name"), ver)
        pkg.locations = [Location(i, i)]
        out.append(pkg)
    return out


def parse_pipfile_lock(content: bytes) -> list[Package]:
    doc = json.loads(content)
    out = []
    for section, dev in (("default", False), ("develop", True)):
        for name, meta in (doc.get(section) or {}).items():
            version = (meta.get("version") or "").lstrip("=")
            if not version:
                continue
            out.append(_mk(name, version, dev=dev))
    return sorted(out, key=lambda p: p.id)


def parse_poetry_lock(content: bytes) -> list[Package]:
    try:
        import tomllib
    except ImportError:  # Python <= 3.10: stdlib tomllib is 3.11+
        from trivy_tpu.parsers import toml_compat as tomllib

    doc = tomllib.loads(content.decode("utf-8", "replace"))
    out = []
    for meta in doc.get("package") or []:
        name, version = meta.get("name"), meta.get("version")
        if not name or not version:
            continue
        pkg = _mk(name, version)
        pkg.depends_on = sorted(
            f"{d}" for d in (meta.get("dependencies") or {})
        )
        if meta.get("category") == "dev":
            pkg.dev = True
        out.append(pkg)
    # resolve dependency names to ids
    by_name = {p.name.lower(): p.id for p in out}
    for p in out:
        p.depends_on = sorted(
            {by_name[d.lower()] for d in p.depends_on if d.lower() in by_name}
        )
    return sorted(out, key=lambda p: p.id)


def parse_uv_lock(content: bytes) -> list[Package]:
    try:
        import tomllib
    except ImportError:  # Python <= 3.10: stdlib tomllib is 3.11+
        from trivy_tpu.parsers import toml_compat as tomllib

    doc = tomllib.loads(content.decode("utf-8", "replace"))
    out = []
    for meta in doc.get("package") or []:
        name, version = meta.get("name"), meta.get("version")
        if not name or not version:
            continue
        if meta.get("source", {}).get("virtual"):
            continue  # the project itself
        pkg = _mk(name, version)
        pkg.depends_on = sorted(
            d.get("name", "") for d in (meta.get("dependencies") or [])
            if isinstance(d, dict)
        )
        out.append(pkg)
    by_name = {p.name.lower(): p.id for p in out}
    for p in out:
        p.depends_on = sorted(
            {by_name[d.lower()] for d in p.depends_on if d.lower() in by_name}
        )
    return sorted(out, key=lambda p: p.id)


def _norm_name(name: str) -> str:
    """PEP 503 name normalization (reference parser/python NormalizePkgName)."""
    return re.sub(r"[-_.]+", "-", name).lower()


def parse_pyproject(content: bytes) -> dict:
    """pyproject.toml (PEP 518) -> {"dependencies": set of direct poetry
    dep names, "groups": {group: set}} (reference
    parser/python/pyproject/pyproject.go:14-45).  Used to mark
    direct/dev relationships on poetry.lock packages."""
    try:
        import tomllib
    except ImportError:  # Python <= 3.10: stdlib tomllib is 3.11+
        from trivy_tpu.parsers import toml_compat as tomllib

    doc = tomllib.loads(content.decode("utf-8", "replace"))
    poetry = (doc.get("tool") or {}).get("poetry") or {}
    deps = {_norm_name(n) for n in (poetry.get("dependencies") or {})}
    groups = {
        gname: {_norm_name(n) for n in (g.get("dependencies") or {})}
        for gname, g in (poetry.get("group") or {}).items()
    }
    # PEP 621 project dependencies supplement the poetry table
    for spec in (doc.get("project") or {}).get("dependencies") or []:
        m = re.match(r"[A-Za-z0-9._-]+", spec)
        if m:
            deps.add(_norm_name(m.group(0)))
    return {"dependencies": deps, "groups": groups}


_META_NAME = re.compile(r"^Name: (.+)$", re.M)
_META_VERSION = re.compile(r"^Version: (.+)$", re.M)
_META_LICENSE = re.compile(r"^License: (.+)$", re.M)
_META_LICENSE_EXPR = re.compile(r"^License-Expression: (.+)$", re.M)


def parse_dist_metadata(content: bytes) -> Package | None:
    """dist-info/METADATA or egg-info/PKG-INFO -> python-pkg."""
    text = content.decode("utf-8", "replace")
    mn = _META_NAME.search(text)
    mv = _META_VERSION.search(text)
    if not mn or not mv:
        return None
    pkg = _mk(mn.group(1).strip(), mv.group(1).strip())
    ml = _META_LICENSE_EXPR.search(text) or _META_LICENSE.search(text)
    if ml:
        lic = ml.group(1).strip()
        if lic and lic != "UNKNOWN" and len(lic) < 200:
            pkg.licenses = [lic]
    return pkg
