"""Lockfile/binary dependency parsers (reference pkg/dependency/parser/*):
each parse_* takes file content and returns a list of Package."""
