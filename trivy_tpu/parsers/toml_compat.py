"""Minimal TOML loader for Python <= 3.10 (no stdlib ``tomllib``).

The lockfile/manifest parsers (`poetry.lock`, `uv.lock`, `Cargo.lock`,
`pyproject.toml`, Julia `Manifest.toml`) import ``tomllib`` lazily and
fall back to this module on interpreters that predate it.  It covers
the TOML subset those documents actually use:

- tables ``[a.b]`` and arrays-of-tables ``[[a.b]]`` (dotted headers);
- dotted keys, bare/quoted keys;
- basic / literal strings, their multi-line forms, common escapes;
- integers, floats, booleans;
- arrays (multi-line, trailing comma) and inline tables;
- comments and blank lines anywhere whitespace is legal.

Exotic corners (date-times, ``+nan``, CRLF escapes inside multi-line
strings…) raise ``TOMLDecodeError`` rather than mis-parse — callers
already treat a decode error as "not a parseable manifest".
"""

from __future__ import annotations

import re


class TOMLDecodeError(ValueError):
    """The document does not parse under the supported TOML subset."""


_BARE_KEY_RX = re.compile(r"[A-Za-z0-9_-]+")
_NUM_RX = re.compile(
    r"[+-]?(?:0x[0-9A-Fa-f_]+|0o[0-7_]+|0b[01_]+"
    r"|(?:[0-9][0-9_]*)(?:\.[0-9_]+)?(?:[eE][+-]?[0-9_]+)?)")
_ESCAPES = {
    "b": "\b", "t": "\t", "n": "\n", "f": "\f", "r": "\r",
    '"': '"', "\\": "\\",
}


def load(fp) -> dict:
    return loads(fp.read().decode("utf-8"))


def loads(s: str) -> dict:
    if isinstance(s, (bytes, bytearray)):  # tolerated, like tomllib isn't
        s = bytes(s).decode("utf-8")
    return _Parser(s).parse()


class _Parser:
    def __init__(self, s: str):
        self.s = s.replace("\r\n", "\n")
        self.i = 0
        self.n = len(self.s)

    # ------------------------------------------------------------ cursor

    def _err(self, msg: str) -> TOMLDecodeError:
        line = self.s.count("\n", 0, self.i) + 1
        return TOMLDecodeError(f"{msg} (line {line})")

    def _peek(self) -> str:
        return self.s[self.i] if self.i < self.n else ""

    def _skip_ws(self, newlines: bool = False) -> None:
        """Skip spaces/tabs and comments; with ``newlines`` also skip
        line breaks (value positions inside arrays)."""
        while self.i < self.n:
            c = self.s[self.i]
            if c in " \t" or (newlines and c == "\n"):
                self.i += 1
            elif c == "#":
                nl = self.s.find("\n", self.i)
                self.i = self.n if nl < 0 else nl
            else:
                return

    def _expect_eol(self) -> None:
        self._skip_ws()
        if self.i < self.n and self.s[self.i] != "\n":
            raise self._err(
                f"unexpected trailing content {self.s[self.i:self.i+8]!r}")

    # ------------------------------------------------------------- keys

    def _key_part(self) -> str:
        c = self._peek()
        if c in ('"', "'"):
            return self._string()
        m = _BARE_KEY_RX.match(self.s, self.i)
        if not m:
            raise self._err("expected a key")
        self.i = m.end()
        return m.group(0)

    def _dotted_key(self) -> list[str]:
        parts = [self._key_part()]
        while True:
            self._skip_ws()
            if self._peek() != ".":
                return parts
            self.i += 1
            self._skip_ws()
            parts.append(self._key_part())

    @staticmethod
    def _descend(table: dict, parts: list[str]) -> dict:
        for p in parts:
            nxt = table.setdefault(p, {})
            if isinstance(nxt, list):  # [[x]] then [x.y]: into the last
                nxt = nxt[-1]
            if not isinstance(nxt, dict):
                raise TOMLDecodeError(f"key {p!r} is not a table")
            table = nxt
        return table

    # ------------------------------------------------------------ values

    def _string(self) -> str:
        q = self.s[self.i]
        triple = self.s.startswith(q * 3, self.i)
        self.i += 3 if triple else 1
        if triple and self._peek() == "\n":
            self.i += 1  # a newline right after ''' / """ is trimmed
        out: list[str] = []
        while self.i < self.n:
            c = self.s[self.i]
            if triple:
                if self.s.startswith(q * 3, self.i):
                    self.i += 3
                    return "".join(out)
            elif c == q:
                self.i += 1
                return "".join(out)
            elif c == "\n":
                raise self._err("newline in single-line string")
            if q == '"' and c == "\\":
                self.i += 1
                e = self._peek()
                if e in _ESCAPES:
                    out.append(_ESCAPES[e])
                    self.i += 1
                elif e in "uU":
                    width = 4 if e == "u" else 8
                    hexs = self.s[self.i + 1: self.i + 1 + width]
                    if len(hexs) != width:
                        raise self._err("truncated unicode escape")
                    try:
                        out.append(chr(int(hexs, 16)))
                    except ValueError:
                        raise self._err(f"bad unicode escape {hexs!r}")
                    self.i += 1 + width
                elif triple and e == "\n":
                    # line-ending backslash: skip following whitespace
                    self.i += 1
                    while self._peek() in (" ", "\t", "\n"):
                        self.i += 1
                else:
                    raise self._err(f"unsupported escape \\{e}")
            else:
                out.append(c)
                self.i += 1
        raise self._err("unterminated string")

    def _value(self):
        self._skip_ws()
        c = self._peek()
        if c in ('"', "'"):
            return self._string()
        if c == "[":
            return self._array()
        if c == "{":
            return self._inline_table()
        if self.s.startswith("true", self.i):
            self.i += 4
            return True
        if self.s.startswith("false", self.i):
            self.i += 5
            return False
        m = _NUM_RX.match(self.s, self.i)
        if m:
            tok = m.group(0)
            # a date-time would continue with '-' or ':' — unsupported
            nxt = self.s[m.end(): m.end() + 1]
            if nxt in ("-", ":"):
                raise self._err("date-time values are not supported")
            self.i = m.end()
            tok = tok.replace("_", "")
            try:
                if any(x in tok for x in (".", "e", "E")) \
                        and not tok.lower().startswith(("0x", "-0x", "+0x")):
                    return float(tok)
                return int(tok, 0)
            except ValueError:
                raise self._err(f"bad number {tok!r}")
        raise self._err(f"cannot parse value at {self.s[self.i:self.i+12]!r}")

    def _array(self) -> list:
        self.i += 1  # '['
        out: list = []
        while True:
            self._skip_ws(newlines=True)
            if self._peek() == "]":
                self.i += 1
                return out
            if self.i >= self.n:
                raise self._err("unterminated array")
            out.append(self._value())
            self._skip_ws(newlines=True)
            if self._peek() == ",":
                self.i += 1
            elif self._peek() != "]":
                raise self._err("expected ',' or ']' in array")

    def _inline_table(self) -> dict:
        self.i += 1  # '{'
        out: dict = {}
        self._skip_ws()
        if self._peek() == "}":
            self.i += 1
            return out
        while True:
            self._skip_ws()
            parts = self._dotted_key()
            self._skip_ws()
            if self._peek() != "=":
                raise self._err("expected '=' in inline table")
            self.i += 1
            self._descend(out, parts[:-1])[parts[-1]] = self._value()
            self._skip_ws()
            c = self._peek()
            if c == ",":
                self.i += 1
            elif c == "}":
                self.i += 1
                return out
            else:
                raise self._err("expected ',' or '}' in inline table")

    # ----------------------------------------------------------- document

    def parse(self) -> dict:
        root: dict = {}
        cur = root
        while True:
            self._skip_ws(newlines=True)
            if self.i >= self.n:
                return root
            if self._peek() == "[":
                aot = self.s.startswith("[[", self.i)
                self.i += 2 if aot else 1
                self._skip_ws()
                parts = self._dotted_key()
                self._skip_ws()
                closer = "]]" if aot else "]"
                if not self.s.startswith(closer, self.i):
                    raise self._err(f"expected {closer!r}")
                self.i += len(closer)
                self._expect_eol()
                parent = self._descend(root, parts[:-1])
                leaf = parts[-1]
                if aot:
                    arr = parent.setdefault(leaf, [])
                    if not isinstance(arr, list):
                        raise self._err(f"key {leaf!r} is not an array "
                                        "of tables")
                    arr.append({})
                    cur = arr[-1]
                else:
                    nxt = parent.setdefault(leaf, {})
                    if isinstance(nxt, list):
                        nxt = nxt[-1]
                    if not isinstance(nxt, dict):
                        raise self._err(f"key {leaf!r} redefined as a "
                                        "table")
                    cur = nxt
            else:
                parts = self._dotted_key()
                self._skip_ws()
                if self._peek() != "=":
                    raise self._err("expected '=' after key")
                self.i += 1
                self._descend(cur, parts[:-1])[parts[-1]] = self._value()
                self._expect_eol()
