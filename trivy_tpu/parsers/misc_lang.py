"""Parsers for cargo, composer, ruby, java, dotnet, dart, elixir, swift,
conan, conda and gradle/sbt lockfiles (reference pkg/dependency/parser/*)."""

from __future__ import annotations

import json
import re

from trivy_tpu.types.artifact import Location, Package


def _mk(name: str, version: str, **kw) -> Package:
    return Package(id=f"{name}@{version}", name=name, version=version, **kw)


# ------------------------------------------------------------ rust


def parse_cargo_lock(content: bytes) -> list[Package]:
    try:
        import tomllib
    except ImportError:  # Python <= 3.10: stdlib tomllib is 3.11+
        from trivy_tpu.parsers import toml_compat as tomllib

    doc = tomllib.loads(content.decode("utf-8", "replace"))
    out = []
    for meta in doc.get("package") or []:
        name, version = meta.get("name"), meta.get("version")
        if not name or not version:
            continue
        pkg = _mk(name, version)
        deps = []
        for d in meta.get("dependencies") or []:
            deps.append(d.split(" ")[0])
        pkg.depends_on = deps
        out.append(pkg)
    by_name = {p.name: p.id for p in out}
    for p in out:
        p.depends_on = sorted(
            {by_name[d] for d in p.depends_on if d in by_name}
        )
    return sorted(out, key=lambda p: p.id)


# ------------------------------------------------------------ php


def parse_composer_lock(content: bytes) -> list[Package]:
    doc = json.loads(content)
    if isinstance(doc, list):
        # composer 1.x installed.json is a bare package array
        doc = {"packages": doc}
    if not isinstance(doc, dict):
        return []
    out = []
    for section, dev in (("packages", False), ("packages-dev", True)):
        for meta in doc.get(section) or []:
            name, version = meta.get("name"), meta.get("version", "")
            if not name or not version:
                continue
            pkg = _mk(name, version.lstrip("v"), dev=dev)
            lic = meta.get("license")
            if isinstance(lic, list):
                pkg.licenses = [str(x) for x in lic]
            pkg.depends_on = sorted(
                d for d in (meta.get("require") or {})
                if "/" in d  # real packages, not "php"/extensions
            )
            out.append(pkg)
    by_name = {p.name: p.id for p in out}
    for p in out:
        p.depends_on = sorted(
            {by_name[d] for d in p.depends_on if d in by_name}
        )
    return sorted(out, key=lambda p: p.id)


# ------------------------------------------------------------ ruby

_GEM_RX = re.compile(r"^ {4}(?P<name>\S+) \((?P<ver>[^)]+)\)$")


def parse_gemfile_lock(content: bytes) -> list[Package]:
    out = []
    in_gem = False
    for i, line in enumerate(content.decode("utf-8", "replace").splitlines(), 1):
        if line.strip() == "GEM":
            in_gem = True
            continue
        if line and not line.startswith(" "):
            in_gem = False
            continue
        if in_gem:
            m = _GEM_RX.match(line)
            if m:
                pkg = _mk(m.group("name"), m.group("ver"))
                pkg.locations = [Location(i, i)]
                out.append(pkg)
    return out


_GEMSPEC_NAME = re.compile(r"\.name\s*=\s*['\"]([^'\"]+)['\"]")
_GEMSPEC_VER = re.compile(r"\.version\s*=\s*['\"]([^'\"]+)['\"]")
_GEMSPEC_LIC = re.compile(r"\.licenses?\s*=\s*\[?\s*['\"]([^'\"]+)['\"]")


def parse_gemspec(content: bytes) -> Package | None:
    text = content.decode("utf-8", "replace")
    mn, mv = _GEMSPEC_NAME.search(text), _GEMSPEC_VER.search(text)
    if not mn or not mv:
        return None
    pkg = _mk(mn.group(1), mv.group(1))
    ml = _GEMSPEC_LIC.search(text)
    if ml:
        pkg.licenses = [ml.group(1)]
    return pkg


# ------------------------------------------------------------ java


_JAR_FILENAME_RX = re.compile(r"(?P<name>.+?)-(?P<ver>\d[\w.]*)\.[jwe]ar$")


def _parse_jar_filename(path: str) -> tuple[str, str]:
    """name-1.2.3.jar -> (artifactId, version)."""
    m = _JAR_FILENAME_RX.match(path.rsplit("/", 1)[-1])
    return (m.group("name"), m.group("ver")) if m else ("", "")


def parse_jar(content: bytes, path: str = "", client=None,
              _depth: int = 0) -> list[Package]:
    """JAR/WAR/EAR identification (reference
    pkg/dependency/parser/java/jar/parse.go:120-260):
    pom.properties preferred; inner jars recursed; then javadb sha1
    lookup; MANIFEST.MF Implementation-*; javadb artifactId->groupId
    heuristic; filename last.  `client` is a db.javadb.JavaDB (the
    process-wide one is used when None)."""
    import hashlib
    import io
    import zipfile

    if client is None:
        from trivy_tpu.db.javadb import client as _javadb_client

        client = _javadb_client()

    out: list[Package] = []
    try:
        zf = zipfile.ZipFile(io.BytesIO(content))
    except zipfile.BadZipFile:
        return []
    file_aid, file_ver = _parse_jar_filename(path)
    found_own_pom = False
    manifest_fields: dict[str, str] = {}
    with zf:
        for name in zf.namelist():
            base = name.rsplit("/", 1)[-1]
            if base == "pom.properties":
                try:
                    props = dict(
                        line.split("=", 1)
                        for line in
                        zf.read(name).decode("utf-8", "replace").splitlines()
                        if "=" in line and not line.startswith("#")
                    )
                except Exception:
                    continue
                gid = props.get("groupId", "").strip()
                aid = props.get("artifactId", "").strip()
                ver = props.get("version", "").strip()
                if gid and aid and ver:
                    out.append(_mk(f"{gid}:{aid}", ver, file_path=path))
                    if aid == file_aid and ver == file_ver:
                        found_own_pom = True
            elif base == "MANIFEST.MF":
                text = zf.read(name).decode("utf-8", "replace")
                for line in text.splitlines():
                    if ":" in line:
                        k, _, v = line.partition(":")
                        manifest_fields[k.strip()] = v.strip()
            elif base.endswith((".jar", ".war", ".ear")) and _depth < 3:
                # fat jars bundle their dependencies (parse.go:184-196)
                try:
                    inner = zf.read(name)
                except Exception:
                    continue
                out.extend(parse_jar(inner, f"{path}/{name}" if path else name,
                                     client=client, _depth=_depth + 1))
    if found_own_pom or (out and not file_aid):
        return out

    # manifest identification (parse.go:100-118)
    if manifest_fields:
        gid = manifest_fields.get("Implementation-Vendor-Id") or \
            manifest_fields.get("Bundle-SymbolicName", "").split(";")[0]
        aid = manifest_fields.get("Implementation-Title") or ""
        ver = manifest_fields.get("Implementation-Version") or \
            manifest_fields.get("Bundle-Version", "")
        if aid and ver:
            name = f"{gid}:{aid}" if gid and ":" not in aid else aid
            out.append(_mk(name, ver, file_path=path))
            return out

    # sha1 lookup against the java DB (parse.go:123-127, :235-249)
    if client is not None:
        sha1 = hashlib.sha1(content).hexdigest()
        gav = client.search_by_sha1(sha1)
        if gav is not None:
            out.append(_mk(gav.name, gav.version, file_path=path))
            return out

    if file_aid and file_ver:
        # groupId via the (artifactId, version) heuristic (parse.go:139)
        name = file_aid
        if client is not None:
            gid = client.search_by_artifact_id(file_aid, file_ver)
            if gid:
                name = f"{gid}:{file_aid}"
        out.append(_mk(name, file_ver, file_path=path))
    return out


def parse_gradle_lockfile(content: bytes) -> list[Package]:
    out = []
    for i, line in enumerate(content.decode("utf-8", "replace").splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        coord = line.split("=")[0]
        parts = coord.split(":")
        if len(parts) == 3:
            pkg = _mk(f"{parts[0]}:{parts[1]}", parts[2])
            pkg.locations = [Location(i, i)]
            out.append(pkg)
    return out


def parse_sbt_lockfile(content: bytes) -> list[Package]:
    doc = json.loads(content)
    out = []
    for dep in doc.get("dependencies") or []:
        org, name, ver = dep.get("org"), dep.get("name"), dep.get("version")
        if org and name and ver:
            out.append(_mk(f"{org}:{name}", ver))
    return sorted(out, key=lambda p: p.id)


# ------------------------------------------------------------ dotnet


def parse_deps_json(content: bytes) -> list[Package]:
    """Reference pkg/dependency/parser/dotnet/core_deps: type=package
    entries from "libraries", filtered to runtime libraries when the
    runtimeTarget's target section is present (an entry that exists there
    but has no runtime/runtimeTargets/native content is compile-only)."""
    doc = json.loads(content)
    target_libs = (doc.get("targets") or {}).get(
        ((doc.get("runtimeTarget") or {}).get("name")) or "")
    out = {}
    for key, meta in (doc.get("libraries") or {}).items():
        if "/" not in key or str(meta.get("type", "")).lower() != "package":
            continue
        if target_libs is not None:
            lib = target_libs.get(key)
            if lib is not None and not lib:
                continue  # present but empty: compile-only
        name, version = key.split("/", 1)
        out.setdefault(f"{name}@{version}", _mk(name, version))
    return sorted(out.values(), key=lambda p: p.id)


def parse_nuget_lock(content: bytes) -> list[Package]:
    doc = json.loads(content)
    out = {}
    for _fw, deps in (doc.get("dependencies") or {}).items():
        for name, meta in (deps or {}).items():
            version = meta.get("resolved", "")
            if not version:
                continue
            indirect = meta.get("type") == "Transitive"
            out.setdefault(
                f"{name}@{version}",
                _mk(name, version, indirect=indirect,
                    relationship="indirect" if indirect else "direct"),
            )
    return sorted(out.values(), key=lambda p: p.id)


# ------------------------------------------------------------ dart / elixir / swift / conan / conda


def parse_pubspec_lock(content: bytes) -> list[Package]:
    import yaml

    doc = yaml.safe_load(content) or {}
    out = []
    for name, meta in (doc.get("packages") or {}).items():
        version = str(meta.get("version", ""))
        if not version:
            continue
        indirect = meta.get("dependency") == "transitive"
        out.append(_mk(name, version, indirect=indirect,
                       relationship="indirect" if indirect else "direct"))
    return sorted(out, key=lambda p: p.id)


_MIX_RX = re.compile(
    r'"(?P<name>[^"]+)":\s*\{:\w+,\s*:"?(?P=name)"?,\s*"(?P<ver>[^"]+)"'
)


def parse_mix_lock(content: bytes) -> list[Package]:
    out = []
    for i, line in enumerate(content.decode("utf-8", "replace").splitlines(), 1):
        m = _MIX_RX.search(line)
        if m:
            pkg = _mk(m.group("name"), m.group("ver"))
            pkg.locations = [Location(i, i)]
            out.append(pkg)
    return out


_PODFILE_RX = re.compile(r"^ {2}- (?P<name>\S+) \((?P<ver>[^)]+)\)$")


def parse_podfile_lock(content: bytes) -> list[Package]:
    import yaml

    doc = yaml.safe_load(content) or {}
    out = {}
    for entry in doc.get("PODS") or []:
        if isinstance(entry, dict):
            entry = next(iter(entry))
        m = re.match(r"(?P<name>\S+) \((?P<ver>[^)]+)\)", str(entry))
        if m:
            out.setdefault(m.group("name"),
                           _mk(m.group("name"), m.group("ver")))
    return sorted(out.values(), key=lambda p: p.id)


def parse_swift_resolved(content: bytes) -> list[Package]:
    doc = json.loads(content)
    out = []
    pins = (doc.get("pins") or
            (doc.get("object") or {}).get("pins") or [])
    for pin in pins:
        name = pin.get("location") or pin.get("repositoryURL") or pin.get("identity", "")
        name = name.removesuffix(".git")
        # reference trims the URL scheme: "github.com/apple/swift-nio"
        name = name.removeprefix("https://").removeprefix("http://")
        state = pin.get("state") or {}
        version = state.get("version") or ""
        if name and version:
            out.append(_mk(name, version))
    return sorted(out, key=lambda p: p.id)


def parse_conan_lock(content: bytes) -> list[Package]:
    doc = json.loads(content)
    out = []
    # v2: {"requires": ["name/1.0#rrev%ts", ...]}
    for req in doc.get("requires") or []:
        ref = req.split("#")[0].split("%")[0]
        if "/" in ref:
            name, version = ref.split("/", 1)
            out.append(_mk(name, version.split("@")[0]))
    # v1: graph_lock.nodes
    nodes = (doc.get("graph_lock") or {}).get("nodes") or {}
    for _id, node in nodes.items():
        ref = (node.get("ref") or "").split("#")[0]
        if "/" in ref:
            name, version = ref.split("/", 1)
            out.append(_mk(name, version.split("@")[0]))
    uniq = {p.id: p for p in out}
    return sorted(uniq.values(), key=lambda p: p.id)


def parse_conda_meta(content: bytes) -> Package | None:
    doc = json.loads(content)
    name, version = doc.get("name"), doc.get("version")
    if not name or not version:
        return None
    pkg = _mk(str(name), str(version))
    lic = doc.get("license")
    if lic:
        pkg.licenses = [str(lic)]
    return pkg


def parse_conda_environment(content: bytes) -> list[Package]:
    import yaml

    doc = yaml.safe_load(content) or {}
    out = []
    for dep in doc.get("dependencies") or []:
        if not isinstance(dep, str):
            continue
        # only exact "name=version(=build)" pins; range specs
        # (>=, <=, !=, name>...) are not concrete packages
        if any(c in dep for c in "<>!"):
            continue
        parts = dep.split("=")
        if len(parts) >= 2 and parts[0] and parts[1]:
            out.append(_mk(parts[0], parts[1]))
    return out


# ------------------------------------------------------------ julia


def parse_julia_manifest(content: bytes) -> list[Package]:
    """Manifest.toml (reference pkg/dependency/parser/julia/manifest):
    supports both the flat pre-1.7 layout and the 1.7+ [deps] table."""
    try:
        import tomllib
    except ImportError:  # Python <= 3.10: stdlib tomllib is 3.11+
        from trivy_tpu.parsers import toml_compat as tomllib

    try:
        doc = tomllib.loads(content.decode("utf-8", "replace"))
    except tomllib.TOMLDecodeError:
        return []
    deps = doc.get("deps", doc)  # 1.7+ nests under [deps]
    # flat (pre-1.7) manifests carry no julia_version: stdlib entries
    # report version "unknown" (reference julia/manifest parse.go:52-57)
    julia_version = str(doc.get("julia_version") or "unknown")
    out = []
    for name, entries in deps.items():
        if not isinstance(entries, list):
            continue
        for e in entries:
            if not isinstance(e, dict):
                continue
            # stdlib entries carry no version: the julia runtime
            # provides them at the manifest's julia_version (reference
            # julia/manifest parse.go:24)
            version = str(e.get("version") or julia_version or "")
            uuid = e.get("uuid") or ""
            if not version:
                continue
            pkg = _mk(name, version)
            if uuid:
                pkg.id = uuid  # manifests distinguish same-name
                # packages by uuid (reference uses the uuid as pkg ID)
                pkg.identifier.purl = (
                    f"pkg:julia/{name}@{version}?uuid={uuid}")
            out.append(pkg)
    return sorted(out, key=lambda p: (p.name, p.version, p.id))


# ------------------------------------------------------------ wordpress


_WP_VERSION_RX = re.compile(
    rb"\$wp_version\s*=\s*['\"]([0-9][0-9a-zA-Z.\-]*)['\"]")


def parse_wordpress_version(content: bytes) -> Package | None:
    """wp-includes/version.php (reference
    pkg/dependency/parser/wordpress: reads $wp_version)."""
    m = _WP_VERSION_RX.search(content)
    if not m:
        return None
    return _mk("wordpress", m.group(1).decode())


# ------------------------------------------------------------ rust binary


def parse_rust_binary(content: bytes) -> list[Package]:
    """Rust binaries built with cargo-auditable embed a zlib-compressed
    JSON dependency list in a dedicated section named .dep-v0 (reference
    pkg/dependency/parser/rust/binary via rust-audit-info). Rather than
    fully parsing ELF/PE section tables, scan for zlib streams and accept
    the one that inflates to the audit JSON shape. The section *name*
    appearing in the binary's string table is the cheap gate; candidate
    streams are probed with a bounded 64-byte inflate before committing
    to a (size-capped) full decompression."""
    import zlib

    if b"dep-v0" not in content:
        return []
    view = memoryview(content)
    out: list[Package] = []
    pos = 0
    while True:
        idx = content.find(b"\x78", pos)
        if idx < 0 or idx + 2 > len(content):
            break
        pos = idx + 1
        if content[idx + 1] not in (0x01, 0x5E, 0x9C, 0xDA):
            continue
        window = view[idx: idx + 8 * 1024 * 1024]
        try:
            probe = zlib.decompressobj().decompress(window, 64)
        except zlib.error:
            continue
        if not probe.startswith(b'{"packages":'):
            continue
        try:  # bounded full inflate: audit JSON is small (< 16 MiB)
            blob = zlib.decompressobj().decompress(window, 16 * 1024 * 1024)
        except zlib.error:
            continue
        try:
            doc = json.loads(blob)
        except json.JSONDecodeError:
            continue
        pkgs = doc.get("packages") or []
        roots = {i for i, p in enumerate(pkgs) if p.get("root")}
        for i, p in enumerate(pkgs):
            name, version = p.get("name"), p.get("version")
            if not name or not version or i in roots:
                continue
            if p.get("kind", "runtime") != "runtime":
                continue
            out.append(_mk(name, version))
        break
    return sorted(out, key=lambda p: p.id)


# ------------------------------------------------------------ nuget config


def parse_nuget_packages_config(content: bytes) -> list[Package]:
    """packages.config (reference pkg/dependency/parser/nuget/config)."""
    import xml.etree.ElementTree as ET

    try:
        root = ET.fromstring(content)
    except ET.ParseError:
        return []
    out = []
    for pkg in root.iter("package"):
        name = pkg.get("id")
        version = pkg.get("version")
        if name and version:
            out.append(_mk(name, version,
                           dev=pkg.get("developmentDependency") == "true"))
    return sorted(out, key=lambda p: p.id)


def parse_nuget_packages_props(content: bytes) -> list[Package]:
    """Directory.Packages.props central package management (reference
    pkg/dependency/parser/nuget/packagesprops)."""
    import xml.etree.ElementTree as ET

    try:
        root = ET.fromstring(content)
    except ET.ParseError:
        return []
    out = []
    for tag in ("PackageVersion", "GlobalPackageReference"):
        for item in root.iter(tag):
            name = item.get("Include")
            version = item.get("Version") or ""
            # MSBuild variable versions can't be resolved offline
            if not name or not version or "$(" in version or "$(" in name:
                continue
            out.append(_mk(name, version))
    return sorted(out, key=lambda p: p.id)
