"""Go parsers (reference pkg/dependency/parser/golang/{mod,binary}):
go.mod requires (honoring replace directives) and Go-binary embedded
build info."""

from __future__ import annotations

import re
import struct

from trivy_tpu.types.artifact import Package


def _mk(name: str, version: str, **kw) -> Package:
    # go module versions keep their "v" prefix (reference
    # pkg/dependency/parser/golang/mod reports "v2.7.1+incompatible")
    return Package(id=f"{name}@{version}", name=name,
                   version=version, **kw)


_REQ_BLOCK = re.compile(r"require\s*\(([^)]*)\)", re.S)
_REQ_LINE = re.compile(r"require\s+([^\s(]+)\s+(\S+)")
_MOD_LINE = re.compile(r"^\s*([^\s]+)\s+(v[^\s/]+)(\s*//\s*indirect)?", re.M)
_REPLACE_BLOCK = re.compile(r"replace\s*\(([^)]*)\)", re.S)
_REPLACE_LINE = re.compile(
    r"(?:^|\n)\s*([^\s=]+)(?:\s+(v\S+))?\s*=>\s*([^\s]+)(?:\s+(v\S+))?"
)


def parse_go_mod(content: bytes) -> list[Package]:
    text = content.decode("utf-8", "replace")
    pkgs: dict[str, Package] = {}
    for block in _REQ_BLOCK.findall(text):
        for m in _MOD_LINE.finditer(block):
            name, ver, indirect = m.group(1), m.group(2), bool(m.group(3))
            pkgs[name] = _mk(name, ver, indirect=indirect,
                             relationship="indirect" if indirect else "direct")
    for m in _REQ_LINE.finditer(re.sub(_REQ_BLOCK, "", text)):
        name, ver = m.group(1), m.group(2)
        indirect = "// indirect" in text.split(name, 1)[-1].split("\n", 1)[0]
        pkgs[name] = _mk(name, ver, indirect=indirect,
                         relationship="indirect" if indirect else "direct")
    # replace directives override
    replaces = []
    for block in _REPLACE_BLOCK.findall(text):
        replaces.extend(_REPLACE_LINE.findall(block))
    replaces.extend(
        _REPLACE_LINE.findall(re.sub(_REPLACE_BLOCK, "", text))
    )
    for old, _old_v, new, new_v in replaces:
        if old in pkgs and new_v:
            prev = pkgs.pop(old)
            # the replacement inherits the replaced module's position in
            # the graph (a replaced direct dep is still a direct dep)
            pkgs[new] = _mk(new, new_v, indirect=prev.indirect,
                            relationship=prev.relationship)
    out = sorted(pkgs.values(), key=lambda p: p.id)
    # the main module is the graph ROOT (reference golang/mod parser):
    # VEX products name it (pkg:golang/<module>) with the vulnerable
    # dependency as subcomponent, so reachability needs the edge. Empty
    # version keeps it out of vulnerability matching (detect_app skips
    # empty packages).
    m = re.search(r"^module\s+(\S+)", text, re.M)
    if m:
        root = _mk(m.group(1), "", relationship="root")
        root.depends_on = [p.id for p in out
                           if p.relationship == "direct"]
        out.insert(0, root)
    return out


_BUILDINFO_MAGIC = b"\xff Go buildinf:"


def parse_go_binary(content: bytes) -> list[Package]:
    """Extract the embedded module list from a Go binary (buildinfo blob,
    go1.18+ inline format)."""
    idx = content.find(_BUILDINFO_MAGIC)
    if idx < 0:
        return []
    hdr = content[idx: idx + 32]
    if len(hdr) < 32:
        return []
    flags = hdr[15]
    if not flags & 0x2:
        # old pointer-based format: would need to follow pointers; skip
        return []
    # inline format: two varint-prefixed strings follow the 32-byte header
    p = idx + 32

    def read_string(pos):
        n = 0
        shift = 0
        while True:
            b = content[pos]
            pos += 1
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return content[pos: pos + n].decode("utf-8", "replace"), pos + n

    try:
        go_version, p = read_string(p)
        modinfo, p = read_string(p)
    except (IndexError, UnicodeDecodeError):
        return []
    pkgs: list[Package] = []
    if go_version.startswith("go"):
        pkgs.append(_mk("stdlib", go_version[2:].split(" ")[0]))
    for line in modinfo.split("\n"):
        parts = line.split("\t")
        if len(parts) >= 3 and parts[0] in ("dep", "mod"):
            name, ver = parts[1], parts[2]
            if ver and ver != "(devel)":
                pkgs.append(_mk(name, ver))
    return pkgs


def parse_go_sum(content: bytes) -> list[Package]:
    """go.sum (reference pkg/dependency/parser/golang/sum): used as the
    dependency source when go.mod predates go 1.17 and lists no indirect
    deps. Lines: `module version[/go.mod] hash`."""
    pkgs: dict[str, Package] = {}
    for line in content.splitlines():
        parts = line.split()
        if len(parts) < 3:
            continue
        name, ver = parts[0].decode(), parts[1].decode()
        if ver.endswith("/go.mod"):
            ver = ver[: -len("/go.mod")]
        if name and ver:
            pkgs[name] = _mk(name, ver)
    return sorted(pkgs.values(), key=lambda p: p.id)
