from trivy_tpu.sbom.decode import decode_sbom_file, detect_sbom_format

__all__ = ["decode_sbom_file", "detect_sbom_format"]
