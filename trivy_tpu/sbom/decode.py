"""SBOM decoding: CycloneDX / SPDX (JSON) -> BlobInfo.

Model: reference pkg/sbom/io/decode.go —
- the "operating-system" component becomes OS metadata
- packages with apk/deb/rpm purls attach to the OS package set
- language packages group into Applications: under their parent
  "application" component (lockfile) when referenced by the dependency
  graph, else aggregated per language type (decode.go addLangPkgs /
  addOrphanPkgs)
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from trivy_tpu.log import logger
from trivy_tpu.types.artifact import (
    Application,
    BlobInfo,
    OS,
    Package,
    PackageInfo,
    PkgIdentifier,
)
from trivy_tpu.utils.purl import parse_purl, purl_kind

_log = logger("sbom")


@dataclass
class SBOMMeta:
    artifact_name: str = ""
    image_id: str = ""
    repo_tags: list[str] = field(default_factory=list)
    repo_digests: list[str] = field(default_factory=list)
    diff_ids: list[str] = field(default_factory=list)
    artifact_type: str = "cyclonedx"


def _classify_doc(doc) -> str | None:
    if isinstance(doc, dict):
        if doc.get("bomFormat") == "CycloneDX":
            return "cyclonedx-json"
        if "spdxVersion" in doc:
            return "spdx-json"
    return None


def detect_sbom_format(path: str) -> str | None:
    """-> "cyclonedx-json" | "spdx-json" | "attest-*" | None
    (reference pkg/sbom/sbom.go format sniffing incl. in-toto
    attestations)."""
    try:
        with open(path, "rb") as f:
            head = f.read(8 * 1024 * 1024)
        doc = json.loads(head)
    except (json.JSONDecodeError, UnicodeDecodeError):
        if head.lstrip().startswith(b"SPDXVersion:"):
            return "spdx-tv"
        return None
    fmt = _classify_doc(doc)
    if fmt:
        return fmt
    from trivy_tpu.attestation import is_attestation

    if is_attestation(doc):
        return "attestation"
    return None


def decode_sbom_bytes(content: bytes) -> tuple[BlobInfo, SBOMMeta]:
    """Decode an in-memory SBOM document (used by the in-image SBOM
    analyzer, reference pkg/fanal/analyzer/sbom)."""
    doc = json.loads(content)
    fmt = _classify_doc(doc)
    if fmt == "cyclonedx-json":
        return _decode_cyclonedx(doc)
    if fmt == "spdx-json":
        return _decode_spdx(doc)
    raise ValueError("unsupported SBOM document")


def decode_sbom_file(path: str) -> tuple[BlobInfo, SBOMMeta]:
    fmt = detect_sbom_format(path)
    if fmt == "spdx-tv":
        with open(path, encoding="utf-8", errors="replace") as f:
            return _decode_spdx(parse_spdx_tag_value(f.read()))
    with open(path) as f:
        doc = json.load(f)
    if fmt == "attestation":
        # cosign SBOM attestation: DSSE envelope -> in-toto statement ->
        # predicate(.Data) holds the actual SBOM (reference
        # pkg/attestation + sbom.go attestation decode)
        from trivy_tpu.attestation import parse_statement, unwrap_cosign_predicate

        inner = unwrap_cosign_predicate(parse_statement(doc))
        if isinstance(inner, str):
            inner = json.loads(inner)
        doc = inner
        fmt = _classify_doc(doc)
    if fmt == "cyclonedx-json":
        return _decode_cyclonedx(doc)
    if fmt == "spdx-json":
        return _decode_spdx(doc)
    raise ValueError(f"unsupported SBOM format: {path}")


# ------------------------------------------------------------ CycloneDX


def _decode_cyclonedx(doc: dict) -> tuple[BlobInfo, SBOMMeta]:
    meta = SBOMMeta(artifact_type="cyclonedx")
    blob = BlobInfo()
    root = (doc.get("metadata") or {}).get("component") or {}
    if root:
        meta.artifact_name = root.get("name", "")
        for prop in root.get("properties") or []:
            name, value = prop.get("name", ""), prop.get("value", "")
            if name == "aquasecurity:trivy:ImageID":
                meta.image_id = value
            elif name == "aquasecurity:trivy:RepoDigest":
                meta.repo_digests.append(value)
            elif name == "aquasecurity:trivy:RepoTag":
                meta.repo_tags.append(value)
            elif name == "aquasecurity:trivy:DiffID":
                meta.diff_ids.append(value)

    os_info = OS()
    os_pkgs: list[Package] = []
    # bom-ref -> lockfile application placeholder
    apps: dict[str, Application] = {}
    # bom-ref -> (lang_type, Package)
    lang_pkgs: dict[str, tuple[str, Package]] = {}
    counter = [0]

    components = list(doc.get("components") or [])
    for c in components:
        ctype = c.get("type", "")
        ref = c.get("bom-ref") or f"comp-{counter[0]}"
        counter[0] += 1
        if ctype == "operating-system":
            if not os_info.detected:
                os_info = OS(family=c.get("name", ""), name=c.get("version", ""))
            continue
        if ctype == "application":
            app_type, fpath = _cdx_app_props(c)
            if app_type:
                apps[ref] = Application(type=app_type, file_path=fpath)
                continue
        pkg, kind, type_str = _component_to_package(c)
        if pkg is None:
            continue
        if kind == "os":
            os_pkgs.append(pkg)
        else:
            lang_pkgs.setdefault(ref, (type_str, pkg))

    # dependency graph: lockfile app -> its packages
    deps = {
        d.get("ref"): d.get("dependsOn") or []
        for d in doc.get("dependencies") or []
    }
    placed: set[str] = set()
    for app_ref, app in apps.items():
        stack = list(deps.get(app_ref, []))
        seen = set()
        while stack:
            r = stack.pop()
            if r in seen:
                continue
            seen.add(r)
            if r in lang_pkgs:
                t, pkg = lang_pkgs[r]
                app.packages.append(pkg)
                placed.add(r)
                stack.extend(deps.get(r, []))

    # orphans aggregate per language type
    orphan_by_type: dict[str, Application] = {}
    for ref, (t, pkg) in lang_pkgs.items():
        if ref in placed:
            continue
        orphan_by_type.setdefault(t, Application(type=t)).packages.append(pkg)

    applications = [a for a in apps.values() if a.packages]
    applications += [orphan_by_type[t] for t in sorted(orphan_by_type)]
    applications.sort(key=lambda a: (a.type, a.file_path))

    blob.os = os_info
    if os_pkgs:
        blob.package_infos = [PackageInfo(packages=os_pkgs)]
    blob.applications = applications
    return blob, meta


def _cdx_app_props(c: dict) -> tuple[str, str]:
    app_type = fpath = ""
    for prop in c.get("properties") or []:
        if prop.get("name") == "aquasecurity:trivy:Type":
            app_type = prop.get("value", "")
        elif prop.get("name") == "aquasecurity:trivy:FilePath":
            fpath = prop.get("value", "")
    return app_type, fpath or c.get("name", "")


def _component_to_package(c: dict):
    purl_str = c.get("purl", "")
    if not purl_str:
        return None, None, None
    try:
        p = parse_purl(purl_str)
    except ValueError:
        _log.debug("unparseable purl", purl=purl_str)
        return None, None, None
    kind = purl_kind(p)
    if kind is None:
        return None, None, None
    pkg = Package(
        name=p.full_name,
        version=c.get("version", p.version),
        identifier=PkgIdentifier(purl=purl_str, bom_ref=c.get("bom-ref", "")),
    )
    if kind[0] == "os":
        pkg.arch = p.qualifiers.get("arch", "")
        epoch = p.qualifiers.get("epoch", "")
        if epoch.isdigit():
            pkg.epoch = int(epoch)
            pkg.src_epoch = int(epoch)
        ver = pkg.version
        if "-" in ver and p.type in ("deb", "rpm", "apk"):
            v, _, r = ver.rpartition("-")
            pkg.version, pkg.release = v, r
        for prop in c.get("properties") or []:
            pn, pv = prop.get("name", ""), prop.get("value", "")
            if pn == "aquasecurity:trivy:SrcName":
                pkg.src_name = pv
            elif pn == "aquasecurity:trivy:SrcVersion":
                pkg.src_version = pv
            elif pn == "aquasecurity:trivy:SrcRelease":
                pkg.src_release = pv
            elif pn == "aquasecurity:trivy:SrcEpoch" and pv.isdigit():
                pkg.src_epoch = int(pv)
            elif pn == "aquasecurity:trivy:LayerDiffID":
                pkg.layer.diff_id = pv
        if not pkg.src_name:
            pkg.src_name = pkg.name
        if not pkg.src_version:
            pkg.src_version = pkg.version
            pkg.src_release = pkg.release
    for prop in c.get("properties") or []:
        if prop.get("name") == "aquasecurity:trivy:PkgID":
            pkg.id = prop.get("value", "")
        elif prop.get("name") == "aquasecurity:trivy:FilePath":
            pkg.file_path = prop.get("value", "")
    for lic in c.get("licenses") or []:
        if not isinstance(lic, dict):
            continue
        inner = lic.get("license") or {}
        name = inner.get("name") or inner.get("id") or \
            lic.get("expression")
        if name:
            pkg.licenses.append(str(name))
    if not pkg.id:
        pkg.id = f"{pkg.name}@{c.get('version', p.version)}"
    return pkg, kind[0], (kind[1] if kind[0] == "lang" else p.type)


# ------------------------------------------------------------ SPDX


def parse_spdx_tag_value(text: str) -> dict:
    """SPDX tag-value -> the JSON-shaped document _decode_spdx consumes
    (reference supports both encodings; spdx/tvloader equivalent for the
    subset trivy emits)."""
    doc: dict = {"spdxVersion": "", "name": "", "packages": [],
                 "relationships": []}
    cur: dict | None = None
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        key, sep, val = line.partition(":")
        if not sep:
            continue
        key, val = key.strip(), val.strip()
        if key == "SPDXVersion":
            doc["spdxVersion"] = val
        elif key == "DocumentName":
            doc["name"] = val
        elif key == "PackageName":
            cur = {"name": val}
            doc["packages"].append(cur)
        elif key == "Relationship":
            parts = val.split()
            if len(parts) == 3:
                doc["relationships"].append({
                    "spdxElementId": parts[0],
                    "relationshipType": parts[1],
                    "relatedSpdxElement": parts[2],
                })
        elif cur is not None:
            if key == "SPDXID":
                cur["SPDXID"] = val
            elif key == "PackageVersion":
                cur["versionInfo"] = val
            elif key == "PackageSourceInfo":
                cur["sourceInfo"] = val
            elif key == "PrimaryPackagePurpose":
                cur["primaryPackagePurpose"] = val
            elif key == "PackageAttributionText":
                cur.setdefault("attributionTexts", []).append(val)
            elif key == "ExternalRef":
                parts = val.split(None, 2)
                if len(parts) == 3:
                    cur.setdefault("externalRefs", []).append({
                        "referenceCategory": parts[0],
                        "referenceType": parts[1],
                        "referenceLocator": parts[2],
                    })
    return doc


def _split_evr(evr: str) -> tuple[int, str, str]:
    """'[epoch:]ver[-rel]' -> (epoch, version, release)."""
    epoch = 0
    if ":" in evr:
        e, _, evr = evr.partition(":")
        if e.isdigit():
            epoch = int(e)
    ver, _, rel = evr.rpartition("-") if "-" in evr else (evr, "", "")
    return epoch, ver or evr, rel


def _decode_spdx(doc: dict) -> tuple[BlobInfo, SBOMMeta]:
    """SPDX document (reference pkg/sbom/spdx/unmarshal.go): element-ID
    prefixes classify packages (OperatingSystem / Application /
    Package); the PURL external ref is authoritative for identity,
    sourceInfo ('built package from: name evr') carries the source
    package, attributionTexts carry PkgID/layer info."""
    meta = SBOMMeta(artifact_type="spdx", artifact_name=doc.get("name", ""))
    blob = BlobInfo()
    os_info = OS()
    os_pkgs: list[Package] = []
    apps: dict[str, Application] = {}  # SPDXID -> Application
    lang_pkgs: dict[str, tuple[str, Package]] = {}
    orphan_by_type: dict[str, Application] = {}

    for sp in doc.get("packages") or []:
        spdx_id = str(sp.get("SPDXID", ""))
        purl_str = ""
        for ref in sp.get("externalRefs") or []:
            if ref.get("referenceType") == "purl":
                purl_str = ref.get("referenceLocator", "")
                break
        if spdx_id.startswith("SPDXRef-OperatingSystem") or \
                sp.get("primaryPackagePurpose") == "OPERATING-SYSTEM":
            os_info = OS(
                family=sp.get("name", ""), name=sp.get("versionInfo", "")
            )
            continue
        if spdx_id.startswith("SPDXRef-Application"):
            # two trivy encodings exist: older docs name the package
            # after the app TYPE with the lockfile path in sourceInfo;
            # current docs name it after the lockfile path (type is
            # inferred from member packages below)
            name = sp.get("name", "")
            src = str(sp.get("sourceInfo") or "")
            if src and not src.startswith(("application:",
                                           "package found in:")):
                apps[spdx_id] = Application(type=name, file_path=src)
            else:
                apps[spdx_id] = Application(type="", file_path=name)
            continue
        if not purl_str:
            continue
        # the purl is authoritative for version identity; versionInfo
        # renders the full EVR which may disagree with it
        c = {"purl": purl_str, "bom-ref": spdx_id}
        pkg, kind, type_str = _component_to_package(c)
        if pkg is None:
            continue
        src = str(sp.get("sourceInfo") or "")
        if src.startswith("built package from:"):
            parts = src[len("built package from:"):].strip().rsplit(" ", 1)
            if len(parts) == 2:
                pkg.src_name = parts[0]
                (pkg.src_epoch, pkg.src_version,
                 pkg.src_release) = _split_evr(parts[1])
        # current trivy emits PkgID/Layer info as SPDX annotations;
        # older releases used attributionTexts — read both (reference
        # unmarshal.go checks annotations first)
        texts = [str(a.get("comment", ""))
                 for a in sp.get("annotations") or []]
        texts += [str(t) for t in sp.get("attributionTexts") or []]
        for text in texts:
            key, _, val = text.partition(": ")
            if key == "PkgID":
                pkg.id = val
            elif key == "LayerDiffID":
                pkg.layer.diff_id = val
            elif key == "LayerDigest":
                pkg.layer.digest = val
        if kind == "os":
            os_pkgs.append(pkg)
        else:
            lang_pkgs[spdx_id] = (type_str, pkg)

    # relationships place language packages under their Application:
    # trivy links them with DEPENDS_ON (CONTAINS in older releases) —
    # any edge type counts as membership (reference unmarshal.go
    # parseRelationships)
    placed: set[str] = set()
    for rel in doc.get("relationships") or []:
        owner = str(rel.get("spdxElementId", ""))
        member = str(rel.get("relatedSpdxElement", ""))
        if owner in apps and member in lang_pkgs:
            app = apps[owner]
            t, pkg = lang_pkgs[member]
            if not app.type:
                app.type = t  # inferred from the member's purl ecosystem
            app.packages.append(pkg)
            placed.add(member)
    for ref, (t, pkg) in lang_pkgs.items():
        if ref not in placed:
            orphan_by_type.setdefault(
                t, Application(type=t)).packages.append(pkg)

    blob.os = os_info
    if os_pkgs:
        blob.package_infos = [PackageInfo(packages=os_pkgs)]
    applications = [a for a in apps.values() if a.packages]
    applications += [orphan_by_type[t] for t in sorted(orphan_by_type)]
    applications.sort(key=lambda a: (a.type, a.file_path))
    blob.applications = applications
    return blob, meta
