"""Retry policy with decorrelated jitter + per-scan deadline budgets.

The deadline is a *budget*, not a wall-clock timestamp: the client sends
the remaining budget (seconds, as decimal text) in the `X-Trivy-Deadline`
header, so client and server need no clock agreement. The server turns
the header back into a local Deadline and sheds work it cannot finish
(503 + Retry-After) instead of blocking the caller.

The current deadline propagates through the scan spine via a
thread-local scope (`deadline_scope`), so the RPC client and the local
driver's phase checkpoints see it without threading a parameter through
every signature. Scopes are per-thread: the CLI enters the scope inside
its scan worker thread, the server inside each request handler thread.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator

DEADLINE_HEADER = "X-Trivy-Deadline"


class DeadlineExceeded(Exception):
    """The per-scan deadline budget ran out."""

    def __init__(self, msg: str, budget_s: float | None = None):
        super().__init__(msg)
        self.budget_s = budget_s


class Deadline:
    """A monotonic budget with an injectable clock (testable)."""

    __slots__ = ("budget_s", "_clock", "_expires")

    def __init__(self, budget_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.budget_s = float(budget_s)
        self._clock = clock
        self._expires = clock() + self.budget_s

    @classmethod
    def after(cls, budget_s: float,
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(budget_s, clock)

    def remaining(self) -> float:
        return self._expires - self._clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "") -> None:
        if self.expired:
            where = f" during {what}" if what else ""
            raise DeadlineExceeded(
                f"deadline of {self.budget_s:.3f}s exhausted{where}",
                budget_s=self.budget_s)

    def header_value(self) -> str:
        return f"{max(self.remaining(), 0.0):.3f}"

    @classmethod
    def from_header(cls, value: str | None,
                    clock: Callable[[], float] = time.monotonic
                    ) -> "Deadline | None":
        if not value:
            return None
        try:
            budget = float(value)
        except ValueError:
            return None
        return cls(budget, clock)


_local = threading.local()


def current_deadline() -> Deadline | None:
    return getattr(_local, "deadline", None)


@contextlib.contextmanager
def deadline_scope(deadline: Deadline | None):
    """Make `deadline` ambient for this thread (None clears it — the
    degraded fallback path runs with the budget deliberately lifted)."""
    prev = current_deadline()
    _local.deadline = deadline
    try:
        yield deadline
    finally:
        _local.deadline = prev


def checkpoint(what: str = "") -> None:
    """Raise DeadlineExceeded if the ambient deadline has run out.
    Called between scan phases so a deadlined scan sheds promptly
    instead of finishing work nobody will wait for."""
    d = current_deadline()
    if d is not None:
        d.check(what)


@dataclass
class RetryPolicy:
    """Transient-failure retry with decorrelated jitter.

    delays() yields sleeps per the decorrelated-jitter recipe
    (sleep = min(cap, U(base, 3*prev))): successive waits spread out
    without the synchronized thundering herd of fixed exponential
    backoff. `sleep` and `seed` are injectable so tests are instant and
    deterministic.
    """

    attempts: int = 3
    base_s: float = 0.5
    cap_s: float = 10.0
    respect_retry_after: bool = True
    seed: int | None = None
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    def rng(self) -> random.Random:
        return random.Random(self.seed)

    def delays(self, rng: random.Random | None = None) -> Iterator[float]:
        rng = rng or self.rng()
        prev = self.base_s
        while True:
            prev = min(self.cap_s, rng.uniform(self.base_s, prev * 3.0))
            yield prev


def parse_retry_after(value: str | None) -> float | None:
    """HTTP Retry-After -> seconds (delta-seconds or HTTP-date)."""
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        from email.utils import parsedate_to_datetime
        import datetime

        when = parsedate_to_datetime(value)
        now = datetime.datetime.now(datetime.timezone.utc)
        return max(0.0, (when - now).total_seconds())
    except (TypeError, ValueError):
        return None
