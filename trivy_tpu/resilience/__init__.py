"""Resilience layer: deterministic fault injection, retry policy with
decorrelated jitter, per-scan deadline budgets, circuit breaking, and the
degraded local fallback driver (docs/resilience.md).

Everything in this package is stdlib-only so it can be imported from the
RPC hot path, the match engine, and tests without pulling in jax.
"""

from trivy_tpu.resilience.breaker import BreakerOpen, CircuitBreaker
from trivy_tpu.resilience.retry import (
    DEADLINE_HEADER,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    current_deadline,
    deadline_scope,
)

__all__ = [
    "BreakerOpen",
    "CircuitBreaker",
    "DEADLINE_HEADER",
    "Deadline",
    "DeadlineExceeded",
    "RetryPolicy",
    "current_deadline",
    "deadline_scope",
]
