"""Deterministic, config-driven fault injector for the scan spine.

A fault plan is a list of rules compiled from a compact spec string —
either installed programmatically (tests) or read from the
`TRIVY_TPU_FAULTS` environment variable (operators / CI fault matrices).
Instrumented call sites ask `fire(site)` which rules apply to the current
call; the injector itself never touches the network or the device, it
only tells the call site what to simulate.

Spec grammar (rules joined by ";" or ","):

    rule     := site ":" action [ "=" param ] [ "@" selector ]
    site     := "rpc" | "rpc.scan" | "rpc.cache" | "rpc.cache.PutBlob"
                | "engine" | "cache.write" | "db.install" | "fleet.scan"
                | "journal.append" | "sched.submit" | "analysis.fetch"
                | ...  (dotted, prefix-matched)
    action   := "drop" | "timeout" | "delay" | "error" | "corrupt"
                | "device-lost" | "kill" | "torn-write" | "bitflip"
    selector := N        fire on the Nth matching call only (1-based)
              | N "+"    fire on the Nth and every later call
              | N "-" M  fire on calls N..M inclusive
              | "p" F    fire with probability F (seeded, deterministic)
              | (none)   fire on every matching call
    seed     := "seed=" INT   (plan-wide RNG seed for "p" selectors;
                               defaults to TRIVY_TPU_FAULT_SEED, then 0)

Examples:

    TRIVY_TPU_FAULTS="rpc.scan:drop"             # remote scans never land
    TRIVY_TPU_FAULTS="rpc:error=503@1-2"         # first two RPCs get a 503
    TRIVY_TPU_FAULTS="rpc.scan:delay=0.2@3+"     # slow from the 3rd scan on
    TRIVY_TPU_FAULTS="seed=7;rpc:drop@p0.3"      # 30% drop, deterministic
    TRIVY_TPU_FAULTS="engine:device-lost@1"      # TPU dies on first batch
    TRIVY_TPU_FAULTS="fleet.scan:kill@2"         # SIGKILL on 2nd artifact
    TRIVY_TPU_FAULTS="cache.write:bitflip"       # every cache entry rots

Each rule keeps its own call counter, so selectors are deterministic per
rule regardless of how many rules share a site.

Durability fault kinds (docs/durability.md):

- ``kill``       the process dies (SIGKILL) when the rule fires — crash-
                 point testing for the atomic-install / journal paths.
                 Tests may flip to raise-mode (`set_kill_mode("raise")`)
                 so the "death" is an in-process `InjectedKill` that
                 unwinds without running recovery code.
- ``torn-write`` the payload handed to `mangle_write` is truncated
                 (param = fraction kept, default 0.5) — a torn disk
                 write or partial download.
- ``bitflip``    one bit of the payload is flipped (param = byte index,
                 default middle) — silent corruption a checksum must
                 catch.
"""

from __future__ import annotations

import os
import random
import re
import threading

from trivy_tpu.analysis.witness import make_lock
from dataclasses import dataclass, field

ENV_VAR = "TRIVY_TPU_FAULTS"
SEED_ENV_VAR = "TRIVY_TPU_FAULT_SEED"

ACTIONS = {"drop", "timeout", "delay", "error", "corrupt", "device-lost",
           "kill", "torn-write", "bitflip"}

# The site grammar as STRUCTURED data — one source of truth consumed by
# the linter (`fault-site` rule), docs/resilience.md, and tests.  Each
# entry is (site, actions-the-site's-call-site-handles).  Sites are
# prefix-matched at fire() time, so "rpc" covers every rpc.* child; the
# atomic-write sites (cache.write, db.save, ...) also fire a ``kill``
# probe at "<site>.commit" between the tmp write and the rename.
SITES: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("rpc", ("drop", "timeout", "delay", "error", "corrupt")),
    ("rpc.scan", ("drop", "timeout", "delay", "error", "corrupt")),
    ("rpc.cache", ("drop", "timeout", "delay", "error", "corrupt")),
    ("rpc.wire", ("drop", "delay", "error", "corrupt")),
    ("engine", ("device-lost",)),
    ("engine.device", ("drop", "delay", "device-lost")),
    ("engine.shard", ("drop", "delay", "error", "device-lost")),
    ("engine.host", ("drop", "delay", "error", "device-lost")),
    ("sched.submit", ("drop", "delay", "error")),
    ("secret.device", ("drop", "delay", "error", "device-lost")),
    ("fleet.endpoint", ("drop", "timeout", "delay", "error")),
    ("fleet.rollout", ("delay", "error", "kill")),
    ("fleet.controller", ("drop", "delay", "error", "kill")),
    ("analysis.fetch", ("drop", "delay", "error", "kill")),
    ("analysis.lane", ("drop", "delay", "error", "kill")),
    ("fleet.scan", ("kill",)),
    ("journal.append", ("kill", "torn-write", "bitflip")),
    ("monitor.index", ("drop", "error", "kill", "torn-write", "bitflip")),
    ("monitor.rematch", ("drop", "delay", "error", "kill")),
    ("db.download", ("torn-write", "bitflip")),
    ("db.install.extract", ("kill",)),
    ("db.install.promote", ("kill",)),
    ("db.save", ("kill", "torn-write", "bitflip")),
    ("db.save.metadata", ("kill", "torn-write", "bitflip")),
    ("cache.write", ("kill", "torn-write", "bitflip")),
    ("compile_cache.save", ("kill", "torn-write", "bitflip")),
    ("report.write", ("kill", "torn-write", "bitflip")),
)


class FaultError(Exception):
    """Base class for injected faults."""


class DeviceLost(FaultError):
    """Injected accelerator loss (site ``engine``)."""


class InjectedKill(BaseException):
    """Raise-mode stand-in for SIGKILL (site-level ``kill`` fault).

    Deliberately a BaseException: a crash does not run `except
    Exception` cleanup handlers, and neither should its simulation —
    state on disk must be exactly what a real kill would leave."""


class InjectedHTTPError(FaultError):
    """Injected HTTP error response (site ``rpc*``, action ``error``)."""

    def __init__(self, code: int):
        super().__init__(f"injected HTTP {code}")
        self.code = code


class FaultSpecError(ValueError):
    """The fault spec string does not parse."""


_RULE_RX = re.compile(
    r"(?P<site>[A-Za-z0-9_.]+):(?P<action>[a-z-]+)"
    r"(?:=(?P<param>[0-9.]+))?"
    r"(?:@(?P<sel>[0-9p.+-]+))?$"
)


@dataclass
class Rule:
    site: str
    action: str
    param: float | None = None
    start: int = 1
    stop: int | None = None  # inclusive; None = open-ended
    prob: float | None = None
    calls: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def fires(self, n: int, rng: random.Random) -> bool:
        if self.prob is not None:
            return rng.random() < self.prob
        if n < self.start:
            return False
        return self.stop is None or n <= self.stop

    def matches(self, site: str) -> bool:
        return site == self.site or site.startswith(self.site + ".")

    def token(self) -> str:
        """The rule back as a spec token — `from_spec(token())` rebuilds
        an equal rule, so shrunk schedules paste straight into
        TRIVY_TPU_FAULTS."""
        out = f"{self.site}:{self.action}"
        if self.param is not None:
            p = self.param
            out += f"={int(p)}" if p == int(p) else f"={p}"
        if self.prob is not None:
            out += f"@p{self.prob}"
        elif self.start == self.stop:
            out += f"@{self.start}"
        elif self.stop is not None:
            out += f"@{self.start}-{self.stop}"
        elif self.start != 1:
            out += f"@{self.start}+"
        return out


def _parse_selector(sel: str | None) -> tuple[int, int | None, float | None]:
    """-> (start, stop, prob)."""
    if sel is None:
        return 1, None, None
    if sel.startswith("p"):
        try:
            prob = float(sel[1:])
        except ValueError:
            raise FaultSpecError(f"bad probability selector {sel!r}")
        if not 0.0 <= prob <= 1.0:
            raise FaultSpecError(f"probability out of [0,1]: {sel!r}")
        return 1, None, prob
    if sel.endswith("+"):
        return int(sel[:-1]), None, None
    if "-" in sel:
        lo, _, hi = sel.partition("-")
        start, stop = int(lo), int(hi)
        if stop < start:
            raise FaultSpecError(f"empty selector range {sel!r}")
        return start, stop, None
    n = int(sel)
    return n, n, None


class FaultPlan:
    """A compiled fault spec; thread-safe (call counters live under one
    lock so concurrent RPC workers see a consistent ordinal per rule)."""

    def __init__(self, rules: list[Rule], seed: int = 0):
        self.rules = list(rules)
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = make_lock("resilience.faults._lock")

    def to_spec(self) -> str:
        """Round-trip back to a TRIVY_TPU_FAULTS string (seed token first
        so a pasted repro replays the same `@pF` draws)."""
        toks = [f"seed={self.seed}"] if self.seed else []
        toks += [r.token() for r in self.rules]
        return ";".join(toks)

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Compile a spec string.  When the spec carries no `seed=` token,
        the plan-wide RNG seed for `@pF` selectors falls back to
        TRIVY_TPU_FAULT_SEED (default 0), so probabilistic specs replay
        deterministically without editing the spec itself."""
        rules: list[Rule] = []
        try:
            seed = int(os.environ.get(SEED_ENV_VAR, "0"))
        except ValueError:
            raise FaultSpecError(
                f"bad {SEED_ENV_VAR}={os.environ.get(SEED_ENV_VAR)!r}")
        for tok in re.split(r"[;,]", spec):
            tok = tok.strip()
            if not tok:
                continue
            if tok.startswith("seed="):
                try:
                    seed = int(tok[5:])
                except ValueError:
                    raise FaultSpecError(f"bad seed {tok!r}")
                continue
            m = _RULE_RX.match(tok)
            if not m:
                raise FaultSpecError(f"bad fault rule {tok!r}")
            action = m.group("action")
            if action not in ACTIONS:
                raise FaultSpecError(
                    f"unknown fault action {action!r} "
                    f"(valid: {', '.join(sorted(ACTIONS))})")
            try:
                start, stop, prob = _parse_selector(m.group("sel"))
            except ValueError as exc:
                raise FaultSpecError(f"bad selector in {tok!r}: {exc}")
            param = m.group("param")
            rules.append(Rule(
                site=m.group("site"), action=action,
                param=float(param) if param is not None else None,
                start=start, stop=stop, prob=prob,
            ))
        return cls(rules, seed=seed)

    def fire(self, site: str) -> list[Rule]:
        """Which rules apply to this call at `site`? Increments the call
        counter of every matching rule, firing or not. Every firing is
        counted in trivy_tpu_fault_injections_total{site,action} so a
        fault-matrix run's metrics show exactly what was injected."""
        out: list[Rule] = []
        with self._lock:
            for r in self.rules:
                if r.matches(site):
                    r.calls += 1
                    if r.fires(r.calls, self._rng):
                        r.fired += 1
                        out.append(r)
        if out:
            from trivy_tpu.obs import metrics as obs_metrics

            for r in out:
                obs_metrics.FAULT_FIRES.inc(site=r.site, action=r.action)
        return out


# ------------------------------------------------------------ module state

_installed: FaultPlan | None = None
_env_cache: tuple[str, FaultPlan] | None = None
_kill_mode = "sigkill"  # "sigkill" (real death) | "raise" (InjectedKill)


def install(plan: FaultPlan) -> FaultPlan:
    """Activate a plan for the whole process (tests)."""
    global _installed
    _installed = plan
    return plan


def install_spec(spec: str) -> FaultPlan:
    return install(FaultPlan.from_spec(spec))


def reset() -> None:
    global _installed, _env_cache, _kill_mode
    _installed = None
    _env_cache = None
    _kill_mode = "sigkill"


def set_kill_mode(mode: str) -> None:
    """"sigkill" (default): a firing ``kill`` rule really SIGKILLs the
    process — for subprocess crash tests. "raise": it raises
    InjectedKill instead, so in-process tests can crash a write path at
    an exact point and then assert on the surviving on-disk state."""
    if mode not in ("sigkill", "raise"):
        raise ValueError(f"unknown kill mode {mode!r}")
    global _kill_mode
    _kill_mode = mode


def active() -> FaultPlan | None:
    """The installed plan, else one compiled from TRIVY_TPU_FAULTS."""
    if _installed is not None:
        return _installed
    spec = os.environ.get(ENV_VAR, "")
    if not spec:
        return None
    global _env_cache
    if _env_cache is None or _env_cache[0] != spec:
        _env_cache = (spec, FaultPlan.from_spec(spec))
    return _env_cache[1]


def fire(site: str) -> list[Rule]:
    plan = active()
    return plan.fire(site) if plan is not None else []


def validate_env() -> None:
    """Compile the TRIVY_TPU_FAULTS spec now so an operator typo fails
    at process startup with a clean FaultSpecError naming the bad rule,
    not mid-scan at the first instrumented call site."""
    spec = os.environ.get(ENV_VAR, "")
    if spec:
        FaultPlan.from_spec(spec)


# ------------------------------------------------------------ site helpers

def rpc_site(path: str) -> str:
    """Map an RPC URL path onto a dotted fault site."""
    tail = path.rsplit("/", 1)[-1]
    if "/trivy.cache." in path:
        return f"rpc.cache.{tail}"
    if tail == "Scan":
        return "rpc.scan"
    return f"rpc.{tail}"


def check_device(site: str = "engine") -> None:
    """Raise DeviceLost when a device-lost rule fires for `site`."""
    for r in fire(site):
        if r.action == "device-lost":
            raise DeviceLost(f"injected device loss at {site}")


def corrupt_bytes(raw: bytes) -> bytes:
    """Deterministically mangle a response body so decoding fails."""
    return b"\xff\x00corrupted\x00" + raw[: len(raw) // 2]


def check_kill(site: str, rules: list[Rule] | None = None) -> None:
    """Die (or raise InjectedKill in raise-mode) when a ``kill`` rule
    fires for `site` — the crash-point hook of the durability layer.
    Pass pre-fired `rules` to share one probe (one ordinal increment)
    with a mangle_write at the same site."""
    for r in (fire(site) if rules is None else rules):
        if r.action != "kill":
            continue
        if _kill_mode == "raise":
            raise InjectedKill(f"injected kill at {site}")
        import signal

        os.kill(os.getpid(), signal.SIGKILL)


def mangle_write(site: str, data: bytes,
                 rules: list[Rule] | None = None) -> bytes:
    """Apply firing ``torn-write`` / ``bitflip`` rules to a payload
    about to hit disk (or just fetched from the network). Deterministic:
    torn-write keeps the first `param` fraction (default 0.5); bitflip
    flips bit 0 of the byte at `param` (default the middle byte)."""
    for r in (fire(site) if rules is None else rules):
        if r.action == "torn-write":
            keep = 0.5 if r.param is None else min(max(r.param, 0.0), 1.0)
            data = data[: int(len(data) * keep)]
        elif r.action == "bitflip" and data:
            idx = (len(data) // 2 if r.param is None
                   else int(r.param) % len(data))
            data = data[:idx] + bytes([data[idx] ^ 0x01]) + data[idx + 1:]
    return data
