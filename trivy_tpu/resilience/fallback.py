"""Degrading drivers: remote-first scan with a local completion
guarantee.

FallbackDriver implements the scanner Driver protocol
(trivy_tpu/scanner/scan.py) around a primary driver (typically
rpc.client.RemoteDriver). It degrades to a lazily-built LocalDriver when
the circuit breaker is open, the deadline budget is already exhausted,
or the primary scan fails — and records why in `degraded_reason`, which
Scanner.scan_artifact stamps into Report.metadata.degraded so consumers
can tell a fallback scan from a primary one.

FallbackCache mirrors every cache write into a local cache while
forwarding to the remote cache best-effort through the same breaker, so
the blobs a degraded scan needs are always present locally. Its
missing_blobs answer is the UNION of both sides' missing sets: a blob
the server already has but the mirror lacks is still (re)analyzed, which
keeps the local fallback self-sufficient.
"""

from __future__ import annotations

from typing import Callable

from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.resilience.breaker import CircuitBreaker
from trivy_tpu.resilience.retry import (
    DeadlineExceeded,
    current_deadline,
    deadline_scope,
)

_log = logger("resilience")


class FallbackDriver:
    """Driver that prefers `primary` and degrades to a local scan."""

    def __init__(self, primary, local_factory: Callable[[], object],
                 breaker: CircuitBreaker | None = None):
        self.primary = primary
        self._local_factory = local_factory
        self._local = None
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, recovery_s=30.0, name="rpc")
        self.degraded_reason: str | None = None

    def local(self):
        if self._local is None:
            self._local = self._local_factory()
        return self._local

    def scan(self, target, artifact_key, blob_keys, options):
        self.degraded_reason = None
        reason = self._primary_blocked()
        if reason is None:
            try:
                out = self.primary.scan(
                    target, artifact_key, blob_keys, options)
            except DeadlineExceeded as exc:
                # the CALLER's budget ran out — that says nothing about
                # remote health, so it must not push the breaker open
                reason = str(exc)
            except Exception as exc:
                self.breaker.record_failure()
                reason = f"remote scan failed: {exc}"
            else:
                self.breaker.record_success()
                return out
        _log.warn("degrading to local scan", reason=reason)
        obs_metrics.DEGRADED_TOTAL.inc(component="driver")
        # the fallback is the completion guarantee: it runs with the
        # budget lifted (a deadlined local scan would shed at the next
        # checkpoint and the caller would get nothing at all)
        with deadline_scope(None):
            out = self.local().scan(target, artifact_key, blob_keys, options)
        self.degraded_reason = reason
        return out

    def _primary_blocked(self) -> str | None:
        d = current_deadline()
        if d is not None and d.expired:
            return (f"deadline budget ({d.budget_s:.3f}s) exhausted "
                    "before remote dispatch")
        if not self.breaker.allow():
            return (f"circuit breaker open "
                    f"(retry in {self.breaker.retry_in():.1f}s)")
        return None


class FallbackCache:
    """ArtifactCache that writes locally and forwards best-effort."""

    def __init__(self, remote, local, breaker: CircuitBreaker | None = None):
        self.remote = remote
        self.local = local
        self.breaker = breaker
        self._warned = False

    # ------------------------------------------------------------ writes

    def put_artifact(self, artifact_id: str, info) -> None:
        self.local.put_artifact(artifact_id, info)
        self._forward("put_artifact", artifact_id, info)

    def put_blob(self, blob_id: str, blob) -> None:
        self.local.put_blob(blob_id, blob)
        self._forward("put_blob", blob_id, blob)

    def delete_blobs(self, blob_ids: list[str]) -> None:
        self.local.delete_blobs(blob_ids)
        self._forward("delete_blobs", blob_ids)

    def _forward(self, method: str, *args) -> None:
        if self.breaker is not None and not self.breaker.allow():
            return  # open breaker: don't burn the budget on a dead remote
        try:
            getattr(self.remote, method)(*args)
        except Exception as exc:
            if self.breaker is not None:
                self.breaker.record_failure()
            obs_metrics.DEGRADED_TOTAL.inc(component="cache")
            if not self._warned:
                self._warned = True
                _log.warn("remote cache unavailable; mirroring locally",
                          op=method, err=str(exc))
        else:
            if self.breaker is not None:
                self.breaker.record_success()

    # ------------------------------------------------------------ reads

    def missing_blobs(self, artifact_id: str, blob_ids: list[str]):
        l_missing_a, l_missing = self.local.missing_blobs(
            artifact_id, blob_ids)
        if self.breaker is not None and not self.breaker.allow():
            return l_missing_a, l_missing
        try:
            r_missing_a, r_missing = self.remote.missing_blobs(
                artifact_id, blob_ids)
        except Exception as exc:
            if self.breaker is not None:
                self.breaker.record_failure()
            _log.warn("remote missing_blobs failed; using local answer",
                      err=str(exc))
            return l_missing_a, l_missing
        if self.breaker is not None:
            self.breaker.record_success()
        missing_set = set(r_missing) | set(l_missing)
        missing = [b for b in blob_ids if b in missing_set]
        return (r_missing_a or l_missing_a), missing

    def get_artifact(self, artifact_id: str) -> dict:
        return self.local.get_artifact(artifact_id)

    def get_blob(self, blob_id: str) -> dict:
        return self.local.get_blob(blob_id)

    def close(self) -> None:
        self.local.close()
        try:
            self.remote.close()
        except Exception:
            pass
