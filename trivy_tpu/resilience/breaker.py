"""Closed / open / half-open circuit breaker with an injectable clock.

State machine:

    closed    -- N consecutive failures -->        open
    open      -- recovery_s elapsed -->            half-open
    half-open -- trial success -->                 closed
    half-open -- trial failure -->                 open (timer restarts)

In half-open at most `half_open_max` trial calls are admitted until one
of them settles; everything else is shed. All transitions happen under
one lock so concurrent callers observe a consistent state.
"""

from __future__ import annotations

import threading

from trivy_tpu.analysis.witness import make_lock
import time
from typing import Callable

from trivy_tpu.obs import metrics as obs_metrics

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerOpen(Exception):
    """Call refused because the breaker is open."""

    def __init__(self, name: str, retry_in: float):
        super().__init__(
            f"circuit breaker {name!r} is open (retry in {retry_in:.1f}s)")
        self.retry_in = retry_in


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 5, recovery_s: float = 30.0,
                 half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "breaker"):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.recovery_s = recovery_s
        self.half_open_max = half_open_max
        self.name = name
        self._clock = clock
        self._lock = make_lock("resilience.breaker._lock")
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._trials = 0
        obs_metrics.BREAKER_STATE.set(0, name=name)

    def _set_state(self, state: str) -> None:
        # lock held by caller; publishes the trivy_tpu_breaker_state
        # gauge + transition counter on every actual state change
        if state == self._state:
            return
        self._state = state
        obs_metrics.BREAKER_STATE.set(_STATE_VALUE[state], name=self.name)
        obs_metrics.BREAKER_TRANSITIONS.inc(name=self.name, state=state)

    # ------------------------------------------------------------ state

    @property
    def state(self) -> str:
        with self._lock:
            self._tick()
            return self._state

    def retry_in(self) -> float:
        """Seconds until an open breaker admits a trial call (0 if it
        already would)."""
        with self._lock:
            self._tick()
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._opened_at + self.recovery_s
                       - self._clock())

    def _tick(self) -> None:
        # lock held by caller
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.recovery_s:
            self._set_state(HALF_OPEN)
            self._trials = 0

    def _trip(self) -> None:
        # lock held by caller
        self._set_state(OPEN)
        self._opened_at = self._clock()
        self._failures = 0
        self._trials = 0

    # ------------------------------------------------------------ calls

    def allow(self) -> bool:
        """May a call proceed right now? Half-open admissions consume a
        trial slot; callers MUST follow up with record_success or
        record_failure."""
        with self._lock:
            self._tick()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and self._trials < self.half_open_max:
                self._trials += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._tick()
            self._set_state(CLOSED)
            self._failures = 0
            self._trials = 0

    def record_failure(self) -> None:
        with self._lock:
            self._tick()
            if self._state == HALF_OPEN:
                self._trip()  # the trial failed: back to open, timer reset
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._trip()

    def call(self, fn, *args, **kwargs):
        """Run fn through the breaker; raises BreakerOpen when shed."""
        if not self.allow():
            raise BreakerOpen(self.name, self.retry_in())
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out
