"""Append-only fleet-scan journal (docs/durability.md).

One JSONL file per fleet run. The first record is a header naming the
subcommand, the full target list, and a fingerprint of every scan-
affecting option; after it, per-artifact lifecycle records:

    {"kind": "pending", "target": t}            enqueued
    {"kind": "running", "target": t}            a worker picked it up
    {"kind": "layer", "blob": blob_id}          a layer analysis landed
                                                durably in the cache
    {"kind": "done", "target": t,
     "digest": "sha256:…", "report": {…}}       finished; report embedded
    {"kind": "failed", "target": t, "error": e} scan raised

Layer records are fleet-wide (blob ids are content-addressed, so one
record covers every image sharing that layer): a resumed crawl replays
them as dedupe hints and skips re-journaling, and the analysis pipeline
counts cache hits on them as journal-replayed layers.

Every append is flushed + fsynced before the writer proceeds, so the
journal is a write-ahead log of fleet progress: after SIGKILL, replay
yields exactly the set of artifacts whose reports are durable. The
`digest` is the sha256 of the canonical report JSON — a bit-flipped
`done` record is detected at replay and the artifact re-runs.

Replay is torn-tail tolerant: a record that did not finish hitting the
disk (the common crash artifact) simply did not happen.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading

from trivy_tpu.analysis.witness import make_lock

from trivy_tpu.log import logger
from trivy_tpu.resilience import faults

_log = logger("journal")

JOURNAL_VERSION = 1
FAULT_SITE = "journal.append"


class JournalError(Exception):
    pass


def canonical_json(doc: dict) -> str:
    """One byte-stable rendering per document: digest computation and
    resume-time re-rendering must agree."""
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def report_digest(doc: dict) -> str:
    return "sha256:" + hashlib.sha256(canonical_json(doc).encode()).hexdigest()


def options_fingerprint(command: str, args) -> str:
    """Hash of every option that changes scan results. A journal resumed
    under different options would merge skew into the report — refuse
    instead (the Vexed-by-VEX-tools failure mode, arXiv:2503.14388)."""
    payload = {
        "command": command,
        "scanners": getattr(args, "scanners", ""),
        "pkg_types": getattr(args, "pkg_types", ""),
        "severity": getattr(args, "severity", None),
        "ignore_unfixed": getattr(args, "ignore_unfixed", False),
        "ignore_status": getattr(args, "ignore_status", None),
        "ignorefile": getattr(args, "ignorefile", None),
        "ignore_policy": getattr(args, "ignore_policy", None),
        "list_all_pkgs": getattr(args, "list_all_pkgs", False),
        "dependency_tree": getattr(args, "dependency_tree", False),
        "include_dev_deps": getattr(args, "include_dev_deps", False),
        "show_suppressed": getattr(args, "show_suppressed", False),
        "vex": list(getattr(args, "vex", []) or []),
        "skip_files": list(getattr(args, "skip_files", []) or []),
        "skip_dirs": list(getattr(args, "skip_dirs", []) or []),
        "file_patterns": list(getattr(args, "file_patterns", []) or []),
        "secret_config": getattr(args, "secret_config", None),
        "sbom_sources": getattr(args, "sbom_sources", ""),
    }
    digest = hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()
    return f"sha256:{digest}"


class ScanJournal:
    """Writer + replayer for one fleet journal file."""

    def __init__(self, path: str, header: dict):
        self.path = path
        self.header = header
        self._lock = make_lock("durability.journal._lock")
        self._layer_lock = make_lock("durability.journal._layer_lock")
        self._fh = None
        self.done: dict[str, dict] = {}
        self.failed: dict[str, str] = {}
        self.layers: set[str] = set()

    # ------------------------------------------------------------ open

    @classmethod
    def create(cls, path: str, command: str, targets: list[str],
               fingerprint: str) -> "ScanJournal":
        if os.path.exists(path):
            raise JournalError(
                f"journal {path} already exists; pass --resume to continue "
                "it or choose a fresh path")
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        header = {"kind": "header", "v": JOURNAL_VERSION,
                  "command": command, "fingerprint": fingerprint,
                  "targets": list(targets)}
        j = cls(path, header)
        j._fh = open(path, "ab")
        j._append(header)
        for t in targets:
            j._append({"kind": "pending", "target": t})
        return j

    @classmethod
    def resume(cls, path: str) -> "ScanJournal":
        """Replay an existing journal, tolerate a torn tail, verify every
        embedded report digest, and reopen for appending.

        A tail without a trailing newline is the signature of a write
        that never finished — it is dropped from the replay AND
        truncated from the file, so the next append starts a fresh line
        instead of merging with (and thereby destroying) the fragment.
        A newline-terminated line that fails to parse is mid-file
        corruption: warned about, skipped, and left in place (it is
        line-bounded, so later records are unaffected)."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise JournalError(f"cannot read journal {path}: {e}")
        durable_end = len(raw)
        if raw and not raw.endswith(b"\n"):
            durable_end = raw.rfind(b"\n") + 1
            _log.debug(
                f"dropping torn journal tail ({len(raw) - durable_end} "
                "bytes past the last complete record)")
            raw = raw[:durable_end]
        records: list[dict] = []
        for i, line in enumerate(raw.split(b"\n")):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                # line-bounded but unreadable: disk rot, not a torn
                # write — surface it (the record's artifact re-runs)
                _log.warn("skipping corrupt journal record",
                          path=path, line=i + 1)
                continue
            records.append(rec)
        if not records or records[0].get("kind") != "header":
            raise JournalError(f"journal {path} has no header record")
        header = records[0]
        if header.get("v") != JOURNAL_VERSION:
            raise JournalError(
                f"journal {path} is version {header.get('v')}, "
                f"this build writes v{JOURNAL_VERSION}")
        j = cls(path, header)
        running: set[str] = set()
        for rec in records[1:]:
            kind, target = rec.get("kind"), rec.get("target")
            if kind == "running" and target:
                running.add(target)
            if kind == "done" and target:
                doc = rec.get("report")
                if not isinstance(doc, dict) or \
                        report_digest(doc) != rec.get("digest"):
                    _log.warn("journal report failed digest check; "
                              "artifact will re-run", target=target)
                    continue
                j.done[target] = doc
                j.failed.pop(target, None)
            elif kind == "failed" and target:
                if target not in j.done:
                    j.failed[target] = rec.get("error", "")
            elif kind == "layer" and rec.get("blob"):
                j.layers.add(rec["blob"])
        # artifacts that were mid-scan at the crash (running, never
        # done/failed): they re-run, but the distinction matters to an
        # operator reading the resume log
        inflight = running - set(j.done) - set(j.failed)
        if inflight:
            _log.info("journal has artifacts that were in flight at the "
                      "crash; they will re-run", count=len(inflight))
        j._fh = open(path, "r+b")
        j._fh.truncate(durable_end)  # torn fragment must not prefix the
        j._fh.seek(0, os.SEEK_END)   # next append
        return j

    # ------------------------------------------------------------ props

    @property
    def targets(self) -> list[str]:
        return list(self.header.get("targets") or [])

    @property
    def command(self) -> str:
        return self.header.get("command", "")

    @property
    def fingerprint(self) -> str:
        return self.header.get("fingerprint", "")

    # ------------------------------------------------------------ write

    def _append(self, rec: dict) -> None:
        # NOT canonical_json: the embedded report must round-trip with
        # its key order intact or a resumed merged report would not be
        # byte-identical to an uninterrupted one (digests are computed
        # over the canonical form, so verification is order-free)
        line = (json.dumps(rec, separators=(",", ":")) + "\n").encode()
        # one fault probe per append: rule ordinals count appends
        # (1=header, then pending/running/done records in write order)
        rules = faults.fire(FAULT_SITE)
        faults.check_kill(FAULT_SITE, rules=rules)
        line = faults.mangle_write(FAULT_SITE, line, rules=rules)
        with self._lock:
            if self._fh is None:
                raise JournalError("journal is closed")
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())

    def mark_running(self, target: str) -> None:
        self._append({"kind": "running", "target": target})

    def mark_layer(self, blob_id: str) -> None:
        """Record one durable layer analysis (called after put_blob
        returns, so the cache entry exists when the record does). Each
        blob id is journaled once per fleet, however many images share
        it — repeats and replayed layers are no-ops."""
        if blob_id in self.layers:
            return
        with self._layer_lock:
            if blob_id in self.layers:
                return
            self.layers.add(blob_id)
            self._append({"kind": "layer", "blob": blob_id})

    def mark_done(self, target: str, report_doc: dict) -> None:
        self._append({"kind": "done", "target": target,
                      "digest": report_digest(report_doc),
                      "report": report_doc})
        self.done[target] = report_doc
        self.failed.pop(target, None)

    def mark_failed(self, target: str, error: str) -> None:
        self._append({"kind": "failed", "target": target, "error": error})
        self.failed[target] = error

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "ScanJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
