"""Durability layer: crash-safe state for every durable path in the
scanner (docs/durability.md).

- `atomic` — tmp+fsync+rename writes, sha256 checksum framing, stale-tmp
  sweeping, whole-tree fsync for staged directories
- `journal` — append-only JSONL fleet-scan journal with torn-tail
  tolerant replay (`trivy-tpu <kind> --targets … --journal/--resume`)
- `appendlog` — the generic fsynced JSONL append-log primitive the
  journal pioneered, reused by the monitor's package→artifact index

Stdlib-only so it can be imported from the cache, the DB lifecycle, the
server, and tests without pulling in jax.
"""

from trivy_tpu.durability.appendlog import AppendLog, AppendLogError
from trivy_tpu.durability.atomic import (
    CorruptEntry,
    atomic_write,
    frame,
    fsync_dir,
    fsync_tree,
    sweep_stale_tmp,
    unframe,
)
from trivy_tpu.durability.journal import (
    JournalError,
    ScanJournal,
    options_fingerprint,
    report_digest,
)

__all__ = [
    "AppendLog",
    "AppendLogError",
    "CorruptEntry",
    "JournalError",
    "ScanJournal",
    "atomic_write",
    "frame",
    "fsync_dir",
    "fsync_tree",
    "options_fingerprint",
    "report_digest",
    "sweep_stale_tmp",
    "unframe",
]
