"""Crash-safe write primitives (docs/durability.md).

Every durable state path in the system — cache entries, advisory-DB
files, journal segments — funnels through these helpers so the
durability contract lives in one place:

- a reader never observes a half-written file (tmp + fsync + rename);
- silent corruption is detectable (sha256 footer on framed payloads);
- crash points are deterministically testable (resilience.faults
  ``kill`` / ``torn-write`` / ``bitflip`` rules keyed by write site).

Stdlib-only, importable from any layer without pulling in jax.
"""

from __future__ import annotations

import contextlib
import errno
import hashlib
import os
import uuid

from trivy_tpu.resilience import faults

# footer marker for checksummed payloads: <body> "\n#sha256:" <hex>
CHECKSUM_MARK = b"\n#sha256:"


class CorruptEntry(Exception):
    """A framed payload failed its checksum (or never finished)."""


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename/creation inside it is durable.
    Best-effort on platforms that refuse O_RDONLY dir fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        with contextlib.suppress(OSError):
            os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes, fault_site: str = "") -> None:
    """Write `data` to `path` atomically: unique tmp sibling, fsync,
    rename over the destination, fsync the directory.

    `fault_site` names the write for the fault injector: torn-write /
    bitflip rules mangle the payload (simulating rot the reader must
    catch), and a ``kill`` rule at ``<site>.commit`` dies after the tmp
    file is durable but before the rename — proving a crash there leaves
    the previous version intact and only a stale tmp behind."""
    if fault_site:
        data = faults.mangle_write(fault_site, data)
    d = os.path.dirname(path) or "."
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp-{uuid.uuid4().hex}")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
    with os.fdopen(fd, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    if fault_site:
        faults.check_kill(fault_site + ".commit")
    os.replace(tmp, path)
    fsync_dir(d)


def frame(body: bytes) -> bytes:
    """Append the sha256 checksum footer the reader verifies."""
    return body + CHECKSUM_MARK + hashlib.sha256(body).hexdigest().encode()


def unframe(raw: bytes) -> bytes:
    """Strip and verify the checksum footer.

    Raises CorruptEntry on a bad or truncated footer. Payloads without
    any footer are returned as-is — pre-durability writers produced
    bare JSON, and their entries must keep loading (the caller's parser
    is the integrity check for those)."""
    body, sep, footer = raw.rpartition(CHECKSUM_MARK)
    if not sep:
        return raw
    if hashlib.sha256(body).hexdigest().encode() != footer.strip():
        raise CorruptEntry("checksum footer mismatch")
    return body


# a tmp file this old cannot belong to a live writer; younger ones
# might (a concurrently starting process must not unlink an in-flight
# sibling out from under its os.replace)
STALE_TMP_AGE_S = 3600.0


def sweep_stale_tmp(directory: str, min_age_s: float = STALE_TMP_AGE_S) -> int:
    """Remove leftover atomic-write tmp files (a crash between fsync and
    rename orphans exactly one) older than `min_age_s` — the age gate
    keeps a startup sweep from racing a live writer. Returns how many
    were removed."""
    import time

    removed = 0
    cutoff = time.time() - min_age_s
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        if not (name.startswith(".") and ".tmp-" in name):
            continue
        p = os.path.join(directory, name)
        with contextlib.suppress(FileNotFoundError, IsADirectoryError):
            try:
                if os.stat(p).st_mtime > cutoff:
                    continue
                os.unlink(p)
                removed += 1
            except OSError as e:  # pragma: no cover - platform specific
                if e.errno != errno.EISDIR:
                    raise
    return removed


def fsync_tree(root: str) -> None:
    """fsync every regular file under `root`, then the directories —
    used before atomically renaming a fully-staged directory into
    place (DB generation install)."""
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            p = os.path.join(dirpath, name)
            try:
                fd = os.open(p, os.O_RDONLY)
            except OSError:
                continue
            try:
                with contextlib.suppress(OSError):
                    os.fsync(fd)
            finally:
                os.close(fd)
        fsync_dir(dirpath)
