"""Generic fsynced JSONL append log (docs/durability.md).

The fleet ScanJournal (journal.py) proved the shape: an append-only
JSONL file whose first record is a header, every append flushed+fsynced
before the writer proceeds, and a replay that tolerates a torn tail
(the signature crash artifact) by truncating it.  The monitor's
package→artifact index needs the same write-ahead discipline with a
different record schema, so the mechanics live here once.

Contract:

- ``append`` is durable-when-returned: the record hit the disk (fsync)
  before control comes back.  An injected ``kill`` at the instrumented
  fault site dies *before* the write, an injected ``torn-write`` /
  ``bitflip`` mangles the payload — exactly the journal.append matrix.
- ``replay`` truncates an unterminated tail (the write never happened),
  skips line-bounded unparsable records with a warning (mid-file rot —
  later records are unaffected), and returns the surviving records.
- ``rewrite`` compacts the log: the full replacement content is
  published atomically (tmp + fsync + rename), so a crash mid-compact
  leaves the previous log intact.
"""

from __future__ import annotations

import json
import os

from trivy_tpu.analysis.witness import make_lock
from trivy_tpu.durability import atomic
from trivy_tpu.log import logger
from trivy_tpu.resilience import faults

_log = logger("appendlog")


class AppendLogError(Exception):
    pass


def _encode(rec: dict) -> bytes:
    return (json.dumps(rec, separators=(",", ":")) + "\n").encode()


class AppendLog:
    """One durable JSONL file: header + appended records."""

    def __init__(self, path: str, header: dict,
                 fault_site: str = "journal.append"):
        self.path = path
        self.header = header
        self.fault_site = fault_site
        self._lock = make_lock("durability.appendlog._lock")
        self._fh = None
        self.records_written = 0

    # ------------------------------------------------------------ open

    @classmethod
    def create(cls, path: str, header: dict,
               fault_site: str = "journal.append") -> "AppendLog":
        """Start a fresh log (refuses to clobber an existing one)."""
        if os.path.exists(path):
            raise AppendLogError(f"append log {path} already exists")
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        log = cls(path, dict(header, kind="header"), fault_site)
        log._fh = open(path, "ab")
        log.append(log.header)
        return log

    @classmethod
    def replay(cls, path: str, fault_site: str = "journal.append",
               ) -> tuple["AppendLog", list[dict]]:
        """-> (reopened log, surviving records after the header).

        Torn tail truncated from the file AND absent from the replay;
        unparsable line-bounded records are skipped with a warning.
        Raises AppendLogError when the file is unreadable or has no
        header (the caller decides whether to rebuild or start fresh).
        """
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise AppendLogError(f"cannot read append log {path}: {e}")
        durable_end = len(raw)
        if raw and not raw.endswith(b"\n"):
            durable_end = raw.rfind(b"\n") + 1
            _log.debug(
                f"dropping torn append-log tail "
                f"({len(raw) - durable_end} bytes past the last complete "
                "record)")
            raw = raw[:durable_end]
        records: list[dict] = []
        for i, line in enumerate(raw.split(b"\n")):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                _log.warn("skipping corrupt append-log record",
                          path=path, line=i + 1)
                continue
            if isinstance(rec, dict):
                records.append(rec)
        if not records or records[0].get("kind") != "header":
            raise AppendLogError(f"append log {path} has no header record")
        log = cls(path, records[0], fault_site)
        log._fh = open(path, "r+b")
        log._fh.truncate(durable_end)  # the torn fragment must not
        log._fh.seek(0, os.SEEK_END)   # prefix the next append
        log.records_written = len(records)
        return log, records[1:]

    @classmethod
    def salvage(cls, path: str, header: dict,
                fault_site: str = "journal.append",
                ) -> tuple["AppendLog", list[dict]]:
        """Rebuild a log whose header record never durably landed (the
        create-time append tore, so replay sees "no header").  Appends
        made after the torn header in the original process are complete
        line-bounded records and MUST survive — for a write-ahead
        journal they carry the applied-id set exactly-once replay
        depends on.  A fresh header is stamped and the repaired file is
        published atomically, then replayed as usual."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise AppendLogError(f"cannot read append log {path}: {e}")
        if raw and not raw.endswith(b"\n"):
            raw = raw[:raw.rfind(b"\n") + 1]
        survivors: list[dict] = []
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("kind") != "header":
                survivors.append(rec)
        _log.warn("salvaging headerless append log", path=path,
                  survivors=len(survivors))
        body = b"".join([_encode(dict(header, kind="header"))]
                        + [_encode(r) for r in survivors])
        atomic.atomic_write(path, body, fault_site=fault_site)
        return cls.replay(path, fault_site)

    # ------------------------------------------------------------ write

    def append(self, rec: dict) -> None:
        """Durably append one record. Fault rules at ``fault_site``
        apply per append: ``kill`` dies before the write, ``torn-write``
        / ``bitflip`` mangle the payload, ``error`` raises
        AppendLogError, ``drop`` silently loses the record (an
        undetected lost write — replay simply never sees it)."""
        line = _encode(rec)
        rules = faults.fire(self.fault_site)
        faults.check_kill(self.fault_site, rules=rules)
        for r in rules:
            if r.action == "error":
                raise AppendLogError(
                    f"injected append failure at {self.fault_site}")
            if r.action == "drop":
                return
        line = faults.mangle_write(self.fault_site, line, rules=rules)
        with self._lock:
            if self._fh is None:
                raise AppendLogError("append log is closed")
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self.records_written += 1

    def rewrite(self, records: list[dict]) -> None:
        """Compact: atomically replace the whole log with header +
        `records`. A crash mid-rewrite leaves the previous log. On a
        failed rewrite the handle is left None (closed), so later
        appends raise AppendLogError — the caller's degrade path —
        instead of ValueError from a closed file object."""
        body = b"".join([_encode(self.header)]
                        + [_encode(r) for r in records])
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            atomic.atomic_write(self.path, body,
                                fault_site=self.fault_site)
            self._fh = open(self.path, "ab")
            self.records_written = 1 + len(records)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "AppendLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
