// Match-result decode: bit-packed device hit masks -> screened
// candidate triples (query row, advisory id, rescreen flag).
//
// Replaces the numpy chain unpackbits -> nonzero -> fancy-gather ->
// token-compare in trivy_tpu/detector/engine.py::_collect_unique with
// one cache-friendly pass. The caller still lexsort-dedupes across
// sources (main / hot / shards) and applies the rescreen memo — those
// stay in Python where the memo lives.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 collect.cpp -o libcollect.so

#include <cstdint>

extern "C" {

// Count set bits over the whole mask (capacity for decode_mask).
int64_t count_bits(const uint32_t* words, int64_t n_words) {
    int64_t total = 0;
    for (int64_t i = 0; i < n_words; i++) {
        total += __builtin_popcount(words[i]);
    }
    return total;
}

// words:    uint32[b][w32] bit-packed hit masks (bit k of word j set =>
//           row start[b] + j*32 + k is a hit for query b)
// start:    int64[b] window start row per query
// n_rows:   total DB rows (bits past the end are ignored)
// row_adv:  int32[n_rows] advisory id per row
// row_flags:int32[n_rows]
// adv_tok:  int64[n_adv] (space,name) token per advisory
// q_tok:    int64[b] query name token (-2 = unknown name)
// q_flags:  int32[b] query flags
// flag_mask: NEEDS_HOST|RESCREEN
// out_rows/out_ids/out_resc: capacity >= count_bits(...)
// returns number of screened candidates written
int64_t decode_mask(const uint32_t* words, int64_t b, int64_t w32,
                    const int64_t* start, int64_t n_rows,
                    const int32_t* row_adv, const int32_t* row_flags,
                    const int64_t* adv_tok,
                    const int64_t* q_tok, const int32_t* q_flags,
                    int32_t flag_mask,
                    int64_t* out_rows, int64_t* out_ids,
                    uint8_t* out_resc) {
    int64_t n = 0;
    for (int64_t q = 0; q < b; q++) {
        const uint32_t* row = words + q * w32;
        const int64_t base = start[q];
        const int64_t qt = q_tok[q];
        const int32_t qf = q_flags[q];
        for (int64_t j = 0; j < w32; j++) {
            uint32_t bits = row[j];
            while (bits) {
                const int k = __builtin_ctz(bits);
                bits &= bits - 1;
                const int64_t ridx = base + j * 32 + k;
                if (ridx >= n_rows) continue;
                const int32_t id = row_adv[ridx];
                if (adv_tok[id] != qt) continue;  // hash collision
                out_rows[n] = q;
                out_ids[n] = id;
                out_resc[n] =
                    ((row_flags[ridx] | qf) & flag_mask) != 0;
                n++;
            }
        }
    }
    return n;
}

}  // extern "C"
