// Match-result decode: bit-packed device hit masks -> screened
// candidate triples (query row, advisory id, rescreen flag).
//
// Replaces the numpy chain unpackbits -> nonzero -> fancy-gather ->
// token-compare in trivy_tpu/detector/engine.py::_collect_unique with
// one cache-friendly pass, plus the cross-source (row, id) sort-dedupe
// (was np.lexsort + keep-mask) and the confirmed-hit CSR grouping (was
// searchsorted + per-query slicing). The rescreen memo stays in Python
// where the version comparators live.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 collect.cpp -o libcollect.so

#include <algorithm>
#include <cstdint>

extern "C" {

// Count set bits over the whole mask (capacity for decode_mask).
int64_t count_bits(const uint32_t* words, int64_t n_words) {
    int64_t total = 0;
    for (int64_t i = 0; i < n_words; i++) {
        total += __builtin_popcount(words[i]);
    }
    return total;
}

// words:    uint32[b][w32] bit-packed hit masks (bit k of word j set =>
//           row start[b] + j*32 + k is a hit for query b)
// start:    int64[b] window start row per query
// n_rows:   total DB rows (bits past the end are ignored)
// row_adv:  int32[n_rows] advisory id per row
// row_flags:int32[n_rows]
// adv_tok:  int64[n_adv] (space,name) token per advisory
// q_tok:    int64[b] query name token (-2 = unknown name)
// q_flags:  int32[b] query flags
// flag_mask: NEEDS_HOST|RESCREEN
// out_rows/out_ids/out_resc: capacity >= count_bits(...)
// returns number of screened candidates written
int64_t decode_mask(const uint32_t* words, int64_t b, int64_t w32,
                    const int64_t* start, int64_t n_rows,
                    const int32_t* row_adv, const int32_t* row_flags,
                    const int64_t* adv_tok,
                    const int64_t* q_tok, const int32_t* q_flags,
                    int32_t flag_mask,
                    int64_t* out_rows, int64_t* out_ids,
                    uint8_t* out_resc) {
    int64_t n = 0;
    for (int64_t q = 0; q < b; q++) {
        const uint32_t* row = words + q * w32;
        const int64_t base = start[q];
        const int64_t qt = q_tok[q];
        const int32_t qf = q_flags[q];
        for (int64_t j = 0; j < w32; j++) {
            uint32_t bits = row[j];
            while (bits) {
                const int k = __builtin_ctz(bits);
                bits &= bits - 1;
                const int64_t ridx = base + j * 32 + k;
                if (ridx >= n_rows) continue;
                const int32_t id = row_adv[ridx];
                if (adv_tok[id] != qt) continue;  // hash collision
                out_rows[n] = q;
                out_ids[n] = id;
                out_resc[n] =
                    ((row_flags[ridx] | qf) & flag_mask) != 0;
                n++;
            }
        }
    }
    return n;
}

// In-place sort by (row, id, resc) and dedupe on (row, id), keeping the
// first occurrence (exact hit preferred over its rescreen twin - resc is
// the sort tiebreaker). Requires rows < 2^21 and ids < 2^42 (checked by
// the Python caller); triples pack into one u64 so the sort runs on a
// flat key array instead of a 3-key lexsort.
// Returns the deduped count m; rows/ids/resc are compacted in place.
int64_t sort_dedupe(int64_t* rows, int64_t* ids, uint8_t* resc,
                    int64_t n) {
    if (n <= 0) return 0;
    uint64_t* keys = new uint64_t[n];
    uint64_t key_or = 0;
    for (int64_t i = 0; i < n; i++) {
        keys[i] = (uint64_t(rows[i]) << 43) | (uint64_t(ids[i]) << 1) |
                  uint64_t(resc[i]);
        key_or |= keys[i];
    }
    // LSD radix sort (11-bit digits), skipping all-zero digit positions
    // — ~3x std::sort on the multi-million-candidate dense batches
    if (n > 4096) {
        constexpr int RADIX_BITS = 11;
        constexpr int BUCKETS = 1 << RADIX_BITS;
        uint64_t* tmp = new uint64_t[n];
        int64_t count[BUCKETS];
        uint64_t* src = keys;
        uint64_t* dst = tmp;
        for (int shift = 0; shift < 64; shift += RADIX_BITS) {
            const uint64_t rem = key_or >> shift;
            if (rem == 0) break;  // no key has bits at or above shift
            if ((rem & (BUCKETS - 1)) == 0) continue;  // no-op digit
            for (int b = 0; b < BUCKETS; b++) count[b] = 0;
            for (int64_t i = 0; i < n; i++) {
                count[(src[i] >> shift) & (BUCKETS - 1)]++;
            }
            int64_t sum = 0;
            for (int b = 0; b < BUCKETS; b++) {
                int64_t c = count[b];
                count[b] = sum;
                sum += c;
            }
            for (int64_t i = 0; i < n; i++) {
                dst[count[(src[i] >> shift) & (BUCKETS - 1)]++] = src[i];
            }
            uint64_t* t = src;
            src = dst;
            dst = t;
        }
        if (src != keys) {
            for (int64_t i = 0; i < n; i++) keys[i] = src[i];
        }
        delete[] tmp;
    } else {
        std::sort(keys, keys + n);
    }
    int64_t m = 0;
    uint64_t prev_rowid = ~uint64_t(0);
    for (int64_t i = 0; i < n; i++) {
        const uint64_t rowid = keys[i] >> 1;
        if (rowid == prev_rowid) continue;  // same (row, id): keep first
        prev_rowid = rowid;
        rows[m] = int64_t(keys[i] >> 43);
        ids[m] = int64_t((keys[i] >> 1) & ((uint64_t(1) << 42) - 1));
        resc[m] = uint8_t(keys[i] & 1);
        m++;
    }
    delete[] keys;
    return m;
}

// Compact confirmed hits into a CSR over queries: out_ids gets the
// advisory ids of rows[i] where conf[i] != 0 (already sorted by row
// then id), out_bounds[q]..out_bounds[q+1] brackets query q's slice.
// rows must be sorted ascending (sort_dedupe's postcondition).
// Returns total confirmed count.
int64_t group_confirmed(const int64_t* rows, const int64_t* ids,
                        const uint8_t* conf, int64_t m,
                        int64_t n_queries,
                        int64_t* out_ids, int64_t* out_bounds) {
    int64_t n = 0;
    int64_t q = 0;
    out_bounds[0] = 0;
    for (int64_t i = 0; i < m; i++) {
        if (!conf[i]) continue;
        const int64_t r = rows[i];
        while (q < r && q < n_queries) out_bounds[++q] = n;
        out_ids[n++] = ids[i];
    }
    while (q < n_queries) out_bounds[++q] = n;
    return n;
}

}  // extern "C"
