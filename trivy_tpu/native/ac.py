"""ctypes binding for the C++ Aho-Corasick keyword scanner (ac.cpp).

The shared library is compiled on first use with g++ and cached under
~/.cache/trivy-tpu/native keyed by a source hash; when no toolchain is
available the caller falls back to the pure-Python prefilter.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

import numpy as np

from trivy_tpu.log import logger

_log = logger("native")

_SRC = os.path.join(os.path.dirname(__file__), "ac.cpp")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_LIB_FAILED = False


def _cache_dir() -> str:
    return os.environ.get(
        "TRIVY_TPU_NATIVE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "trivy-tpu",
                     "native"))


def _build_library() -> str | None:
    with open(_SRC, "rb") as f:
        src = f.read()
    digest = hashlib.sha256(src).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"libac-{digest}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_cache_dir(), exist_ok=True)
    tmp = tempfile.mktemp(suffix=".so", dir=_cache_dir())
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        stderr = getattr(e, "stderr", b"") or b""
        _log.warn("native build failed; using python prefilter",
                  err=str(e), stderr=stderr.decode()[:500])
        return None
    os.replace(tmp, out)  # atomic: concurrent builders race safely
    return out


def _load() -> ctypes.CDLL | None:
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        path = _build_library()
        if path is None:
            _LIB_FAILED = True
            return None
        lib = ctypes.CDLL(path)
        lib.ac_build.restype = ctypes.c_void_p
        lib.ac_build.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.ac_scan.restype = ctypes.c_int32
        lib.ac_scan.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.ac_free.restype = None
        lib.ac_free.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not None


class NativeMatcher:
    """Multi-pattern case-insensitive matcher over one byte pass."""

    def __init__(self, keywords: list[bytes]):
        lib = _load()
        if lib is None:
            raise RuntimeError("native AC library unavailable")
        self._lib = lib
        self.keywords = keywords
        n = len(keywords)
        arr = (ctypes.c_char_p * n)(*[bytes(k.lower()) for k in keywords])
        lens = (ctypes.c_int32 * n)(*[len(k) for k in keywords])
        self._handle = lib.ac_build(
            ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)), lens, n)
        self._hits_buf = (ctypes.c_uint8 * max(n, 1))()

    def scan(self, content: bytes) -> np.ndarray:
        """-> bool[n_keywords] — which keywords occur in content."""
        self._lib.ac_scan(self._handle, content, len(content),
                          self._hits_buf)
        return np.frombuffer(self._hits_buf, dtype=np.uint8).astype(bool)

    def __del__(self):
        lib = getattr(self, "_lib", None)
        handle = getattr(self, "_handle", None)
        if lib is not None and handle:
            try:
                lib.ac_free(handle)
            except Exception:
                pass
            self._handle = None
