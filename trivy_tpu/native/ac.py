"""ctypes binding for the C++ Aho-Corasick keyword scanner (ac.cpp).

Build/load scaffolding shared with collect.py via native/build.py; when
no toolchain is available the caller falls back to the pure-Python
prefilter.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from trivy_tpu.native.build import LazyLibrary

_SRC = os.path.join(os.path.dirname(__file__), "ac.cpp")


def _configure(lib: ctypes.CDLL) -> None:
    lib.ac_build.restype = ctypes.c_void_p
    lib.ac_build.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
    ]
    lib.ac_scan.restype = ctypes.c_int32
    lib.ac_scan.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.ac_scan_pos.restype = ctypes.c_int64
    lib.ac_scan_pos.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
    ]
    lib.ac_free.restype = None
    lib.ac_free.argtypes = [ctypes.c_void_p]


_LIB = LazyLibrary(_SRC, "libac", _configure)


def available() -> bool:
    return _LIB.available()


class NativeMatcher:
    """Multi-pattern case-insensitive matcher over one byte pass."""

    def __init__(self, keywords: list[bytes]):
        lib = _LIB.load()
        if lib is None:
            raise RuntimeError("native AC library unavailable")
        self._lib = lib
        self.keywords = keywords
        n = len(keywords)
        arr = (ctypes.c_char_p * n)(*[bytes(k.lower()) for k in keywords])
        lens = (ctypes.c_int32 * n)(*[len(k) for k in keywords])
        self._handle = lib.ac_build(
            ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)), lens, n)
        self._n = n

    def scan(self, content: bytes) -> np.ndarray:
        """-> bool[n_keywords] — which keywords occur in content.
        The hits buffer is per call, so concurrent scans (fleet lanes
        sharing one scanner) cannot tear each other's verdicts."""
        hits = (ctypes.c_uint8 * max(self._n, 1))()
        self._lib.ac_scan(self._handle, content, len(content), hits)
        return np.frombuffer(hits, dtype=np.uint8).astype(bool)

    # generous default: secret-bearing files have few candidate-window
    # anchors; a file denser than this gets the whole-buffer fallback
    POS_CAP = 16384

    def scan_positions(self, content: bytes,
                       cap: int | None = None):
        """-> (ids int32[n], end_offsets int64[n]) of every case-folded
        keyword occurrence, or None when the buffer holds more than
        `cap` occurrences (the caller must NOT trust a truncated set —
        fall back to scanning the whole buffer)."""
        cap = self.POS_CAP if cap is None else int(cap)
        ids = (ctypes.c_int32 * max(cap, 1))()
        pos = (ctypes.c_int64 * max(cap, 1))()
        n = self._lib.ac_scan_pos(self._handle, content, len(content),
                                  ids, pos, cap)
        if n < 0:
            return None
        return (np.frombuffer(ids, dtype=np.int32)[:n].copy(),
                np.frombuffer(pos, dtype=np.int64)[:n].copy())

    def __del__(self):
        lib = getattr(self, "_lib", None)
        handle = getattr(self, "_handle", None)
        if lib is not None and handle:
            try:
                lib.ac_free(handle)
            except Exception:
                pass
            self._handle = None
