"""ctypes binding for the C++ match-result decoder (collect.cpp).

Build/load scaffolding shared with ac.py via native/build.py; callers
fall back to the numpy path when no toolchain is available.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from trivy_tpu.native.build import LazyLibrary

_SRC = os.path.join(os.path.dirname(__file__), "collect.cpp")


def _configure(lib: ctypes.CDLL) -> None:
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.count_bits.restype = ctypes.c_int64
    lib.count_bits.argtypes = [u32p, ctypes.c_int64]
    lib.decode_mask.restype = ctypes.c_int64
    lib.decode_mask.argtypes = [
        u32p, ctypes.c_int64, ctypes.c_int64,   # words, b, w32
        i64p, ctypes.c_int64,                   # start, n_rows
        i32p, i32p,                             # row_adv, row_flags
        i64p,                                   # adv_tok
        i64p, i32p,                             # q_tok, q_flags
        ctypes.c_int32,                         # flag_mask
        i64p, i64p, u8p,                        # out rows/ids/resc
    ]
    lib.sort_dedupe.restype = ctypes.c_int64
    lib.sort_dedupe.argtypes = [i64p, i64p, u8p, ctypes.c_int64]
    lib.group_confirmed.restype = ctypes.c_int64
    lib.group_confirmed.argtypes = [
        i64p, i64p, u8p, ctypes.c_int64, ctypes.c_int64,  # rows/ids/conf/m/nq
        i64p, i64p,                                       # out ids, bounds
    ]


_LIB = LazyLibrary(_SRC, "libcollect", _configure)


def available() -> bool:
    return _LIB.available()


def decode_mask(words: np.ndarray, start: np.ndarray, n_rows: int,
                row_adv: np.ndarray, row_flags: np.ndarray,
                adv_tok: np.ndarray, q_tok: np.ndarray,
                q_flags: np.ndarray, flag_mask: int):
    """-> (rows, ids, resc) screened candidate triples, or None when the
    native library is unavailable. Shapes: words uint32[B, W32] in the
    original query order; everything else as in collect.cpp."""
    lib = _LIB.load()
    if lib is None:
        return None
    words = np.ascontiguousarray(words, dtype=np.uint32)
    b, w32 = words.shape
    cap = int(lib.count_bits(words.reshape(-1), words.size))
    rows = np.empty(cap, dtype=np.int64)
    ids = np.empty(cap, dtype=np.int64)
    resc = np.empty(cap, dtype=np.uint8)
    n = int(lib.decode_mask(
        words.reshape(-1), b, w32,
        np.ascontiguousarray(start, dtype=np.int64), n_rows,
        np.ascontiguousarray(row_adv, dtype=np.int32),
        np.ascontiguousarray(row_flags, dtype=np.int32),
        np.ascontiguousarray(adv_tok, dtype=np.int64),
        np.ascontiguousarray(q_tok, dtype=np.int64),
        np.ascontiguousarray(q_flags, dtype=np.int32),
        flag_mask, rows, ids, resc))
    return rows[:n], ids[:n], resc[:n].astype(bool)


def sort_dedupe(rows: np.ndarray, ids: np.ndarray, resc: np.ndarray):
    """In-place sort by (row, id, resc) + dedupe on (row, id) keeping the
    exact (non-rescreen) twin. -> (rows, ids, resc) compacted views, or
    None when the library is unavailable or the values exceed the packed
    key ranges (rows < 2^21, ids < 2^42)."""
    lib = _LIB.load()
    if lib is None or len(rows) == 0:
        return None
    if (rows.max() >= (1 << 21) or rows.min() < 0
            or ids.max() >= (1 << 42) or ids.min() < 0):
        return None  # caller falls back to np.lexsort
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    ids = np.ascontiguousarray(ids, dtype=np.int64)
    resc8 = np.ascontiguousarray(resc, dtype=np.uint8)
    m = int(lib.sort_dedupe(rows, ids, resc8, len(rows)))
    return rows[:m], ids[:m], resc8[:m].astype(bool)


def group_confirmed(rows: np.ndarray, ids: np.ndarray, conf: np.ndarray,
                    n_queries: int):
    """-> (out_ids, bounds) CSR of confirmed hits per query, or None when
    the library is unavailable. rows must be sorted ascending."""
    lib = _LIB.load()
    if lib is None:
        return None
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    ids = np.ascontiguousarray(ids, dtype=np.int64)
    conf8 = np.ascontiguousarray(conf, dtype=np.uint8)
    out_ids = np.empty(len(ids), dtype=np.int64)
    bounds = np.empty(n_queries + 1, dtype=np.int64)
    n = int(lib.group_confirmed(rows, ids, conf8, len(rows), n_queries,
                                out_ids, bounds))
    return out_ids[:n], bounds
