// Aho-Corasick multi-pattern scanner for the secret-engine host
// prefilter.  The reference's hot loop is rules x files x
// strings.Contains on every keyword (pkg/fanal/secret/scanner.go:174-186);
// this automaton finds every keyword in one pass over the input.
//
// C ABI (used via ctypes from trivy_tpu.native.ac):
//   ac_build(keywords, lengths, n)      -> handle
//   ac_scan(handle, data, len, hits[n]) -> number of distinct keywords hit
//   ac_free(handle)
//
// Matching is case-insensitive: patterns are expected lowercase and
// input bytes are folded with a 256-byte table (no locale).

#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

namespace {

constexpr int ALPHA = 256;

struct Node {
  int32_t next[ALPHA];
  int32_t fail = 0;
  std::vector<int32_t> out;  // keyword ids terminating here
  Node() { memset(next, -1, sizeof(next)); }
};

struct Automaton {
  std::vector<Node> nodes;
  int n_keywords = 0;
  uint8_t fold[ALPHA];

  Automaton() {
    for (int i = 0; i < ALPHA; i++) {
      fold[i] = (i >= 'A' && i <= 'Z') ? uint8_t(i - 'A' + 'a') : uint8_t(i);
    }
    nodes.emplace_back();
  }

  void add(const uint8_t* kw, int len, int id) {
    int cur = 0;
    for (int i = 0; i < len; i++) {
      uint8_t c = fold[kw[i]];
      if (nodes[cur].next[c] < 0) {
        nodes[cur].next[c] = (int32_t)nodes.size();
        nodes.emplace_back();
      }
      cur = nodes[cur].next[c];
    }
    nodes[cur].out.push_back(id);
  }

  void build() {
    std::queue<int> q;
    for (int c = 0; c < ALPHA; c++) {
      int v = nodes[0].next[c];
      if (v < 0) {
        nodes[0].next[c] = 0;
      } else {
        nodes[v].fail = 0;
        q.push(v);
      }
    }
    while (!q.empty()) {
      int u = q.front();
      q.pop();
      for (int c = 0; c < ALPHA; c++) {
        int v = nodes[u].next[c];
        if (v < 0) {
          nodes[u].next[c] = nodes[nodes[u].fail].next[c];
        } else {
          int f = nodes[nodes[u].fail].next[c];
          nodes[v].fail = f;
          // merge output links so one transition reports all suffixes
          const auto& fo = nodes[f].out;
          nodes[v].out.insert(nodes[v].out.end(), fo.begin(), fo.end());
          q.push(v);
        }
      }
    }
  }
};

}  // namespace

extern "C" {

void* ac_build(const uint8_t** keywords, const int32_t* lengths,
               int32_t n) {
  auto* ac = new Automaton();
  ac->n_keywords = n;
  for (int32_t i = 0; i < n; i++) {
    if (lengths[i] > 0) ac->add(keywords[i], lengths[i], i);
  }
  ac->build();
  return ac;
}

// Position-reporting variant: writes (keyword id, END offset) pairs of
// every occurrence (case-folded) into out_ids/out_pos, up to cap.
// Returns the number written, or -1 when the input holds more than cap
// occurrences — the caller must then treat positions as unknown (fall
// back to a whole-buffer scan), never as a truncated-but-trusted set.
int64_t ac_scan_pos(void* handle, const uint8_t* data, int64_t len,
                    int32_t* out_ids, int64_t* out_pos, int64_t cap) {
  auto* ac = static_cast<Automaton*>(handle);
  int64_t found = 0;
  int cur = 0;
  const auto* nodes = ac->nodes.data();
  const uint8_t* fold = ac->fold;
  for (int64_t i = 0; i < len; i++) {
    cur = nodes[cur].next[fold[data[i]]];
    const auto& out = nodes[cur].out;
    if (!out.empty()) {
      for (int32_t id : out) {
        if (found == cap) return -1;
        out_ids[found] = id;
        out_pos[found] = i;  // offset of the occurrence's LAST byte
        found++;
      }
    }
  }
  return found;
}

int32_t ac_scan(void* handle, const uint8_t* data, int64_t len,
                uint8_t* hits) {
  auto* ac = static_cast<Automaton*>(handle);
  memset(hits, 0, ac->n_keywords);
  int32_t found = 0;
  int cur = 0;
  const auto* nodes = ac->nodes.data();
  const uint8_t* fold = ac->fold;
  for (int64_t i = 0; i < len; i++) {
    cur = nodes[cur].next[fold[data[i]]];
    const auto& out = nodes[cur].out;
    if (!out.empty()) {
      for (int32_t id : out) {
        if (!hits[id]) {
          hits[id] = 1;
          if (++found == ac->n_keywords) return found;  // all hit: done
        }
      }
    }
  }
  return found;
}

void ac_free(void* handle) { delete static_cast<Automaton*>(handle); }

}  // extern "C"
