"""Shared build/load scaffolding for the C++ helpers (ac.cpp,
collect.cpp): compile on first use with g++, cache under
~/.cache/trivy-tpu/native keyed by source hash, fall back to the
caller's pure-Python path when no toolchain is available."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

from trivy_tpu.analysis.witness import make_lock

from trivy_tpu.log import logger

_log = logger("native")


def cache_dir() -> str:
    return os.environ.get(
        "TRIVY_TPU_NATIVE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "trivy-tpu",
                     "native"))


def build_library(src_path: str, lib_prefix: str,
                  link_flags: tuple[str, ...] = ()) -> str | None:
    """Compile `src_path` to a cached shared library; None on failure.

    `link_flags` (e.g. ("-lz",)) participate in the cache key so the
    same source built with different libraries does not collide.
    """
    with open(src_path, "rb") as f:
        src = f.read()
    digest = hashlib.sha256(src + b"\0" +
                            " ".join(link_flags).encode()).hexdigest()[:16]
    out = os.path.join(cache_dir(), f"{lib_prefix}-{digest}.so")
    if os.path.exists(out):
        return out
    os.makedirs(cache_dir(), exist_ok=True)
    tmp = tempfile.mktemp(suffix=".so", dir=cache_dir())
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src_path,
           "-o", tmp, *link_flags]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        stderr = getattr(e, "stderr", b"") or b""
        _log.warn("native build failed; using python fallback",
                  src=os.path.basename(src_path), err=str(e),
                  stderr=stderr.decode()[:500])
        return None
    # lint: allow[atomic-write] atomic-publish idiom: tmp build + rename, racing builders converge
    os.replace(tmp, out)  # atomic: concurrent builders race safely
    return out


class LazyLibrary:
    """Thread-safe once-only build+load; `configure(lib)` sets the
    ctypes signatures on first success."""

    def __init__(self, src_path: str, lib_prefix: str, configure,
                 link_flags: tuple[str, ...] = ()):
        self._src = src_path
        self._prefix = lib_prefix
        self._configure = configure
        self._link_flags = link_flags
        self._lock = make_lock("native.build._lock")
        self._lib: ctypes.CDLL | None = None
        self._failed = False

    def load(self) -> ctypes.CDLL | None:
        if self._lib is not None or self._failed:
            return self._lib
        with self._lock:
            if self._lib is not None or self._failed:
                return self._lib
            path = build_library(self._src, self._prefix,
                                 self._link_flags)
            if path is None:
                self._failed = True
                return None
            lib = ctypes.CDLL(path)
            self._configure(lib)
            self._lib = lib
            return lib

    def available(self) -> bool:
        return self.load() is not None
