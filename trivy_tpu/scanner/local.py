"""Standalone (local) scan driver (reference pkg/scanner/local/scan.go):
applier squash -> ScanTarget -> per-class scans -> metadata fill ->
post-scan hooks. The vulnerability matching inside runs on the TPU via
MatchEngine."""

from __future__ import annotations

from trivy_tpu import vulnerability
from trivy_tpu.detector import langpkg, ospkg
from trivy_tpu.detector.engine import MatchEngine
from trivy_tpu.fanal.applier import apply_layers
from trivy_tpu.log import logger
from trivy_tpu.types.artifact import ArtifactDetail, BlobInfo, OS
from trivy_tpu.types.enums import ResultClass, Scanner as ScannerEnum
from trivy_tpu.types.report import (
    DetectedLicense,
    DetectedSecret,
    Result,
)
from trivy_tpu.types.scan import ScanOptions
from trivy_tpu.types.serde import from_dict

_log = logger("local")

from trivy_tpu.detector.langpkg import PKG_TARGETS  # noqa: E402
# (re-export: historical import site for the target-name table)


class LocalDriver:
    def __init__(self, engine: MatchEngine, cache, post_hooks=None,
                 scheduler=None):
        self.engine = engine
        self.cache = cache
        self.post_hooks = post_hooks or []
        # server mode attaches the cross-request match scheduler so the
        # detect phase joins shared device micro-batches instead of
        # dispatching privately (trivy_tpu/sched); None = direct path
        self.scheduler = scheduler

    def _match_engine(self):
        """Engine handle for the detect phase: with a scheduler
        attached, detect() routes through its coalesced micro-batches —
        byte-identical results, one saturated dispatch lane. Everything
        else (db, cdb, advisories) reads through to the real engine.
        Under an active monitor capture scope the handle additionally
        records query inventory + finding keys for the package→artifact
        index (trivy_tpu/monitor; no-op wrapper otherwise)."""
        from trivy_tpu.monitor.capture import tap

        if self.scheduler is None:
            return tap(self.engine)
        from trivy_tpu.sched.scheduler import SchedEngine

        return tap(SchedEngine(self.engine, self.scheduler))

    def scan(self, target, artifact_key, blob_keys, options: ScanOptions):
        from trivy_tpu import obs
        from trivy_tpu.obs import tracing as trace
        from trivy_tpu.resilience.retry import checkpoint
        from trivy_tpu.scanner import post

        # phase-boundary deadline checkpoints: under an ambient deadline
        # budget (server header / --scan-timeout) a scan that cannot
        # finish sheds promptly between phases instead of burning device
        # time nobody will wait for
        checkpoint("apply_layers")
        # blob reads + squash are the "cache" phase of the latency
        # histogram; the span keeps its historical name
        with obs.phase("apply_layers", phase="cache"):
            detail = self._apply_layers(blob_keys)
            self._merge_artifact_info(detail, artifact_key)
            trace.add_meta(pkgs=len(detail.packages),
                           apps=len(detail.applications))
        if not options.include_dev_deps:
            # development dependencies are excluded unless requested
            # (reference pkg/scanner/local/scan.go:438 excludeDevDeps)
            for app in detail.applications:
                if any(getattr(p, "dev", False) for p in app.packages):
                    app.packages = [p for p in app.packages
                                    if not getattr(p, "dev", False)]
        if "rekor" in (options.sbom_sources or []):
            from trivy_tpu.fanal.unpackaged import discover_sboms

            checkpoint("rekor_sbom_discovery")
            with trace.span("rekor_sbom_discovery"):
                discover_sboms(detail, options.rekor_url)
        checkpoint("detect")
        with obs.phase("detect"):
            results = self._scan_detail(target, detail, options)
        checkpoint("post_hooks")
        with trace.span("post_hooks"):
            for hook in self.post_hooks:
                results = hook(results, options)
            # globally registered hooks (module extensions; reference
            # pkg/scanner/local/scan.go:152 -> post/post_scan.go:35)
            results = post.scan(results, options)
        return results, detail.os

    def _merge_artifact_info(self, detail: ArtifactDetail,
                             artifact_key: str) -> None:
        """Merge image-config analysis (env secrets, apk-history
        packages) into the squashed detail (reference applier
        ApplyLayers consumes ArtifactInfo alongside the blobs)."""
        if not artifact_key:
            return
        raw = self.cache.get_artifact(artifact_key)
        if not raw:
            return
        from trivy_tpu.types.artifact import ArtifactInfo

        info = from_dict(ArtifactInfo, raw)
        detail.image_config = info
        if info.secret is not None and info.secret.findings:
            detail.secrets.append(info.secret)

    # ------------------------------------------------------------ layers

    def _apply_layers(self, blob_keys: list[str]) -> ArtifactDetail:
        blobs = []
        for key in blob_keys:
            raw = self.cache.get_blob(key)
            if not raw:
                raise RuntimeError(f"missing blob in cache: {key}")
            blob = from_dict(BlobInfo, raw)
            blob.diff_id = blob.diff_id or key
            blobs.append(blob)
        return apply_layers(blobs)

    # ------------------------------------------------------------ scans

    def _scan_detail(
        self, target: str, detail: ArtifactDetail, options: ScanOptions
    ) -> list[Result]:
        from trivy_tpu import obs

        results: list[Result] = []
        if ScannerEnum.VULN in options.scanners:
            results.extend(self._scan_vulns(target, detail, options))
        if ScannerEnum.SECRET in options.scanners:
            with obs.phase("secret_results", phase="secret"):
                results.extend(self._secret_results(detail))
        if ScannerEnum.LICENSE in options.scanners:
            results.extend(self._license_results(detail, options))
        results.extend(self._misconfig_results(detail))
        return results

    def _scan_vulns(
        self, target: str, detail: ArtifactDetail, options: ScanOptions
    ) -> list[Result]:
        results: list[Result] = []
        include_os = "os" in options.pkg_types
        include_lib = "library" in options.pkg_types
        engine = self._match_engine()

        if include_os and (detail.os.detected or detail.packages):
            vulns, eosl = ([], False)
            if detail.os.detected and detail.packages:
                vulns, eosl = ospkg.detect(
                    engine, detail.os, detail.repository, detail.packages
                )
                detail.os.eosl = eosl
            vulnerability.fill_info(self.engine.db, vulns)
            res = Result(
                target=f"{target} ({detail.os.family} {detail.os.name})"
                if detail.os.detected else target,
                result_class=ResultClass.OS_PKGS,
                type=detail.os.family,
                vulnerabilities=sorted(vulns, key=lambda v: v.sort_key()),
            )
            # packages always travel with the result (the VEX
            # reachability graph needs them); the runner strips them at
            # render time unless --list-all-pkgs (reference behavior).
            # Result ROWS still appear only for findings / detected OS /
            # explicit package listing, as before.
            res.packages = detail.packages
            if res.vulnerabilities or detail.os.detected \
                    or options.list_all_pkgs:
                results.append(res)

        if include_lib:
            for app in detail.applications:
                if not app.packages:
                    continue
                vulns = langpkg.detect_app(engine, app)
                vulnerability.fill_info(self.engine.db, vulns)
                res = Result(
                    target=app.file_path
                    or PKG_TARGETS.get(app.type, app.type),
                    result_class=ResultClass.LANG_PKGS,
                    type=app.type,
                    vulnerabilities=sorted(vulns, key=lambda v: v.sort_key()),
                )
                res.packages = app.packages
                if res.vulnerabilities or options.list_all_pkgs:
                    results.append(res)
        return results

    def _secret_results(self, detail: ArtifactDetail) -> list[Result]:
        results = []
        for secret in sorted(detail.secrets, key=lambda s: s.file_path):
            results.append(Result(
                target=secret.file_path,
                result_class=ResultClass.SECRET,
                secrets=[
                    DetectedSecret(
                        rule_id=f.rule_id, category=f.category,
                        severity=f.severity, title=f.title,
                        start_line=f.start_line, end_line=f.end_line,
                        match=f.match, layer=f.layer,
                    )
                    for f in secret.findings
                ],
            ))
        return results

    def _license_results(
        self, detail: ArtifactDetail, options: ScanOptions
    ) -> list[Result]:
        from trivy_tpu.licensing.scanner import scan_licenses

        return scan_licenses(detail, options)

    def _misconfig_results(self, detail: ArtifactDetail) -> list[Result]:
        results = []
        for misconf in sorted(
            detail.misconfigurations, key=lambda m: m.file_path
        ):
            from trivy_tpu.misconf.result import to_result

            res = to_result(misconf)
            if res is not None:
                results.append(res)
        return results
