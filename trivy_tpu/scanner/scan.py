"""Scan façade (reference pkg/scanner/scan.go).

Scanner{driver, artifact}.scan_artifact() is the top of the scan spine;
the Driver protocol (scan.go:141-144) is THE seam where local, remote and
TPU execution are swapped — LocalDriver runs the match engine in-process,
client.RemoteDriver ships the same call over RPC.
"""

from __future__ import annotations

from typing import Protocol

from trivy_tpu.artifact.base import Artifact
from trivy_tpu.types.artifact import OS
from trivy_tpu.types.report import Metadata, Report, Result
from trivy_tpu.types.scan import ScanOptions
from trivy_tpu.utils import clock

REPORT_SCHEMA_VERSION = 2


class Driver(Protocol):
    """reference pkg/scanner/scan.go:141-144"""

    def scan(
        self,
        target: str,
        artifact_key: str,
        blob_keys: list[str],
        options: ScanOptions,
    ) -> tuple[list[Result], OS]: ...


class Scanner:
    def __init__(self, driver: Driver, artifact: Artifact):
        self.driver = driver
        self.artifact = artifact

    def scan_artifact(self, options: ScanOptions) -> Report:
        from trivy_tpu import obs
        from trivy_tpu.obs import tracing as trace

        # every scan gets an ambient scan id (kept when a fleet lane
        # already set one) that log records carry next to trace ids
        with trace.scan_scope(), trace.span("scan_artifact"):
            with obs.phase("inspect"):
                ref = self.artifact.inspect()
                trace.add_meta(blobs=len(ref.blob_ids))
            try:
                with trace.span("driver.scan"), trace.jax_profile():
                    results, os_found = self.driver.scan(
                        ref.name, ref.id, ref.blob_ids, options
                    )
            finally:
                self.artifact.clean(ref)

        metadata = Metadata(
            os=os_found if os_found.detected else None,
            # a degrading driver (resilience.fallback.FallbackDriver)
            # records why it fell back; primary scans leave this empty
            degraded=getattr(self.driver, "degraded_reason", "") or "",
        )
        if ref.image_metadata:
            metadata.image_id = ref.image_metadata.get("ImageID", "")
            metadata.diff_ids = ref.image_metadata.get("DiffIDs", [])
            metadata.repo_tags = ref.image_metadata.get("RepoTags", [])
            metadata.repo_digests = ref.image_metadata.get("RepoDigests", [])
            metadata.image_config = ref.image_metadata.get("ImageConfig", {})
            metadata.size = ref.image_metadata.get("Size", 0)
        if ref.sbom_meta is not None:
            sm = ref.sbom_meta
            metadata.image_id = sm.image_id
            metadata.diff_ids = sm.diff_ids
            metadata.repo_tags = sm.repo_tags
            metadata.repo_digests = sm.repo_digests

        return Report(
            schema_version=REPORT_SCHEMA_VERSION,
            created_at=clock.now_rfc3339(),
            artifact_name=ref.name,
            artifact_type=ref.type,
            metadata=metadata,
            results=results,
        )
