from trivy_tpu.scanner.scan import Scanner
from trivy_tpu.scanner.local import LocalDriver

__all__ = ["LocalDriver", "Scanner"]
