"""Post-scan hook registry (reference pkg/scanner/post/post_scan.go):
hooks run after detection + enrichment and may insert, update, or delete
results.  Used by the module extension system."""

from __future__ import annotations

_HOOKS: list = []


def register_post_scanner(hook) -> None:
    """hook: callable(results, options) -> results."""
    _HOOKS.append(hook)


def unregister_post_scanner(hook) -> None:
    try:
        _HOOKS.remove(hook)
    except ValueError:
        pass


def clear() -> None:
    _HOOKS.clear()


def scan(results, options):
    for hook in list(_HOOKS):
        results = hook(results, options)
    return results
