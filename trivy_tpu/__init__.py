"""trivy_tpu — a TPU-native security-scanning framework.

A ground-up re-design of the capabilities of aquasecurity/trivy
(reference: /root/reference, pure Go) for TPU hardware:

- The artifact-analysis engine (fanal), parsers, reporting, RPC and CLI are
  idiomatic host Python (reference layer map: SURVEY.md §1).
- The two hot loops — the (package x advisory) vulnerability match
  (reference pkg/detector/ospkg/detect.go:66, pkg/detector/library/driver.go:115)
  and the secret-rule engine (reference pkg/fanal/secret/scanner.go:377) — are
  batched JAX/XLA kernels. The advisory DB is compiled once into dense
  name-hash + version-interval-rank tensors resident in HBM
  (trivy_tpu.tensorize), shardable over a jax.sharding.Mesh.

Zero-diff guarantee: the device kernel is a provably superset prefilter
(exact where version encodings are exact, conservative where flagged), and a
host rescreen using the exact comparators (trivy_tpu.versioning) confirms
every candidate, so match sets are byte-identical to the CPU oracle.
"""

__version__ = "0.1.0"
