"""Structured logging with prefixes, colors, trace correlation, and an
optional JSON line format for fleet runs.

Re-expression of the reference slog setup (pkg/log/logger.go:14-35,
handler.go colored tty handler, context.go prefixes) on Python logging,
plus the observability spine's correlation fields: every record carries
the ambient trace_id / span_id / scan_id (obs.tracing contextvars) so a
log line joins the span tree it was emitted under
(docs/observability.md).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_COLORS = {
    logging.DEBUG: "\x1b[35m",  # magenta
    logging.INFO: "\x1b[34m",  # blue
    logging.WARNING: "\x1b[33m",  # yellow
    logging.ERROR: "\x1b[31m",  # red
}
_RESET = "\x1b[0m"
_PREFIX_COLOR = "\x1b[36m"  # cyan, like the reference's prefix rendering

_tracing = None  # lazy module ref (obs.tracing lazily imports us back)


def _trace_fields() -> dict | None:
    global _tracing
    if _tracing is None:
        from trivy_tpu.obs import tracing

        _tracing = tracing
    return _tracing.log_fields()


class _Formatter(logging.Formatter):
    # timestamps render with a "Z" suffix, so they must BE UTC — the
    # default formatTime uses localtime
    converter = time.gmtime

    def __init__(self, color: bool):
        super().__init__()
        self.color = color

    def format(self, record: logging.LogRecord) -> str:
        ts = self.formatTime(record, "%Y-%m-%dT%H:%M:%SZ")
        level = record.levelname
        prefix = getattr(record, "prefix", "")
        msg = record.getMessage()
        kvs = dict(getattr(record, "kvs", None) or {})
        kvs.update(getattr(record, "trace", None) or {})
        kv_str = "".join(f"\t{k}={v}" for k, v in kvs.items())
        if self.color:
            c = _COLORS.get(record.levelno, "")
            level = f"{c}{level}{_RESET}"
            if prefix:
                prefix = f"{_PREFIX_COLOR}[{prefix}]{_RESET} "
        elif prefix:
            prefix = f"[{prefix}] "
        return f"{ts}\t{level}\t{prefix}{msg}{kv_str}"


class _JSONFormatter(logging.Formatter):
    """One JSON object per line (--log-format json): fleet runs feed
    these into log pipelines, joined to traces via trace_id/span_id/
    scan_id."""

    converter = time.gmtime  # "Z"-suffixed ts must be UTC

    def format(self, record: logging.LogRecord) -> str:
        doc: dict = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%SZ"),
            "level": record.levelname,
            "msg": record.getMessage(),
        }
        prefix = getattr(record, "prefix", "")
        if prefix:
            doc["logger"] = prefix
        doc.update(getattr(record, "trace", None) or {})
        for k, v in (getattr(record, "kvs", None) or {}).items():
            doc.setdefault(k, v)
        return json.dumps(doc, default=str)


class Logger:
    """Thin wrapper adding the reference's prefix + key/value style."""

    def __init__(self, name: str = "trivy_tpu", prefix: str = ""):
        self._log = logging.getLogger(name)
        self._prefix = prefix

    def with_prefix(self, prefix: str) -> "Logger":
        return Logger(self._log.name, prefix)

    def _emit(self, level: int, msg: str, kwargs: dict) -> None:
        if not self._log.isEnabledFor(level):
            return
        self._log.log(level, msg, extra={
            "prefix": self._prefix, "kvs": kwargs,
            "trace": _trace_fields(),
        })

    def debug(self, msg: str, **kw) -> None:
        self._emit(logging.DEBUG, msg, kw)

    def info(self, msg: str, **kw) -> None:
        self._emit(logging.INFO, msg, kw)

    def warn(self, msg: str, **kw) -> None:
        self._emit(logging.WARNING, msg, kw)

    warning = warn

    def error(self, msg: str, **kw) -> None:
        self._emit(logging.ERROR, msg, kw)


_initialized = False


def init(debug: bool = False, quiet: bool = False,
         fmt: str = "text") -> None:
    global _initialized
    root = logging.getLogger("trivy_tpu")
    root.handlers.clear()
    handler = logging.StreamHandler(sys.stderr)
    if fmt == "json":
        handler.setFormatter(_JSONFormatter())
    else:
        color = sys.stderr.isatty() and os.environ.get("NO_COLOR") is None
        handler.setFormatter(_Formatter(color))
    root.addHandler(handler)
    if quiet:
        root.setLevel(logging.CRITICAL + 1)
    else:
        root.setLevel(logging.DEBUG if debug else logging.INFO)
    _initialized = True


def logger(prefix: str = "") -> Logger:
    if not _initialized:
        init()
    return Logger(prefix=prefix)
