"""Structured logging with prefixes and colors.

Re-expression of the reference slog setup (pkg/log/logger.go:14-35,
handler.go colored tty handler, context.go prefixes) on Python logging.
"""

from __future__ import annotations

import logging
import os
import sys

_COLORS = {
    logging.DEBUG: "\x1b[35m",  # magenta
    logging.INFO: "\x1b[34m",  # blue
    logging.WARNING: "\x1b[33m",  # yellow
    logging.ERROR: "\x1b[31m",  # red
}
_RESET = "\x1b[0m"
_PREFIX_COLOR = "\x1b[36m"  # cyan, like the reference's prefix rendering


class _Formatter(logging.Formatter):
    def __init__(self, color: bool):
        super().__init__()
        self.color = color

    def format(self, record: logging.LogRecord) -> str:
        ts = self.formatTime(record, "%Y-%m-%dT%H:%M:%SZ")
        level = record.levelname
        prefix = getattr(record, "prefix", "")
        msg = record.getMessage()
        kvs = getattr(record, "kvs", None)
        kv_str = "".join(f"\t{k}={v}" for k, v in (kvs or {}).items())
        if self.color:
            c = _COLORS.get(record.levelno, "")
            level = f"{c}{level}{_RESET}"
            if prefix:
                prefix = f"{_PREFIX_COLOR}[{prefix}]{_RESET} "
        elif prefix:
            prefix = f"[{prefix}] "
        return f"{ts}\t{level}\t{prefix}{msg}{kv_str}"


class Logger:
    """Thin wrapper adding the reference's prefix + key/value style."""

    def __init__(self, name: str = "trivy_tpu", prefix: str = ""):
        self._log = logging.getLogger(name)
        self._prefix = prefix

    def with_prefix(self, prefix: str) -> "Logger":
        return Logger(self._log.name, prefix)

    def _emit(self, level: int, msg: str, kwargs: dict) -> None:
        self._log.log(level, msg, extra={"prefix": self._prefix, "kvs": kwargs})

    def debug(self, msg: str, **kw) -> None:
        self._emit(logging.DEBUG, msg, kw)

    def info(self, msg: str, **kw) -> None:
        self._emit(logging.INFO, msg, kw)

    def warn(self, msg: str, **kw) -> None:
        self._emit(logging.WARNING, msg, kw)

    warning = warn

    def error(self, msg: str, **kw) -> None:
        self._emit(logging.ERROR, msg, kw)


_initialized = False


def init(debug: bool = False, quiet: bool = False) -> None:
    global _initialized
    root = logging.getLogger("trivy_tpu")
    root.handlers.clear()
    handler = logging.StreamHandler(sys.stderr)
    color = sys.stderr.isatty() and os.environ.get("NO_COLOR") is None
    handler.setFormatter(_Formatter(color))
    root.addHandler(handler)
    if quiet:
        root.setLevel(logging.CRITICAL + 1)
    else:
        root.setLevel(logging.DEBUG if debug else logging.INFO)
    _initialized = True


def logger(prefix: str = "") -> Logger:
    if not _initialized:
        init()
    return Logger(prefix=prefix)
