"""Check-bundle lifecycle (reference pkg/policy/policy.go:20-25):
a directory of check files distributed as an OCI artifact (the
trivy-checks equivalent), cached under ``<cache>/policy/content`` with
a metadata.json recording when it was downloaded; refreshed at most
every 24 h unless --skip-check-update.

The bundle content is plain check files in this framework's formats
(``*.py`` / ``*.yaml`` — see iac/engine.py), so a downloaded bundle and
a --config-check dir load identically."""

from __future__ import annotations

import json
import os
import time

from trivy_tpu.durability import atomic_write
from trivy_tpu.log import logger

_log = logger("policy")

UPDATE_INTERVAL_S = 24 * 3600  # reference policy.go updateInterval


def _policy_dir(cache_dir: str) -> str:
    return os.path.join(cache_dir, "policy")


def _content_dir(cache_dir: str) -> str:
    return os.path.join(_policy_dir(cache_dir), "content")


def _metadata_path(cache_dir: str) -> str:
    return os.path.join(_policy_dir(cache_dir), "metadata.json")


def _needs_update(cache_dir: str, now: float | None = None) -> bool:
    try:
        with open(_metadata_path(cache_dir)) as f:
            meta = json.load(f)
        downloaded = float(meta["downloaded_at"])
    except (OSError, ValueError, KeyError):
        return True
    return (now if now is not None else time.time()) - downloaded \
        >= UPDATE_INTERVAL_S


def update_bundle(cache_dir: str, repository: str,
                  insecure: bool = False) -> str:
    """Pull the bundle OCI artifact into the policy cache and stamp
    metadata.json. Returns the content dir."""
    from trivy_tpu.db.oci import download_artifact

    content = _content_dir(cache_dir)
    download_artifact(repository, content, media_type=None,
                      insecure=insecure)
    os.makedirs(_policy_dir(cache_dir), exist_ok=True)
    atomic_write(_metadata_path(cache_dir), json.dumps(
        {"downloaded_at": time.time(),
         "repository": repository}).encode())
    return content


def bundle_check_paths(cache_dir: str, repository: str = "",
                       skip_update: bool = False,
                       insecure: bool = False) -> list[str]:
    """Paths to feed the check engine for the downloaded bundle (empty
    if no bundle is configured or cached). Downloads/refreshes first
    when a repository is set and the 24 h interval elapsed."""
    content = _content_dir(cache_dir)
    if repository and not skip_update and _needs_update(cache_dir):
        try:
            update_bundle(cache_dir, repository, insecure=insecure)
        except Exception as e:
            # stale/offline bundle is non-fatal, like the reference's
            # fallback to the embedded checks
            _log.warn("check bundle update failed", err=str(e))
    return [content] if os.path.isdir(content) else []
