from trivy_tpu.policy.bundle import bundle_check_paths, update_bundle

__all__ = ["bundle_check_paths", "update_bundle"]
