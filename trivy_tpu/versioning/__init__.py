"""Version schemes, comparators and constraint matching.

Scheme registry + the ecosystem->scheme map used by the library detector
(reference pkg/detector/library/driver.go:25-97) and the per-distro OS
detectors (reference pkg/detector/ospkg/*).
"""

from __future__ import annotations

from trivy_tpu.log import logger
from trivy_tpu.versioning import (
    apk,
    base,
    bitnami,
    deb,
    maven,
    npm,
    pep440,
    rpm,
    rubygems,
    semver,
)
from trivy_tpu.versioning.base import Inexact, ParseError, Scheme
from trivy_tpu.versioning.constraints import Constraints, Interval

SCHEMES: dict[str, Scheme] = {
    s.name: s
    for s in (
        apk.SCHEME,
        deb.SCHEME,
        rpm.SCHEME,
        semver.SCHEME,  # "generic"
        npm.SCHEME,
        pep440.SCHEME,
        maven.SCHEME,
        rubygems.SCHEME,
        bitnami.SCHEME,
    )
}

# ecosystem (trivy-db bucket prefix) -> version scheme name
# (reference pkg/detector/library/driver.go:29-91)
ECOSYSTEM_SCHEME: dict[str, str] = {
    "rubygems": "rubygems",
    "cargo": "generic",
    "composer": "generic",
    "go": "generic",
    "maven": "maven",
    "npm": "npm",
    "nuget": "generic",
    "pip": "pep440",
    "pub": "generic",
    "erlang": "generic",
    "conan": "generic",
    "swift": "generic",
    "cocoapods": "rubygems",
    "bitnami": "bitnami",
    # trivy-db names the upstream Kubernetes CVE feed ecosystem "k8s"
    # (bucket "k8s::Official Kubernetes CVE Feed")
    "k8s": "generic",
    "kubernetes": "generic",
}

# OS family -> version scheme for package versions
OS_SCHEME: dict[str, str] = {
    "alpine": "apk",
    "chainguard": "apk",
    "wolfi": "apk",
    "minimos": "apk",
    "echo": "deb",
    "debian": "deb",
    "ubuntu": "deb",
    "alma": "rpm",
    "amazon": "rpm",
    "azurelinux": "rpm",
    "cbl-mariner": "rpm",
    "centos": "rpm",
    "fedora": "rpm",
    "oracle": "rpm",
    "photon": "rpm",
    "redhat": "rpm",
    "rocky": "rpm",
    "opensuse": "rpm",
    "opensuse-leap": "rpm",
    "opensuse-tumbleweed": "rpm",
    "suse linux enterprise micro": "rpm",
    "suse linux enterprise server": "rpm",
}

_log = logger("version")


def get_scheme(name: str) -> Scheme:
    return SCHEMES[name]


def scheme_for_ecosystem(eco: str) -> Scheme | None:
    name = ECOSYSTEM_SCHEME.get(eco)
    return SCHEMES[name] if name else None


def scheme_for_os(family: str) -> Scheme | None:
    name = OS_SCHEME.get(family)
    return SCHEMES[name] if name else None


def parse_constraints(eco: str, expr: str) -> Constraints:
    scheme = scheme_for_ecosystem(eco)
    if scheme is None:
        raise ParseError(f"no scheme for ecosystem {eco!r}")
    return Constraints(scheme, expr, npm_mode=(scheme.name == "npm"))


def is_vulnerable(
    eco: str,
    version: str,
    vulnerable_versions: list[str],
    patched_versions: list[str],
    unaffected_versions: list[str],
) -> bool:
    """Library-advisory satisfaction (reference
    pkg/detector/library/compare/compare.go:22-56): the version must match
    the vulnerable ranges and must NOT match patched/unaffected ranges.
    An empty-string range value means 'always vulnerable'."""
    for v in list(vulnerable_versions) + list(patched_versions):
        if v == "":
            return True
    scheme = scheme_for_ecosystem(eco)
    if scheme is None:
        return False
    npm_mode = scheme.name == "npm"
    try:
        ver = scheme.parse(version)
    except ParseError as e:
        _log.debug("failed to parse version", version=version, err=str(e))
        return False

    matched = False
    if vulnerable_versions:
        try:
            c = Constraints(scheme, " || ".join(vulnerable_versions), npm_mode)
            matched = c.check(ver)
        except ParseError as e:
            _log.warn("version constraint error", constraint=str(vulnerable_versions), err=str(e))
            return False
        if not matched:
            return False

    secure = list(patched_versions) + list(unaffected_versions)
    if not secure:
        return matched
    try:
        c = Constraints(scheme, " || ".join(secure), npm_mode)
        return not c.check(ver)
    except ParseError as e:
        _log.warn("version constraint error", constraint=str(secure), err=str(e))
        return False
