"""PEP 440 version ordering (reference uses aquasecurity/go-pep440-version,
pkg/detector/library/compare/pep440).

Parsing and total order delegate to the stdlib-adjacent `packaging` library
(the canonical PEP 440 implementation). Token encoding converts the parsed
components (epoch, release, pre/post/dev) into the shared tagged stream;
exotic combinations (local version segments) fall back to Inexact.
"""

from __future__ import annotations

from packaging.version import InvalidVersion, Version

from trivy_tpu.versioning import base
from trivy_tpu.versioning.base import Inexact, ParseError, Scheme, cmp

RELEASE_SLOTS = 5

# ascending tag order == ascending version order.
# PEP 440 suffix order: .devN < aN < bN < rcN < release < .postN
TAG_DEV = 0x04
TAG_PRE_A = 0x08
TAG_PRE_B = 0x0a
TAG_PRE_RC = 0x0c
TAG_RELEASE = 0x10
TAG_POST = 0x18
TAG_NUM = 0x30

# within a pre-release: 1.0a1.dev2 < 1.0a1 < 1.0a1.post1 (post within pre is
# not legal PEP 440 input, but dev within pre is)
TAG_SUB_DEV = 0x04
TAG_SUB_END = 0x10

_PRE_TAG = {"a": TAG_PRE_A, "b": TAG_PRE_B, "rc": TAG_PRE_RC}


class Pep440Scheme(Scheme):
    name = "pep440"

    def parse(self, s: str) -> Version:
        try:
            return Version(s.strip())
        except InvalidVersion as e:
            raise ParseError(str(e)) from e

    def compare_parsed(self, a: Version, b: Version) -> int:
        return cmp(a, b)

    def tokens(self, s: str):
        v = self.parse(s)
        if v.local:
            raise Inexact(f"local version segment: {s!r}")
        release = v.release
        if len(release) > RELEASE_SLOTS:
            if any(n != 0 for n in release[RELEASE_SLOTS:]):
                raise Inexact(f"release too long: {s!r}")
            release = release[:RELEASE_SLOTS]
        toks = [(TAG_NUM, base.num_payload(v.epoch))]
        for i in range(RELEASE_SLOTS):
            n = release[i] if i < len(release) else 0
            toks.append((TAG_NUM, base.num_payload(n)))
        # suffix structure, in PEP 440 precedence order
        if v.pre is not None:
            letter, num = v.pre
            if v.post is not None:
                # e.g. 1.0a1.post1 — legal but vanishingly rare; host path
                raise Inexact(f"pre+post combination: {s!r}")
            toks.append((_PRE_TAG[letter], base.num_payload(num)))
            if v.dev is not None:
                toks.append((TAG_SUB_DEV, base.num_payload(v.dev)))
            else:
                toks.append((TAG_SUB_END, b"\x00" * 7))
        elif v.post is not None:
            toks.append((TAG_POST, base.num_payload(v.post)))
            if v.dev is not None:
                toks.append((TAG_SUB_DEV, base.num_payload(v.dev)))
            else:
                toks.append((TAG_SUB_END, b"\x00" * 7))
        elif v.dev is not None:
            toks.append((TAG_DEV, base.num_payload(v.dev)))
            toks.append((TAG_SUB_END, b"\x00" * 7))
        else:
            toks.append((TAG_RELEASE, b"\x00" * 7))
            toks.append((TAG_SUB_END, b"\x00" * 7))
        return toks

    def _tokens_lossy(self, s: str):
        v = self.parse(s)
        cap = (1 << 56) - 1
        toks = [(TAG_NUM, base.num_payload(min(v.epoch, cap)))]
        for i in range(RELEASE_SLOTS):
            n = v.release[i] if i < len(v.release) else 0
            toks.append((TAG_NUM, base.num_payload(min(n, cap))))
        if v.pre is not None:
            toks.append((_PRE_TAG[v.pre[0]], base.num_payload(min(v.pre[1], cap))))
        elif v.post is not None:
            toks.append((TAG_POST, base.num_payload(min(v.post, cap))))
        elif v.dev is not None:
            toks.append((TAG_DEV, base.num_payload(min(v.dev, cap))))
        else:
            toks.append((TAG_RELEASE, b"\x00" * 7))
        return toks


SCHEME = Pep440Scheme()
