"""Alpine apk version comparison (apk-tools version.c semantics).

Exact re-implementation of the ordering used by the reference via
knqyf263/go-apk-version (reference pkg/detector/ospkg/alpine/alpine.go:8).

Format: digits('.'digits)* [letter] ('_'suffix[digits])* ['-r'digits]
Token kinds, by apk enum (higher enum = OLDER when kinds differ, with a
special case: a pre-release suffix is older than end-of-version):
  DIGIT(_OR_ZERO) < LETTER < SUFFIX < SUFFIX_NO < REVISION_NO < END
Pre suffixes: alpha < beta < pre < rc;  post: cvs < svn < git < hg < p.
Numeric components after the first compare as C strings when either side
has a leading zero (fractional semantics), else numerically.
"""

from __future__ import annotations

import re

from trivy_tpu.versioning import base
from trivy_tpu.versioning.base import Inexact, ParseError, Scheme, cmp

_PRE = {"alpha": 0, "beta": 1, "pre": 2, "rc": 3}
_POST = {"cvs": 0, "svn": 1, "git": 2, "hg": 3, "p": 4}

_RX = re.compile(
    r"^(?P<nums>\d+(?:\.\d+)*)"
    r"(?P<letter>[a-z])?"
    r"(?P<suffixes>(?:_(?:alpha|beta|pre|rc|cvs|svn|git|hg|p)\d*)*)"
    r"(?P<rev>-r\d+)?$"
)

# ascending tag order == ascending version order at a given position.
# Derived from the apk rule "higher token enum is older" plus the
# pre-release exception (see module docstring):
#   SUFFIX_PRE < END < REVISION_NO < SUFFIX_NO < SUFFIX_POST < LETTER
#   < NUM_ZERO < NUM
TAG_SUFFIX_PRE = 0x08
TAG_END = 0x10
TAG_REV = 0x18
TAG_SUFFIX_NO = 0x20
TAG_SUFFIX_POST = 0x28
TAG_LETTER = 0x30
TAG_NUM_ZERO = 0x38  # numeric component with leading zero: string compare
TAG_NUM = 0x40

# token kinds for compare()
_K_NUM, _K_LETTER, _K_SUFFIX, _K_SUFFIX_NO, _K_REV, _K_END = range(6)


class ApkVersion:
    __slots__ = ("parts",)

    def __init__(self, parts: list):
        self.parts = parts  # [(kind, value)]


def _parse_tokens(s: str) -> list:
    m = _RX.match(s)
    if not m:
        raise ParseError(f"invalid apk version {s!r}")
    toks: list = []
    for i, comp in enumerate(m.group("nums").split(".")):
        # first component always numeric; later ones keep the raw string so
        # leading-zero fractional compare is possible
        toks.append((_K_NUM, comp if i > 0 else str(int(comp))))
    if m.group("letter"):
        toks.append((_K_LETTER, m.group("letter")))
    for suf in filter(None, m.group("suffixes").split("_")):
        name = suf.rstrip("0123456789")
        num = suf[len(name):]
        toks.append((_K_SUFFIX, name))
        if num:
            toks.append((_K_SUFFIX_NO, int(num)))
    if m.group("rev"):
        toks.append((_K_REV, int(m.group("rev")[2:])))
    return toks


def _cmp_numeric(a: str, b: str) -> int:
    # apk: if either has a leading zero (len>1), compare as C strings
    if (a.startswith("0") and len(a) > 1) or (b.startswith("0") and len(b) > 1):
        return cmp(a, b)
    return cmp(int(a), int(b))


class ApkScheme(Scheme):
    name = "apk"

    def parse(self, s: str) -> ApkVersion:
        return ApkVersion(_parse_tokens(s.strip()))

    def compare_parsed(self, a: ApkVersion, b: ApkVersion) -> int:
        ta, tb = a.parts, b.parts
        for i in range(max(len(ta), len(tb))):
            ka, va = ta[i] if i < len(ta) else (_K_END, None)
            kb, vb = tb[i] if i < len(tb) else (_K_END, None)
            if ka == kb:
                if ka == _K_END:
                    return 0
                if ka == _K_NUM:
                    d = _cmp_numeric(va, vb)
                elif ka == _K_SUFFIX:
                    pa, pb = va in _PRE, vb in _PRE
                    if pa != pb:
                        return -1 if pa else 1
                    table = _PRE if pa else _POST
                    d = cmp(table[va], table[vb])
                else:
                    d = cmp(va, vb)
                if d:
                    return d
                continue
            # different kinds: pre-release suffix is older than anything
            if ka == _K_SUFFIX and va in _PRE:
                return -1
            if kb == _K_SUFFIX and vb in _PRE:
                return 1
            # otherwise higher kind enum = older
            return 1 if ka < kb else -1
        return 0

    def tokens(self, s: str):
        toks = []
        for k, v in self.parse(s).parts:
            if k == _K_NUM:
                # any '0'-led component (including "0" itself) sorts below all
                # 1-9-led ones both under apk string compare and numerically,
                # so NUM_ZERO(string payload) < NUM(numeric payload) is exact
                if v.startswith("0"):
                    toks.append((TAG_NUM_ZERO, base.str_payload(v)))
                else:
                    toks.append((TAG_NUM, base.num_payload(int(v))))
            elif k == _K_LETTER:
                toks.append((TAG_LETTER, base.str_payload(v)))
            elif k == _K_SUFFIX:
                if v in _PRE:
                    toks.append((TAG_SUFFIX_PRE, base.num_payload(_PRE[v])))
                else:
                    toks.append((TAG_SUFFIX_POST, base.num_payload(_POST[v])))
            elif k == _K_SUFFIX_NO:
                toks.append((TAG_SUFFIX_NO, base.num_payload(v)))
            elif k == _K_REV:
                toks.append((TAG_REV, base.num_payload(v)))
        toks.append((TAG_END, b"\x00" * 7))
        return toks

    def _tokens_lossy(self, s: str):
        toks = []
        for k, v in self.parse(s).parts:
            try:
                if k == _K_NUM:
                    if v.startswith("0"):
                        toks.append((TAG_NUM_ZERO, base.str_payload(v[:6])))
                    else:
                        toks.append((TAG_NUM, base.num_payload(min(int(v), (1 << 56) - 1))))
                elif k == _K_LETTER:
                    toks.append((TAG_LETTER, base.str_payload(v)))
                elif k == _K_SUFFIX:
                    if v in _PRE:
                        toks.append((TAG_SUFFIX_PRE, base.num_payload(_PRE[v])))
                    else:
                        toks.append((TAG_SUFFIX_POST, base.num_payload(_POST[v])))
                elif k == _K_SUFFIX_NO:
                    toks.append((TAG_SUFFIX_NO, base.num_payload(min(v, (1 << 56) - 1))))
                elif k == _K_REV:
                    toks.append((TAG_REV, base.num_payload(min(v, (1 << 56) - 1))))
            except Inexact:
                break
        toks.append((TAG_END, b"\x00" * 7))
        return toks


SCHEME = ApkScheme()
