"""Version-scheme plumbing shared by all comparators.

Each scheme (apk/deb/rpm/generic/npm/pep440/maven/rubygems/bitnami) provides:
  parse(s)        -> opaque parsed form
  compare(a, b)   -> -1/0/+1 exact total order (host truth; mirrors the
                     reference's per-scheme Go libs, e.g. knqyf263/go-deb-version)
  tokens(s)       -> [(tag, payload)] token stream whose flat lexicographic
                     order equals compare() order — or raises Inexact.

The token stream is packed (pack_key) into a fixed-width byte key so numpy
searchsorted / the TPU kernel can rank versions with pure integer compares.
A version whose ordering cannot be exactly captured in the fixed width is
flagged inexact; the tensor compiler then marks the row NEEDS_HOST and the
match kernel emits it as an always-candidate for exact host rescreen
(zero-diff guarantee, SURVEY.md §7 hard part #1).
"""

from __future__ import annotations

# 24 groups comfortably covers real distro versions (e.g. debian
# "1.1.1k-1+deb11u2" = 18 tokens); keys are host-side only — the device
# sees int32 ranks — so width costs nothing on TPU
KEY_GROUPS = 24  # tokens per key
GROUP_BYTES = 8  # 1 tag byte + 7 payload bytes
KEY_BYTES = KEY_GROUPS * GROUP_BYTES

# Reserved low tag values usable by any scheme. A scheme may define its own
# tags as long as their numeric order equals the intended sort order.
TAG_MIN = 0x01
TAG_END = 0x10  # terminator; every token stream must end with exactly one

STR_TERM = 0x02  # terminator char appended to every string payload


class Inexact(Exception):
    """Raised when a version can't be exactly encoded in the fixed key."""


class ParseError(ValueError):
    """Raised when a version string is unparseable for the scheme."""


def num_payload(n: int) -> bytes:
    """7-byte big-endian unsigned. Values >= 2^56 can't be represented."""
    if n < 0:
        raise Inexact(f"negative numeric component {n}")
    if n >= 1 << 56:
        raise Inexact(f"numeric component too large: {n}")
    return n.to_bytes(7, "big")


def str_payload(s: str, char_map=None) -> bytes:
    """Remapped chars + terminator, zero-padded to 7 bytes.

    char_map maps a character to its 1-byte sort value (must be > STR_TERM
    for chars that sort after end-of-string, < STR_TERM for chars like
    Debian's '~' that sort before it). Default: chr -> ord clamped printable,
    offset above STR_TERM.
    """
    out = bytearray()
    for ch in s:
        if char_map is not None:
            v = char_map(ch)
        else:
            v = min(ord(ch), 0xFF - STR_TERM - 1) + STR_TERM + 1
        out.append(v)
    out.append(STR_TERM)
    if len(out) > 7:
        raise Inexact(f"string component too long: {s!r}")
    return bytes(out) + b"\x00" * (7 - len(out))


def pack_key(tokens) -> bytes:
    """[(tag, payload7)] -> fixed KEY_BYTES key. Raises Inexact on overflow."""
    if len(tokens) > KEY_GROUPS:
        raise Inexact(f"too many tokens: {len(tokens)}")
    out = bytearray()
    for tag, payload in tokens:
        if not (0 < tag < 256):
            raise ValueError(f"bad tag {tag}")
        if len(payload) != 7:
            raise ValueError(f"payload must be 7 bytes, got {len(payload)}")
        out.append(tag)
        out += payload
    out += b"\x00" * (KEY_BYTES - len(out))
    return bytes(out)


MIN_KEY = b"\x00" * KEY_BYTES  # sorts before every packed key
MAX_KEY = b"\xff" * KEY_BYTES  # sorts after every packed key


class Scheme:
    """Base class; subclasses implement parse/compare_parsed/tokens."""

    name = "base"

    def parse(self, s: str):
        raise NotImplementedError

    def compare_parsed(self, a, b) -> int:
        raise NotImplementedError

    def compare(self, a: str, b: str) -> int:
        return self.compare_parsed(self.parse(a), self.parse(b))

    def tokens(self, s: str):
        raise NotImplementedError

    def key(self, s: str) -> tuple[bytes, bool]:
        """Returns (packed key, exact). On Inexact — or an unparseable
        version — returns a best-effort key with exact=False (still usable
        as a search anchor; the caller must treat comparisons against it as
        uncertain and take the exact host path)."""
        try:
            return pack_key(self.tokens(s)), True
        except (Inexact, ParseError):
            try:
                toks = self._tokens_lossy(s)
                if len(toks) > KEY_GROUPS:
                    toks = toks[:KEY_GROUPS - 1] + toks[-1:]
                return pack_key(toks), False
            except Exception:
                return MIN_KEY, False

    def _tokens_lossy(self, s: str):
        """Best-effort token stream where individual tokens never raise:
        long strings truncated, large numbers clamped."""
        raise Inexact("no lossy encoding")


def cmp(a, b) -> int:
    return (a > b) - (a < b)
