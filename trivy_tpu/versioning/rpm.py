"""RPM version comparison (rpmvercmp semantics with epoch:version-release).

Exact re-implementation of the ordering used by the reference via
knqyf263/go-rpm-version (reference pkg/detector/ospkg/redhat/redhat.go,
oracle, amazon, etc.).

rpmvercmp: tokenize into digit runs and alpha runs (separators delimit only);
'~' sorts before anything including end; '^' sorts after end but before any
further token; digit tokens beat alpha tokens; digit runs compare numerically
(leading zeros stripped); alpha runs compare by ASCII.
"""

from __future__ import annotations

import re

from trivy_tpu.versioning import base
from trivy_tpu.versioning.base import ParseError, Scheme, cmp

# ascending tag order == ascending version order at a given position
TAG_TILDE = 0x08
TAG_END = 0x10  # also the epoch/version/release field separator
TAG_CARET = 0x18
TAG_ALPHA = 0x20
TAG_NUM = 0x30

_TOKEN = re.compile(r"[0-9]+|[A-Za-z]+|~|\^")


def _tokenize(s: str) -> list:
    """-> list of int | str | '~' | '^'; separators dropped."""
    out: list = []
    for m in _TOKEN.finditer(s):
        t = m.group(0)
        if t[0].isdigit():
            out.append(int(t))
        else:
            out.append(t)
    return out


def rpmvercmp(a: str, b: str) -> int:
    if a == b:
        return 0
    ta, tb = _tokenize(a), _tokenize(b)
    for i in range(max(len(ta), len(tb))):
        xa = ta[i] if i < len(ta) else None
        xb = tb[i] if i < len(tb) else None
        if xa == xb:
            continue
        # tilde sorts lowest, even vs end
        if xa == "~":
            return -1
        if xb == "~":
            return 1
        # caret: above end, below any other continuation
        if xa == "^":
            return 1 if xb is None else -1
        if xb == "^":
            return -1 if xa is None else 1
        if xa is None:
            return -1
        if xb is None:
            return 1
        na, nb = isinstance(xa, int), isinstance(xb, int)
        if na and nb:
            d = cmp(xa, xb)
        elif na != nb:
            d = 1 if na else -1  # digits beat alphas
        else:
            d = cmp(xa, xb)
        if d:
            return d
    return 0


class RpmVersion:
    __slots__ = ("epoch", "version", "release")

    def __init__(self, epoch: int, version: str, release: str):
        self.epoch = epoch
        self.version = version
        self.release = release


class RpmScheme(Scheme):
    name = "rpm"

    def parse(self, s: str) -> RpmVersion:
        s = s.strip()
        if not s:
            raise ParseError("empty rpm version")
        epoch = 0
        if ":" in s:
            e, _, rest = s.partition(":")
            if e.isdigit():
                epoch, s = int(e), rest
            elif e == "":
                s = rest
            else:
                raise ParseError(f"bad epoch in {s!r}")
        if "-" in s:
            version, _, release = s.rpartition("-")
        else:
            version, release = s, ""
        return RpmVersion(epoch, version, release)

    def compare_parsed(self, a: RpmVersion, b: RpmVersion) -> int:
        return (
            cmp(a.epoch, b.epoch)
            or rpmvercmp(a.version, b.version)
            or rpmvercmp(a.release, b.release)
        )

    def _field_tokens(self, field: str, toks: list) -> None:
        for t in _tokenize(field):
            if t == "~":
                toks.append((TAG_TILDE, b"\x00" * 7))
            elif t == "^":
                toks.append((TAG_CARET, b"\x00" * 7))
            elif isinstance(t, int):
                toks.append((TAG_NUM, base.num_payload(t)))
            else:
                toks.append((TAG_ALPHA, base.str_payload(t)))
        toks.append((TAG_END, b"\x00" * 7))

    def tokens(self, s: str):
        v = self.parse(s)
        toks = [(TAG_NUM, base.num_payload(v.epoch))]
        self._field_tokens(v.version, toks)
        self._field_tokens(v.release, toks)
        return toks

    def _tokens_lossy(self, s: str):
        v = self.parse(s)
        toks = [(TAG_NUM, base.num_payload(min(v.epoch, (1 << 56) - 1)))]
        for field in (v.version, v.release):
            for t in _tokenize(field):
                if t == "~":
                    toks.append((TAG_TILDE, b"\x00" * 7))
                elif t == "^":
                    toks.append((TAG_CARET, b"\x00" * 7))
                elif isinstance(t, int):
                    toks.append((TAG_NUM, base.num_payload(min(t, (1 << 56) - 1))))
                else:
                    payload = t.encode("ascii", "replace")[:6] + bytes([base.STR_TERM])
                    toks.append((TAG_ALPHA, payload.ljust(7, b"\x00")))
            toks.append((TAG_END, b"\x00" * 7))
        return toks


SCHEME = RpmScheme()
