"""Debian package version comparison (Debian Policy 5.6.12).

Exact re-implementation of the ordering used by the reference via
knqyf263/go-deb-version (reference pkg/detector/ospkg/debian/debian.go:7).

Format: [epoch:]upstream[-revision]
- epoch: integer, default 0
- revision: split on the LAST '-'; absent revision == "0"
- verrevcmp: alternate longest non-digit / digit runs; non-digit runs compare
  char-wise with all letters before all non-letters and '~' before anything,
  including end-of-part; digit runs compare numerically.
"""

from __future__ import annotations

import re

from trivy_tpu.versioning import base
from trivy_tpu.versioning.base import ParseError, Scheme, cmp

_VALID = re.compile(r"^[0-9][A-Za-z0-9.+:~-]*$|^[A-Za-z0-9.+:~-]+$")

TAG_STR = 0x20
TAG_NUM = 0x30


def _char_order(c: str) -> int:
    """Debian lexical order: '~' < end-of-part < letters < non-letters."""
    if c == "~":
        return base.STR_TERM - 1  # 0x01, below the terminator
    if c.isalpha():
        return base.STR_TERM + 1 + (ord(c) - 65)  # letters keep ASCII order
    return base.STR_TERM + 1 + 58 + min(ord(c), 150)  # non-letters after


def _split_runs(s: str) -> list:
    """-> alternating [str, int, str, int, ...] starting with a (possibly
    empty) non-digit run."""
    runs: list = []
    i, n = 0, len(s)
    while i < n:
        j = i
        while j < n and not s[j].isdigit():
            j += 1
        runs.append(s[i:j])
        i = j
        j = i
        while j < n and s[j].isdigit():
            j += 1
        runs.append(int(s[i:j]) if j > i else 0)
        i = j
    if not runs:
        runs = ["", 0]
    return runs


def _cmp_nondigit(a: str, b: str) -> int:
    for ca, cb in zip(a, b):
        d = cmp(_char_order(ca), _char_order(cb))
        if d:
            return d
    if len(a) == len(b):
        return 0
    # the shorter part ends first; end-of-part sorts before anything but '~'
    if len(a) < len(b):
        return -1 if b[len(a)] != "~" else 1
    return 1 if a[len(b)] != "~" else -1


def _verrevcmp(a: str, b: str) -> int:
    ra, rb = _split_runs(a), _split_runs(b)
    for i in range(max(len(ra), len(rb))):
        xa = ra[i] if i < len(ra) else ("" if i % 2 == 0 else 0)
        xb = rb[i] if i < len(rb) else ("" if i % 2 == 0 else 0)
        d = _cmp_nondigit(xa, xb) if i % 2 == 0 else cmp(xa, xb)
        if d:
            return d
    return 0


class DebVersion:
    __slots__ = ("epoch", "upstream", "revision")

    def __init__(self, epoch: int, upstream: str, revision: str):
        self.epoch = epoch
        self.upstream = upstream
        self.revision = revision


class DebScheme(Scheme):
    name = "deb"

    def parse(self, s: str) -> DebVersion:
        s = s.strip()
        if not s:
            raise ParseError("empty debian version")
        epoch = 0
        if ":" in s:
            e, _, rest = s.partition(":")
            if not e.isdigit():
                raise ParseError(f"bad epoch in {s!r}")
            epoch, s = int(e), rest
        if "-" in s:
            upstream, _, revision = s.rpartition("-")
        else:
            upstream, revision = s, "0"
        if not upstream:
            raise ParseError(f"empty upstream version in {s!r}")
        if not _VALID.match(upstream) or not re.match(r"^[A-Za-z0-9+.~]*$", revision):
            raise ParseError(f"invalid debian version {s!r}")
        return DebVersion(epoch, upstream, revision)

    def compare_parsed(self, a: DebVersion, b: DebVersion) -> int:
        return (
            cmp(a.epoch, b.epoch)
            or _verrevcmp(a.upstream, b.upstream)
            or _verrevcmp(a.revision, b.revision)
        )

    def _runs_tokens(self, runs: list, toks: list) -> None:
        for i, r in enumerate(runs):
            if i % 2 == 0:
                toks.append((TAG_STR, base.str_payload(r, _char_order)))
            else:
                toks.append((TAG_NUM, base.num_payload(r)))

    def tokens(self, s: str):
        v = self.parse(s)
        toks = [(TAG_NUM, base.num_payload(v.epoch))]
        self._runs_tokens(_split_runs(v.upstream), toks)
        # field separator doubles as end-of-upstream: empty string payload
        # sorts above '~'-led continuations and below everything else,
        # exactly like Debian end-of-part.
        toks.append((TAG_STR, base.str_payload("", _char_order)))
        self._runs_tokens(_split_runs(v.revision), toks)
        toks.append((TAG_STR, base.str_payload("", _char_order)))
        return toks

    def _tokens_lossy(self, s: str):
        v = self.parse(s)
        toks = [(TAG_NUM, base.num_payload(min(v.epoch, (1 << 56) - 1)))]
        for field in (v.upstream, v.revision):
            for i, r in enumerate(_split_runs(field)):
                if i % 2 == 0:
                    payload = bytearray()
                    for ch in r[:6]:
                        payload.append(_char_order(ch))
                    payload.append(base.STR_TERM)
                    payload = bytes(payload[:7]).ljust(7, b"\x00")
                    toks.append((TAG_STR, payload))
                else:
                    toks.append((TAG_NUM, base.num_payload(min(r, (1 << 56) - 1))))
            toks.append((TAG_STR, base.str_payload("", _char_order)))
        return toks


SCHEME = DebScheme()
