"""Flexible semver core shared by the generic/npm/bitnami schemes.

Models the behavior of aquasecurity/go-version (used by the reference's
GenericComparer, pkg/detector/library/compare/compare.go:58) and
node-semver ordering (aquasecurity/go-npm-version, compare/npm/):
- dot-separated numeric segments (any count; missing segments == 0)
- optional pre-release after '-' (dot-separated identifiers; numeric
  identifiers compare numerically and sort before alphanumeric ones;
  a version WITH pre-release sorts before the same version without)
- build metadata after '+' is ignored for ordering
"""

from __future__ import annotations

import re

from trivy_tpu.versioning import base
from trivy_tpu.versioning.base import Inexact, ParseError, Scheme, cmp

_RX = re.compile(
    r"^[vV]?\s*(?P<nums>\d+(?:\.\d+)*)"
    r"(?:[-.](?P<pre>[0-9A-Za-z-]+(?:\.[0-9A-Za-z-]+)*))?"
    r"(?:\+(?P<build>[0-9A-Za-z.-]+))?$"
)

NUM_SLOTS = 5  # numeric segments kept exactly; more -> Inexact

# ascending tag order == ascending version order
TAG_PRE_MARK = 0x08  # has pre-release
TAG_REL_MARK = 0x10  # release (no pre-release)
TAG_PRE_NUM = 0x18  # numeric pre-release identifier (< alphanumeric)
TAG_PRE_STR = 0x20
TAG_PRE_END = 0x0c  # end of pre-release identifiers (sorts below more idents)
TAG_NUM = 0x30


class SemVersion:
    __slots__ = ("nums", "pre", "build", "raw")

    def __init__(self, nums, pre, build, raw):
        self.nums = nums  # tuple[int, ...]
        self.pre = pre  # tuple of int|str identifiers, () if release
        self.build = build
        self.raw = raw

    def num(self, i: int) -> int:
        return self.nums[i] if i < len(self.nums) else 0

    @property
    def major(self) -> int:
        return self.num(0)

    @property
    def minor(self) -> int:
        return self.num(1)

    @property
    def patch(self) -> int:
        return self.num(2)

    def core(self) -> tuple:
        return (self.major, self.minor, self.patch)


def parse_semver(s: str, loose_pre_dot: bool = False) -> SemVersion:
    raw = s
    s = s.strip()
    m = _RX.match(s)
    if not m:
        raise ParseError(f"invalid version {raw!r}")
    if not loose_pre_dot and m.group("pre") is not None:
        # strict: pre-release must be introduced by '-', not '.'
        core_end = m.end("nums")
        if core_end < len(s) and s[core_end] == ".":
            raise ParseError(f"invalid version {raw!r}")
    nums = tuple(int(x) for x in m.group("nums").split("."))
    pre_raw = m.group("pre")
    pre: tuple = ()
    if pre_raw is not None:
        pre = tuple(
            int(p) if p.isdigit() else p for p in pre_raw.split(".")
        )
    return SemVersion(nums, pre, m.group("build") or "", raw)


def cmp_prerelease(a: tuple, b: tuple) -> int:
    if not a and not b:
        return 0
    if not a:
        return 1  # release > pre-release
    if not b:
        return -1
    for xa, xb in zip(a, b):
        na, nb = isinstance(xa, int), isinstance(xb, int)
        if na and nb:
            d = cmp(xa, xb)
        elif na != nb:
            d = -1 if na else 1  # numeric idents sort before alphanumeric
        else:
            d = cmp(xa, xb)
        if d:
            return d
    return cmp(len(a), len(b))  # more identifiers = higher precedence


def cmp_semver(a: SemVersion, b: SemVersion) -> int:
    for i in range(max(len(a.nums), len(b.nums))):
        d = cmp(a.num(i), b.num(i))
        if d:
            return d
    return cmp_prerelease(a.pre, b.pre)


def semver_tokens(v: SemVersion) -> list:
    """Token stream for a parsed semver-ish version (see module docstring
    of trivy_tpu.versioning.base for the key contract)."""
    if len(v.nums) > NUM_SLOTS:
        # the extra segments would be silently dropped -> inexact unless zero
        if any(n != 0 for n in v.nums[NUM_SLOTS:]):
            raise Inexact(f"more than {NUM_SLOTS} numeric segments: {v.raw!r}")
    toks = [(TAG_NUM, base.num_payload(v.num(i))) for i in range(NUM_SLOTS)]
    if not v.pre:
        toks.append((TAG_REL_MARK, b"\x00" * 7))
        return toks
    toks.append((TAG_PRE_MARK, b"\x00" * 7))
    for ident in v.pre:
        if isinstance(ident, int):
            toks.append((TAG_PRE_NUM, base.num_payload(ident)))
        else:
            toks.append((TAG_PRE_STR, base.str_payload(ident)))
    toks.append((TAG_PRE_END, b"\x00" * 7))
    return toks


def semver_tokens_lossy(v: SemVersion) -> list:
    toks = []
    for i in range(NUM_SLOTS):
        toks.append((TAG_NUM, base.num_payload(min(v.num(i), (1 << 56) - 1))))
    if not v.pre:
        toks.append((TAG_REL_MARK, b"\x00" * 7))
        return toks
    toks.append((TAG_PRE_MARK, b"\x00" * 7))
    for ident in v.pre[:4]:
        if isinstance(ident, int):
            toks.append((TAG_PRE_NUM, base.num_payload(min(ident, (1 << 56) - 1))))
        else:
            toks.append((TAG_PRE_STR, base.str_payload(ident[:6])))
    toks.append((TAG_PRE_END, b"\x00" * 7))
    return toks


class GenericScheme(Scheme):
    """aquasecurity/go-version-style flexible semver (reference
    pkg/detector/library/compare/compare.go GenericComparer)."""

    name = "generic"

    def parse(self, s: str) -> SemVersion:
        return parse_semver(s)

    def compare_parsed(self, a: SemVersion, b: SemVersion) -> int:
        return cmp_semver(a, b)

    def tokens(self, s: str):
        return semver_tokens(self.parse(s))

    def _tokens_lossy(self, s: str):
        return semver_tokens_lossy(self.parse(s))


SCHEME = GenericScheme()
