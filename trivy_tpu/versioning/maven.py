"""Maven ComparableVersion ordering (reference uses
aquasecurity/go-mvn-version, pkg/detector/library/compare/maven).

compare() implements the full org.apache.maven ComparableVersion algorithm
(ListItem/StringItem/IntegerItem with trailing-null normalization, qualifier
ranking alpha < beta < milestone < rc < snapshot < "" < sp < other, implicit
separators at digit<->letter transitions, string < list < int at a given
position).

tokens() flattens to the shared tagged stream for the common shapes
(dotted numerals + a simple qualifier chain). Shapes where flattening can
misorder against a differently-nested spelling (a '-' group followed by
further separators) raise Inexact -> exact host path.
"""

from __future__ import annotations

import re

from trivy_tpu.versioning import base
from trivy_tpu.versioning.base import Inexact, ParseError, Scheme, cmp

_QUALIFIERS = ["alpha", "beta", "milestone", "rc", "snapshot", "", "sp"]
_ALIASES = {"ga": "", "final": "", "release": "", "cr": "rc"}
_SHORT = {"a": "alpha", "b": "beta", "m": "milestone"}

# ascending tag order == ascending version order at the qualifier position:
#   alpha..snapshot < release(end) < sp < unknown strings < numbers
TAG_Q_ALPHA = 0x08
TAG_Q_BETA = 0x09
TAG_Q_MILESTONE = 0x0a
TAG_Q_RC = 0x0b
TAG_Q_SNAPSHOT = 0x0c
TAG_END = 0x10
TAG_Q_SP = 0x14
TAG_Q_OTHER = 0x18
TAG_NUM = 0x30

_Q_TAG = {
    "alpha": TAG_Q_ALPHA,
    "beta": TAG_Q_BETA,
    "milestone": TAG_Q_MILESTONE,
    "rc": TAG_Q_RC,
    "snapshot": TAG_Q_SNAPSHOT,
    "sp": TAG_Q_SP,
}

NUM_SLOTS = 5


# ---------------------------------------------------------------- parsing

INT, STR, LIST = 0, 1, 2


def _parse_item(s: str, is_digit: bool, followed_by_digit: bool):
    if is_digit:
        return (INT, int(s))
    s = _ALIASES.get(s, s)
    if followed_by_digit and s in _SHORT:
        s = _SHORT[s]
    return (STR, s)


def _is_null(item) -> bool:
    kind, val = item
    if kind == INT:
        return val == 0
    if kind == STR:
        return val in ("", "final", "ga")
    return len(val) == 0


def _trim(lst: list) -> None:
    while lst and _is_null(lst[-1]):
        lst.pop()


def _normalize(lst: list) -> None:
    for kind, val in lst:
        if kind == LIST:
            _normalize(val)
    _trim(lst)


def parse_cv(version: str) -> tuple:
    """Parse into the nested (LIST, [...]) structure of ComparableVersion.

    '-' (and any digit<->letter transition, which the version-order spec
    treats as a hyphen) normalizes the current list (trims trailing nulls)
    and opens a sub-list; '.' appends to the current list.
    """
    version = version.lower()
    root: list = []
    cur = root
    start = 0
    is_digit = version[:1].isdigit()

    def open_sublist():
        nonlocal cur
        _trim(cur)
        new: list = []
        cur.append((LIST, new))
        cur = new

    i = 0
    for i, ch in enumerate(version):
        if ch == ".":
            cur.append(
                (INT, 0) if i == start
                else _parse_item(version[start:i], is_digit, False)
            )
            start = i + 1
            is_digit = version[i + 1: i + 2].isdigit()
        elif ch == "-":
            cur.append(
                (INT, 0) if i == start
                else _parse_item(version[start:i], is_digit,
                                 version[i + 1: i + 2].isdigit())
            )
            start = i + 1
            open_sublist()
            is_digit = version[i + 1: i + 2].isdigit()
        elif ch.isdigit() != is_digit:
            # digit<->letter transition == hyphen
            if i > start:
                cur.append(_parse_item(version[start:i], is_digit, ch.isdigit()))
            start = i
            open_sublist()
            is_digit = ch.isdigit()
    if len(version) > start:
        cur.append(_parse_item(version[start:], is_digit, False))
    elif version.endswith((".", "-")) or not version:
        cur.append((INT, 0))
    _normalize(root)
    return (LIST, root)


def _q_order(q: str) -> tuple:
    q = _ALIASES.get(q, q)
    if q in _QUALIFIERS:
        return (_QUALIFIERS.index(q), "")
    return (len(_QUALIFIERS), q)


def _cmp_items(a, b) -> int:
    if a is None and b is None:
        return 0
    if a is None:
        return -_cmp_items(b, None)
    ka, va = a
    if b is None:
        if ka == INT:
            return 0 if va == 0 else 1
        if ka == STR:
            return cmp(_q_order(va), _q_order(""))
        # LIST vs null: decided by the list's first item (maven quirk)
        return _cmp_items(va[0], None) if va else 0
    kb, vb = b
    if ka != kb:
        # string < list < int
        rank = {STR: 1, LIST: 2, INT: 3}
        return cmp(rank[ka], rank[kb])
    if ka == INT:
        return cmp(va, vb)
    if ka == STR:
        return cmp(_q_order(va), _q_order(vb))
    # both lists
    for i in range(max(len(va), len(vb))):
        xa = va[i] if i < len(va) else None
        xb = vb[i] if i < len(vb) else None
        d = _cmp_items(xa, xb)
        if d:
            return d
    return 0


# -------------------------------------------------------------- tokens

_SIMPLE = re.compile(r"^v?(?P<nums>\d+(\.\d+)*)(?P<rest>[.\-a-z0-9]*)$", re.I)
_CHAIN_EL = re.compile(r"[0-9]+|[a-z]+", re.I)


class MavenVersion:
    __slots__ = ("cv", "raw")

    def __init__(self, cv, raw: str):
        self.cv = cv
        self.raw = raw


class MavenScheme(Scheme):
    name = "maven"

    def parse(self, s: str) -> MavenVersion:
        s = s.strip()
        if not s:
            raise ParseError("empty maven version")
        return MavenVersion(parse_cv(s), s)

    def compare_parsed(self, a: MavenVersion, b: MavenVersion) -> int:
        return _cmp_items(a.cv, b.cv)

    def tokens(self, s: str):
        s0 = s.strip().lower()
        m = _SIMPLE.match(s0)
        if not m:
            raise Inexact(f"non-simple maven version: {s!r}")
        rest = m.group("rest")
        # '.'-separated suffix elements nest differently than '-'/transition
        # ones ([alpha,1] vs [alpha,[1]]), which a flat encoding cannot
        # distinguish -> host path. Pure release aliases are a no-op.
        if "." in rest and rest not in (".ga", ".final", ".release"):
            raise Inexact(f"dotted maven suffix: {s!r}")
        nums = [int(x) for x in m.group("nums").split(".")]
        while nums and nums[-1] == 0:
            nums.pop()
        if len(nums) > NUM_SLOTS:
            raise Inexact(f"too many numeric segments: {s!r}")
        toks = [
            (TAG_NUM, base.num_payload(nums[i] if i < len(nums) else 0))
            for i in range(NUM_SLOTS)
        ]
        # chain elements: alternating qualifiers / numbers
        els = _CHAIN_EL.findall(rest)
        # canonical: drop trailing null elements (0, release aliases)
        while els and (els[-1] in ("ga", "final", "release") or
                       (els[-1].isdigit() and int(els[-1]) == 0)):
            els.pop()
        for i, el in enumerate(els):
            if el.isdigit():
                toks.append((TAG_NUM, base.num_payload(int(el))))
                continue
            q = _ALIASES.get(el, el)
            nxt_digit = i + 1 < len(els) and els[i + 1].isdigit()
            if q in _SHORT and nxt_digit:
                q = _SHORT[q]
            if q in _Q_TAG:
                toks.append((_Q_TAG[q], b"\x00" * 7))
            elif q == "":
                # mid-chain release alias ("1.0-ga-1") nests as ['',[1]]
                # which a flat stream can't distinguish from [1] -> host path
                raise Inexact(f"mid-chain release alias: {s!r}")
            else:
                toks.append((TAG_Q_OTHER, base.str_payload(q)))
        toks.append((TAG_END, b"\x00" * 7))
        return toks

    def _tokens_lossy(self, s: str):
        s0 = s.strip().lower()
        m = _SIMPLE.match(s0)
        if not m:
            raise Inexact(f"unencodable maven version: {s!r}")
        cap = (1 << 56) - 1
        nums = [int(x) for x in m.group("nums").split(".")]
        while nums and nums[-1] == 0:
            nums.pop()
        toks = [
            (TAG_NUM, base.num_payload(min(nums[i] if i < len(nums) else 0, cap)))
            for i in range(NUM_SLOTS)
        ]
        for el in _CHAIN_EL.findall(m.group("rest"))[:4]:
            if el.isdigit():
                toks.append((TAG_NUM, base.num_payload(min(int(el), cap))))
            else:
                q = _ALIASES.get(el, el)
                if q in _Q_TAG:
                    toks.append((_Q_TAG[q], b"\x00" * 7))
                else:
                    toks.append((TAG_Q_OTHER, base.str_payload(q[:6])))
        toks.append((TAG_END, b"\x00" * 7))
        return toks


SCHEME = MavenScheme()
