"""Version-range constraints.

Grammar (covers the constraint strings stored in trivy-db, which the
reference evaluates via per-ecosystem Go libs — pkg/detector/library/compare):
  constraint  = group ("||" group)*          # OR
  group       = comparator ((","|space) comparator)*   # AND
  comparator  = [op] version | version " - " version   # npm hyphen range
  op          = = | == | != | > | < | >= | <= | ~> | ~ | ^
  version may contain x/X/* wildcard segments (npm/pep440 style)

Every constraint can also be compiled to a union of half-open intervals over
the scheme's total order (intervals()), which is what the DB tensor compiler
feeds the TPU kernel (SURVEY.md §7 step 2). The interval set is always a
SUPERSET of check() (equal except for the npm pre-release restriction), so
kernel candidates can never miss a true match.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from trivy_tpu.versioning.base import ParseError, Scheme

_OPS = ("~=", "==", ">=", "<=", "!=", "~>", "=", ">", "<", "~", "^")

_COMP_RX = re.compile(
    r"\s*(?P<op>~=|==|>=|<=|!=|~>|=|>|<|~|\^)?\s*(?P<ver>[^\s,|]+)"
)


@dataclass(frozen=True)
class Interval:
    """lo/hi are parsed versions or None for unbounded."""

    lo: object = None
    lo_incl: bool = True
    hi: object = None
    hi_incl: bool = True

    def is_empty(self, scheme: Scheme) -> bool:
        if self.lo is None or self.hi is None:
            return False
        d = scheme.compare_parsed(self.lo, self.hi)
        return d > 0 or (d == 0 and not (self.lo_incl and self.hi_incl))

    def contains(self, v, scheme: Scheme) -> bool:
        if self.lo is not None:
            d = scheme.compare_parsed(v, self.lo)
            if d < 0 or (d == 0 and not self.lo_incl):
                return False
        if self.hi is not None:
            d = scheme.compare_parsed(v, self.hi)
            if d > 0 or (d == 0 and not self.hi_incl):
                return False
        return True


def _intersect(a: Interval, b: Interval, scheme: Scheme) -> Interval | None:
    lo, lo_incl = a.lo, a.lo_incl
    if b.lo is not None:
        if lo is None:
            lo, lo_incl = b.lo, b.lo_incl
        else:
            d = scheme.compare_parsed(b.lo, lo)
            if d > 0:
                lo, lo_incl = b.lo, b.lo_incl
            elif d == 0:
                lo_incl = lo_incl and b.lo_incl
    hi, hi_incl = a.hi, a.hi_incl
    if b.hi is not None:
        if hi is None:
            hi, hi_incl = b.hi, b.hi_incl
        else:
            d = scheme.compare_parsed(b.hi, hi)
            if d < 0:
                hi, hi_incl = b.hi, b.hi_incl
            elif d == 0:
                hi_incl = hi_incl and b.hi_incl
    out = Interval(lo, lo_incl, hi, hi_incl)
    return None if out.is_empty(scheme) else out


class Comparator:
    """One op+version term, expanded to a union of intervals plus metadata
    for the npm pre-release rule."""

    __slots__ = ("op", "ver_str", "intervals", "pre_core")

    def __init__(self, op: str, ver_str: str, intervals: list, pre_core):
        self.op = op
        self.ver_str = ver_str
        self.intervals = intervals  # list[Interval], ORed
        self.pre_core = pre_core  # (maj, min, patch) if version had a pre tag

    def check(self, v, scheme: Scheme) -> bool:
        return any(iv.contains(v, scheme) for iv in self.intervals)


_BRACKET_RX = re.compile(r"[\[\(][^\[\]\(\)]*[\]\)]")


def _maven_ranges(expr: str) -> str:
    """Maven bracket ranges -> operator syntax: "[2.9.0,2.9.10.7)" becomes
    ">=2.9.0, <2.9.10.7"; comma-separated bracket groups are a union
    (reference maven comparer via go-mvn-version). OR-groups without
    brackets pass through unchanged; a group mixing bracket and bare
    syntax is an error, never silently truncated."""
    out = []
    for group in expr.split("||"):
        g = group.strip()
        if "[" not in g and "(" not in g:
            out.append(g)
            continue
        brackets = _BRACKET_RX.findall(g)
        rest = _BRACKET_RX.sub("", g).strip(" ,")
        if rest or not brackets:
            raise ParseError(f"mixed/unbalanced maven range {group!r}")
        for b in brackets:
            open_b, close_b = b[0], b[-1]
            inner = b[1:-1].strip()
            if "," not in inner:
                if open_b == "[" and close_b == "]" and inner:
                    out.append(f"={inner}")
                else:
                    raise ParseError(f"invalid maven range {b!r}")
                continue
            lo, hi = (s.strip() for s in inner.split(",", 1))
            parts = []
            if lo:
                parts.append((">=" if open_b == "[" else ">") + lo)
            if hi:
                parts.append(("<=" if close_b == "]" else "<") + hi)
            if not parts:
                raise ParseError(f"unbounded maven range {b!r}")
            out.append(", ".join(parts))
    return " || ".join(out)


class Constraints:
    """Parsed constraint: OR of AND-groups of comparators."""

    def __init__(self, scheme: Scheme, expr: str, npm_mode: bool = False):
        self.scheme = scheme
        self.expr = expr
        self.npm_mode = npm_mode
        if scheme.name == "maven":
            expr = _maven_ranges(expr)
        self.groups: list[list[Comparator]] = []
        for group_expr in expr.split("||"):
            group_expr = group_expr.strip()
            self.groups.append(self._parse_group(group_expr))

    # -------------------------------------------------- parsing

    def _parse_group(self, expr: str) -> list[Comparator]:
        if expr == "*" and not self.npm_mode:
            # the reference's generic comparer rejects a bare '*' constraint
            # (aquasecurity/go-version errors -> not vulnerable)
            raise ParseError("invalid constraint '*'")
        if not expr or expr == "*":
            return [Comparator("", "*", [Interval()], None)]
        # npm hyphen range: "1.2.3 - 2.0.0"
        m = re.match(r"^\s*([^\s,|]+)\s+-\s+([^\s,|]+)\s*$", expr)
        if m and self.npm_mode:
            lo_str, hi_str = m.group(1), m.group(2)
            lo_wild = self._has_wildcard(lo_str) or self._is_partial(lo_str)
            lo = self._floor(lo_str) if lo_wild else self.scheme.parse(lo_str)
            if self._has_wildcard(hi_str) or self._is_partial(hi_str):
                hi_iv = self._wildcard_interval(hi_str)
                iv = Interval(lo, True, hi_iv.hi, hi_iv.hi_incl)
                hi_pre = None
            else:
                iv = Interval(lo, True, self.scheme.parse(hi_str), True)
                hi_pre = self._pre_core(hi_str)
            # desugared bounds keep their pre-release cores for the npm rule
            lo_pre = None if lo_wild else self._pre_core(lo_str)
            return [
                Comparator(">=", lo_str, [iv], lo_pre),
                Comparator("<=", hi_str, [Interval()], hi_pre),
            ]
        comps = []
        for part in re.split(r",", expr):
            part = part.strip()
            if not part:
                continue
            for cm in _COMP_RX.finditer(part):
                comps.append(self._parse_comparator(cm.group("op") or "", cm.group("ver")))
        if not comps:
            raise ParseError(f"empty constraint group {expr!r}")
        return comps

    def _has_wildcard(self, s: str) -> bool:
        return bool(re.search(r"(^|\.)[xX*](\.|$)", s)) or s in ("*", "x", "X")

    def _is_partial(self, s: str) -> bool:
        # "1" / "1.2" style (semver family only)
        return bool(re.match(r"^[vV]?\d+(\.\d+)?$", s)) and self.npm_mode

    def _nums_of(self, s: str) -> list[int]:
        s = s.lstrip("vV")
        out = []
        for seg in s.split("."):
            seg = seg.split("-")[0].split("+")[0]
            if seg in ("x", "X", "*", ""):
                break
            if not seg.isdigit():
                break
            out.append(int(seg))
        return out

    def _mk(self, nums: list[int]) -> object:
        return self.scheme.parse(".".join(str(n) for n in nums) or "0")

    def _floor(self, s: str) -> object:
        """Lowest concrete version matching a possibly-partial/wildcard one."""
        return self._mk(self._nums_of(s))

    def _block_floor(self, nums: list[int]) -> object:
        """Smallest version carrying the given release prefix. For PEP 440
        that includes pre-releases ("1.5.dev0" < "1.5a1" < "1.5"), matching
        the reference's prefix-match semantics for '==1.5.*'."""
        if self.scheme.name == "pep440" and nums:
            return self.scheme.parse(
                ".".join(str(n) for n in nums) + ".dev0"
            )
        return self._mk(nums)

    def _bump(self, nums: list[int]) -> object | None:
        """Smallest version above the wildcard block: bump last given seg."""
        if not nums:
            return None  # "*": unbounded
        return self._block_floor(nums[:-1] + [nums[-1] + 1])

    def _wildcard_interval(self, s: str) -> Interval:
        nums = self._nums_of(s)
        hi = self._bump(nums)
        return Interval(self._block_floor(nums), True, hi, False)

    def _pre_core(self, ver_str: str):
        v = None
        try:
            v = self.scheme.parse(ver_str)
        except ParseError:
            return None
        pre = getattr(v, "pre", ())
        if pre:
            return v.core() if hasattr(v, "core") else None
        return None

    def _parse_comparator(self, op: str, ver_str: str) -> Comparator:
        scheme = self.scheme
        wildcard = self._has_wildcard(ver_str) or self._is_partial(ver_str)
        pre_core = None if wildcard else self._pre_core(ver_str)

        if op in ("", "=", "=="):
            if ver_str in ("*", "x", "X"):
                if not self.npm_mode and self.scheme.name != "pep440":
                    raise ParseError("invalid constraint '*'")
                return Comparator(op, ver_str, [Interval()], None)
            if wildcard:
                return Comparator(op, ver_str, [self._wildcard_interval(ver_str)], None)
            v = scheme.parse(ver_str)
            return Comparator(op, ver_str, [Interval(v, True, v, True)], pre_core)
        if op == "!=":
            if wildcard:
                iv = self._wildcard_interval(ver_str)
                return Comparator(op, ver_str, [
                    Interval(None, True, iv.lo, False),
                    Interval(iv.hi, True, None, True),
                ], None)
            v = scheme.parse(ver_str)
            return Comparator(op, ver_str, [
                Interval(None, True, v, False),
                Interval(v, False, None, True),
            ], pre_core)
        if op == ">":
            if wildcard:
                # ">1.2.x" == ">=1.3.0"
                iv = self._wildcard_interval(ver_str)
                return Comparator(op, ver_str, [Interval(iv.hi, True, None, True)], None)
            return Comparator(op, ver_str,
                              [Interval(scheme.parse(ver_str), False, None, True)], pre_core)
        if op == ">=":
            v = self._floor(ver_str) if wildcard else scheme.parse(ver_str)
            return Comparator(op, ver_str, [Interval(v, True, None, True)], pre_core)
        if op == "<":
            v = self._floor(ver_str) if wildcard else scheme.parse(ver_str)
            return Comparator(op, ver_str, [Interval(None, True, v, False)], pre_core)
        if op == "<=":
            if wildcard:
                iv = self._wildcard_interval(ver_str)
                return Comparator(op, ver_str, [Interval(None, True, iv.hi, False)], None)
            return Comparator(op, ver_str,
                              [Interval(None, True, scheme.parse(ver_str), True)], pre_core)
        if op == "~=":
            # PEP 440 compatible release: ~=2.2 -> >=2.2,<3.0;
            # ~=1.4.5 -> >=1.4.5,<1.5.0 (bump second-to-last)
            return self._tilde("~>", ver_str, pre_core)
        if op in ("~", "~>"):
            return self._tilde(op, ver_str, pre_core)
        if op == "^":
            return self._caret(op, ver_str, pre_core)
        raise ParseError(f"unknown operator {op!r}")

    def _tilde(self, op: str, ver_str: str, pre_core) -> Comparator:
        """~1.2.3 / ~>1.2.3: >=1.2.3 <1.3.0; ~1.2 -> <1.3.0 (npm) but
        pessimistic ~>1.2 -> <2.0 (ruby/generic, bump second-to-last)."""
        nums = self._nums_of(ver_str)
        if self._has_wildcard(ver_str) or self._is_partial(ver_str):
            lo = self._mk(nums)  # "~1.x" / "~1.2" floors to "1.0.0" / "1.2.0"
        else:
            lo = self.scheme.parse(ver_str)
        if op == "~>" and not self.npm_mode:
            # ruby pessimistic: drop last segment, bump the new last
            bump_nums = nums[:-1] if len(nums) > 1 else nums
            hi = self._mk(bump_nums[:-1] + [bump_nums[-1] + 1])
        elif len(nums) >= 2:
            hi = self._mk([nums[0], nums[1] + 1])
        else:
            hi = self._mk([nums[0] + 1] if nums else [1])
        return Comparator(op, ver_str, [Interval(lo, True, hi, False)], pre_core)

    def _caret(self, op: str, ver_str: str, pre_core) -> Comparator:
        """^1.2.3: >=1.2.3 <2.0.0; ^0.2.3: <0.3.0; ^0.0.3: <0.0.4."""
        nums = self._nums_of(ver_str)
        if not nums:
            return Comparator(op, ver_str, [Interval()], None)  # "^*"
        if self._has_wildcard(ver_str) or self._is_partial(ver_str):
            lo = self._mk(nums)
        else:
            lo = self.scheme.parse(ver_str)
        idx = 0
        for i, n in enumerate(nums):
            if n != 0 or i == len(nums) - 1:
                idx = i
                break
        hi = self._mk(nums[: idx] + [nums[idx] + 1])
        return Comparator(op, ver_str, [Interval(lo, True, hi, False)], pre_core)

    # -------------------------------------------------- evaluation

    def check(self, v) -> bool:
        """Exact host-side satisfaction check (the oracle)."""
        for group in self.groups:
            if all(c.check(v, self.scheme) for c in group):
                if self.npm_mode and getattr(v, "pre", ()):
                    # npm rule: pre-release versions only satisfy if some
                    # comparator shares their [major,minor,patch] core and
                    # carries a pre-release tag itself
                    core = v.core()
                    if not any(c.pre_core == core for c in group):
                        continue
                return True
        return False

    def check_str(self, version: str) -> bool:
        return self.check(self.scheme.parse(version))

    # -------------------------------------------------- intervals

    def intervals(self) -> list[Interval]:
        """Union-of-intervals superset of check() over the total order.
        (Exactly equal except the npm pre-release restriction, which only
        removes matches and is re-applied in the host rescreen.)"""
        out: list[Interval] = []
        for group in self.groups:
            group_ivs = [Interval()]
            for comp in group:
                nxt = []
                for giv in group_ivs:
                    for civ in comp.intervals:
                        got = _intersect(giv, civ, self.scheme)
                        if got is not None:
                            nxt.append(got)
                group_ivs = nxt
                if not group_ivs:
                    break
            out.extend(group_ivs)
        return out
