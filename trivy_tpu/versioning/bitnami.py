"""Bitnami version ordering (reference pkg/detector/library/compare/bitnami,
via bitnami/go-version).

Bitnami package versions are semver cores with an optional numeric revision
suffix: "1.2.3-4". Ordering: semver core first, then revision numerically
(missing revision == 0). Pre-release identifiers are not used by Bitnami.
"""

from __future__ import annotations

import re

from trivy_tpu.versioning import base
from trivy_tpu.versioning.base import ParseError, Scheme, cmp

_RX = re.compile(r"^[vV]?(?P<nums>\d+(?:\.\d+)*)(?:-(?P<rev>\d+))?$")

NUM_SLOTS = 4
TAG_NUM = 0x30


class BitnamiVersion:
    __slots__ = ("nums", "rev", "raw")

    def __init__(self, nums: tuple, rev: int, raw: str = ""):
        self.nums = nums
        self.rev = rev
        self.raw = raw

    def num(self, i: int) -> int:
        return self.nums[i] if i < len(self.nums) else 0


class BitnamiScheme(Scheme):
    name = "bitnami"

    def parse(self, s: str) -> BitnamiVersion:
        s = s.strip()
        m = _RX.match(s)
        if not m:
            raise ParseError(f"invalid bitnami version {s!r}")
        nums = tuple(int(x) for x in m.group("nums").split("."))
        return BitnamiVersion(nums, int(m.group("rev") or 0), s)

    def compare_parsed(self, a: BitnamiVersion, b: BitnamiVersion) -> int:
        for i in range(max(len(a.nums), len(b.nums))):
            d = cmp(a.num(i), b.num(i))
            if d:
                return d
        return cmp(a.rev, b.rev)

    def tokens(self, s: str):
        v = self.parse(s)
        if len(v.nums) > NUM_SLOTS and any(n for n in v.nums[NUM_SLOTS:]):
            raise base.Inexact(f"too many segments: {s!r}")
        toks = [(TAG_NUM, base.num_payload(v.num(i))) for i in range(NUM_SLOTS)]
        toks.append((TAG_NUM, base.num_payload(v.rev)))
        return toks

    def _tokens_lossy(self, s: str):
        v = self.parse(s)
        cap = (1 << 56) - 1
        toks = [
            (TAG_NUM, base.num_payload(min(v.num(i), cap)))
            for i in range(NUM_SLOTS)
        ]
        toks.append((TAG_NUM, base.num_payload(min(v.rev, cap))))
        return toks


SCHEME = BitnamiScheme()
