"""RubyGems Gem::Version ordering (reference uses aquasecurity/go-gem-version,
pkg/detector/library/compare/rubygems; also used for cocoapods).

Gem::Version semantics:
- segments = runs of digits or runs of letters, split on '.', '-' also
  separates (treated like '.pre.'? no: Gem treats '-' by replacing with
  '.pre.'), scanned as /[0-9]+|[a-z]+/i
- numeric segments compare numerically; a string segment vs numeric segment:
  the string is SMALLER (string segments mark pre-releases)
- both streams are conceptually padded with zeros
"""

from __future__ import annotations

import re

from trivy_tpu.versioning import base
from trivy_tpu.versioning.base import Inexact, ParseError, Scheme, cmp

_VALID = re.compile(r"^\s*([0-9]+(\.[0-9a-zA-Z]+)*(-[0-9A-Za-z-]+(\.[0-9A-Za-z-]+)*)?)?\s*$")
_SEG = re.compile(r"[0-9]+|[a-z]+", re.IGNORECASE)

TAG_NUM = 0x30


class GemVersion:
    __slots__ = ("segments", "raw")

    def __init__(self, segments: tuple, raw: str):
        self.segments = segments
        self.raw = raw

    @property
    def is_prerelease(self) -> bool:
        return any(isinstance(s, str) for s in self.segments)

def _canonical(segments: list) -> tuple:
    # trailing zero segments never affect comparison
    while segments and segments[-1] == 0:
        segments.pop()
    return tuple(segments)


class RubyGemsScheme(Scheme):
    name = "rubygems"

    def parse(self, s: str) -> GemVersion:
        raw = s
        s = s.strip()
        if not _VALID.match(s):
            raise ParseError(f"invalid gem version {raw!r}")
        if not s:
            s = "0"
        # Gem::Version: "-" introduces a pre-release part
        s = s.replace("-", ".pre.")
        segs: list = []
        for m in _SEG.finditer(s):
            t = m.group(0)
            segs.append(int(t) if t.isdigit() else t)
        if not segs:
            segs = [0]
        return GemVersion(_canonical(segs), raw)

    def compare_parsed(self, a: GemVersion, b: GemVersion) -> int:
        sa, sb = a.segments, b.segments
        for i in range(max(len(sa), len(sb))):
            xa = sa[i] if i < len(sa) else 0
            xb = sb[i] if i < len(sb) else 0
            na, nb = isinstance(xa, int), isinstance(xb, int)
            if na and nb:
                d = cmp(xa, xb)
            elif na != nb:
                d = 1 if na else -1  # numbers beat strings (strings = pre)
            else:
                d = cmp(xa, xb)
            if d:
                return d
        return 0

    def tokens(self, s: str):
        v = self.parse(s)
        if v.is_prerelease:
            # string segments sort *below zero*, which a flat tag order
            # cannot express next to trailing-zero trimming; pre-release
            # gems are rare as installed versions -> host path
            raise Inexact(f"pre-release gem version: {s!r}")
        toks = [(TAG_NUM, base.num_payload(n)) for n in v.segments]
        # canonical form has no trailing zeros, so zero padding after the
        # last token is exactly Gem's infinite-zero padding
        return toks

    def _tokens_lossy(self, s: str):
        v = self.parse(s)
        cap = (1 << 56) - 1
        toks = []
        for seg in v.segments:
            if isinstance(seg, str):
                break
            toks.append((TAG_NUM, base.num_payload(min(seg, cap))))
        return toks


SCHEME = RubyGemsScheme()
