"""node-semver ordering (reference uses aquasecurity/go-npm-version,
pkg/detector/library/compare/npm).

Ordering is standard semver (exactly three numeric components, loose parse
pads missing ones). Range semantics live in trivy_tpu.versioning.constraints
(x-ranges, hyphen ranges, ^/~, and the npm pre-release rule: a version with a
pre-release tag only satisfies a comparator set if some comparator with the
same [major, minor, patch] tuple also has a pre-release).
"""

from __future__ import annotations

from trivy_tpu.versioning import base  # noqa: F401  (tags re-exported)
from trivy_tpu.versioning.base import ParseError, Scheme
from trivy_tpu.versioning.semver import (
    SemVersion,
    cmp_semver,
    parse_semver,
    semver_tokens,
    semver_tokens_lossy,
)


class NpmScheme(Scheme):
    name = "npm"

    def parse(self, s: str) -> SemVersion:
        s = s.strip().lstrip("=vV ")
        v = parse_semver(s)
        if len(v.nums) > 3:
            raise ParseError(f"npm versions have 3 components: {s!r}")
        return SemVersion(
            (v.major, v.minor, v.patch), v.pre, v.build, v.raw
        )

    def compare_parsed(self, a: SemVersion, b: SemVersion) -> int:
        return cmp_semver(a, b)

    def tokens(self, s: str):
        return semver_tokens(self.parse(s))

    def _tokens_lossy(self, s: str):
        return semver_tokens_lossy(self.parse(s))


SCHEME = NpmScheme()
