"""Enumerations for the data model.

References: pkg/types/scan.go:31-50 (Scanners), pkg/fanal/types const enums
(analyzer/const.go:9-148, artifact.go OSType/LangType), dbTypes severity.
"""

from __future__ import annotations

import enum


class Severity(enum.IntEnum):
    """Ordered severity (reference trivy-db types: Unknown..Critical)."""

    UNKNOWN = 0
    LOW = 1
    MEDIUM = 2
    HIGH = 3
    CRITICAL = 4

    def __str__(self) -> str:  # renders like the reference report JSON
        return self.name

    @classmethod
    def parse(cls, s: str) -> "Severity":
        try:
            return cls[s.strip().upper()]
        except KeyError:
            return cls.UNKNOWN


class Scanner(str, enum.Enum):
    """Which scanner classes run (reference pkg/types/scan.go:31-50)."""

    VULN = "vuln"
    MISCONFIG = "misconfig"
    SECRET = "secret"
    LICENSE = "license"
    NONE = "none"


class ResultClass(str, enum.Enum):
    """Result.Class (reference pkg/types/report.go ClassOSPkg etc.)."""

    OS_PKGS = "os-pkgs"
    LANG_PKGS = "lang-pkgs"
    CONFIG = "config"
    SECRET = "secret"
    LICENSE = "license"
    LICENSE_FILE = "license-file"
    CUSTOM = "custom"

    def __str__(self) -> str:  # str() must render the wire value
        return self.value


class ArtifactType(str, enum.Enum):
    """reference pkg/fanal/types artifact types."""

    CONTAINER_IMAGE = "container_image"
    FILESYSTEM = "filesystem"
    REPOSITORY = "repository"
    CYCLONEDX = "cyclonedx"
    SPDX = "spdx"
    VM = "vm"


class TargetType(str, enum.Enum):
    """CLI target kinds (reference pkg/commands/artifact/run.go TargetKind)."""

    IMAGE = "image"
    FILESYSTEM = "fs"
    ROOTFS = "rootfs"
    REPOSITORY = "repo"
    SBOM = "sbom"
    VM = "vm"


class Compression(str, enum.Enum):
    NONE = "none"
    GZIP = "gzip"


class OSType(str, enum.Enum):
    """OS families (reference pkg/fanal/types/os.go / detector map
    pkg/detector/ospkg/detect.go:32-51)."""

    ALPINE = "alpine"
    ALMA = "alma"
    AMAZON = "amazon"
    AZURE = "azurelinux"
    CBL_MARINER = "cbl-mariner"
    CENTOS = "centos"
    CHAINGUARD = "chainguard"
    DEBIAN = "debian"
    ECHO = "echo"
    FEDORA = "fedora"
    MINIMOS = "minimos"
    OPENSUSE = "opensuse"
    OPENSUSE_LEAP = "opensuse-leap"
    OPENSUSE_TUMBLEWEED = "opensuse-tumbleweed"
    ORACLE = "oracle"
    PHOTON = "photon"
    REDHAT = "redhat"
    ROCKY = "rocky"
    SLEM = "suse linux enterprise micro"
    SLES = "suse linux enterprise server"
    UBUNTU = "ubuntu"
    WOLFI = "wolfi"

    def __str__(self) -> str:
        return self.value


class LangType(str, enum.Enum):
    """Language package types (reference pkg/fanal/types LangType, selection
    in pkg/detector/library/driver.go:25-97)."""

    BUNDLER = "bundler"
    GEMSPEC = "gemspec"
    CARGO = "cargo"
    RUST_BINARY = "rustbinary"
    COMPOSER = "composer"
    COMPOSER_VENDOR = "composer-vendor"
    GO_BINARY = "gobinary"
    GO_MODULE = "gomod"
    JAR = "jar"
    POM = "pom"
    GRADLE = "gradle"
    SBT = "sbt"
    NPM = "npm"
    YARN = "yarn"
    PNPM = "pnpm"
    BUN = "bun"
    NODE_PKG = "node-pkg"
    JAVASCRIPT = "javascript"
    NUGET = "nuget"
    DOTNET_CORE = "dotnet-core"
    PACKAGES_PROPS = "packages-props"
    PIPENV = "pipenv"
    POETRY = "poetry"
    UV = "uv"
    PIP = "pip"
    PYTHON_PKG = "python-pkg"
    PUB = "pub"
    HEX = "hex"
    CONAN = "conan"
    SWIFT = "swift"
    COCOAPODS = "cocoapods"
    CONDA_PKG = "conda-pkg"
    CONDA_ENV = "conda-environment"
    BITNAMI = "bitnami"
    K8S_UPSTREAM = "kubernetes"
    JULIA = "julia"
    WORDPRESS = "wordpress"

    def __str__(self) -> str:
        return self.value


class Status(enum.IntEnum):
    """Vulnerability status (reference trivy-db types.Status)."""

    UNKNOWN = 0
    NOT_AFFECTED = 1
    AFFECTED = 2
    FIXED = 3
    UNDER_INVESTIGATION = 4
    WILL_NOT_FIX = 5
    FIX_DEFERRED = 6
    END_OF_LIFE = 7

    @property
    def label(self) -> str:
        return self.name.lower()

    def __str__(self) -> str:
        return self.label

    @classmethod
    def parse(cls, s: str) -> "Status":
        try:
            return cls[s.strip().upper()]
        except KeyError:
            return cls.UNKNOWN
