"""Minimal dataclass <-> dict structuring for cache round-trips
(asdict on the way in, from_dict on the way out)."""

from __future__ import annotations

import dataclasses
import enum
import types as _pytypes
import typing


# (class -> resolved type hints) memo: get_type_hints re-compiles every
# stringified annotation on every call, which dominated server-side blob
# decoding (~1s per 1k-package artifact); hints are per-class constants
_HINTS: dict[type, dict] = {}
_FIELDS: dict[type, tuple] = {}


def from_dict(cls, d):
    """Rebuild a dataclass (recursively) from an asdict() dict."""
    if d is None:
        return None
    if not dataclasses.is_dataclass(cls):
        return d
    hints = _HINTS.get(cls)
    if hints is None:
        hints = typing.get_type_hints(cls)
        # _FIELDS publishes first: a concurrent decoder that sees the
        # _HINTS entry must never miss the fields entry (GIL-atomic
        # dict stores; no lock needed for idempotent values)
        _FIELDS[cls] = tuple(f.name for f in dataclasses.fields(cls))
        _HINTS[cls] = hints
    kwargs = {}
    for name in _FIELDS[cls]:
        if name not in d:
            continue
        kwargs[name] = _convert(hints.get(name), d[name])
    return cls(**kwargs)


def _convert(hint, value):
    if value is None or hint is None:
        return value
    origin = typing.get_origin(hint)
    if origin is typing.Union or origin is _pytypes.UnionType:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if len(args) == 1:
            return _convert(args[0], value)
        return value
    if origin in (list, tuple):
        (inner,) = typing.get_args(hint) or (None,)
        return [_convert(inner, v) for v in value]
    if origin is dict:
        return value
    if dataclasses.is_dataclass(hint) and isinstance(value, dict):
        return from_dict(hint, value)
    if isinstance(hint, type) and issubclass(hint, enum.Enum) \
            and not isinstance(value, enum.Enum):
        try:
            return hint(value)
        except ValueError:
            return value
    return value
