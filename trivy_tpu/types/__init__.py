"""Core report/artifact data model.

Mirrors the *shape* of the reference data model so reports are
interchangeable, re-expressed as Python dataclasses:
- report model: reference pkg/types/report.go:14 (Report), :109 (Result)
- artifact model: reference pkg/fanal/types/artifact.go (BlobInfo, Package,
  Application, OS)
- scan options: reference pkg/types/scan.go:115-126
"""

from trivy_tpu.types.artifact import (
    OS,
    Application,
    ArtifactDetail,
    ArtifactInfo,
    BlobInfo,
    CustomResource,
    Layer,
    License,
    LicenseFile,
    LicenseFinding,
    Misconfiguration,
    Package,
    PackageInfo,
    Repository,
    Secret,
    SecretFinding,
)
from trivy_tpu.types.enums import (
    ArtifactType,
    Compression,
    LangType,
    OSType,
    ResultClass,
    Scanner,
    Severity,
    Status,
    TargetType,
)
from trivy_tpu.types.report import (
    CauseMetadata,
    Code,
    DataSource,
    DetectedLicense,
    DetectedMisconfiguration,
    DetectedSecret,
    DetectedVulnerability,
    Line,
    Metadata,
    Report,
    Result,
    VulnerabilityInfo,
)
from trivy_tpu.types.scan import ScanOptions, ScanTarget

__all__ = [
    "OS",
    "Application",
    "ArtifactDetail",
    "ArtifactInfo",
    "ArtifactType",
    "BlobInfo",
    "CauseMetadata",
    "Code",
    "Compression",
    "CustomResource",
    "DataSource",
    "DetectedLicense",
    "DetectedMisconfiguration",
    "DetectedSecret",
    "DetectedVulnerability",
    "LangType",
    "Layer",
    "License",
    "LicenseFile",
    "LicenseFinding",
    "Line",
    "Metadata",
    "Misconfiguration",
    "OSType",
    "Package",
    "PackageInfo",
    "Report",
    "Repository",
    "Result",
    "ResultClass",
    "ScanOptions",
    "ScanTarget",
    "Scanner",
    "Secret",
    "SecretFinding",
    "Severity",
    "Status",
    "TargetType",
    "VulnerabilityInfo",
]
