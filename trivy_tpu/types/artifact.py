"""Artifact analysis data model.

Same shape as the reference fanal model so cached blobs / applied details are
semantically interchangeable:
- Package: reference pkg/fanal/types/package.go:179-219
- BlobInfo/ArtifactDetail: reference pkg/fanal/types/artifact.go:122-175
- Application/PackageInfo/OS: reference pkg/fanal/types/{app,os}.go
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any

from trivy_tpu.types.enums import Severity

SCHEMA_VERSION = 2  # blob/artifact schema version (ref artifact.go SchemaVersion)


# Field names whose Go JSON rendering is not plain snake->Pascal
_JSON_NAMES = {
    "os": "OS",
    "id": "ID",
    "uid": "UID",
    "purl": "PURL",
    "url": "URL",
    "diff_id": "DiffID",
    "diff_ids": "DiffIDs",
    "avd_id": "AVDID",
    "eosl": "EOSL",
    "rule_id": "RuleID",
    "image_id": "ImageID",
    "cwe_ids": "CweIDs",
    "vendor_ids": "VendorIDs",
    "pkg_id": "PkgID",
    "vulnerability_id": "VulnerabilityID",
    "primary_url": "PrimaryURL",
    "modularity_label": "Modularitylabel",
}


def _pascal(name: str) -> str:
    return _JSON_NAMES.get(name, "".join(p.capitalize() for p in name.split("_")))


def _drop_empty(obj: Any) -> Any:
    """Recursive dataclass -> dict with the reference's Go JSON rendering:
    PascalCase names and `json:",omitempty"` semantics (zero values — 0,
    False, "", empty containers, None — are omitted unless the field is
    marked keep). Classes overriding to_dict() are dispatched to it."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        if type(obj).to_dict is not JSONMixin.to_dict:
            return obj.to_dict()
        out = {}
        for f in dataclasses.fields(obj):
            if f.metadata.get("skip_json"):
                continue
            v = _drop_empty(getattr(obj, f.name))
            if not f.metadata.get("keep") and v in (None, "", 0, False, [], {}, ()):
                continue
            out[f.metadata.get("json", _pascal(f.name))] = v
        return out
    if isinstance(obj, dict):
        return {k: _drop_empty(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_drop_empty(v) for v in obj]
    if isinstance(obj, Severity):
        return str(obj)
    if hasattr(obj, "value") and hasattr(obj, "name") and not isinstance(obj, (int, float)):
        return obj.value  # str enums
    return obj


class JSONMixin:
    def to_dict(self) -> dict:
        return _drop_empty(self)


@dataclass
class Layer(JSONMixin):
    digest: str = ""
    diff_id: str = ""
    created_by: str = ""

    def to_dict(self) -> dict:
        out = {}
        if self.digest:
            out["Digest"] = self.digest
        if self.diff_id:
            out["DiffID"] = self.diff_id
        if self.created_by:
            out["CreatedBy"] = self.created_by
        return out


@dataclass
class Location(JSONMixin):
    start_line: int = 0
    end_line: int = 0

    def to_dict(self) -> dict:
        return {"StartLine": self.start_line, "EndLine": self.end_line}


@dataclass
class ExternalRef(JSONMixin):
    type: str = ""
    url: str = ""


@dataclass
class PkgIdentifier(JSONMixin):
    """Reference pkg/fanal/types/package.go PkgIdentifier: PURL + UID + BOMRef."""

    purl: str = ""
    uid: str = ""
    bom_ref: str = ""

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.purl:
            out["PURL"] = self.purl
        if self.uid:
            out["UID"] = self.uid
        if self.bom_ref:
            out["BOMRef"] = self.bom_ref
        return out


@dataclass
class Package(JSONMixin):
    name: str = ""
    version: str = ""
    id: str = ""
    identifier: PkgIdentifier = field(default_factory=PkgIdentifier)
    release: str = ""
    epoch: int = 0
    arch: str = ""
    dev: bool = False
    src_name: str = ""
    src_version: str = ""
    src_release: str = ""
    src_epoch: int = 0
    licenses: list[str] = field(default_factory=list)
    maintainer: str = ""
    modularity_label: str = ""
    indirect: bool = False
    relationship: str = ""  # "direct" | "indirect" | "root" | "workspace" | ""
    depends_on: list[str] = field(default_factory=list)
    # Red Hat build metadata attached by the applier (reference attaches
    # the owning layer's buildinfo per package; artifact-level here)
    build_info: "BuildInfo | None" = None
    layer: Layer = field(default_factory=Layer)
    file_path: str = ""
    digest: str = ""
    locations: list[Location] = field(default_factory=list)
    installed_files: list[str] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.name or not self.version

    def full_version(self) -> str:
        """epoch:version-release rendering used for OS packages
        (reference pkg/scanner/utils/util.go FormatVersion)."""
        v = self.version
        if self.release:
            v = f"{v}-{self.release}"
        if self.epoch:
            v = f"{self.epoch}:{v}"
        return v

    def full_src_version(self) -> str:
        v = self.src_version
        if self.src_release:
            v = f"{v}-{self.src_release}"
        if self.src_epoch:
            v = f"{self.src_epoch}:{v}"
        return v

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.id:
            out["ID"] = self.id
        if self.name:
            out["Name"] = self.name
        ident = self.identifier.to_dict()
        if ident:
            out["Identifier"] = ident
        if self.version:
            out["Version"] = self.version
        if self.release:
            out["Release"] = self.release
        if self.epoch:
            out["Epoch"] = self.epoch
        if self.arch:
            out["Arch"] = self.arch
        if self.dev:
            out["Dev"] = True
        if self.src_name:
            out["SrcName"] = self.src_name
        if self.src_version:
            out["SrcVersion"] = self.src_version
        if self.src_release:
            out["SrcRelease"] = self.src_release
        if self.src_epoch:
            out["SrcEpoch"] = self.src_epoch
        if self.licenses:
            out["Licenses"] = self.licenses
        if self.maintainer:
            out["Maintainer"] = self.maintainer
        if self.modularity_label:
            out["Modularitylabel"] = self.modularity_label
        if self.relationship:
            out["Relationship"] = self.relationship
        if self.indirect:
            out["Indirect"] = True
        if self.depends_on:
            out["DependsOn"] = self.depends_on
        layer = self.layer.to_dict()
        if layer:
            out["Layer"] = layer
        if self.file_path:
            out["FilePath"] = self.file_path
        if self.digest:
            out["Digest"] = self.digest
        if self.locations:
            out["Locations"] = [loc.to_dict() for loc in self.locations]
        if self.installed_files:
            out["InstalledFiles"] = self.installed_files
        return out


@dataclass
class PackageInfo(JSONMixin):
    """OS packages found at one file path (e.g. lib/apk/db/installed)."""

    file_path: str = ""
    packages: list[Package] = field(default_factory=list)


@dataclass
class Application(JSONMixin):
    """Language-ecosystem app: one lockfile / binary / site-packages set.
    Reference pkg/fanal/types Application."""

    type: str = ""  # LangType value
    file_path: str = ""
    packages: list[Package] = field(default_factory=list)


@dataclass
class OS(JSONMixin):
    family: str = ""
    name: str = ""
    eosl: bool = False
    extended: bool = False  # e.g. Ubuntu ESM

    @property
    def detected(self) -> bool:
        return bool(self.family)

    def merge(self, other: "OS") -> "OS":
        """Layer-merge semantics (reference pkg/fanal/types/artifact.go:38-68):
        earlier detection wins (fill-empty only), EXCEPT a detected
        redhat/debian family is fully replaced — OLE ships
        /etc/redhat-release and Ubuntu ships Debian files, so the more
        specific later file must override. Extended (ESM) is sticky."""
        if not other.detected and not other.name:
            return self
        if self.family in ("redhat", "debian"):
            return OS(family=other.family, name=other.name,
                      eosl=other.eosl, extended=other.extended)
        return OS(
            family=self.family or other.family,
            name=self.name or other.name,
            eosl=self.eosl,
            extended=self.extended or other.extended,
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"Family": self.family, "Name": self.name}
        if self.eosl:
            out["EOSL"] = True
        return out


@dataclass
class Repository(JSONMixin):
    family: str = ""
    release: str = ""


@dataclass
class CauseMetadata(JSONMixin):
    resource: str = ""
    provider: str = ""
    service: str = ""
    start_line: int = 0
    end_line: int = 0
    code: Any = None
    occurrences: list = field(default_factory=list)


@dataclass
class Misconfiguration(JSONMixin):
    file_type: str = ""
    file_path: str = ""
    successes: list = field(default_factory=list)
    failures: list = field(default_factory=list)


@dataclass
class SecretFinding(JSONMixin):
    rule_id: str = ""
    category: str = ""
    severity: str = "UNKNOWN"
    title: str = ""
    start_line: int = 0
    end_line: int = 0
    match: str = ""
    code: Any = None
    offset: int = 0
    layer: Layer = field(default_factory=Layer)

    def to_dict(self) -> dict:
        out = {
            "RuleID": self.rule_id,
            "Category": self.category,
            "Severity": self.severity,
            "Title": self.title,
            "StartLine": self.start_line,
            "EndLine": self.end_line,
            "Match": self.match,
        }
        if self.code is not None:
            out["Code"] = self.code
        layer = self.layer.to_dict()
        if layer:
            out["Layer"] = layer
        return out


@dataclass
class Secret(JSONMixin):
    file_path: str = ""
    findings: list[SecretFinding] = field(default_factory=list)


@dataclass
class LicenseFinding(JSONMixin):
    category: str = ""
    name: str = ""
    confidence: float = 1.0
    link: str = ""


@dataclass
class LicenseFile(JSONMixin):
    type: str = ""  # "dpkg" | "header" | "license-file"
    file_path: str = ""
    package_name: str = ""
    findings: list[LicenseFinding] = field(default_factory=list)
    layer: Layer = field(default_factory=Layer)


@dataclass
class License(JSONMixin):
    name: str = ""
    text: str = ""


@dataclass
class CustomResource(JSONMixin):
    type: str = ""
    file_path: str = ""
    layer: Layer = field(default_factory=Layer)
    data: Any = None


@dataclass
class BuildInfo(JSONMixin):
    """Red Hat build metadata (reference artifact.go BuildInfo):
    content sets from root/buildinfo content manifests, NVR/arch from
    buildinfo Dockerfiles — used for Red Hat advisory matching."""

    content_sets: list[str] = field(default_factory=list)
    nvr: str = ""
    arch: str = ""


@dataclass
class BlobInfo(JSONMixin):
    """Per-layer (or per-pseudo-blob) analysis result
    (reference pkg/fanal/types/artifact.go:122-149)."""

    schema_version: int = field(default=SCHEMA_VERSION, metadata={"keep": True})
    digest: str = ""
    diff_id: str = ""
    created_by: str = ""
    opaque_dirs: list[str] = field(default_factory=list)
    whiteout_files: list[str] = field(default_factory=list)
    os: OS = field(default_factory=OS)
    repository: Repository | None = None
    package_infos: list[PackageInfo] = field(default_factory=list)
    applications: list[Application] = field(default_factory=list)
    misconfigurations: list[Misconfiguration] = field(default_factory=list)
    secrets: list[Secret] = field(default_factory=list)
    licenses: list[LicenseFile] = field(default_factory=list)
    custom_resources: list[CustomResource] = field(default_factory=list)
    build_info: BuildInfo | None = None
    # sha256 digests of unpackaged executables (rekor SBOM discovery)
    digests: dict[str, str] = field(default_factory=dict)


@dataclass
class ArtifactInfo(JSONMixin):
    """Per-artifact config analysis (image config) result."""

    schema_version: int = field(default=SCHEMA_VERSION, metadata={"keep": True})
    architecture: str = ""
    created: str = ""
    docker_version: str = ""
    os: str = ""
    misconfiguration: Misconfiguration | None = None
    secret: Secret | None = None
    history_packages: list[Package] = field(default_factory=list)


@dataclass
class ArtifactDetail(JSONMixin):
    """Squashed view of all layers (reference artifact.go:152-175 +
    applier output pkg/fanal/applier/docker.go:95)."""

    os: OS = field(default_factory=OS)
    repository: Repository | None = None
    packages: list[Package] = field(default_factory=list)
    applications: list[Application] = field(default_factory=list)
    misconfigurations: list[Misconfiguration] = field(default_factory=list)
    secrets: list[Secret] = field(default_factory=list)
    licenses: list[LicenseFile] = field(default_factory=list)
    image_config: ArtifactInfo | None = None
    custom_resources: list[CustomResource] = field(default_factory=list)
    build_info: BuildInfo | None = None
    digests: dict[str, str] = field(default_factory=dict)
