"""Scan options and targets (reference pkg/types/scan.go:115-126,
pkg/fanal/types ScanTarget)."""

from __future__ import annotations

from dataclasses import dataclass, field

from trivy_tpu.types.artifact import Application, OS, Package
from trivy_tpu.types.enums import Scanner, Severity


@dataclass
class ScanOptions:
    pkg_types: list[str] = field(default_factory=lambda: ["os", "library"])
    pkg_relationships: list[str] = field(default_factory=list)
    scanners: list[Scanner] = field(
        default_factory=lambda: [Scanner.VULN, Scanner.SECRET]
    )
    severities: list[Severity] = field(default_factory=list)
    include_dev_deps: bool = False
    detection_priority: str = "precise"  # "precise" | "comprehensive"
    license_full: bool = False
    license_categories: dict[str, list[str]] = field(default_factory=dict)
    distro: str = ""
    list_all_pkgs: bool = False
    # SBOM discovery sources for unpackaged binaries ("rekor")
    sbom_sources: list[str] = field(default_factory=list)
    rekor_url: str = "https://rekor.sigstore.dev"

    def has_scanner(self, s: Scanner) -> bool:
        return s in self.scanners


@dataclass
class ScanTarget:
    """Squashed artifact ready for detection
    (reference pkg/fanal/types ScanTarget / pkg/scanner/local/scan.go:115)."""

    name: str = ""
    os: OS = field(default_factory=OS)
    repository: object | None = None
    packages: list[Package] = field(default_factory=list)
    applications: list[Application] = field(default_factory=list)
