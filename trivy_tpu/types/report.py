"""Report data model.

JSON shape is compatible with the reference report schema (schema v2):
- Report/Metadata/Result: reference pkg/types/report.go:14-129
- DetectedVulnerability: reference pkg/types/vulnerability.go:9-31
- Vulnerability detail (embedded): reference trivy-db types.Vulnerability
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from trivy_tpu.types.artifact import (
    CustomResource,
    JSONMixin,
    Layer,
    OS,
    Package,
    PkgIdentifier,
)
from trivy_tpu.types.enums import ResultClass, Severity, Status

REPORT_SCHEMA_VERSION = 2


@dataclass
class DataSource(JSONMixin):
    """Where an advisory came from (reference trivy-db types.DataSource)."""

    id: str = ""
    name: str = ""
    url: str = ""
    base_id: str = ""

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.id:
            out["ID"] = self.id
        if self.base_id:
            out["BaseID"] = self.base_id
        if self.name:
            out["Name"] = self.name
        if self.url:
            out["URL"] = self.url
        return out


@dataclass
class VulnerabilityInfo(JSONMixin):
    """Vulnerability metadata (reference trivy-db types.Vulnerability),
    joined host-side by trivy_tpu.vulnerability.Client.fill_info
    (reference pkg/vulnerability/vulnerability.go:70)."""

    title: str = ""
    description: str = ""
    severity: str = "UNKNOWN"
    cwe_ids: list[str] = field(default_factory=list)
    vendor_severity: dict[str, int] = field(default_factory=dict)
    cvss: dict[str, dict] = field(default_factory=dict)
    references: list[str] = field(default_factory=list)
    published_date: str = ""
    last_modified_date: str = ""

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.title:
            out["Title"] = self.title
        if self.description:
            out["Description"] = self.description
        out["Severity"] = self.severity
        if self.cwe_ids:
            out["CweIDs"] = self.cwe_ids
        if self.vendor_severity:
            out["VendorSeverity"] = self.vendor_severity
        if self.cvss:
            out["CVSS"] = self.cvss
        if self.references:
            out["References"] = self.references
        if self.published_date:
            out["PublishedDate"] = self.published_date
        if self.last_modified_date:
            out["LastModifiedDate"] = self.last_modified_date
        return out


@dataclass
class DetectedVulnerability(JSONMixin):
    vulnerability_id: str = ""
    vendor_ids: list[str] = field(default_factory=list)
    pkg_id: str = ""
    pkg_name: str = ""
    pkg_path: str = ""
    pkg_identifier: PkgIdentifier = field(default_factory=PkgIdentifier)
    installed_version: str = ""
    fixed_version: str = ""
    status: Status = Status.UNKNOWN
    layer: Layer = field(default_factory=Layer)
    severity_source: str = ""
    primary_url: str = ""
    data_source: DataSource | None = None
    info: VulnerabilityInfo | None = None

    @property
    def severity(self) -> Severity:
        return Severity.parse(self.info.severity if self.info else "UNKNOWN")

    def sort_key(self) -> tuple:
        return (
            self.vulnerability_id,
            self.pkg_name,
            self.pkg_path,
            self.installed_version,
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"VulnerabilityID": self.vulnerability_id}
        if self.vendor_ids:
            out["VendorIDs"] = self.vendor_ids
        if self.pkg_id:
            out["PkgID"] = self.pkg_id
        out["PkgName"] = self.pkg_name
        if self.pkg_path:
            out["PkgPath"] = self.pkg_path
        ident = self.pkg_identifier.to_dict()
        if ident:
            out["PkgIdentifier"] = ident
        out["InstalledVersion"] = self.installed_version
        out["FixedVersion"] = self.fixed_version
        if self.status != Status.UNKNOWN:
            out["Status"] = self.status.label
        layer = self.layer.to_dict()
        if layer:
            out["Layer"] = layer
        if self.severity_source:
            out["SeveritySource"] = self.severity_source
        if self.primary_url:
            out["PrimaryURL"] = self.primary_url
        if self.data_source is not None:
            out["DataSource"] = self.data_source.to_dict()
        if self.info is not None:
            out.update(self.info.to_dict())
        return out


@dataclass
class Line(JSONMixin):
    number: int = 0
    content: str = ""
    is_cause: bool = False
    annotation: str = ""
    truncated: bool = False
    highlighted: str = ""
    first_cause: bool = False
    last_cause: bool = False

    def to_dict(self) -> dict:
        return {
            "Number": self.number,
            "Content": self.content,
            "IsCause": self.is_cause,
            "Annotation": self.annotation,
            "Truncated": self.truncated,
            **({"Highlighted": self.highlighted} if self.highlighted else {}),
            "FirstCause": self.first_cause,
            "LastCause": self.last_cause,
        }


@dataclass
class Code(JSONMixin):
    lines: list[Line] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"Lines": [l.to_dict() for l in self.lines] or None}


@dataclass
class CauseMetadata(JSONMixin):
    resource: str = ""
    provider: str = ""
    service: str = ""
    start_line: int = 0
    end_line: int = 0
    code: Code = field(default_factory=Code)
    occurrences: list = field(default_factory=list)

    def to_dict(self) -> dict:
        out: dict[str, Any] = {
            "Resource": self.resource,
            "Provider": self.provider,
            "Service": self.service,
        }
        if self.start_line:
            out["StartLine"] = self.start_line
        if self.end_line:
            out["EndLine"] = self.end_line
        out["Code"] = self.code.to_dict()
        return out


@dataclass
class DetectedMisconfiguration(JSONMixin):
    type: str = ""
    id: str = ""
    avd_id: str = ""
    title: str = ""
    description: str = ""
    message: str = ""
    namespace: str = ""
    query: str = ""
    resolution: str = ""
    severity: str = "UNKNOWN"
    primary_url: str = ""
    references: list[str] = field(default_factory=list)
    status: str = ""  # "PASS" | "FAIL" | "EXCEPTION"
    layer: Layer = field(default_factory=Layer)
    cause_metadata: CauseMetadata = field(default_factory=CauseMetadata)

    def sort_key(self) -> tuple:
        return (-Severity.parse(self.severity), self.id, self.message)

    def to_dict(self) -> dict:
        out = {
            "Type": self.type,
            "ID": self.id,
            "AVDID": self.avd_id,
            "Title": self.title,
            "Description": self.description,
            "Message": self.message,
            "Namespace": self.namespace,
            "Query": self.query,
            "Resolution": self.resolution,
            "Severity": self.severity,
            "PrimaryURL": self.primary_url,
            "References": self.references,
            "Status": self.status,
            "CauseMetadata": self.cause_metadata.to_dict(),
        }
        layer = self.layer.to_dict()
        if layer:
            out["Layer"] = layer
        return out


@dataclass
class DetectedSecret(JSONMixin):
    rule_id: str = ""
    category: str = ""
    severity: str = "UNKNOWN"
    title: str = ""
    start_line: int = 0
    end_line: int = 0
    match: str = ""
    code: Code = field(default_factory=Code)
    layer: Layer = field(default_factory=Layer)

    def to_dict(self) -> dict:
        out = {
            "RuleID": self.rule_id,
            "Category": self.category,
            "Severity": self.severity,
            "Title": self.title,
            "StartLine": self.start_line,
            "EndLine": self.end_line,
            "Match": self.match,
        }
        if self.code.lines:
            out["Code"] = self.code.to_dict()
        layer = self.layer.to_dict()
        if layer:
            out["Layer"] = layer
        return out


@dataclass
class DetectedLicense(JSONMixin):
    severity: str = "UNKNOWN"
    category: str = ""
    pkg_name: str = ""
    file_path: str = ""
    name: str = ""
    text: str = ""
    confidence: float = 1.0
    link: str = ""

    def to_dict(self) -> dict:
        return {
            "Severity": self.severity,
            "Category": self.category,
            "PkgName": self.pkg_name,
            "FilePath": self.file_path,
            "Name": self.name,
            **({"Text": self.text} if self.text else {}),
            "Confidence": self.confidence,
            "Link": self.link,
        }


@dataclass
class MisconfSummary(JSONMixin):
    successes: int = 0
    failures: int = 0

    def to_dict(self) -> dict:
        return {"Successes": self.successes, "Failures": self.failures}


@dataclass
class Result(JSONMixin):
    target: str = ""
    result_class: ResultClass | str = ""
    type: str = ""
    packages: list[Package] = field(default_factory=list)
    vulnerabilities: list[DetectedVulnerability] = field(default_factory=list)
    misconf_summary: MisconfSummary | None = None
    misconfigurations: list[DetectedMisconfiguration] = field(default_factory=list)
    secrets: list[DetectedSecret] = field(default_factory=list)
    licenses: list[DetectedLicense] = field(default_factory=list)
    custom_resources: list[CustomResource] = field(default_factory=list)
    # findings suppressed by VEX/ignore policies (reference
    # types.ModifiedFinding, rendered as ExperimentalModifiedFindings)
    modified_findings: list[dict] = field(default_factory=list)

    @property
    def is_empty(self) -> bool:
        return not (
            self.packages
            or self.vulnerabilities
            or self.misconfigurations
            or self.secrets
            or self.licenses
            or self.custom_resources
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"Target": self.target}
        if self.result_class:
            cls = self.result_class
            out["Class"] = cls.value if isinstance(cls, ResultClass) else cls
        if self.type:
            out["Type"] = self.type
        if self.packages:
            out["Packages"] = [p.to_dict() for p in self.packages]
        if self.vulnerabilities:
            out["Vulnerabilities"] = [v.to_dict() for v in self.vulnerabilities]
        if self.misconf_summary is not None:
            out["MisconfSummary"] = self.misconf_summary.to_dict()
        if self.misconfigurations:
            out["Misconfigurations"] = [m.to_dict() for m in self.misconfigurations]
        if self.secrets:
            out["Secrets"] = [s.to_dict() for s in self.secrets]
        if self.licenses:
            out["Licenses"] = [l.to_dict() for l in self.licenses]
        if self.custom_resources:
            out["CustomResources"] = [c.to_dict() for c in self.custom_resources]
        if self.modified_findings:
            out["ExperimentalModifiedFindings"] = self.modified_findings
        return out


@dataclass
class Metadata(JSONMixin):
    size: int = 0
    os: OS | None = None
    image_id: str = ""
    diff_ids: list[str] = field(default_factory=list)
    repo_tags: list[str] = field(default_factory=list)
    repo_digests: list[str] = field(default_factory=list)
    image_config: dict = field(default_factory=dict)
    # non-empty when the scan degraded to a fallback path (circuit
    # breaker open / deadline exhausted / remote failure); the value is
    # the human-readable reason. Consumers use it to tell a fallback
    # scan from a primary one (docs/resilience.md).
    degraded: str = ""

    def to_dict(self) -> dict:
        out: dict[str, Any] = {}
        if self.size:
            out["Size"] = self.size
        if self.os is not None and self.os.detected:
            out["OS"] = self.os.to_dict()
        if self.image_id:
            out["ImageID"] = self.image_id
        if self.diff_ids:
            out["DiffIDs"] = self.diff_ids
        if self.repo_tags:
            out["RepoTags"] = self.repo_tags
        if self.repo_digests:
            out["RepoDigests"] = self.repo_digests
        if self.image_config:
            out["ImageConfig"] = self.image_config
        if self.degraded:
            out["Degraded"] = self.degraded
        return out


@dataclass
class Report(JSONMixin):
    schema_version: int = REPORT_SCHEMA_VERSION
    created_at: str = ""
    artifact_name: str = ""
    artifact_type: str = ""
    metadata: Metadata = field(default_factory=Metadata)
    results: list[Result] = field(default_factory=list)

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"SchemaVersion": self.schema_version}
        if self.created_at:
            out["CreatedAt"] = self.created_at
        if self.artifact_name:
            out["ArtifactName"] = self.artifact_name
        if self.artifact_type:
            out["ArtifactType"] = self.artifact_type
        md = self.metadata.to_dict()
        if md:
            out["Metadata"] = md
        if self.results:
            out["Results"] = [r.to_dict() for r in self.results]
        return out
