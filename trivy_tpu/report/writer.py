"""Report writer dispatch (reference pkg/report/writer.go:45-99)."""

from __future__ import annotations

import sys

from trivy_tpu.types.report import Report

FORMATS = ("table", "json", "sarif", "cyclonedx", "spdx-json", "github",
           "cosign-vuln", "template")


def write_report(
    report: Report,
    fmt: str = "table",
    output: str | None = None,
    template: str | None = None,
    severities=None,
    dependency_tree: bool = False,
) -> None:
    if fmt == "json":
        from trivy_tpu.report.json_writer import render_json

        text = render_json(report)
    elif fmt == "table":
        from trivy_tpu.report.table import render_table

        text = render_table(report, severities=severities,
                            dependency_tree=dependency_tree)
    elif fmt == "sarif":
        from trivy_tpu.report.sarif import render_sarif

        text = render_sarif(report)
    elif fmt == "cyclonedx":
        from trivy_tpu.report.cyclonedx import render_cyclonedx

        text = render_cyclonedx(report)
    elif fmt == "spdx-json":
        from trivy_tpu.report.spdx import render_spdx_json

        text = render_spdx_json(report)
    elif fmt == "github":
        from trivy_tpu.report.github import render_github

        text = render_github(report)
    elif fmt == "cosign-vuln":
        from trivy_tpu.report.cosign import render_cosign_vuln

        text = render_cosign_vuln(report)
    elif fmt == "template":
        from trivy_tpu.report.template import render_template

        if not template:
            raise ValueError("--format template requires --template")
        text = render_template(report, template)
    else:
        raise ValueError(f"unknown format {fmt!r} (supported: {FORMATS})")

    if output:
        # lint: allow[atomic-write] user-requested report stream (--output), partial file is visible to the user
        with open(output, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)


def read_report_json(path: str) -> dict:
    import json

    with open(path) as f:
        return json.load(f)
