"""SPDX 2.3 JSON writer (reference pkg/sbom/spdx/marshal.go).

Document layout: one DESCRIBES root package (the artifact), one package
per OS / application holder, one package per installed package with
CONTAINS / DEPENDS_ON relationships, and one File entry per distinct
package file path.
"""

from __future__ import annotations

import hashlib
import json
import re

import trivy_tpu
from trivy_tpu.types.report import Report
from trivy_tpu.utils import clock

SPDX_VERSION = "SPDX-2.3"
DATA_LICENSE = "CC0-1.0"
_DOC_NS_BASE = "https://trivy-tpu.dev"


def _spdx_id(kind: str, *parts: str) -> str:
    h = hashlib.sha1((":".join(parts)).encode()).hexdigest()[:16]
    return f"SPDXRef-{kind}-{h}"


def _safe_license(expr_list) -> str:
    if not expr_list:
        return "NONE"
    # SPDX license expressions must be valid idstrings; non-conforming
    # names are wrapped as LicenseRef in the reference — approximate by
    # sanitizing
    out = []
    for e in expr_list:
        if re.fullmatch(r"[A-Za-z0-9.+\-]+", e):
            out.append(e)
        else:
            out.append("LicenseRef-" + re.sub(r"[^A-Za-z0-9.\-]", "-", e))
    return " AND ".join(out)


def render_spdx_json(report: Report) -> str:
    root_id = _spdx_id("Artifact", report.artifact_name or "artifact")
    root_pkg = {
        "SPDXID": root_id,
        "name": report.artifact_name or "artifact",
        "downloadLocation": "NONE",
        "copyrightText": "NOASSERTION",
        "licenseConcluded": "NOASSERTION",
        "licenseDeclared": "NOASSERTION",
        "primaryPackagePurpose": "CONTAINER"
        if report.artifact_type == "container_image" else "APPLICATION",
        "supplier": "NOASSERTION",
    }
    md = report.metadata
    attrs = []
    if md.image_id:
        attrs.append(f"ImageID: {md.image_id}")
    for d in md.repo_digests:
        attrs.append(f"RepoDigest: {d}")
    for d in md.diff_ids:
        attrs.append(f"DiffID: {d}")
    for t in md.repo_tags:
        attrs.append(f"RepoTag: {t}")
    if attrs:
        root_pkg["attributionTexts"] = attrs

    packages = [root_pkg]
    files = []
    relationships = [{
        "spdxElementId": "SPDXRef-DOCUMENT",
        "relatedSpdxElement": root_id,
        "relationshipType": "DESCRIBES",
    }]
    seen_files: dict[str, str] = {}

    if md.os is not None and md.os.detected:
        os_id = _spdx_id("OperatingSystem", md.os.family, md.os.name)
        packages.append({
            "SPDXID": os_id,
            "name": md.os.family,
            "versionInfo": md.os.name,
            "downloadLocation": "NONE",
            "copyrightText": "NOASSERTION",
            "licenseConcluded": "NOASSERTION",
            "licenseDeclared": "NOASSERTION",
            "primaryPackagePurpose": "OPERATING-SYSTEM",
            "supplier": "NOASSERTION",
        })
        relationships.append({
            "spdxElementId": root_id,
            "relatedSpdxElement": os_id,
            "relationshipType": "CONTAINS",
        })
        os_holder = os_id
    else:
        os_holder = None

    # language packages not tied to a lock file attach directly to the
    # root element (reference ftypes.AggregatingTypes)
    from trivy_tpu.fanal.applier import AGGREGATE_TYPES as aggregating

    for res in report.results:
        cls = str(res.result_class)
        if not res.packages:
            continue
        if cls == "os-pkgs" and os_holder:
            holder = os_holder
        elif (res.type or "") in aggregating:
            holder = root_id
        else:
            holder = _spdx_id("Application", res.type or "", res.target)
            packages.append({
                "SPDXID": holder,
                # reference spdx marshal names application packages
                # after the lockfile path (the result Target)
                "name": res.target or res.type,
                "downloadLocation": "NONE",
                "copyrightText": "NOASSERTION",
                "licenseConcluded": "NOASSERTION",
                "licenseDeclared": "NOASSERTION",
                "primaryPackagePurpose": "APPLICATION",
                "supplier": "NOASSERTION",
            })
            relationships.append({
                "spdxElementId": root_id,
                "relatedSpdxElement": holder,
                "relationshipType": "CONTAINS",
            })

        id_by_pkgid: dict[str, str] = {}
        for pkg in res.packages:
            pid = _spdx_id("Package", res.target, pkg.name,
                           pkg.full_version())
            if pkg.id:
                id_by_pkgid[pkg.id] = pid
        for pkg in res.packages:
            pid = _spdx_id("Package", res.target, pkg.name,
                           pkg.full_version())
            entry = {
                "SPDXID": pid,
                "name": pkg.name,
                "versionInfo": pkg.full_version(),
                "downloadLocation": "NONE",
                "copyrightText": "NOASSERTION",
                "licenseConcluded": "NOASSERTION",
                "licenseDeclared": _safe_license(pkg.licenses),
                "primaryPackagePurpose": "LIBRARY",
                "supplier": "NOASSERTION",
            }
            if pkg.identifier.purl:
                entry["externalRefs"] = [{
                    "referenceCategory": "PACKAGE-MANAGER",
                    "referenceType": "purl",
                    "referenceLocator": pkg.identifier.purl,
                }]
            if pkg.src_name and pkg.src_name != pkg.name:
                entry["sourceInfo"] = (
                    f"built package from: {pkg.src_name} "
                    f"{pkg.full_src_version()}"
                )
            elif cls == "lang-pkgs" and res.target \
                    and (res.type or "") not in aggregating:
                # reference encode.go sets SrcFile only for lock-file
                # results, not aggregated types
                entry["sourceInfo"] = f"package found in: {res.target}"
            packages.append(entry)
            relationships.append({
                "spdxElementId": holder,
                "relatedSpdxElement": pid,
                "relationshipType": "CONTAINS",
            })
            for dep in getattr(pkg, "depends_on", None) or []:
                if dep in id_by_pkgid:
                    relationships.append({
                        "spdxElementId": pid,
                        "relatedSpdxElement": id_by_pkgid[dep],
                        "relationshipType": "DEPENDS_ON",
                    })
            fp = pkg.file_path
            if fp:
                if fp not in seen_files:
                    fid = _spdx_id("File", fp)
                    seen_files[fp] = fid
                    files.append({
                        "SPDXID": fid,
                        "fileName": fp,
                        "copyrightText": "NOASSERTION",
                        "licenseConcluded": "NOASSERTION",
                    })
                relationships.append({
                    "spdxElementId": pid,
                    "relatedSpdxElement": seen_files[fp],
                    "relationshipType": "CONTAINS",
                })

    doc = {
        "spdxVersion": SPDX_VERSION,
        "dataLicense": DATA_LICENSE,
        "SPDXID": "SPDXRef-DOCUMENT",
        "name": report.artifact_name or "artifact",
        "documentNamespace": (
            f"{_DOC_NS_BASE}/{report.artifact_type or 'artifact'}/"
            f"{_spdx_id('ns', report.artifact_name)[8:]}"
        ),
        "creationInfo": {
            "creators": [
                "Organization: trivy-tpu",
                f"Tool: trivy-tpu-{trivy_tpu.__version__}",
            ],
            "created": clock.now_rfc3339(),
        },
        "packages": packages,
        "relationships": relationships,
    }
    if files:
        doc["files"] = files
    return json.dumps(doc, indent=2, ensure_ascii=False) + "\n"
