"""SARIF 2.1.0 writer (reference pkg/report/sarif.go).

One run, tool.driver = trivy-tpu; a deduplicated rule per finding ID;
one result per detected vulnerability / misconfiguration / secret /
license, located at the scanned target (or package file path when known).
"""

from __future__ import annotations

import json
import re

import trivy_tpu
from trivy_tpu.types.enums import Severity
from trivy_tpu.types.report import Report

_SARIF_VERSION = "2.1.0"
_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

# reference pkg/report/sarif.go toSarifErrorLevel
_LEVELS = {
    Severity.CRITICAL: "error",
    Severity.HIGH: "error",
    Severity.MEDIUM: "warning",
    Severity.LOW: "note",
    Severity.UNKNOWN: "note",
}

# SARIF security-severity property (GitHub code-scanning convention)
_SECURITY_SEVERITY = {
    Severity.CRITICAL: "9.5",
    Severity.HIGH: "8.0",
    Severity.MEDIUM: "5.5",
    Severity.LOW: "2.0",
    Severity.UNKNOWN: "0.0",
}


def _clean_uri(target: str) -> str:
    # artifactLocation.uri must be a valid URI: strip scheme-ish prefixes
    # and leading slashes the way the reference does for image refs
    out = re.sub(r"^(oci|docker|container-image)://", "", target or "")
    return out.lstrip("/") or "."


def _rule(rule_id: str, name: str, short: str, full: str, help_uri: str,
          severity: Severity, tags: list[str]) -> dict:
    help_text = f"Vulnerability {rule_id}" if "CVE" in rule_id else short
    rule = {
        "id": rule_id,
        "name": name,
        "shortDescription": {"text": short},
        "fullDescription": {"text": full},
        "defaultConfiguration": {"level": _LEVELS[severity]},
        "properties": {
            "precision": "very-high",
            "security-severity": _SECURITY_SEVERITY[severity],
            "tags": ["security", *tags],
        },
    }
    if help_uri:
        rule["helpUri"] = help_uri
        rule["help"] = {
            "text": f"{help_text}\n{help_uri}",
            "markdown": f"**{help_text}**\n\n{help_uri}",
        }
    return rule


def _result(rule_id: str, rule_index: int, level: str, message: str,
            uri: str, start_line: int = 1, end_line: int = 1) -> dict:
    return {
        "ruleId": rule_id,
        "ruleIndex": rule_index,
        "level": level,
        "message": {"text": message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": uri, "uriBaseId": "ROOTPATH"},
                "region": {
                    "startLine": max(start_line, 1),
                    "startColumn": 1,
                    "endLine": max(end_line, start_line, 1),
                    "endColumn": 1,
                },
            },
            "message": {"text": uri},
        }],
    }


def render_sarif(report: Report) -> str:
    rules: list[dict] = []
    rule_index: dict[str, int] = {}
    results: list[dict] = []

    def add_rule(rid: str, **kw) -> int:
        if rid not in rule_index:
            rule_index[rid] = len(rules)
            rules.append(_rule(rid, **kw))
        return rule_index[rid]

    for res in report.results:
        uri = _clean_uri(res.target)
        for v in res.vulnerabilities:
            sev = v.severity
            title = (v.info.title if v.info else "") or v.vulnerability_id
            desc = (v.info.description if v.info else "") or title
            idx = add_rule(
                v.vulnerability_id,
                name="OsPackageVulnerability"
                if res.result_class and "os" in str(res.result_class)
                else "LanguageSpecificPackageVulnerability",
                short=title,
                full=desc,
                help_uri=v.primary_url,
                severity=sev,
                tags=["vulnerability", str(sev)],
            )
            message = (
                f"Package: {v.pkg_name}\n"
                f"Installed Version: {v.installed_version}\n"
                f"Vulnerability {v.vulnerability_id}\n"
                f"Severity: {sev}\n"
                f"Fixed Version: {v.fixed_version or ''}\n"
                f"Link: [{v.vulnerability_id}]({v.primary_url})"
            )
            results.append(_result(
                v.vulnerability_id, idx, _LEVELS[sev], message,
                _clean_uri(v.pkg_path) if v.pkg_path else uri,
            ))
        for m in res.misconfigurations:
            sev = Severity.parse(m.severity)
            idx = add_rule(
                m.id, name="Misconfiguration", short=m.title,
                full=m.description, help_uri=m.primary_url, severity=sev,
                tags=["misconfiguration", str(sev)],
            )
            message = (
                f"Artifact: {res.target}\nType: {res.type}\n"
                f"Vulnerability {m.id}\nSeverity: {sev}\n"
                f"Message: {m.message}\n"
                f"Link: [{m.id}]({m.primary_url})"
            )
            results.append(_result(
                m.id, idx, _LEVELS[sev], message, uri,
                m.cause_metadata.start_line, m.cause_metadata.end_line,
            ))
        for s in res.secrets:
            sev = Severity.parse(s.severity)
            idx = add_rule(
                s.rule_id, name="Secret", short=s.title, full=s.title,
                help_uri="", severity=sev, tags=["secret", str(sev)],
            )
            message = (
                f"Artifact: {res.target}\nType: {res.type}\n"
                f"Secret {s.title}\nSeverity: {sev}\n"
                f"Match: {s.match}"
            )
            results.append(_result(
                s.rule_id, idx, _LEVELS[sev], message, uri,
                s.start_line, s.end_line,
            ))
        for lic in res.licenses:
            sev = Severity.parse(lic.severity)
            rid = f"license-{lic.name}"
            idx = add_rule(
                rid, name="License", short=f"License {lic.name}",
                full=f"License {lic.name} (category: {lic.category})",
                help_uri=lic.link, severity=sev, tags=["license", str(sev)],
            )
            message = (
                f"Artifact: {res.target}\nLicense {lic.name}\n"
                f"Category: {lic.category}\nPackage: {lic.pkg_name}"
            )
            results.append(_result(
                rid, idx, _LEVELS[sev], message,
                _clean_uri(lic.file_path) if lic.file_path else uri,
            ))

    doc = {
        "version": _SARIF_VERSION,
        "$schema": _SCHEMA,
        "runs": [{
            "tool": {
                "driver": {
                    "fullName": "trivy-tpu: TPU-native vulnerability scanner",
                    "informationUri": "https://github.com/trivy-tpu",
                    "name": "trivy-tpu",
                    "rules": rules,
                    "version": trivy_tpu.__version__,
                },
            },
            "results": results,
            "columnKind": "utf16CodeUnits",
            "originalUriBaseIds": {
                "ROOTPATH": {"uri": "file:///"},
            },
            "properties": {
                "imageName": report.artifact_name,
                "repoTags": report.metadata.repo_tags,
                "repoDigests": report.metadata.repo_digests,
                "imageID": report.metadata.image_id,
            },
        }],
    }
    return json.dumps(doc, indent=2, ensure_ascii=False) + "\n"
