"""SARIF 2.1.0 writer (reference pkg/report/sarif.go).

One run; a rule per unique finding ID (indexed in first-seen order, rule
data refreshed on every occurrence, matching the reference's AddRule
semantics); one result per detected vulnerability / misconfiguration /
secret / license. Help text, CVSS-backed security-severity, tags and
location messages follow the reference's shapes byte-for-byte so SARIF
consumers (GitHub code scanning) see identical reports.
"""

from __future__ import annotations

import html
import json
import re

import trivy_tpu
from trivy_tpu.types.enums import Severity
from trivy_tpu.types.report import Report

_SARIF_VERSION = "2.1.0"
_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/main/"
    "sarif-2.1/schema/sarif-schema-2.1.0.json"
)

_SECRET_RULES_URL = (
    "https://github.com/aquasecurity/trivy/blob/main/pkg/fanal/secret/"
    "builtin-rules.go"
)

# reference pkg/report/sarif.go toSarifErrorLevel
_LEVELS = {
    "CRITICAL": "error",
    "HIGH": "error",
    "MEDIUM": "warning",
    "LOW": "note",
    "UNKNOWN": "note",
}

# severityToScore (used when no vendor CVSS score exists)
_SEVERITY_SCORE = {
    "CRITICAL": "9.5",
    "HIGH": "8.0",
    "MEDIUM": "5.5",
    "LOW": "2.0",
}

# strip a trailing " (distro info)" from OS-package targets (pathRegex)
_PATH_RX = re.compile(r"^(?P<path>.+?)(?:\s*\((?:.*?)\).*?)?$")


def _level(severity: str) -> str:
    return _LEVELS.get(str(severity), "none")


def _escape(s: str) -> str:
    """Go html.EscapeString: <, >, &, ', " (in that charset)."""
    return html.escape(s or "", quote=True).replace(
        "&#x27;", "&#39;").replace("&quot;", "&#34;")


_REPO_COMPONENT = re.compile(r"^[a-z0-9]+(?:(?:[._]|__|[-]+)[a-z0-9]+)*$")


def _repository_str(name: str) -> str | None:
    """go-containerregistry ParseReference(...).Context().RepositoryStr():
    drop tag/digest and the registry host, add the library/ namespace for
    single-component Docker Hub names. None when `name` does not parse
    as an image reference (callers keep the input unchanged)."""
    s = name
    if "@" in s:
        s = s.split("@", 1)[0]
    # a ":" after the last "/" is a tag separator
    head, _, last = s.rpartition("/")
    if ":" in last:
        last = last.split(":", 1)[0]
        s = f"{head}/{last}" if head else last
    parts = s.split("/")
    # leading registry component contains "." / ":" or is localhost
    if len(parts) > 1 and ("." in parts[0] or ":" in parts[0]
                           or parts[0] == "localhost"):
        parts = parts[1:]
    if not parts or not all(_REPO_COMPONENT.match(p) for p in parts):
        return None
    if len(parts) == 1:
        return f"library/{parts[0]}"
    return "/".join(parts)


def _to_path_uri(target: str, result_class: str) -> str:
    """ToPathUri: only OS-package targets carry image/distro decoration
    worth stripping."""
    if result_class != "os-pkgs":
        return target
    m = _PATH_RX.match(target or "")
    if m:
        target = m.group("path")
    repo = _repository_str(target)
    if repo is not None:
        target = repo
    return _clear_uri(target)


def _clear_uri(s: str) -> str:
    """clearURI: normalize go-getter-style module sources to URLs."""
    s = (s or "").replace("\\", "/")
    if s.startswith("git@github.com:"):
        s = s.replace("git@github.com:", "github.com/")
        s = s.replace(".git", "").replace("?ref=", "/tree/")
    elif s.startswith("git::https:/") and not s.startswith("git::https://"):
        s = s[len("git::https:/"):].replace(".git", "")
    elif s.startswith("git::ssh://"):
        _, _, rest = s.partition("@")
        if rest:
            s = rest
        s = s.replace(".git", "")
    elif s.startswith("git::"):
        s = s[len("git::"):].replace(".git", "")
    elif s.startswith("hg::"):
        s = s[len("hg::"):].replace(".hg", "")
    elif s.startswith(("s3::", "gcs::")):
        s = s.split("::", 1)[1]
    return s


def _rule_name(result_class: str) -> str:
    return {
        "os-pkgs": "OsPackageVulnerability",
        "lang-pkgs": "LanguageSpecificPackageVulnerability",
        "config": "MisconfigurationFiles",
        "secret": "SecretFiles",
        "license": "LicenseFiles",
        "license-file": "LicenseFiles",
    }.get(str(result_class), "UnknownIssue")


def _cvss_score(v) -> str:
    """Vendor CVSS V3 score when present (getCVSSScore: the
    SeveritySource's entry), else severity-derived."""
    cvss = (getattr(v.info, "cvss", None) or {}) if v.info else {}
    entry = cvss.get(v.severity_source or "")
    if isinstance(entry, dict):
        # Go formats the struct field (0 when absent) with %.1f
        return f"{float(entry.get('V3Score') or 0.0):.1f}"
    return _SEVERITY_SCORE.get(str(v.severity), "0.0")


class _Run:
    """Accumulates rules (dedup by id, last data wins) and results."""

    def __init__(self):
        self.rules: list[dict] = []
        self.index: dict[str, int] = {}
        self.results: list[dict] = []

    def add(self, *, rule_id: str, name: str, short: str, full: str,
            help_text: str, help_md: str, severity: str, score: str,
            tag: str, url: str, message: str, location_msg: str,
            artifact_uri: str, locations: list[tuple[int, int]]):
        rule = {
            "id": rule_id,
            "name": name,
            # the reference html-escapes both descriptions
            # (html.EscapeString in sarif.go)
            "shortDescription": {"text": _escape(short)},
            "fullDescription": {"text": _escape(full)},
            "defaultConfiguration": {"level": _level(severity)},
        }
        if url:
            rule["helpUri"] = url
        rule["help"] = {"text": help_text, "markdown": help_md}
        rule["properties"] = {
            "precision": "very-high",
            "security-severity": score,
            "tags": [tag, "security", str(severity)],
        }
        idx = self.index.get(rule_id)
        if idx is None:
            idx = len(self.rules)
            self.index[rule_id] = idx
            self.rules.append(rule)
        else:
            self.rules[idx] = rule  # AddRule refreshes existing rule data
        if not locations:
            locations = [(1, 1)]
        self.results.append({
            "ruleId": rule_id,
            "ruleIndex": idx,
            "level": _level(severity),
            "message": {"text": message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": artifact_uri,
                        "uriBaseId": "ROOTPATH",
                    },
                    "region": {
                        "startLine": lo or 1,
                        "startColumn": 1,
                        "endLine": hi or lo or 1,
                        "endColumn": 1,
                    },
                },
                "message": {"text": location_msg},
            } for lo, hi in locations],
        })


def _pkg_locations(res, name: str, version: str) -> list[tuple[int, int]]:
    for pkg in getattr(res, "packages", None) or []:
        if pkg.name == name and pkg.version == version:
            return [(loc.start_line, loc.end_line)
                    for loc in getattr(pkg, "locations", None) or []]
    return []


def render_sarif(report: Report) -> str:
    run = _Run()

    for res in report.results:
        rclass = str(res.result_class or "")
        target = _to_path_uri(res.target, rclass)
        for v in res.vulnerabilities:
            sev = str(v.severity)
            title = (v.info.title if v.info else "") or ""
            desc = (v.info.description if v.info else "") or ""
            full = desc or title
            path = target
            if v.pkg_path:
                path = _to_path_uri(v.pkg_path, rclass)
            vid, url = v.vulnerability_id, v.primary_url
            fixed = v.fixed_version or ""
            run.add(
                rule_id=vid, name=_rule_name(rclass), short=title,
                full=full, severity=sev, score=_cvss_score(v),
                tag="vulnerability", url=url,
                help_text=(
                    f"Vulnerability {vid}\nSeverity: {sev}\n"
                    f"Package: {v.pkg_name}\nFixed Version: {fixed}\n"
                    f"Link: [{vid}]({url})\n{desc}"),
                help_md=(
                    f"**Vulnerability {vid}**\n"
                    "| Severity | Package | Fixed Version | Link |\n"
                    "| --- | --- | --- | --- |\n"
                    f"|{sev}|{v.pkg_name}|{fixed}|[{vid}]({url})|\n\n"
                    f"{desc}"),
                message=(
                    f"Package: {v.pkg_name}\n"
                    f"Installed Version: {v.installed_version}\n"
                    f"Vulnerability {vid}\nSeverity: {sev}\n"
                    f"Fixed Version: {fixed}\nLink: [{vid}]({url})"),
                location_msg=(
                    f"{path}: {v.pkg_name}@{v.installed_version}"),
                artifact_uri=path,
                locations=_pkg_locations(res, v.pkg_name,
                                         v.installed_version),
            )
        for m in res.misconfigurations:
            sev = str(Severity.parse(m.severity))
            uri = _clear_uri(res.target)
            mid, url = m.id, m.primary_url
            run.add(
                rule_id=mid, name=_rule_name(rclass), short=m.title,
                full=m.description, severity=sev,
                score=_SEVERITY_SCORE.get(sev, "0.0"),
                tag="misconfiguration", url=url,
                help_text=(
                    f"Misconfiguration {mid}\nType: {res.type}\n"
                    f"Severity: {sev}\nCheck: {m.title}\n"
                    f"Message: {m.message}\nLink: [{mid}]({url})\n"
                    f"{m.description}"),
                help_md=(
                    f"**Misconfiguration {mid}**\n"
                    "| Type | Severity | Check | Message | Link |\n"
                    "| --- | --- | --- | --- | --- |\n"
                    f"|{res.type}|{sev}|{m.title}|{m.message}|"
                    f"[{mid}]({url})|\n\n{m.description}"),
                message=(
                    f"Artifact: {uri}\nType: {res.type}\n"
                    f"Vulnerability {mid}\nSeverity: {sev}\n"
                    f"Message: {m.message}\nLink: [{mid}]({url})"),
                location_msg=uri, artifact_uri=uri,
                locations=[(m.cause_metadata.start_line,
                            m.cause_metadata.end_line)],
            )
        for s in res.secrets:
            sev = str(Severity.parse(s.severity))
            run.add(
                rule_id=s.rule_id, name=_rule_name(rclass),
                short=s.title, full=s.match, severity=sev,
                score=_SEVERITY_SCORE.get(sev, "0.0"), tag="secret",
                url=_SECRET_RULES_URL,
                help_text=(
                    f"Secret {s.title}\nSeverity: {sev}\n"
                    f"Match: {s.match}"),
                help_md=(
                    f"**Secret {s.title}**\n| Severity | Match |\n"
                    f"| --- | --- |\n|{sev}|{s.match}|"),
                message=(
                    f"Artifact: {res.target}\nType: {res.type}\n"
                    f"Secret {s.title}\nSeverity: {sev}\n"
                    f"Match: {s.match}"),
                location_msg=target, artifact_uri=target,
                locations=[(s.start_line, s.end_line)],
            )
        for lic in res.licenses:
            sev = str(Severity.parse(lic.severity))
            lid = f"{lic.pkg_name}:{lic.name}"
            desc = f"{lic.name} in {lic.pkg_name}"
            run.add(
                rule_id=lid, name=_rule_name(rclass),
                short=desc, full=desc, severity=sev,
                score=_SEVERITY_SCORE.get(sev, "0.0"), tag="license",
                url=lic.link,
                help_text=f"License {desc}\nClassification: {lic.category}",
                help_md=(
                    f"**License {desc}**\n| Classification |\n"
                    f"| --- |\n|{lic.category}|"),
                message=(
                    f"Artifact: {res.target}\nLicense {lic.name}\n"
                    f"PkgName: {lic.pkg_name}\n"
                    f"Classification: {lic.category}\n"),
                location_msg=target, artifact_uri=target,
                locations=[],
            )

    doc = {
        "version": _SARIF_VERSION,
        "$schema": _SCHEMA,
        "runs": [{
            "tool": {
                "driver": {
                    "fullName": "Trivy Vulnerability Scanner",
                    "informationUri": "https://github.com/aquasecurity/trivy",
                    "name": "Trivy",
                    "rules": run.rules,
                    "version": trivy_tpu.__version__,
                },
            },
            "results": run.results,
            "columnKind": "utf16CodeUnits",
            "originalUriBaseIds": {
                "ROOTPATH": {"uri": "file:///"},
            },
        }],
    }
    if str(report.artifact_type) == "container_image":
        # Go renders this Properties map with sorted keys and JSON null
        # for absent slices
        doc["runs"][0]["properties"] = {
            "imageID": report.metadata.image_id,
            "imageName": report.artifact_name,
            "repoDigests": report.metadata.repo_digests or None,
            "repoTags": report.metadata.repo_tags or None,
        }
    return json.dumps(doc, indent=2, ensure_ascii=False) + "\n"
