"""GitHub dependency snapshot writer (reference pkg/report/github/github.go).

The snapshot maps each result target (manifest path) to its resolved
package purls; intended for POST /repos/{owner}/{repo}/dependency-graph/
snapshots. Envelope field order, detector identity and per-package field
shapes match the reference writer so snapshot consumers are untouched.
"""

from __future__ import annotations

import json
import os

import trivy_tpu
from trivy_tpu.types.report import Report
from trivy_tpu.utils import clock


def render_github(report: Report) -> str:
    snapshot: dict = {
        "version": 0,
        "detector": {
            # detector identity mirrors the reference writer: snapshot
            # consumers (GitHub dependency graph) key on it
            "name": "trivy",
            "version": trivy_tpu.__version__,
            "url": "https://github.com/aquasecurity/trivy",
        },
    }
    # Go marshals maps with sorted keys: RepoDigest sorts before RepoTag
    metadata = {}
    if report.metadata.repo_digests:
        metadata["aquasecurity:trivy:RepoDigest"] = \
            ", ".join(report.metadata.repo_digests)
    if report.metadata.repo_tags:
        metadata["aquasecurity:trivy:RepoTag"] = \
            ", ".join(report.metadata.repo_tags)
    if metadata:
        snapshot["metadata"] = metadata
    if ref := os.environ.get("GITHUB_REF", ""):
        snapshot["ref"] = ref
    if sha := os.environ.get("GITHUB_SHA", ""):
        snapshot["sha"] = sha
    snapshot["job"] = {
        "correlator": "_".join([
            os.environ.get("GITHUB_WORKFLOW", ""),
            os.environ.get("GITHUB_JOB", ""),
        ]),
        "id": os.environ.get("GITHUB_RUN_ID", ""),
    }
    snapshot["scanned"] = clock.now_rfc3339()

    manifests = {}
    for res in report.results:
        if not res.packages:
            continue
        manifest: dict = {"name": str(res.type)}
        # path shown for language-specific packages only
        if str(res.result_class) == "lang-pkgs":
            if str(report.artifact_type) == "container_image":
                src = ", ".join(report.metadata.repo_tags or [])
                with_hash = ", ".join(report.metadata.repo_digests or [])
                _, _, image_hash = with_hash.partition("@")
                if image_hash:
                    src += "@" + image_hash
                manifest["file"] = {"source_location": src}
            else:
                manifest["file"] = {"source_location": res.target}
        resolved = {}
        for pkg in res.packages:
            entry: dict = {}
            if pkg.identifier.purl:  # omitempty: no key for purl-less
                entry["package_url"] = pkg.identifier.purl
            entry["relationship"] = ("indirect"
                                     if pkg.relationship == "indirect"
                                     else "direct")
            if pkg.depends_on:
                entry["dependencies"] = list(pkg.depends_on)
            entry["scope"] = "runtime"
            if pkg.file_path:
                entry["metadata"] = {"source_location": pkg.file_path}
            resolved[pkg.name] = entry
        # map keys render sorted, as Go's encoding/json does
        manifest["resolved"] = dict(sorted(resolved.items()))
        manifests[res.target] = manifest
    snapshot["manifests"] = dict(sorted(manifests.items()))
    return json.dumps(snapshot, indent=2, ensure_ascii=False) + "\n"
