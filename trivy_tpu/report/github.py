"""GitHub dependency snapshot writer (reference pkg/report/github/github.go).

The snapshot maps each result target (manifest path) to its resolved
package purls; intended for POST /repos/{owner}/{repo}/dependency-graph/
snapshots.
"""

from __future__ import annotations

import json
import os

import trivy_tpu
from trivy_tpu.types.report import Report
from trivy_tpu.utils import clock


def render_github(report: Report) -> str:
    manifests = {}
    for res in report.results:
        if not res.packages:
            continue
        resolved = {}
        for pkg in res.packages:
            purl = pkg.identifier.purl
            if not purl:
                continue
            resolved[pkg.name] = {
                "package_url": purl,
                "relationship": "indirect" if pkg.indirect else "direct",
                "scope": "development" if pkg.dev else "runtime",
                "dependencies": sorted(pkg.depends_on or []),
            }
        manifests[res.target] = {
            "name": res.target,
            "file": {"source_location": res.target},
            "resolved": resolved,
        }

    snapshot = {
        "version": 0,
        "detector": {
            "name": "trivy-tpu",
            "version": trivy_tpu.__version__,
            "url": "https://github.com/trivy-tpu",
        },
        "metadata": {
            "aquasecurity:trivy:RepoDigest":
                report.metadata.repo_digests[0]
                if report.metadata.repo_digests else "",
            "aquasecurity:trivy:RepoTag":
                report.metadata.repo_tags[0]
                if report.metadata.repo_tags else "",
        },
        "scanned": clock.now_rfc3339(),
        "job": {
            "correlator": "_".join(filter(None, [
                os.environ.get("GITHUB_WORKFLOW", ""),
                os.environ.get("GITHUB_JOB", ""),
            ])) or "trivy-tpu",
            "id": os.environ.get("GITHUB_RUN_ID", ""),
        },
        "ref": os.environ.get("GITHUB_REF", ""),
        "sha": os.environ.get("GITHUB_SHA", ""),
        "manifests": manifests,
    }
    return json.dumps(snapshot, indent=2, ensure_ascii=False) + "\n"
