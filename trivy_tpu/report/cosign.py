"""Cosign vulnerability-attestation predicate writer
(reference pkg/report/predicate/vuln.go).

Wraps the full JSON report in the https://cosign.sigstore.dev/attestation/
vuln/v1 predicate shape so it can be attached to an image with
`cosign attest --type vuln`.
"""

from __future__ import annotations

import json

import trivy_tpu
from trivy_tpu.types.report import Report
from trivy_tpu.utils import clock, uuid as uuidgen


def render_cosign_vuln(report: Report) -> str:
    now = clock.now_rfc3339()
    doc = {
        "invocation": {
            "parameters": None,
            "uri": "",
            "event_id": uuidgen.new(),
            "builder.id": "",
        },
        "scanner": {
            "uri": f"pkg:github/trivy-tpu@{trivy_tpu.__version__}",
            "version": trivy_tpu.__version__,
            "db": {
                "uri": "",
                "version": "",
            },
            "result": report.to_dict(),
        },
        "metadata": {
            "scanStartedOn": now,
            "scanFinishedOn": now,
        },
    }
    return json.dumps(doc, indent=2, ensure_ascii=False) + "\n"
