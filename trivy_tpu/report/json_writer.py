"""JSON report writer (reference pkg/report JSON format, 2-space indent)."""

from __future__ import annotations

import json

from trivy_tpu.types.report import Report


def render_json(report: Report) -> str:
    return json.dumps(report.to_dict(), indent=2, ensure_ascii=False) + "\n"
