"""Template writer: a Go-text/template subset interpreter
(reference pkg/report/template.go, which feeds the report through
text/template + sprig).

Supported constructs — the set used by the reference's contrib templates:
  {{ .Field.Sub }}         dotted access (maps, lists, report dict)
  {{ $var }} / {{ $var := pipeline }}
  {{ range .X }}...{{ end }}   (also: range $i, $v := .X, with {{ else }})
  {{ if pipeline }}...{{ else if }}...{{ else }}...{{ end }}
  {{ pipeline | func arg | func }}
  {{- ... -}}              whitespace trimming
functions: eq ne lt gt le ge not and or len index default empty
  toLower toUpper title trim nospace abbrev replace escapeXML escapeString
  printf toJson now getEnv sprintf join first last contains hasPrefix
  hasSuffix
Builtin templates are addressed as "@builtin/junit.tpl" etc. or by the
same names the reference documents ("@contrib/junit.tpl").
"""

from __future__ import annotations

import hashlib
import html
import json
import os
import re

import trivy_tpu
from trivy_tpu.types.report import Report
from trivy_tpu.utils import clock

# ------------------------------------------------------------ lexer


_TOKEN = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.S)


def _lex(src: str) -> list[tuple[str, str]]:
    """-> [(kind, value)] where kind is 'text' or 'action'."""
    out: list[tuple[str, str]] = []
    pos = 0
    for m in _TOKEN.finditer(src):
        text = src[pos:m.start()]
        if m.group(0).startswith("{{-"):
            text = text.rstrip()
        if out and out[-1][0] == "rtrim":
            out.pop()
            text = text.lstrip()
        if text:
            out.append(("text", text))
        out.append(("action", m.group(1)))
        if m.group(0).endswith("-}}"):
            out.append(("rtrim", ""))
        pos = m.end()
    tail = src[pos:]
    if out and out[-1][0] == "rtrim":
        out.pop()
        tail = tail.lstrip()
    if tail:
        out.append(("text", tail))
    return out


# ------------------------------------------------------------ parser

class _Node:
    pass


class _Text(_Node):
    def __init__(self, s):
        self.s = s


class _Action(_Node):
    def __init__(self, expr):
        self.expr = expr


class _If(_Node):
    def __init__(self, branches, else_body):
        self.branches = branches  # [(cond_expr, body)]
        self.else_body = else_body


class _Range(_Node):
    def __init__(self, ivar, vvar, expr, body, else_body):
        self.ivar, self.vvar, self.expr = ivar, vvar, expr
        self.body, self.else_body = body, else_body


class _Assign(_Node):
    def __init__(self, var, expr, declare=True):
        self.var, self.expr, self.declare = var, expr, declare


def _parse(tokens: list[tuple[str, str]], i: int = 0,
           until: tuple = ()) -> tuple[list[_Node], int, str | None]:
    body: list[_Node] = []
    while i < len(tokens):
        kind, val = tokens[i]
        if kind == "text":
            body.append(_Text(val))
            i += 1
            continue
        if kind == "rtrim":
            i += 1
            continue
        word = val.split(None, 1)[0] if val else ""
        if word in until:
            return body, i, val
        if word == "if":
            cond = val[2:].strip()
            branches = []
            else_body: list[_Node] = []
            inner, i, stop = _parse(tokens, i + 1, ("else", "end"))
            branches.append((cond, inner))
            while stop and stop.startswith("else"):
                rest = stop[4:].strip()
                if rest.startswith("if"):
                    inner, i, stop = _parse(tokens, i + 1, ("else", "end"))
                    branches.append((rest[2:].strip(), inner))
                else:
                    else_body, i, stop = _parse(tokens, i + 1, ("end",))
                    break
            body.append(_If(branches, else_body))
            i += 1
        elif word == "range":
            rest = val[5:].strip()
            ivar = vvar = None
            m = re.match(
                r"(\$\w+)\s*(?:,\s*(\$\w+))?\s*:=\s*(.*)", rest, re.S
            )
            if m:
                if m.group(2):
                    ivar, vvar, expr = m.group(1), m.group(2), m.group(3)
                else:
                    vvar, expr = m.group(1), m.group(3)
            else:
                expr = rest
            inner, i, stop = _parse(tokens, i + 1, ("else", "end"))
            else_body = []
            if stop == "else":
                else_body, i, stop = _parse(tokens, i + 1, ("end",))
            body.append(_Range(ivar, vvar, expr, inner, else_body))
            i += 1
        elif word == "end":
            raise ValueError("unexpected {{end}}")
        else:
            m = re.match(r"(\$\w+)\s*(:?=)\s*(.*)", val, re.S)
            if m and not val.startswith("$ "):
                body.append(_Assign(m.group(1), m.group(3),
                                    declare=m.group(2) == ":="))
            else:
                body.append(_Action(val))
            i += 1
    return body, i, None


# ------------------------------------------------------------ evaluator

_NOPIPE = object()  # sentinel: "no piped value" (None is a real value)


def _truthy(v) -> bool:
    if v is None:
        return False
    if isinstance(v, (list, dict, str, tuple)):
        return len(v) > 0
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return v != 0
    return True


def _esc_xml(s) -> str:
    return (str(s).replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;").replace('"', "&quot;")
            .replace("'", "&#39;"))


def _go_date(layout, t=None) -> str:
    """Go reference-time layout -> formatted timestamp (the sprig `date`
    subset the contrib templates use)."""
    import datetime as _dt

    if t is None:
        t = clock.now()
    if not hasattr(t, "strftime"):
        return clock.now_rfc3339()
    fmt = str(layout)
    # fractional seconds: .999... trims trailing zeros (omitted when
    # zero), .000... is fixed-width
    frac = ""
    m9 = re.search(r"\.(9+)", fmt)
    m0 = re.search(r"\.(0+)", fmt)
    if m9:
        width = len(m9.group(1))
        digits = f"{t.microsecond:06d}"
        if width > 6:
            # nanosecond layouts pick up the fake clock's sub-µs rest so
            # goldens rendered with a ns fake clock byte-match
            digits = (digits + f"{clock.ns_extra():03d}")[:min(width, 9)]
        else:
            digits = digits[:width]
        digits = digits.rstrip("0")
        frac = f".{digits}" if digits else ""
        fmt = fmt.replace(m9.group(0), "\x00FRAC\x00")
    elif m0:
        micro = f"{t.microsecond:06d}"[: min(len(m0.group(1)), 6)]
        frac = f".{micro}"
        fmt = fmt.replace(m0.group(0), "\x00FRAC\x00")
    # Z07:00 renders "Z" for UTC, else a colon offset (RFC3339)
    off = ""
    if "Z07:00" in fmt:
        utcoff = t.utcoffset() if t.tzinfo else _dt.timedelta(0)
        if not utcoff:
            off = "Z"
        else:
            total = int(utcoff.total_seconds())
            sign = "+" if total >= 0 else "-"
            total = abs(total)
            off = f"{sign}{total // 3600:02d}:{total % 3600 // 60:02d}"
        fmt = fmt.replace("Z07:00", "\x00OFF\x00")
    for go, py in (("2006", "%Y"), ("01", "%m"), ("02", "%d"),
                   ("15", "%H"), ("04", "%M"), ("05", "%S"),
                   ("MST", "%Z"), ("Jan", "%b"), ("Mon", "%a")):
        fmt = fmt.replace(go, py)
    # strftime segments BETWEEN the markers: platform C strftime treats
    # the format as NUL-terminated, so a \x00 marker inside the format
    # string silently truncates everything after it (glibc drops the
    # "Z" of ".999999999Z07:00" layouts)
    out = []
    # re.split with a capture group alternates segment/marker: odd
    # indices are the markers (a literal "FRAC" in a layout stays text)
    for k, tok in enumerate(re.split(r"\x00(FRAC|OFF)\x00", fmt)):
        if k % 2:
            out.append(frac if tok == "FRAC" else off)
        elif tok:
            out.append(t.strftime(tok))
    return "".join(out)


_FUNCS = {
    "eq": lambda a, *bs: any(a == b for b in bs),
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "gt": lambda a, b: a > b,
    "le": lambda a, b: a <= b,
    "ge": lambda a, b: a >= b,
    "not": lambda a: not _truthy(a),
    "and": lambda *a: a[-1] if all(_truthy(x) for x in a) else next(
        (x for x in a if not _truthy(x)), a[-1]),
    "or": lambda *a: next((x for x in a if _truthy(x)), a[-1]),
    "len": lambda a: len(a) if a is not None else 0,
    "index": lambda c, *ks: _index(c, ks),
    "default": lambda d, v=None: v if _truthy(v) else d,
    "empty": lambda v: not _truthy(v),
    "toLower": lambda s: str(s).lower(),
    "lower": lambda s: str(s).lower(),
    "toUpper": lambda s: str(s).upper(),
    "upper": lambda s: str(s).upper(),
    "title": lambda s: str(s).title(),
    "trim": lambda s: str(s).strip(),
    "nospace": lambda s: re.sub(r"\s+", "", str(s)),
    "abbrev": lambda n, s: (str(s)[: n - 3] + "...")
    if len(str(s)) > n else str(s),
    "replace": lambda old, new, s: str(s).replace(old, new),
    "escapeXML": _esc_xml,
    "escapeString": lambda s: html.escape(str(s)),
    "printf": lambda fmt, *a: _sprintf(fmt, a),
    "sprintf": lambda fmt, *a: _sprintf(fmt, a),
    "toJson": lambda v: json.dumps(v),
    "toPrettyJson": lambda v: json.dumps(v, indent=2),
    "now": lambda: clock.now(),
    "date": lambda fmt, t=None: _go_date(fmt, t),
    "getEnv": lambda k: os.environ.get(str(k), ""),
    "env": lambda k: os.environ.get(str(k), ""),
    "appVersion": lambda: trivy_tpu.__version__,
    # trivy registers sourceID to map a string onto its SourceID type
    # (report.CustomTemplateFuncMap); the dict form is the string itself
    "sourceID": lambda s: str(s),
    "list": lambda *a: list(a),
    "add": lambda *a: sum(a),
    "toString": lambda v: str(v),
    "splitList": lambda sep, s: str(s).split(str(sep)),
    "trimSuffix": lambda suf, s: str(s).removesuffix(str(suf)),
    "trimPrefix": lambda pre, s: str(s).removeprefix(str(pre)),
    "regexMatch": lambda pat, s: bool(re.search(pat, str(s))),
    "regexFind": lambda pat, s: (
        (lambda m: m.group(0) if m else "")(re.search(pat, str(s)))),
    # sprig substr start end string (end < 0 = to the end)
    "substr": lambda start, end, s: str(s)[int(start):]
    if int(end) < 0 else str(s)[int(start):int(end)],
    "sha1sum": lambda s: hashlib.sha1(str(s).encode()).hexdigest(),
    "sha256sum": lambda s: hashlib.sha256(str(s).encode()).hexdigest(),
    "join": lambda sep, xs: str(sep).join(str(x) for x in xs or []),
    "first": lambda xs: xs[0] if xs else None,
    "last": lambda xs: xs[-1] if xs else None,
    "contains": lambda sub, s: str(sub) in str(s),
    "hasPrefix": lambda p, s: str(s).startswith(str(p)),
    "hasSuffix": lambda p, s: str(s).endswith(str(p)),
    "endsWith": lambda s, p: str(s).endswith(str(p)),
}


def _sprintf(fmt: str, args) -> str:
    # translate Go verbs to Python %-format (the common ones)
    pyfmt = re.sub(r"%([-+ #0-9.]*)[vs]", r"%\1s", fmt)
    pyfmt = pyfmt.replace("%q", '"%s"')
    try:
        return pyfmt % tuple(args)
    except TypeError:
        return fmt


def _index(c, ks):
    for k in ks:
        if c is None:
            return None
        if isinstance(c, dict):
            c = c.get(k)
        elif isinstance(c, (list, tuple)) and isinstance(k, int):
            c = c[k] if -len(c) <= k < len(c) else None
        else:
            c = getattr(c, str(k), None)
    return c


_STR = re.compile(r'"((?:[^"\\]|\\.)*)"|`([^`]*)`')


def _split_args(expr: str) -> list[str]:
    """Split a command into space-separated args honoring quotes/parens."""
    out, buf, depth, q = [], [], 0, None
    i = 0
    while i < len(expr):
        ch = expr[i]
        if q:
            buf.append(ch)
            if ch == "\\" and i + 1 < len(expr):
                buf.append(expr[i + 1])
                i += 2
                continue
            if ch == q:
                q = None
        elif ch in "\"`":
            q = ch
            buf.append(ch)
        elif ch == "(":
            depth += 1
            buf.append(ch)
        elif ch == ")":
            depth -= 1
            buf.append(ch)
        elif ch.isspace() and depth == 0:
            if buf:
                out.append("".join(buf))
                buf = []
        else:
            buf.append(ch)
        i += 1
    if buf:
        out.append("".join(buf))
    return out


def _go_str(v) -> str:
    """Go's default %v rendering for template output. The only case that
    differs from str() is time.Time: Go prints
    "2006-01-02 15:04:05.999999999 -0700 MST"."""
    import datetime as _dt

    if isinstance(v, _dt.datetime):
        frac = ""
        micro = v.microsecond
        ns = clock.ns_extra()
        if micro or ns:
            frac = f".{micro:06d}{ns:03d}".rstrip("0") if ns \
                else f".{micro:06d}".rstrip("0")
        off = v.utcoffset() or _dt.timedelta(0)
        total = int(off.total_seconds())
        sign = "+" if total >= 0 else "-"
        total = abs(total)
        zone = v.tzname() or "UTC"
        if zone in ("UTC+00:00", "+00:00"):
            zone = "UTC"
        return (f"{v:%Y-%m-%d %H:%M:%S}{frac} "
                f"{sign}{total // 3600:02d}{total % 3600 // 60:02d} {zone}")
    return str(v)


class _Engine:
    def __init__(self, data):
        self.root = data

    def render(self, nodes: list[_Node], dot, scope: dict) -> str:
        out = []
        for n in nodes:
            if isinstance(n, _Text):
                out.append(n.s)
            elif isinstance(n, _Action):
                v = self.eval_pipeline(n.expr, dot, scope)
                if v is None:
                    pass
                elif v is True or v is False:
                    out.append("true" if v else "false")
                else:
                    out.append(_go_str(v))
            elif isinstance(n, _Assign):
                val = self.eval_pipeline(n.expr, dot, scope)
                if not n.declare and n.var in scope:
                    scope[n.var][0] = val
                else:
                    scope[n.var] = [val]
            elif isinstance(n, _If):
                done = False
                for cond, b in n.branches:
                    if _truthy(self.eval_pipeline(cond, dot, scope)):
                        out.append(self.render(b, dot, dict(scope)))
                        done = True
                        break
                if not done and n.else_body:
                    out.append(self.render(n.else_body, dot, dict(scope)))
            elif isinstance(n, _Range):
                coll = self.eval_pipeline(n.expr, dot, scope)
                items = []
                if isinstance(coll, dict):
                    items = list(coll.items())
                elif coll:
                    items = list(enumerate(coll))
                if not items and n.else_body:
                    out.append(self.render(n.else_body, dot, dict(scope)))
                for i, v in items:
                    inner = dict(scope)
                    if n.ivar:
                        inner[n.ivar] = [i]
                    if n.vvar:
                        inner[n.vvar] = [v]
                    out.append(self.render(n.body, v, inner))
        return "".join(out)

    def eval_pipeline(self, expr: str, dot, scope: dict):
        parts = self._split_pipes(expr)
        val = self.eval_command(parts[0], dot, scope, piped=_NOPIPE)
        for p in parts[1:]:
            val = self.eval_command(p, dot, scope, piped=val)
        return val

    @staticmethod
    def _split_pipes(expr: str) -> list[str]:
        out, buf, depth, q = [], [], 0, None
        for ch in expr:
            if q:
                buf.append(ch)
                if ch == q:
                    q = None
            elif ch in "\"`":
                q = ch
                buf.append(ch)
            elif ch == "(":
                depth += 1
                buf.append(ch)
            elif ch == ")":
                depth -= 1
                buf.append(ch)
            elif ch == "|" and depth == 0:
                out.append("".join(buf).strip())
                buf = []
            else:
                buf.append(ch)
        out.append("".join(buf).strip())
        return out

    def eval_command(self, cmd: str, dot, scope, piped):
        args = _split_args(cmd)
        if not args:
            return None if piped is _NOPIPE else piped
        head, rest = args[0], args[1:]
        if head in _FUNCS:
            vals = [self.eval_atom(a, dot, scope) for a in rest]
            if piped is not _NOPIPE:
                vals.append(piped)  # Go: piped value becomes the last arg
            try:
                return _FUNCS[head](*vals)
            except Exception as exc:
                # Go text/template fails loudly on function errors
                raise ValueError(
                    f"template: error calling {head!r}: {exc}"
                ) from exc
        # Go text/template errors on undefined functions at parse time;
        # mirror that instead of silently passing the value through
        if (re.fullmatch(r"[A-Za-z_]\w*", head)
                and head not in ("true", "false", "nil")):
            raise ValueError(f"template: function {head!r} not defined")
        return self.eval_atom(head, dot, scope)

    def eval_atom(self, atom: str, dot, scope):
        atom = atom.strip()
        if not atom:
            return None
        if atom.startswith("("):
            # find the matching close paren: "(expr)" or "(expr).Field"
            # (Go: a parenthesized pipeline is an operand and accepts
            # field chains, e.g. (index .CVSS "nvd").V3Score)
            depth, q = 0, None
            close = -1
            for i, ch in enumerate(atom):
                if q:
                    if ch == q:
                        q = None
                elif ch in "\"`":
                    q = ch
                elif ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        close = i
                        break
            if close == len(atom) - 1:
                return self.eval_pipeline(atom[1:-1], dot, scope)
            if close > 0 and atom[close + 1] == ".":
                inner = self.eval_pipeline(atom[1:close], dot, scope)
                return _walk(inner, atom[close + 2:])
        m = _STR.fullmatch(atom)
        if m:
            s = m.group(1) if m.group(1) is not None else m.group(2)
            return s.replace('\\"', '"').replace("\\n", "\n").replace(
                "\\t", "\t").replace("\\\\", "\\")
        if re.fullmatch(r"-?\d+", atom):
            return int(atom)
        if re.fullmatch(r"-?\d+\.\d+", atom):
            return float(atom)
        if atom == "true":
            return True
        if atom == "false":
            return False
        if atom == "nil":
            return None
        if atom.startswith("$"):
            var, _, path = atom.partition(".")
            cell = scope.get(var)  # every scope entry is a [value] cell
            base = cell[0] if cell is not None else None
            return _walk(base, path) if path else base
        if atom == ".":
            return dot
        if atom.startswith("."):
            return _walk(dot, atom[1:])
        if atom in _FUNCS:
            try:
                return _FUNCS[atom]()
            except Exception:
                return None
        return None


def _walk(base, path: str):
    cur = base
    for part in path.split("."):
        if not part:
            continue
        if cur is None:
            return None
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif isinstance(cur, (list, tuple)) and part.isdigit():
            i = int(part)
            cur = cur[i] if i < len(cur) else None
        else:
            cur = getattr(cur, part, None)
    return cur


def render_template_str(tpl: str, data) -> str:
    nodes, _, _ = _parse(_lex(tpl))
    # Go text/template predefines $ as the root data value; seed the cell
    # so $ / $.Field resolve inside range blocks
    return _Engine(data).render(nodes, data, {"$": [data]})


# ------------------------------------------------------------ builtins

_BUILTIN = {
    "junit.tpl": """<?xml version="1.0" ?>
<testsuites>
{{- range . }}
    <testsuite tests="{{ len .Vulnerabilities }}" failures="{{ len .Vulnerabilities }}" name="{{ .Target | escapeXML }}" errors="0" skipped="0" time="">
    {{- range .Vulnerabilities }}
        <testcase classname="{{ .PkgName | escapeXML }}-{{ .InstalledVersion | escapeXML }}" name="[{{ .Severity }}] {{ .VulnerabilityID }}" time="">
            <failure message="{{ .Title | escapeXML }}" type="description">{{ .Description | abbrev 512 | escapeXML }}</failure>
        </testcase>
    {{- end }}
    </testsuite>
{{- end }}
</testsuites>
""",
    "gitlab-codequality.tpl": """[
{{- $first := true }}
{{- range . }}
{{- $target := .Target }}
{{- range $v := .Vulnerabilities }}
{{- if $first }}{{ $first = false }}{{ else }},{{ end }}
  {
    "type": "issue",
    "check_name": "container_scanning",
    "description": {{ printf "%s - %s" $v.VulnerabilityID $v.Title | toJson }},
    "fingerprint": "{{ $v.VulnerabilityID }}-{{ $v.PkgName }}-{{ $v.InstalledVersion }}",
    "severity": "{{ if eq $v.Severity "CRITICAL" }}critical{{ else if eq $v.Severity "HIGH" }}major{{ else if eq $v.Severity "MEDIUM" }}minor{{ else }}info{{ end }}",
    "location": { "path": {{ $target | toJson }}, "lines": { "begin": 1 } }
  }
{{- end }}
{{- end }}
]
""",
    "html.tpl": """<!DOCTYPE html>
<html><head><title>trivy-tpu report</title>
<style>table{border-collapse:collapse}td,th{border:1px solid #999;padding:4px 8px}</style>
</head><body>
{{- range . }}
<h2>{{ .Target | escapeString }} ({{ .Type }})</h2>
{{- if .Vulnerabilities }}
<table><tr><th>ID</th><th>Severity</th><th>Package</th><th>Installed</th><th>Fixed</th><th>Title</th></tr>
{{- range .Vulnerabilities }}
<tr><td>{{ .VulnerabilityID }}</td><td>{{ .Severity }}</td><td>{{ .PkgName | escapeString }}</td><td>{{ .InstalledVersion | escapeString }}</td><td>{{ .FixedVersion | escapeString }}</td><td>{{ .Title | escapeString }}</td></tr>
{{- end }}
</table>
{{- else }}
<p>No vulnerabilities.</p>
{{- end }}
{{- end }}
</body></html>
""",
}


def _augment(report_dict: dict) -> dict:
    """Flatten vuln info fields to top level the way text/template sees
    the Go struct (Title/Description/Severity are embedded)."""
    for res in report_dict.get("Results", []):
        for v in res.get("Vulnerabilities", []):
            v.setdefault("Title", "")
            v.setdefault("Description", "")
            v.setdefault("Severity", "UNKNOWN")
            v.setdefault("FixedVersion", "")
            # Go's DetectedVulnerability embeds types.Vulnerability as a
            # named field that json inlines; templates address both forms
            # (contrib/junit.tpl uses .Vulnerability.Severity). A flat
            # COPY, not a self-reference: toJson over a vulnerability
            # must not hit a circular structure.
            v.setdefault("Vulnerability", dict(v))
        res.setdefault("Vulnerabilities", [])
        res.setdefault("Misconfigurations", [])
        res.setdefault("Secrets", [])
        res.setdefault("Type", "")
    report_dict.setdefault("Results", [])
    return report_dict


def render_template(report: Report, template: str) -> str:
    """template: inline text, "@/path/to/file.tpl", or a builtin name
    ("@contrib/junit.tpl", "@builtin/html.tpl", "junit")."""
    tpl = template
    if template.startswith("@"):
        path = template[1:]
        base = os.path.basename(path)
        if base in _BUILTIN and not os.path.exists(path):
            tpl = _BUILTIN[base]
        else:
            # newline="" keeps CRLF template bytes intact (Go renders
            # them verbatim; gitlab-codequality.tpl ships with CRLF)
            with open(path, encoding="utf-8", newline="") as f:
                tpl = f.read()
    elif template in _BUILTIN:
        tpl = _BUILTIN[template]
    elif template + ".tpl" in _BUILTIN:
        tpl = _BUILTIN[template + ".tpl"]
    data = _augment(report.to_dict())
    # the template root is the RESULTS slice, exactly like the reference
    # template writer (report/template.go passes report.Results), so
    # published trivy templates (contrib/*.tpl) render unmodified
    return render_template_str(tpl, data.get("Results") or [])
