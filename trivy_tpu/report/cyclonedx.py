"""CycloneDX 1.6 JSON writer (reference pkg/sbom/cyclonedx/marshal.go via
pkg/sbom/io/encode.go).

Structure: root metadata.component = the scanned artifact; one
"application" component per lockfile/app result; one "library" (or
"operating-system") component per package; dependency edges from the
package graph; vulnerabilities with affects[] referencing package
bom-refs.
"""

from __future__ import annotations

import json

import trivy_tpu
from trivy_tpu.types.report import Report, Result
from trivy_tpu.utils import clock, uuid as uuidgen

SPEC_VERSION = "1.6"

_NS = "aquasecurity:trivy:"  # property namespace kept for ecosystem compat


def _prop(name: str, value) -> dict:
    return {"name": _NS + name, "value": str(value)}


def _pkg_ref(pkg) -> str:
    if pkg.identifier.bom_ref:
        return pkg.identifier.bom_ref
    if pkg.identifier.purl:
        return pkg.identifier.purl
    return uuidgen.new()


def _pkg_component(res: Result, pkg) -> dict:
    name = pkg.name
    group = ""
    purl = pkg.identifier.purl
    # maven GroupID and npm scopes render in the `group` field
    # (reference sbom/io/encode.go component)
    if purl and purl.startswith(("pkg:maven/", "pkg:npm/")):
        try:
            from trivy_tpu.utils.purl import parse_purl

            p = parse_purl(purl)
            name = p.name
            group = p.namespace or ""
        except ValueError:
            pass
    comp: dict = {
        "bom-ref": _pkg_ref(pkg),
        "type": "library",
        "name": name,
        "version": pkg.full_version(),
    }
    if group:
        comp["group"] = group
    if pkg.identifier.purl:
        comp["purl"] = pkg.identifier.purl
    props = []
    if pkg.id:
        props.append(_prop("PkgID", pkg.id))
    props.append(_prop("PkgType", res.type or ""))
    if getattr(pkg, "src_name", ""):
        props.append(_prop("SrcName", pkg.src_name))
    if getattr(pkg, "src_version", ""):
        props.append(_prop("SrcVersion", pkg.src_version))
    if getattr(pkg, "file_path", ""):
        props.append(_prop("FilePath", pkg.file_path))
    if getattr(pkg, "layer", None) and pkg.layer.diff_id:
        props.append(_prop("LayerDiffID", pkg.layer.diff_id))
    comp["properties"] = [p for p in props if p["value"]]
    licenses = getattr(pkg, "licenses", None) or []
    if licenses:
        comp["licenses"] = [{"license": {"name": l}} for l in licenses]
    return comp


def _severity_cdx(sev: str) -> str:
    return {"CRITICAL": "critical", "HIGH": "high", "MEDIUM": "medium",
            "LOW": "low", "UNKNOWN": "unknown"}.get(sev, "unknown")


def render_cyclonedx(report: Report) -> str:
    root_type = {
        "container_image": "container",
        "vm_image": "container",
    }.get(report.artifact_type, "application")
    root_ref = uuidgen.new()
    root = {
        "bom-ref": root_ref,
        "type": root_type,
        "name": report.artifact_name,
        "properties": [_prop("SchemaVersion", report.schema_version)],
    }
    md = report.metadata
    if md.image_id:
        root["properties"].append(_prop("ImageID", md.image_id))
    for d in md.repo_digests:
        root["properties"].append(_prop("RepoDigest", d))
    for t in md.repo_tags:
        root["properties"].append(_prop("RepoTag", t))
    if md.diff_ids:
        for d in md.diff_ids:
            root["properties"].append(_prop("DiffID", d))

    components: list[dict] = []
    dependencies: list[dict] = []
    vulnerabilities: dict[str, dict] = {}
    root_deps: list[str] = []
    seen_refs: set[str] = set()
    dep_by_ref: dict[str, dict] = {}

    if md.os is not None and md.os.detected:
        os_ref = uuidgen.new()
        components.append({
            "bom-ref": os_ref,
            "type": "operating-system",
            "name": md.os.family,
            "version": md.os.name,
            "properties": [_prop("Type", md.os.family),
                           _prop("Class", "os-pkgs")],
        })
        root_deps.append(os_ref)
        os_holder = os_ref
    else:
        os_holder = None

    # language packages not tied to a lock file hang directly off the
    # root component (reference ftypes.AggregatingTypes + encode.go
    # encodeResult)
    from trivy_tpu.fanal.applier import AGGREGATE_TYPES as aggregating

    for res in report.results:
        cls = str(res.result_class)
        if cls == "os-pkgs" and os_holder:
            holder_ref = os_holder
        elif res.packages and (res.type or "") in aggregating:
            holder_ref = "__root__"
        elif res.packages:
            holder_ref = uuidgen.new()
            components.append({
                "bom-ref": holder_ref,
                "type": "application",
                "name": res.target,
                "properties": [_prop("Type", res.type or ""),
                               _prop("Class", cls)],
            })
            root_deps.append(holder_ref)
        else:
            holder_ref = None

        ref_by_id: dict[str, str] = {}
        pkg_components = []
        for pkg in res.packages:
            comp = _pkg_component(res, pkg)
            pkg_components.append((pkg, comp))
            if pkg.id:
                ref_by_id[pkg.id] = comp["bom-ref"]
        holder_deps = []
        for pkg, comp in pkg_components:
            ref = comp["bom-ref"]
            holder_deps.append(ref)
            edges = sorted(
                ref_by_id[d] for d in (getattr(pkg, "depends_on", None) or [])
                if d in ref_by_id
            )
            # bom-ref must be unique document-wide: the same purl seen in
            # two results keeps the first component, edges are merged
            if ref in seen_refs:
                existing = dep_by_ref.get(ref)
                if existing is not None:
                    existing["dependsOn"] = sorted(
                        set(existing["dependsOn"]) | set(edges)
                    )
                continue
            seen_refs.add(ref)
            components.append(comp)
            entry = {"ref": ref, "dependsOn": edges}
            dep_by_ref[ref] = entry
            dependencies.append(entry)
        if holder_ref == "__root__":
            root_deps.extend(holder_deps)
        elif holder_ref:
            dependencies.append({"ref": holder_ref,
                                 "dependsOn": sorted(holder_deps)})

        for v in res.vulnerabilities:
            entry = vulnerabilities.setdefault(v.vulnerability_id, {
                "id": v.vulnerability_id,
                "source": (
                    {"name": v.data_source.name, "url": v.data_source.url}
                    if v.data_source else {}
                ),
                "ratings": [{
                    "severity": _severity_cdx(str(v.severity)),
                }],
                "description": (v.info.description if v.info else ""),
                "affects": [],
            })
            if v.info:
                if v.info.published_date:
                    entry["published"] = v.info.published_date
                if v.info.last_modified_date:
                    entry["updated"] = v.info.last_modified_date
                if v.info.references:
                    entry["advisories"] = [
                        {"url": u} for u in v.info.references
                    ]
                if v.info.cwe_ids:
                    entry["cwes"] = [
                        int(c.removeprefix("CWE-"))
                        for c in v.info.cwe_ids
                        if c.removeprefix("CWE-").isdigit()
                    ]
            ref = ref_by_id.get(v.pkg_id) or v.pkg_identifier.bom_ref \
                or v.pkg_identifier.purl
            if ref:
                affect = {
                    "ref": ref,
                    "versions": [{
                        "version": v.installed_version,
                        "status": "affected",
                    }],
                }
                if affect not in entry["affects"]:
                    entry["affects"].append(affect)

    dependencies.append({"ref": root_ref,
                         "dependsOn": sorted(set(root_deps))})
    doc = {
        "$schema": f"http://cyclonedx.org/schema/bom-{SPEC_VERSION}.schema.json",
        "bomFormat": "CycloneDX",
        "specVersion": SPEC_VERSION,
        "serialNumber": f"urn:uuid:{uuidgen.new()}",
        "version": 1,
        "metadata": {
            "timestamp": clock.now_rfc3339(),
            "tools": {
                "components": [{
                    "type": "application",
                    "group": "trivy-tpu",
                    "name": "trivy-tpu",
                    "version": trivy_tpu.__version__,
                }],
            },
            "component": root,
        },
        "components": components,
        "dependencies": sorted(dependencies, key=lambda d: d["ref"]),
        "vulnerabilities": sorted(
            vulnerabilities.values(), key=lambda v: v["id"]
        ),
    }
    if not doc["vulnerabilities"]:
        del doc["vulnerabilities"]
    return json.dumps(doc, indent=2, ensure_ascii=False) + "\n"
