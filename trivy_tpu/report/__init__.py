from trivy_tpu.report.writer import write_report

__all__ = ["write_report"]
