"""Table report writer (reference pkg/report/table/): per-target summary
header + vulnerability/secret/misconfig tables with severity colors."""

from __future__ import annotations

import os
import shutil
import sys

from trivy_tpu.types.enums import Severity
from trivy_tpu.types.report import Report, Result

_SEV_ORDER = ["CRITICAL", "HIGH", "MEDIUM", "LOW", "UNKNOWN"]
_SEV_COLOR = {
    "CRITICAL": "\x1b[31m",  # red
    "HIGH": "\x1b[91m",
    "MEDIUM": "\x1b[33m",
    "LOW": "\x1b[36m",
    "UNKNOWN": "\x1b[35m",
}
_RESET = "\x1b[0m"
_BOLD = "\x1b[1m"


def _color_enabled() -> bool:
    return sys.stdout.isatty() and os.environ.get("NO_COLOR") is None


def _sev(s: str, color: bool) -> str:
    return f"{_SEV_COLOR.get(s, '')}{s}{_RESET}" if color else s


def _render_grid(headers: list[str], rows: list[list[str]], color: bool) -> str:
    """Simple box-drawing table with wrapped cells."""
    if not rows:
        return ""
    width_budget = max(shutil.get_terminal_size((150, 40)).columns, 80)
    ncol = len(headers)
    raw_w = [max(len(headers[i]), *(len(_plain(r[i])) for r in rows))
             for i in range(ncol)]
    total = sum(raw_w) + 3 * ncol + 1
    if total > width_budget:
        # shrink the widest columns
        excess = total - width_budget
        order = sorted(range(ncol), key=lambda i: -raw_w[i])
        for i in order:
            cut = min(excess, max(raw_w[i] - 20, 0))
            raw_w[i] -= cut
            excess -= cut
            if excess <= 0:
                break
    sep = "+" + "+".join("-" * (w + 2) for w in raw_w) + "+"
    out = [sep]
    out.append("| " + " | ".join(headers[i].ljust(raw_w[i])
                                 for i in range(ncol)) + " |")
    out.append(sep.replace("-", "="))
    for r in rows:
        wrapped = [_wrap(r[i], raw_w[i]) for i in range(ncol)]
        height = max(len(w) for w in wrapped)
        for line_i in range(height):
            cells = []
            for i in range(ncol):
                cell = wrapped[i][line_i] if line_i < len(wrapped[i]) else ""
                pad = raw_w[i] - len(_plain(cell))
                cells.append(cell + " " * max(pad, 0))
            out.append("| " + " | ".join(cells) + " |")
        out.append(sep)
    return "\n".join(out) + "\n"


def _plain(s: str) -> str:
    import re

    return re.sub(r"\x1b\[[0-9;]*m", "", s)


def _wrap(s: str, width: int) -> list[str]:
    if len(_plain(s)) <= width:
        return [s]
    words = s.split()
    lines, cur = [], ""
    for w in words:
        if cur and len(_plain(cur)) + 1 + len(_plain(w)) > width:
            lines.append(cur)
            cur = w
        else:
            cur = f"{cur} {w}" if cur else w
    if cur:
        lines.append(cur)
    return lines or [""]


def render_table(report: Report, severities=None,
                 dependency_tree: bool = False) -> str:
    color = _color_enabled()
    out = []
    sev_names = [str(s) for s in severities] if severities else _SEV_ORDER
    for res in report.results:
        rendered = _render_result(res, color, sev_names)
        if rendered and dependency_tree and res.vulnerabilities:
            tree = _render_dependency_tree(res)
            if tree:
                rendered += tree
        out.append(rendered)
    text = "\n".join(x for x in out if x)
    return text if text else "No issues detected.\n"


def _render_dependency_tree(res: Result) -> str:
    """--dependency-tree: why is each vulnerable package present?
    Reversed origin tree from the lockfile dependency graph
    (reference pkg/report/table renderedDeps)."""
    parents: dict[str, list[str]] = {}
    by_id: dict[str, object] = {}
    for p in res.packages:
        pid = p.id or f"{p.name}@{p.version}"
        by_id[pid] = p
        for dep in p.depends_on:
            parents.setdefault(dep, []).append(pid)
    if not parents:
        return ""
    vuln_ids = []
    seen = set()
    for v in res.vulnerabilities:
        pid = v.pkg_id or f"{v.pkg_name}@{v.installed_version}"
        if pid not in seen:
            seen.add(pid)
            vuln_ids.append(pid)
    lines = ["", "Dependency Origin Tree (Reversed)", "=" * 33]
    for pid in vuln_ids:
        lines.append(f"{pid} (vulnerable)")
        chain = []
        cur, depth = pid, 0
        while depth < 8:
            ups = parents.get(cur) or []
            if not ups:
                break
            cur = sorted(ups)[0]
            chain.append(cur)
            depth += 1
        for i, anc in enumerate(chain):
            lines.append("    " * i + "└── " + anc)
    return "\n".join(lines) + "\n"


def _render_result(res: Result, color: bool, sev_names) -> str:
    header_lines = []
    body = ""
    if res.vulnerabilities or res.result_class in ("os-pkgs", "lang-pkgs"):
        counts = {s: 0 for s in _SEV_ORDER}
        for v in res.vulnerabilities:
            counts[str(v.severity)] = counts.get(str(v.severity), 0) + 1
        total = len(res.vulnerabilities)
        summary = ", ".join(
            f"{_sev(s, color)}: {counts.get(s, 0)}" for s in sev_names
        )
        title = f"{res.target} ({res.type})" if res.type else res.target
        header_lines.append(f"{_BOLD if color else ''}{title}{_RESET if color else ''}")
        header_lines.append("=" * len(_plain(title)))
        header_lines.append(f"Total: {total} ({summary})")
        rows = [
            [
                v.pkg_name,
                v.vulnerability_id,
                _sev(str(v.severity), color),
                v.status.label if v.status.value else "",
                v.installed_version,
                v.fixed_version,
                (v.info.title if v.info else "") or v.primary_url,
            ]
            for v in res.vulnerabilities
            if str(v.severity) in sev_names
        ]
        body = _render_grid(
            ["Library", "Vulnerability", "Severity", "Status",
             "Installed Version", "Fixed Version", "Title"],
            rows, color,
        )
    elif res.secrets:
        title = f"{res.target} (secrets)"
        header_lines.append(title)
        header_lines.append("=" * len(title))
        rows = [
            [s.category, s.rule_id, _sev(s.severity, color),
             f"{s.start_line}-{s.end_line}", s.title]
            for s in res.secrets
        ]
        body = _render_grid(
            ["Category", "Rule", "Severity", "Lines", "Title"], rows, color
        )
    elif res.misconfigurations:
        title = f"{res.target} ({res.type})"
        header_lines.append(title)
        header_lines.append("=" * len(title))
        if res.misconf_summary:
            header_lines.append(
                f"Tests: {res.misconf_summary.successes + res.misconf_summary.failures} "
                f"(SUCCESSES: {res.misconf_summary.successes}, "
                f"FAILURES: {res.misconf_summary.failures})"
            )
        rows = [
            [m.id, _sev(m.severity, color), m.status, m.message]
            for m in res.misconfigurations
            if m.status == "FAIL" and m.severity in sev_names
        ]
        body = _render_grid(
            ["ID", "Severity", "Status", "Message"], rows, color
        )
    elif res.licenses:
        title = f"{res.target} (license)"
        header_lines.append(title)
        header_lines.append("=" * len(title))
        rows = [
            [l.pkg_name or l.file_path, l.name, l.category,
             _sev(l.severity, color)]
            for l in res.licenses
            if l.severity in sev_names
        ]
        body = _render_grid(
            ["Package/File", "License", "Category", "Severity"], rows, color
        )
    else:
        if not res.modified_findings:
            return ""
    tail = _render_suppressed(res, color)
    if not header_lines and not tail:
        return ""
    head = "\n".join(header_lines) + "\n\n" if header_lines else ""
    return head + (body or "") + tail + "\n"


def _render_suppressed(res: Result, color: bool) -> str:
    """--show-suppressed section (reference pkg/report/table renders
    suppressed vulnerabilities with status/statement/source columns)."""
    if not res.modified_findings:
        return ""
    rows = []
    for m in res.modified_findings:
        f = m.get("Finding", {})
        rows.append([
            f.get("PkgName", ""),
            f.get("VulnerabilityID", ""),
            _sev(f.get("Severity", "UNKNOWN"), color),
            m.get("Status", ""),
            m.get("Statement", ""),
            m.get("Source", ""),
        ])
    title = f"\nSuppressed Vulnerabilities (Total: {len(rows)})\n"
    return title + "=" * (len(title) - 2) + "\n" + _render_grid(
        ["Library", "Vulnerability", "Severity", "Status", "Statement",
         "Source"],
        rows, color,
    )
