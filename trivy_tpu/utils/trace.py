"""Compatibility shim: the tracer moved to `trivy_tpu.obs.tracing`
(contextvars-based spans with trace/span ids, cross-thread and
cross-RPC parentage, Chrome trace export — see docs/observability.md).

Every historical call site (`from trivy_tpu.utils import trace`) keeps
working; new code should import `trivy_tpu.obs.tracing` directly.
"""

from trivy_tpu.obs.tracing import (  # noqa: F401
    TRACE_HEADER,
    Span,
    add_meta,
    adopt,
    capture,
    chrome_events,
    current,
    current_scan_id,
    enable,
    enabled,
    export_chrome,
    inject_headers,
    jax_profile,
    parse_trace_header,
    render,
    reset,
    scan_scope,
    server_span,
    set_slow_span_ms,
    span,
    spans,
    timings,
)
