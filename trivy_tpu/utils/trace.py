"""Step tracing (SURVEY §5: the reference has no real tracing — only
Rego eval traces — so this is greenfield for the TPU build): named
spans with wall-clock timings, rendered as a tree at scan end, plus an
optional JAX profiler capture of the device portion.

Usage:
    with trace.span("scan"):
        with trace.span("inspect"): ...
Enabled via --trace (CLI) or TRIVY_TPU_TRACE=1; the JAX profiler dump
is written when TRIVY_TPU_JAX_TRACE_DIR is set.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field

_local = threading.local()

_enabled = os.environ.get("TRIVY_TPU_TRACE", "") not in ("", "0", "false")


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


@dataclass
class Span:
    name: str
    start: float = 0.0
    elapsed: float = 0.0
    children: list["Span"] = field(default_factory=list)
    meta: dict = field(default_factory=dict)


def _stack() -> list[Span]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


_roots: list[Span] = []
_roots_lock = threading.Lock()


@contextlib.contextmanager
def span(name: str, **meta):
    if not _enabled:
        yield None
        return
    s = Span(name=name, start=time.perf_counter(), meta=dict(meta))
    stack = _stack()
    if stack:
        stack[-1].children.append(s)
    else:
        with _roots_lock:
            _roots.append(s)
    stack.append(s)
    try:
        yield s
    finally:
        s.elapsed = time.perf_counter() - s.start
        stack.pop()


def add_meta(**meta) -> None:
    stack = _stack()
    if _enabled and stack:
        stack[-1].meta.update(meta)


def reset() -> None:
    with _roots_lock:
        _roots.clear()
    _local.stack = []


def render(out=None) -> str:
    """Render collected spans as an indented tree with timings."""
    lines: list[str] = []

    def walk(s: Span, depth: int):
        extras = "".join(f" {k}={v}" for k, v in s.meta.items())
        lines.append(f"{'  ' * depth}{s.name:<{28 - 2 * depth}} "
                     f"{s.elapsed * 1000:9.1f} ms{extras}")
        for c in s.children:
            walk(c, depth + 1)

    with _roots_lock:
        for root in _roots:
            walk(root, 0)
    text = "\n".join(lines)
    if out is not None and text:
        out.write("-- trace " + "-" * 42 + "\n" + text + "\n")
    return text


@contextlib.contextmanager
def jax_profile():
    """Capture a JAX profiler trace when TRIVY_TPU_JAX_TRACE_DIR is set
    (viewable with tensorboard/xprof)."""
    trace_dir = os.environ.get("TRIVY_TPU_JAX_TRACE_DIR", "")
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
