"""UUID source, switchable to deterministic for tests
(reference pkg/uuid)."""

from __future__ import annotations

import os
import uuid as _uuid

_counter = 0


def new() -> str:
    global _counter
    if os.environ.get("TRIVY_TPU_DETERMINISTIC_UUID") == "1":
        _counter += 1
        return f"00000000-0000-0000-0000-{_counter:012d}"
    return str(_uuid.uuid4())


def reset() -> None:
    global _counter
    _counter = 0


def set_lane(lane: int) -> None:
    """Jump the deterministic counter to a per-slot lane (no-op effect
    outside TRIVY_TPU_DETERMINISTIC_UUID=1, where uuids are random
    anyway). Fleet scans pin each artifact to lane = its fleet index,
    so a resumed run hands every artifact the same uuid stream as an
    uninterrupted one — a prerequisite for byte-identical reports when
    blob ids are uuid-keyed (fs artifacts)."""
    global _counter
    _counter = lane * 1_000_000
