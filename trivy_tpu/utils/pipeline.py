"""Generic bounded worker pipeline (reference pkg/parallel/pipeline.go:28
NewPipeline/Do: N workers over an item channel with a result callback).
Threads, not asyncio: the work units (file IO, YAML parse, regex) release
the GIL often enough, and the device batch calls serialize anyway."""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")
R = TypeVar("R")

DEFAULT_WORKERS = 5  # reference pkg/parallel/pipeline.go:10


def run_pipeline(items: Iterable[T], fn: Callable[[T], R],
                 on_result: Callable[[R], None] | None = None,
                 workers: int = DEFAULT_WORKERS) -> list[R]:
    """Run fn over items with a bounded worker pool; results are returned
    in input order. on_result (if given) is called serially, in order —
    the reference's onItem callback contract."""
    items = list(items)
    if workers <= 1 or len(items) <= 1:
        out = [fn(it) for it in items]
        if on_result:
            for r in out:
                on_result(r)
        return out

    results: list = [None] * len(items)
    errors: list = [None] * len(items)
    q: queue.Queue = queue.Queue()
    for i, it in enumerate(items):
        q.put((i, it))

    def worker():
        while True:
            try:
                i, it = q.get_nowait()
            except queue.Empty:
                return
            try:
                results[i] = fn(it)
            except Exception as e:  # surfaced after join, index-matched
                errors[i] = e
            finally:
                q.task_done()

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(min(workers, len(items)))]
    for t in threads:
        t.start()
    q.join()
    for e in errors:
        if e is not None:
            raise e
    if on_result:
        for r in results:
            on_result(r)
    return results
