"""Generic bounded worker pipeline (reference pkg/parallel/pipeline.go:28
NewPipeline/Do: N workers over an item channel with a result callback).
Threads, not asyncio: the work units (file IO, YAML parse, regex) release
the GIL often enough, and the device batch calls serialize anyway."""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")
R = TypeVar("R")

DEFAULT_WORKERS = 5  # reference pkg/parallel/pipeline.go:10


class PipelineError(Exception):
    """Aggregate of every failed pipeline slot, index-matched to the
    input order — no worker error is silently dropped."""

    def __init__(self, failures: list[tuple[int, Exception]], total: int):
        self.failures = failures
        detail = "; ".join(f"item {i}: {e}" for i, e in failures[:8])
        if len(failures) > 8:
            detail += f"; ... {len(failures) - 8} more"
        super().__init__(
            f"{len(failures)}/{total} pipeline items failed: {detail}")


def run_pipeline(items: Iterable[T], fn: Callable[[T], R],
                 on_result: Callable[[R], None] | None = None,
                 workers: int = DEFAULT_WORKERS,
                 on_start: Callable[[int, T], None] | None = None) -> list[R]:
    """Run fn over items with a bounded worker pool; results are returned
    in input order. on_result (if given) is called serially, in order —
    the reference's onItem callback contract.

    on_start (if given) fires from the worker the moment it picks an
    item up, BEFORE fn — the hook the fleet-scan journal uses to write
    its `running` checkpoint (so a kill mid-item is distinguishable
    from a kill before the item started). It may be called concurrently
    across workers; the callback must be thread-safe. An on_start error
    counts as the item's failure and fn is skipped.

    Worker errors do not vanish: on_result is skipped for failed slots
    and all failures surface together as one index-matched
    PipelineError after the successful slots' callbacks have been
    delivered. In parallel mode every item still runs (the pool drains
    the queue regardless); sequential mode stays fail-fast.

    Observability: the submitting thread's trace context (current span,
    scan id) is captured once and adopted inside every worker, so spans
    opened by fn attach to the submitting scan's span instead of
    becoming orphaned roots (docs/observability.md)."""
    from trivy_tpu.obs import tracing

    items = list(items)
    results: list = [None] * len(items)
    errors: list = [None] * len(items)
    ran = len(items)  # slots actually attempted (sequential fail-fast)

    if workers <= 1 or len(items) <= 1:
        # sequential mode keeps fail-fast (no worker pool is draining
        # anyway): stop at the first error instead of burning the
        # remaining items' cost, but surface it as the same aggregate
        # exception type the parallel path raises
        for i, it in enumerate(items):
            try:
                if on_start:
                    on_start(i, it)
                results[i] = fn(it)
            except Exception as e:
                errors[i] = e
                ran = i + 1
                break
    else:
        q: queue.Queue = queue.Queue()
        for i, it in enumerate(items):
            q.put((i, it))
        # captured in the submitting thread, adopted per worker: a new
        # thread starts from an empty contextvars context, which is how
        # worker spans used to orphan into separate roots
        trace_ctx = tracing.capture()

        def worker():
            with tracing.adopt(trace_ctx):
                while True:
                    try:
                        i, it = q.get_nowait()
                    except queue.Empty:
                        return
                    try:
                        if on_start:
                            on_start(i, it)
                        results[i] = fn(it)
                    # BaseException too (InjectedKill, SystemExit from
                    # fn): letting it kill the worker thread would
                    # strand queued items and hang q.join() forever —
                    # in a pool, every failure must land in a slot, not
                    # take the pool down
                    except BaseException as e:  # noqa: B036  # lint: allow[bare-except] stored per-slot, aggregated into PipelineError on the submitting thread
                        errors[i] = e
                    finally:
                        q.task_done()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(min(workers, len(items)))]
        for t in threads:
            t.start()
        q.join()

    if on_result:
        for i in range(ran):
            if errors[i] is None:  # failed/unran slots explicitly skipped
                on_result(results[i])
    failures = [(i, e) for i, e in enumerate(errors) if e is not None]
    if failures:
        raise PipelineError(failures, len(items))
    return results
