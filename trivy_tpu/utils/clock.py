"""Context-injectable clock (reference pkg/clock/clock.go:20-37): report
timestamps must be fakeable so golden files byte-match in tests."""

from __future__ import annotations

import datetime
import os

_fixed: datetime.datetime | None = None


def set_fixed(dt: datetime.datetime | None) -> None:
    global _fixed
    _fixed = dt


def now() -> datetime.datetime:
    if _fixed is not None:
        return _fixed
    env = os.environ.get("TRIVY_TPU_FAKE_TIME")
    if env:
        return datetime.datetime.fromisoformat(env)
    return datetime.datetime.now(datetime.timezone.utc)


def now_rfc3339() -> str:
    t = now()
    if t.tzinfo is None:
        t = t.replace(tzinfo=datetime.timezone.utc)
    return t.isoformat().replace("+00:00", "Z")
