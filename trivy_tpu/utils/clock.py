"""Context-injectable clock (reference pkg/clock/clock.go:20-37): report
timestamps must be fakeable so golden files byte-match in tests."""

from __future__ import annotations

import datetime
import os

_fixed: datetime.datetime | None = None


def set_fixed(dt: datetime.datetime | None) -> None:
    global _fixed
    _fixed = dt


def _split_ns(iso: str) -> tuple[str, int]:
    """ISO string with a >6-digit fraction -> (µs-precision ISO, extra
    sub-microsecond nanoseconds). datetime only holds microseconds; the
    remainder is kept so Go-layout formatting can byte-match reference
    goldens rendered with a nanosecond fake clock."""
    import re

    m = re.search(r"\.(\d{7,9})", iso)
    if not m:
        return iso, 0
    digits = m.group(1).ljust(9, "0")
    return iso.replace(m.group(0), "." + digits[:6]), int(digits[6:9])


def now() -> datetime.datetime:
    if _fixed is not None:
        return _fixed
    env = os.environ.get("TRIVY_TPU_FAKE_TIME")
    if env:
        return datetime.datetime.fromisoformat(_split_ns(env)[0])
    return datetime.datetime.now(datetime.timezone.utc)


def ns_extra() -> int:
    """Sub-microsecond nanoseconds (0-999) of the fake time; 0 outside
    tests (real timestamps don't need ns)."""
    env = os.environ.get("TRIVY_TPU_FAKE_TIME")
    return _split_ns(env)[1] if env else 0


def now_rfc3339() -> str:
    t = now()
    if t.tzinfo is None:
        t = t.replace(tzinfo=datetime.timezone.utc)
    return t.isoformat().replace("+00:00", "Z")
