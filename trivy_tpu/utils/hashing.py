"""Stable 64-bit string hashing for the name-hash join.

The join key mixes the match-space id (bucket) with the package name so a
single sorted array serves every ecosystem/distro. 64 bits are carried as
two uint32 lanes (h1 primary sort key, h2 verifier) because TPUs prefer
32-bit integers; h1 collisions only widen the gather window and h2+host
name rescreen remove any false positives (SURVEY.md §7 hard part #3).
"""

from __future__ import annotations

import hashlib

import numpy as np

_MASK32 = 0xFFFFFFFF


def hash64(s: str) -> int:
    """Deterministic 64-bit hash (blake2b-8). Stable across processes —
    never use Python's salted hash() for DB-resident keys."""
    return int.from_bytes(hashlib.blake2b(s.encode("utf-8"), digest_size=8).digest(), "big")


def join_key(space: str, name: str) -> tuple[int, int]:
    """(h1, h2) uint32 pair for the (match-space, package-name) join."""
    h = hash64(f"{space}\x00{name}")
    return (h >> 32) & _MASK32, h & _MASK32


def join_keys_np(pairs: list[tuple[str, str]]) -> tuple[np.ndarray, np.ndarray]:
    h1 = np.empty(len(pairs), dtype=np.uint32)
    h2 = np.empty(len(pairs), dtype=np.uint32)
    for i, (space, name) in enumerate(pairs):
        a, b = join_key(space, name)
        h1[i], h2[i] = a, b
    return h1, h2
