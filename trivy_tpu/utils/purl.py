"""Package URL (purl) parsing and mapping to trivy types
(reference pkg/purl/purl.go)."""

from __future__ import annotations

from dataclasses import dataclass, field
from urllib.parse import quote, unquote


@dataclass
class PackageURL:
    type: str = ""
    namespace: str = ""
    name: str = ""
    version: str = ""
    qualifiers: dict[str, str] = field(default_factory=dict)
    subpath: str = ""

    def __str__(self) -> str:
        out = f"pkg:{self.type}/"
        if self.namespace:
            out += quote(self.namespace, safe="/") + "/"
        out += quote(self.name, safe="")
        if self.version:
            out += "@" + quote(self.version, safe="")
        if self.qualifiers:
            q = "&".join(f"{k}={quote(str(v), safe='')}"
                         for k, v in sorted(self.qualifiers.items()))
            out += "?" + q
        if self.subpath:
            out += "#" + self.subpath
        return out

    @property
    def full_name(self) -> str:
        """Name as the detector expects (maven: group:artifact,
        golang/npm scoped: namespace/name; OS purls: the namespace is the
        distro, not part of the package name)."""
        if not self.namespace or self.type in ("apk", "deb", "rpm"):
            return self.name
        if self.type == "maven":
            return f"{self.namespace}:{self.name}"
        return f"{self.namespace}/{self.name}"


def parse_purl(s: str) -> PackageURL:
    if not s.startswith("pkg:"):
        raise ValueError(f"not a purl: {s!r}")
    rest = s[4:]
    subpath = ""
    if "#" in rest:
        rest, subpath = rest.split("#", 1)
    qualifiers: dict[str, str] = {}
    if "?" in rest:
        rest, q = rest.split("?", 1)
        for pair in q.split("&"):
            if "=" in pair:
                k, v = pair.split("=", 1)
                qualifiers[k] = unquote(v)
    version = ""
    if "@" in rest:
        rest, version = rest.rsplit("@", 1)
        version = unquote(version)
    parts = [unquote(p) for p in rest.strip("/").split("/")]
    ptype = parts[0].lower()
    if len(parts) < 2:
        raise ValueError(f"purl missing name: {s!r}")
    name = parts[-1]
    namespace = "/".join(parts[1:-1])
    return PackageURL(ptype, namespace, name, version, qualifiers, subpath)


# purl type -> (kind, type string) where kind is "os" | "lang"
# (reference pkg/purl/purl.go purlType/LangType mapping)
_PURL_LANG = {
    "npm": "node-pkg",
    "pypi": "python-pkg",
    "gem": "gemspec",
    "maven": "jar",
    "golang": "gobinary",
    "cargo": "rustbinary",
    "composer": "composer-vendor",
    "nuget": "nuget",
    "pub": "pub",
    "hex": "hex",
    "conan": "conan",
    "swift": "swift",
    "cocoapods": "cocoapods",
    "conda": "conda-pkg",
    "bitnami": "bitnami",
    "k8s": "kubernetes",
    "julia": "julia",
}
_PURL_OS = {"apk", "deb", "rpm"}


def purl_kind(p: PackageURL) -> tuple[str, str] | None:
    """-> ("os", family) or ("lang", lang_type) or None."""
    if p.type in _PURL_OS:
        distro = p.namespace or p.qualifiers.get("distro", "").split("-")[0]
        return ("os", distro)
    lt = _PURL_LANG.get(p.type)
    if lt:
        return ("lang", lt)
    return None


def purl_for_package(kind: str, type_str: str, name: str, version: str,
                     namespace_hint: str = "") -> str:
    """Best-effort purl construction for report output
    (reference pkg/purl/purl.go New)."""
    type_map = {
        "node-pkg": "npm", "npm": "npm", "yarn": "npm", "pnpm": "npm",
        "bun": "npm", "javascript": "npm",
        "python-pkg": "pypi", "pip": "pypi", "pipenv": "pypi",
        "poetry": "pypi", "uv": "pypi",
        "gemspec": "gem", "bundler": "gem",
        "jar": "maven", "pom": "maven", "gradle": "maven",
        "sbt": "maven",
        "gobinary": "golang", "gomod": "golang",
        "rustbinary": "cargo", "cargo": "cargo",
        "composer": "composer", "composer-vendor": "composer",
        "nuget": "nuget", "dotnet-core": "nuget", "packages-props": "nuget",
        "pub": "pub", "hex": "hex", "conan": "conan", "swift": "swift",
        "cocoapods": "cocoapods", "conda-pkg": "conda",
        "conda-environment": "conda", "bitnami": "bitnami",
        "kubernetes": "k8s", "julia": "julia",
    }
    if kind == "os":
        ptype = type_str  # apk/deb/rpm family passed through
        ns, nm = "", name
    else:
        ptype = type_map.get(type_str, type_str)
        ns, nm = "", name
        if ptype == "maven" and ":" in name:
            ns, nm = name.split(":", 1)
        elif ptype in ("npm", "golang") and "/" in name:
            ns, nm = name.rsplit("/", 1)
    return str(PackageURL(type=ptype, namespace=ns, name=nm, version=version))
