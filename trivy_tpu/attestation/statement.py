"""In-toto attestation parsing (reference pkg/attestation/attestation.go):
a DSSE envelope {payloadType, payload: base64, signatures} whose payload
is an in-toto statement {_type, predicateType, subject, predicate}.
Cosign SBOM attestations wrap the SBOM one level deeper in
predicate.Data (CosignPredicate, attestation.go:17-19)."""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field

IN_TOTO_PAYLOAD_TYPE = "application/vnd.in-toto+json"

# well-known predicate types (cosign / in-toto)
PREDICATE_CYCLONEDX = "https://cyclonedx.org/bom"
PREDICATE_SPDX = "https://spdx.dev/Document"
PREDICATE_COSIGN_VULN = "https://cosign.sigstore.dev/attestation/vuln/v1"


class AttestationError(ValueError):
    pass


@dataclass
class Statement:
    type: str = ""
    predicate_type: str = ""
    subject: list[dict] = field(default_factory=list)
    predicate: dict | list | None = None


def parse_statement(data: bytes | str | dict) -> Statement:
    """Decode a DSSE envelope into its in-toto statement."""
    if isinstance(data, (bytes, str)):
        try:
            envelope = json.loads(data)
        except ValueError as e:
            raise AttestationError(f"not a JSON DSSE envelope: {e}") from e
    else:
        envelope = data
    if not isinstance(envelope, dict):
        raise AttestationError("DSSE envelope must be a JSON object")
    payload_type = envelope.get("payloadType", "")
    if payload_type != IN_TOTO_PAYLOAD_TYPE:
        raise AttestationError(
            f"invalid attestation payload type: {payload_type}")
    try:
        decoded = base64.b64decode(envelope.get("payload", ""))
        doc = json.loads(decoded)
    except ValueError as e:
        raise AttestationError(
            f"failed to decode attestation payload: {e}") from e
    return Statement(
        type=doc.get("_type", ""),
        predicate_type=doc.get("predicateType", ""),
        subject=doc.get("subject") or [],
        predicate=doc.get("predicate"),
    )


def unwrap_cosign_predicate(statement: Statement):
    """Cosign SBOM attestations store the document under
    predicate.Data (reference attestation.go:14-19 + sbom decode)."""
    pred = statement.predicate
    if isinstance(pred, dict) and "Data" in pred:
        return pred["Data"]
    return pred


def is_attestation(doc: dict) -> bool:
    return isinstance(doc, dict) and "payloadType" in doc and \
        "payload" in doc
