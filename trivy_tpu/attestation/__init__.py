from trivy_tpu.attestation.statement import (  # noqa: F401
    AttestationError,
    Statement,
    is_attestation,
    parse_statement,
    unwrap_cosign_predicate,
)
