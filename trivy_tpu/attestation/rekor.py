"""Rekor transparency-log client (reference pkg/rekor/client.go):
search the index by artifact digest, retrieve entries, and extract the
attached in-toto attestation.  Plain REST over urllib — network-gated;
used by the unpackaged-file SBOM discovery handler."""

from __future__ import annotations

import base64
import json
import urllib.request
from dataclasses import dataclass

DEFAULT_URL = "https://rekor.sigstore.dev"
MAX_GET_ENTRIES = 10  # reference client.go:20

_TREE_ID_LEN = 16
_UUID_LEN = 64


class RekorError(Exception):
    pass


class OverGetEntriesLimit(RekorError):
    pass


@dataclass(frozen=True)
class EntryID:
    tree_id: str
    uuid: str

    @classmethod
    def parse(cls, entry_id: str) -> "EntryID":
        if len(entry_id) == _TREE_ID_LEN + _UUID_LEN:
            return cls(entry_id[:_TREE_ID_LEN], entry_id[_TREE_ID_LEN:])
        if len(entry_id) == _UUID_LEN:
            return cls("", entry_id)
        raise RekorError(f"invalid Entry ID length: {len(entry_id)}")

    def __str__(self) -> str:
        return self.tree_id + self.uuid


@dataclass
class Entry:
    statement: bytes  # raw DSSE/in-toto attestation bytes


class Client:
    def __init__(self, url: str = DEFAULT_URL, timeout: float = 30.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _post(self, path: str, body: dict):
        req = urllib.request.Request(
            self.url + path,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     "Accept": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except (OSError, ValueError) as e:
            raise RekorError(f"rekor {path}: {e}") from e

    def search(self, hash_: str) -> list[EntryID]:
        """POST /api/v1/index/retrieve {hash} -> entry IDs
        (reference client.go:75-92)."""
        payload = self._post("/api/v1/index/retrieve", {"hash": hash_})
        return [EntryID.parse(e) for e in payload or []]

    def get_entries(self, entry_ids: list[EntryID]) -> list[Entry]:
        """POST /api/v1/log/entries/retrieve -> attestation bytes
        (reference client.go:94-130)."""
        if len(entry_ids) > MAX_GET_ENTRIES:
            raise OverGetEntriesLimit(
                f"over get entries limit ({MAX_GET_ENTRIES})")
        if not entry_ids:
            return []
        payload = self._post("/api/v1/log/entries/retrieve",
                             {"entryUUIDs": [str(e) for e in entry_ids]})
        out = []
        for resp in payload or []:
            for _uuid, entry in (resp or {}).items():
                att = (entry.get("attestation") or {}).get("data")
                if att:
                    out.append(Entry(statement=base64.b64decode(att)))
        return out
