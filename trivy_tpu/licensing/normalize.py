"""Free-form license-name normalization to SPDX ids.

Behavioral parity with reference pkg/licensing/normalize.go: a
standardize pass (uppercase, LICENCE→LICENSE, strip THE/LICENSE
affixes, fold version suffixes like "VERSION 2.0"/"V2" to "-2.0",
extract +/-or-later/-only), then a lookup in a declared-name mapping
table (normalize.go:14-569; data originally from the OSS Review
Toolkit's license mapping).  SplitLicenses / LaxSplitLicenses mirror
normalize.go:585-767 for comma/or/and-separated declared strings.
"""

from __future__ import annotations

import re

from trivy_tpu.licensing.expression import (
    CompoundExpr,
    SimpleExpr,
    normalize_expression,
    parse,
)

LICENSE_TEXT_PREFIX = "text://"
LICENSE_FILE_PREFIX = "file://"
CUSTOM_LICENSE_PREFIX = "CUSTOM License"


def _plus(spdx: str) -> tuple[str, bool]:
    return (spdx, True)


def _ident(spdx: str) -> tuple[str, bool]:
    return (spdx, False)


# Standardized (upper-cased, affix-stripped) name → (SPDX id, has_plus).
# Same fact table as reference normalize.go:14-569.
_MAPPING: dict[str, tuple[str, bool]] = {
    # ambiguous short names
    "AFL": _ident("AFL-3.0"),
    "AGPL": _ident("AGPL-3.0"),
    "APACHE": _ident("Apache-2.0"),
    "APACHE-STYLE": _ident("Apache-2.0"),
    "ARTISTIC": _ident("Artistic-2.0"),
    "ASL": _ident("Apache-2.0"),
    "BSD": _ident("BSD-3-Clause"),
    "BSD*": _ident("BSD-3-Clause"),
    "BSD-LIKE": _ident("BSD-3-Clause"),
    "BSD-STYLE": _ident("BSD-3-Clause"),
    "BSD-VARIANT": _ident("BSD-3-Clause"),
    "CDDL": _ident("CDDL-1.0"),
    "ECLIPSE": _ident("EPL-1.0"),
    "EPL": _ident("EPL-1.0"),
    "EUPL": _ident("EUPL-1.0"),
    "FDL": _plus("GFDL-1.3"),
    "GFDL": _plus("GFDL-1.3"),
    "GPL": _plus("GPL-2.0"),
    "LGPL": _plus("LGPL-2.0"),
    "MPL": _ident("MPL-2.0"),
    "NETSCAPE": _ident("NPL-1.1"),
    "PYTHON": _ident("Python-2.0"),
    "ZOPE": _ident("ZPL-2.1"),
    # versioned aliases
    "0BSD": _ident("0BSD"),
    "AFL-1.1": _ident("AFL-1.1"),
    "AFL-1.2": _ident("AFL-1.2"),
    "AFL-2": _ident("AFL-2.0"),
    "AFL-2.0": _ident("AFL-2.0"),
    "AFL-2.1": _ident("AFL-2.1"),
    "AFL-3.0": _ident("AFL-3.0"),
    "AGPL-1.0": _ident("AGPL-1.0"),
    "AGPL-3.0": _ident("AGPL-3.0"),
    "AL-2": _ident("Apache-2.0"),
    "AL-2.0": _ident("Apache-2.0"),
    "APACHE-1": _ident("Apache-1.0"),
    "APACHE-1.0": _ident("Apache-1.0"),
    "APACHE-1.1": _ident("Apache-1.1"),
    "APACHE-2": _ident("Apache-2.0"),
    "APACHE-2.0": _ident("Apache-2.0"),
    "APL-2": _ident("Apache-2.0"),
    "APL-2.0": _ident("Apache-2.0"),
    "APSL-1.0": _ident("APSL-1.0"),
    "APSL-1.1": _ident("APSL-1.1"),
    "APSL-1.2": _ident("APSL-1.2"),
    "APSL-2.0": _ident("APSL-2.0"),
    "ARTISTIC-1.0": _ident("Artistic-1.0"),
    "ARTISTIC-1.0-CL-8": _ident("Artistic-1.0-cl8"),
    "ARTISTIC-1.0-PERL": _ident("Artistic-1.0-Perl"),
    "ARTISTIC-2.0": _ident("Artistic-2.0"),
    "ASF-1": _ident("Apache-1.0"),
    "ASF-1.0": _ident("Apache-1.0"),
    "ASF-1.1": _ident("Apache-1.1"),
    "ASF-2": _ident("Apache-2.0"),
    "ASF-2.0": _ident("Apache-2.0"),
    "ASL-1": _ident("Apache-1.0"),
    "ASL-1.0": _ident("Apache-1.0"),
    "ASL-1.1": _ident("Apache-1.1"),
    "ASL-2": _ident("Apache-2.0"),
    "ASL-2.0": _ident("Apache-2.0"),
    "BCL": _ident("BCL"),
    "BEERWARE": _ident("Beerware"),
    "BOOST": _ident("BSL-1.0"),
    "BOOST-1.0": _ident("BSL-1.0"),
    "BOUNCY": _ident("MIT"),
    "BSD-2": _ident("BSD-2-Clause"),
    "BSD-2-CLAUSE": _ident("BSD-2-Clause"),
    "BSD-2-CLAUSE-FREEBSD": _ident("BSD-2-Clause-FreeBSD"),
    "BSD-2-CLAUSE-NETBSD": _ident("BSD-2-Clause-NetBSD"),
    "BSD-3": _ident("BSD-3-Clause"),
    "BSD-3-CLAUSE": _ident("BSD-3-Clause"),
    "BSD-3-CLAUSE-ATTRIBUTION": _ident("BSD-3-Clause-Attribution"),
    "BSD-3-CLAUSE-CLEAR": _ident("BSD-3-Clause-Clear"),
    "BSD-3-CLAUSE-LBNL": _ident("BSD-3-Clause-LBNL"),
    "BSD-4": _ident("BSD-4-Clause"),
    "BSD-4-CLAUSE": _ident("BSD-4-Clause"),
    "BSD-4-CLAUSE-UC": _ident("BSD-4-Clause-UC"),
    "BSD-PROTECTION": _ident("BSD-Protection"),
    "BSL": _ident("BSL-1.0"),
    "BSL-1.0": _ident("BSL-1.0"),
    "CC-BY-1.0": _ident("CC-BY-1.0"),
    "CC-BY-2.0": _ident("CC-BY-2.0"),
    "CC-BY-2.5": _ident("CC-BY-2.5"),
    "CC-BY-3.0": _ident("CC-BY-3.0"),
    "CC-BY-4.0": _ident("CC-BY-4.0"),
    "CC-BY-NC-1.0": _ident("CC-BY-NC-1.0"),
    "CC-BY-NC-2.0": _ident("CC-BY-NC-2.0"),
    "CC-BY-NC-2.5": _ident("CC-BY-NC-2.5"),
    "CC-BY-NC-3.0": _ident("CC-BY-NC-3.0"),
    "CC-BY-NC-4.0": _ident("CC-BY-NC-4.0"),
    "CC-BY-NC-ND-1.0": _ident("CC-BY-NC-ND-1.0"),
    "CC-BY-NC-ND-2.0": _ident("CC-BY-NC-ND-2.0"),
    "CC-BY-NC-ND-2.5": _ident("CC-BY-NC-ND-2.5"),
    "CC-BY-NC-ND-3.0": _ident("CC-BY-NC-ND-3.0"),
    "CC-BY-NC-ND-4.0": _ident("CC-BY-NC-ND-4.0"),
    "CC-BY-NC-SA-1.0": _ident("CC-BY-NC-SA-1.0"),
    "CC-BY-NC-SA-2.0": _ident("CC-BY-NC-SA-2.0"),
    "CC-BY-NC-SA-2.5": _ident("CC-BY-NC-SA-2.5"),
    "CC-BY-NC-SA-3.0": _ident("CC-BY-NC-SA-3.0"),
    "CC-BY-NC-SA-4.0": _ident("CC-BY-NC-SA-4.0"),
    "CC-BY-ND-1.0": _ident("CC-BY-ND-1.0"),
    "CC-BY-ND-2.0": _ident("CC-BY-ND-2.0"),
    "CC-BY-ND-2.5": _ident("CC-BY-ND-2.5"),
    "CC-BY-ND-3.0": _ident("CC-BY-ND-3.0"),
    "CC-BY-ND-4.0": _ident("CC-BY-ND-4.0"),
    "CC-BY-SA-1.0": _ident("CC-BY-SA-1.0"),
    "CC-BY-SA-2.0": _ident("CC-BY-SA-2.0"),
    "CC-BY-SA-2.5": _ident("CC-BY-SA-2.5"),
    "CC-BY-SA-3.0": _ident("CC-BY-SA-3.0"),
    "CC-BY-SA-4.0": _ident("CC-BY-SA-4.0"),
    "CC0": _ident("CC0-1.0"),
    "CC0-1.0": _ident("CC0-1.0"),
    "CDDL-1": _ident("CDDL-1.0"),
    "CDDL-1.0": _ident("CDDL-1.0"),
    "CDDL-1.1": _ident("CDDL-1.1"),
    "COMMONS-CLAUSE": _ident("Commons-Clause"),
    "CPAL": _ident("CPAL-1.0"),
    "CPAL-1.0": _ident("CPAL-1.0"),
    "CPL": _ident("CPL-1.0"),
    "CPL-1.0": _ident("CPL-1.0"),
    "ECLIPSE-1.0": _ident("EPL-1.0"),
    "ECLIPSE-2.0": _ident("EPL-2.0"),
    "EDL-1.0": _ident("BSD-3-Clause"),
    "EGENIX": _ident("eGenix"),
    "EPL-1.0": _ident("EPL-1.0"),
    "EPL-2.0": _ident("EPL-2.0"),
    "EUPL-1.0": _ident("EUPL-1.0"),
    "EUPL-1.1": _ident("EUPL-1.1"),
    "EXPAT": _ident("MIT"),
    "FREEIMAGE": _ident("FreeImage"),
    "FTL": _ident("FTL"),
    "GFDL-1.1": _ident("GFDL-1.1"),
    "GFDL-1.1-INVARIANTS": _ident("GFDL-1.1-invariants"),
    "GFDL-1.1-NO-INVARIANTS": _ident("GFDL-1.1-no-invariants"),
    "GFDL-1.2": _ident("GFDL-1.2"),
    "GFDL-1.2-INVARIANTS": _ident("GFDL-1.2-invariants"),
    "GFDL-1.2-NO-INVARIANTS": _ident("GFDL-1.2-no-invariants"),
    "GFDL-1.3": _ident("GFDL-1.3"),
    "GFDL-1.3-INVARIANTS": _ident("GFDL-1.3-invariants"),
    "GFDL-1.3-NO-INVARIANTS": _ident("GFDL-1.3-no-invariants"),
    "GFDL-NIV-1.3": _ident("GFDL-1.3-no-invariants"),
    "GO": _ident("BSD-3-Clause"),
    "GPL-1": _ident("GPL-1.0"),
    "GPL-1.0": _ident("GPL-1.0"),
    "GPL-2": _ident("GPL-2.0"),
    "GPL-2.0": _ident("GPL-2.0"),
    "GPL-2.0-WITH-AUTOCONF-EXCEPTION": _ident("GPL-2.0-with-autoconf-exception"),
    "GPL-2.0-WITH-BISON-EXCEPTION": _ident("GPL-2.0-with-bison-exception"),
    "GPL-2+-WITH-BISON-EXCEPTION": _plus("GPL-2.0-with-bison-exception"),
    "GPL-2.0-WITH-CLASSPATH-EXCEPTION": _ident("GPL-2.0-with-classpath-exception"),
    "GPL-2.0-WITH-FONT-EXCEPTION": _ident("GPL-2.0-with-font-exception"),
    "GPL-2.0-WITH-GCC-EXCEPTION": _ident("GPL-2.0-with-GCC-exception"),
    "GPL-3": _ident("GPL-3.0"),
    "GPL-3.0": _ident("GPL-3.0"),
    "GPL-3.0-WITH-AUTOCONF-EXCEPTION": _ident("GPL-3.0-with-autoconf-exception"),
    "GPL-3.0-WITH-GCC-EXCEPTION": _ident("GPL-3.0-with-GCC-exception"),
    "GPL-3+-WITH-BISON-EXCEPTION": _plus("GPL-2.0-with-bison-exception"),
    "GPLV2+CE": _plus("GPL-2.0-with-classpath-exception"),
    "GUST-FONT": _ident("GUST-Font-License"),
    "HSQLDB": _ident("BSD-3-Clause"),
    "IMAGEMAGICK": _ident("ImageMagick"),
    "IPL-1.0": _ident("IPL-1.0"),
    "ISC": _ident("ISC"),
    "ISCL": _ident("ISC"),
    "JQUERY": _ident("MIT"),
    "LGPL-2": _ident("LGPL-2.0"),
    "LGPL-2.0": _ident("LGPL-2.0"),
    "LGPL-2.1": _ident("LGPL-2.1"),
    "LGPL-3": _ident("LGPL-3.0"),
    "LGPL-3.0": _ident("LGPL-3.0"),
    "LGPLLR": _ident("LGPLLR"),
    "LIBPNG": _ident("Libpng"),
    "LIL-1.0": _ident("Lil-1.0"),
    "LINUX-OPENIB": _ident("Linux-OpenIB"),
    "LPL-1.0": _ident("LPL-1.0"),
    "LPL-1.02": _ident("LPL-1.02"),
    "LPPL-1.3C": _ident("LPPL-1.3c"),
    "MIT": _ident("MIT"),
    "MIT-0": _ident("MIT"),
    "MIT-LIKE": _ident("MIT"),
    "MIT-STYLE": _ident("MIT"),
    "MPL-1": _ident("MPL-1.0"),
    "MPL-1.0": _ident("MPL-1.0"),
    "MPL-1.1": _ident("MPL-1.1"),
    "MPL-2": _ident("MPL-2.0"),
    "MPL-2.0": _ident("MPL-2.0"),
    "MS-PL": _ident("MS-PL"),
    "NCSA": _ident("NCSA"),
    "NPL-1.0": _ident("NPL-1.0"),
    "NPL-1.1": _ident("NPL-1.1"),
    "OFL-1.1": _ident("OFL-1.1"),
    "OPENSSL": _ident("OpenSSL"),
    "OPENVISION": _ident("OpenVision"),
    "OSL-1": _ident("OSL-1.0"),
    "OSL-1.0": _ident("OSL-1.0"),
    "OSL-1.1": _ident("OSL-1.1"),
    "OSL-2": _ident("OSL-2.0"),
    "OSL-2.0": _ident("OSL-2.0"),
    "OSL-2.1": _ident("OSL-2.1"),
    "OSL-3": _ident("OSL-3.0"),
    "OSL-3.0": _ident("OSL-3.0"),
    "PHP-3.0": _ident("PHP-3.0"),
    "PHP-3.01": _ident("PHP-3.01"),
    "PIL": _ident("PIL"),
    "POSTGRESQL": _ident("PostgreSQL"),
    "PYTHON-2": _ident("Python-2.0"),
    "PYTHON-2.0": _ident("Python-2.0"),
    "PYTHON-2.0-COMPLETE": _ident("Python-2.0-complete"),
    "QPL-1": _ident("QPL-1.0"),
    "QPL-1.0": _ident("QPL-1.0"),
    "RUBY": _ident("Ruby"),
    "SGI-B-1.0": _ident("SGI-B-1.0"),
    "SGI-B-1.1": _ident("SGI-B-1.1"),
    "SGI-B-2.0": _ident("SGI-B-2.0"),
    "SISSL": _ident("SISSL"),
    "SISSL-1.2": _ident("SISSL-1.2"),
    "SLEEPYCAT": _ident("Sleepycat"),
    "UNICODE-DFS-2015": _ident("Unicode-DFS-2015"),
    "UNICODE-DFS-2016": _ident("Unicode-DFS-2016"),
    "UNICODE-TOU": _ident("Unicode-TOU"),
    "UNLICENSE": _ident("Unlicense"),
    "UNLICENSED": _ident("Unlicense"),
    "UPL-1": _ident("UPL-1.0"),
    "UPL-1.0": _ident("UPL-1.0"),
    "W3C": _ident("W3C"),
    "W3C-19980720": _ident("W3C-19980720"),
    "W3C-20150513": _ident("W3C-20150513"),
    "W3CL": _ident("W3C"),
    "WTF": _ident("WTFPL"),
    "WTFPL": _ident("WTFPL"),
    "X11": _ident("X11"),
    "XNET": _ident("Xnet"),
    "ZEND-2": _ident("Zend-2.0"),
    "ZEND-2.0": _ident("Zend-2.0"),
    "ZLIB": _ident("Zlib"),
    "ZLIB-ACKNOWLEDGEMENT": _ident("zlib-acknowledgement"),
    "ZOPE-1.1": _ident("ZPL-1.1"),
    "ZOPE-2.0": _ident("ZPL-2.0"),
    "ZOPE-2.1": _ident("ZPL-2.1"),
    "ZPL-1.1": _ident("ZPL-1.1"),
    "ZPL-2.0": _ident("ZPL-2.0"),
    "ZPL-2.1": _ident("ZPL-2.1"),
    # declared long-form names
    "ACADEMIC FREE LICENSE (AFL)": _ident("AFL-2.1"),
    "APACHE SOFTWARE LICENSES": _ident("Apache-2.0"),
    "APACHE SOFTWARE": _ident("Apache-2.0"),
    "APPLE PUBLIC SOURCE": _ident("APSL-1.0"),
    "BSD SOFTWARE": _ident("BSD-2-Clause"),
    "BSD STYLE": _ident("BSD-3-Clause"),
    "COMMON DEVELOPMENT AND DISTRIBUTION": _ident("CDDL-1.0"),
    "CREATIVE COMMONS - BY": _ident("CC-BY-3.0"),
    "CREATIVE COMMONS ATTRIBUTION": _ident("CC-BY-3.0"),
    "CREATIVE COMMONS": _ident("CC-BY-3.0"),
    "ECLIPSE PUBLIC LICENSE (EPL)": _ident("EPL-1.0"),
    "GENERAL PUBLIC LICENSE (GPL)": _plus("GPL-2.0"),
    "GNU FREE DOCUMENTATION LICENSE (FDL)": _plus("GFDL-1.3"),
    "GNU GENERAL PUBLIC LIBRARY": _plus("GPL-3.0"),
    "GNU GENERAL PUBLIC LICENSE (GPL)": _plus("GPL-3.0"),
    "GNU GPL": _ident("GPL-2.0"),
    "GNU LESSER GENERAL PUBLIC LICENSE (LGPL)": _ident("LGPL-2.1"),
    "GNU LESSER GENERAL PUBLIC": _ident("LGPL-2.1"),
    "GNU LESSER PUBLIC": _ident("LGPL-2.1"),
    "GNU LESSER": _ident("LGPL-2.1"),
    "GNU LGPL": _ident("LGPL-2.1"),
    "GNU LIBRARY OR LESSER GENERAL PUBLIC LICENSE (LGPL)": _ident("LGPL-2.1"),
    "GNU PUBLIC": _plus("GPL-2.0"),
    "GPL (WITH DUAL LICENSING OPTION)": _ident("GPL-2.0"),
    "GPLV2 WITH EXCEPTIONS": _ident("GPL-2.0-with-classpath-exception"),
    "INDIVIDUAL BSD": _ident("BSD-3-Clause"),
    "LESSER GENERAL PUBLIC LICENSE (LGPL)": _plus("LGPL-2.1"),
    "LGPL WITH EXCEPTIONS": _ident("LGPL-3.0"),
    "MOZILLA PUBLIC": _ident("MPL-2.0"),
    "ZOPE PUBLIC": _ident("ZPL-2.1"),
    "(NEW) BSD": _ident("BSD-3-Clause"),
    "2-CLAUSE BSD": _ident("BSD-2-Clause"),
    "2-CLAUSE BSDL": _ident("BSD-2-Clause"),
    "3-CLAUSE BDSL": _ident("BSD-3-Clause"),
    "3-CLAUSE BSD": _ident("BSD-3-Clause"),
    "APACHE 2 STYLE": _ident("Apache-2.0"),
    "APACHE LICENSE, ASL-2.0": _ident("Apache-2.0"),
    "APACHE VERSION 2.0, JANUARY 2004": _ident("Apache-2.0"),
    "BERKELEY SOFTWARE DISTRIBUTION (BSD)": _ident("BSD-2-Clause"),
    "BOOST SOFTWARE": _ident("BSL-1.0"),
    "BOUNCY CASTLE": _ident("MIT"),
    "BSD (3-CLAUSE)": _ident("BSD-3-Clause"),
    "BSD 2 CLAUSE": _ident("BSD-2-Clause"),
    "BSD 2-CLAUSE": _ident("BSD-2-Clause"),
    "BSD 3 CLAUSE": _ident("BSD-3-Clause"),
    "BSD 3-CLAUSE NEW": _ident("BSD-3-Clause"),
    "BSD 3-CLAUSE": _ident("BSD-3-Clause"),
    "BSD 4 CLAUSE": _ident("BSD-4-Clause"),
    "BSD 4-CLAUSE": _ident("BSD-4-Clause"),
    "BSD FOUR CLAUSE": _ident("BSD-4-Clause"),
    "BSD NEW": _ident("BSD-3-Clause"),
    "BSD THREE CLAUSE": _ident("BSD-3-Clause"),
    "BSD TWO CLAUSE": _ident("BSD-2-Clause"),
    "BSD-3 CLAUSE": _ident("BSD-3-Clause"),
    "BSD-STYLE + ATTRIBUTION": _ident("BSD-3-Clause-Attribution"),
    "CC0 1.0 UNIVERSAL": _ident("CC0-1.0"),
    "COMMON PUBLIC": _ident("CPL-1.0"),
    "COMMON PUBLIC-1.0": _ident("CPL-1.0"),
    "CREATIVE COMMONS CC0": _ident("CC0-1.0"),
    "CREATIVE COMMONS ZERO": _ident("CC0-1.0"),
    "CREATIVE COMMONS-3.0": _ident("CC-BY-3.0"),
    "ECLIPSE DISTRIBUTION LICENSE (NEW BSD LICENSE)": _ident("BSD-3-Clause"),
    "ECLIPSE DISTRIBUTION-1.0": _ident("BSD-3-Clause"),
    "ECLIPSE PUBLIC LICENSE (EPL)-1.0": _ident("EPL-1.0"),
    "ECLIPSE PUBLIC LICENSE (EPL)-2.0": _ident("EPL-2.0"),
    "ECLIPSE PUBLIC": _ident("EPL-1.0"),
    "ECLIPSE PUBLIC-1.0": _ident("EPL-1.0"),
    "ECLIPSE PUBLIC-2.0": _ident("EPL-2.0"),
    "EUROPEAN UNION PUBLIC-1.0": _ident("EUPL-1.0"),
    "EUROPEAN UNION PUBLIC-1.1": _ident("EUPL-1.1"),
    "EXPAT (MIT/X11)": _ident("MIT"),
    "MIT (MIT)": _ident("MIT"),
    "MIT / HTTP://OPENSOURCE.ORG/LICENSES/MIT": _ident("MIT"),
    "MIT-0 (HTTPS://SPDX.ORG/LICENSES/MIT-0)": _ident("MIT"),
    "THREE-CLAUSE BSD-STYLE": _ident("BSD-3-Clause"),
    "TWO-CLAUSE BSD-STYLE": _ident("BSD-2-Clause"),
    "UNIVERSAL PERMISSIVE LICENSE (UPL)": _ident("UPL-1.0"),
    "UNIVERSAL PERMISSIVE-1.0": _ident("UPL-1.0"),
    "UNLICENSE (UNLICENSE)": _ident("Unlicense"),
    "W3C SOFTWARE": _ident("W3C"),
    "ZLIB / LIBPNG": _ident("zlib-acknowledgement"),
    "ZLIB/LIBPNG": _ident("zlib-acknowledgement"),
    "['MIT']": _ident("MIT"),
    # remaining declared-name rows (generated to match the
    # reference table 1:1; see normalize.go:14-569)
    'FACEBOOK-2-CLAUSE': _ident('Facebook-2-Clause'),
    'FACEBOOK-3-CLAUSE': _ident('Facebook-3-Clause'),
    'FACEBOOK-EXAMPLES': _ident('Facebook-Examples'),
    'LPGL, SEE LICENSE FILE.': _plus('LGPL-3.0'),
    'ACADEMIC FREE LICENSE (AFL-2.1': _ident('AFL-2.1'),
    'AFFERO GENERAL PUBLIC LICENSE (AGPL-3': _ident('AGPL-3.0'),
    'APACHE LICENSE, VERSION 2.0 (HTTP://WWW.APACHE.ORG/LICENSES/LICENSE-2.0': _ident('Apache-2.0'),
    'APACHE PUBLIC-1.1': _ident('Apache-1.1'),
    'APACHE PUBLIC-2': _ident('Apache-2.0'),
    'APACHE PUBLIC-2.0': _ident('Apache-2.0'),
    'APACHE SOFTWARE LICENSE (APACHE-2': _ident('Apache-2.0'),
    'APACHE SOFTWARE LICENSE (APACHE-2.0': _ident('Apache-2.0'),
    'APACHE SOFTWARE-1.1': _ident('Apache-1.1'),
    'APACHE SOFTWARE-2': _ident('Apache-2.0'),
    'APACHE SOFTWARE-2.0': _ident('Apache-2.0'),
    'APACHE-2.0 */ &#39; &QUOT; &#X3D;END --': _ident('Apache-2.0'),
    'BOOST SOFTWARE LICENSE 1.0 (BSL-1.0': _ident('BSL-1.0'),
    'BSD - SEE NDG/HTTPSCLIENT/LICENSE FILE FOR DETAILS': _ident('BSD-3-Clause'),
    'BSD 3-CLAUSE "NEW" OR "REVISED" LICENSE (BSD-3-CLAUSE)': _ident('BSD-3-Clause'),
    'BSD LICENSE FOR HSQL': _ident('BSD-3-Clause'),
    'CC BY-NC-SA-2.0': _ident('CC-BY-NC-SA-2.0'),
    'CC BY-NC-SA-2.5': _ident('CC-BY-NC-SA-2.5'),
    'CC BY-NC-SA-3.0': _ident('CC-BY-NC-SA-3.0'),
    'CC BY-NC-SA-4.0': _ident('CC-BY-NC-SA-4.0'),
    'CC BY-SA-2.0': _ident('CC-BY-SA-2.0'),
    'CC BY-SA-2.5': _ident('CC-BY-SA-2.5'),
    'CC BY-SA-3.0': _ident('CC-BY-SA-3.0'),
    'CC BY-SA-4.0': _ident('CC-BY-SA-4.0'),
    'CC0 1.0 UNIVERSAL (CC0 1.0) PUBLIC DOMAIN DEDICATION': _ident('CC0-1.0'),
    'COMMON DEVELOPMENT AND DISTRIBUTION LICENSE (CDDL)-1.0': _ident('CDDL-1.0'),
    'COMMON DEVELOPMENT AND DISTRIBUTION LICENSE (CDDL)-1.1': _ident('CDDL-1.1'),
    'COMMON DEVELOPMENT AND DISTRIBUTION LICENSE 1.0 (CDDL-1.0': _ident('CDDL-1.0'),
    'COMMON DEVELOPMENT AND DISTRIBUTION LICENSE 1.1 (CDDL-1.1': _ident('CDDL-1.1'),
    'CREATIVE COMMONS - ATTRIBUTION 4.0 INTERNATIONAL': _ident('CC-BY-4.0'),
    'CREATIVE COMMONS 3.0 BY-SA': _ident('CC-BY-SA-3.0'),
    'CREATIVE COMMONS ATTRIBUTION 3.0 UNPORTED (CC BY-3.0': _ident('CC-BY-3.0'),
    'CREATIVE COMMONS ATTRIBUTION 4.0 INTERNATIONAL (CC BY-4.0': _ident('CC-BY-4.0'),
    'CREATIVE COMMONS ATTRIBUTION 4.0 INTERNATIONAL PUBLIC': _ident('CC-BY-4.0'),
    'CREATIVE COMMONS ATTRIBUTION-1.0': _ident('CC-BY-1.0'),
    'CREATIVE COMMONS ATTRIBUTION-2.5': _ident('CC-BY-2.5'),
    'CREATIVE COMMONS ATTRIBUTION-3.0': _ident('CC-BY-3.0'),
    'CREATIVE COMMONS ATTRIBUTION-4.0': _ident('CC-BY-4.0'),
    'CREATIVE COMMONS ATTRIBUTION-NONCOMMERCIAL 4.0 INTERNATIONAL': _ident('CC-BY-NC-4.0'),
    'CREATIVE COMMONS ATTRIBUTION-NONCOMMERCIAL-NODERIVATIVES 4.0 INTERNATIONAL': _ident('CC-BY-NC-ND-4.0'),
    'CREATIVE COMMONS ATTRIBUTION-NONCOMMERCIAL-SHAREALIKE 3.0 UNPORTED (CC BY-NC-SA-3.0': _ident('CC-BY-NC-SA-3.0'),
    'CREATIVE COMMONS ATTRIBUTION-NONCOMMERCIAL-SHAREALIKE 4.0 INTERNATIONAL PUBLIC': _ident('CC-BY-NC-SA-4.0'),
    'CREATIVE COMMONS GNU LGPL-2.1': _ident('LGPL-2.1'),
    'CREATIVE COMMONS LICENSE ATTRIBUTION-NODERIVS 3.0 UNPORTED': _ident('CC-BY-NC-ND-3.0'),
    'CREATIVE COMMONS LICENSE ATTRIBUTION-NONCOMMERCIAL-SHAREALIKE 3.0 UNPORTED': _ident('CC-BY-NC-SA-3.0'),
    'ECLIPSE DISTRIBUTION LICENSE (EDL)-1.0': _ident('BSD-3-Clause'),
    'ECLIPSE PUBLIC LICENSE 1.0 (EPL-1.0': _ident('EPL-1.0'),
    'ECLIPSE PUBLIC LICENSE 2.0 (EPL-2.0': _ident('EPL-2.0'),
    'ECLIPSE PUBLISH-1.0': _ident('EPL-1.0'),
    'EPL (ECLIPSE PUBLIC LICENSE)-1.0': _ident('EPL-1.0'),
    'EU PUBLIC LICENSE 1.0 (EUPL-1.0': _ident('EUPL-1.0'),
    'EU PUBLIC LICENSE 1.1 (EUPL-1.1': _ident('EUPL-1.1'),
    'EUROPEAN UNION PUBLIC LICENSE (EUPL-1.0': _ident('EUPL-1.0'),
    'EUROPEAN UNION PUBLIC LICENSE (EUPL-1.1': _ident('EUPL-1.1'),
    'EUROPEAN UNION PUBLIC LICENSE 1.0 (EUPL-1.0': _ident('EUPL-1.0'),
    'EUROPEAN UNION PUBLIC LICENSE 1.1 (EUPL-1.1': _ident('EUPL-1.1'),
    'GENERAL PUBLIC LICENSE 2.0 (GPL)': _ident('GPL-2.0'),
    'GNU AFFERO GENERAL PUBLIC LICENSE V3 (AGPL-3': _ident('AGPL-3.0'),
    'GNU AFFERO GENERAL PUBLIC LICENSE V3 (AGPL-3.0': _ident('AGPL-3.0'),
    'GNU AFFERO GENERAL PUBLIC LICENSE V3 OR LATER (AGPL3+)': _plus('AGPL-3.0'),
    'GNU AFFERO GENERAL PUBLIC LICENSE V3 OR LATER (AGPLV3+)': _plus('AGPL-3.0'),
    'GNU AFFERO GENERAL PUBLIC-3': _ident('AGPL-3.0'),
    'GNU FREE DOCUMENTATION LICENSE (GFDL-1.3': _ident('GFDL-1.3'),
    'GNU GENERAL LESSER PUBLIC LICENSE (LGPL)-2.1': _ident('LGPL-2.1'),
    'GNU GENERAL LESSER PUBLIC LICENSE (LGPL)-3.0': _ident('LGPL-3.0'),
    'GNU GENERAL PUBLIC LICENSE (GPL), VERSION 2, WITH CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'GNU GENERAL PUBLIC LICENSE (GPL), VERSION 2, WITH THE CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'GNU GENERAL PUBLIC LICENSE (GPL)-2': _ident('GPL-2.0'),
    'GNU GENERAL PUBLIC LICENSE (GPL)-3': _ident('GPL-3.0'),
    'GNU GENERAL PUBLIC LICENSE V2 (GPL-2': _ident('GPL-2.0'),
    'GNU GENERAL PUBLIC LICENSE V2 OR LATER (GPLV2+)': _plus('GPL-2.0'),
    'GNU GENERAL PUBLIC LICENSE V2.0 ONLY, WITH CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'GNU GENERAL PUBLIC LICENSE V3 (GPL-3': _ident('GPL-3.0'),
    'GNU GENERAL PUBLIC LICENSE V3 OR LATER (GPLV3+)': _plus('GPL-3.0'),
    'GNU GENERAL PUBLIC LICENSE VERSION 2 (GPL-2': _ident('GPL-2.0'),
    'GNU GENERAL PUBLIC LICENSE VERSION 2, JUNE 1991': _ident('GPL-2.0'),
    'GNU GENERAL PUBLIC LICENSE VERSION 3 (GPL-3': _ident('GPL-3.0'),
    'GNU GENERAL PUBLIC LICENSE, VERSION 2 (GPL2), WITH THE CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'GNU GENERAL PUBLIC LICENSE, VERSION 2 WITH THE CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'GNU GENERAL PUBLIC LICENSE, VERSION 2 WITH THE GNU CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'GNU GENERAL PUBLIC LICENSE, VERSION 2, WITH THE CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'GNU GENERAL PUBLIC-2': _ident('GPL-2.0'),
    'GNU GENERAL PUBLIC-3': _ident('GPL-3.0'),
    'GNU GPL-2': _ident('GPL-2.0'),
    'GNU GPL-3': _ident('GPL-3.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE (LGPL)-2': _ident('LGPL-2.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE (LGPL)-2.0': _ident('LGPL-2.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE (LGPL)-2.1': _ident('LGPL-2.1'),
    'GNU LESSER GENERAL PUBLIC LICENSE (LGPL)-3': _ident('LGPL-3.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE (LGPL)-3.0': _ident('LGPL-3.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE (LGPL-2': _ident('LGPL-2.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE (LGPL-2.0': _ident('LGPL-2.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE (LGPL-2.1': _ident('LGPL-2.1'),
    'GNU LESSER GENERAL PUBLIC LICENSE (LGPL-3': _ident('LGPL-3.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE (LGPL-3.0': _ident('LGPL-3.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE V2 (LGPL-2': _ident('LGPL-2.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE V2 OR LATER (LGPLV2+)': _plus('LGPL-2.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE V3 (LGPL-3': _ident('LGPL-3.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE V3 OR LATER (LGPLV3+)': _plus('LGPL-3.0'),
    'GNU LESSER GENERAL PUBLIC LICENSE VERSION 2.1 (LGPL-2.1': _ident('LGPL-2.1'),
    'GNU LESSER GENERAL PUBLIC LICENSE VERSION 2.1, FEBRUARY 1999': _ident('LGPL-2.1'),
    'GNU LESSER GENERAL PUBLIC LICENSE, VERSION 2.1, FEBRUARY 1999': _ident('LGPL-2.1'),
    'GNU LESSER GENERAL PUBLIC-2': _ident('LGPL-2.0'),
    'GNU LESSER GENERAL PUBLIC-2.0': _ident('LGPL-2.0'),
    'GNU LESSER GENERAL PUBLIC-2.1': _ident('LGPL-2.1'),
    'GNU LESSER GENERAL PUBLIC-3': _ident('LGPL-3.0'),
    'GNU LESSER GENERAL PUBLIC-3.0': _ident('LGPL-3.0'),
    'GNU LGP (GNU GENERAL PUBLIC LICENSE)-2': _ident('LGPL-2.0'),
    'GNU LGPL (GNU LESSER GENERAL PUBLIC LICENSE)-2.1': _ident('LGPL-2.1'),
    'GNU LGPL-2': _ident('LGPL-2.0'),
    'GNU LGPL-2.0': _ident('LGPL-2.0'),
    'GNU LGPL-2.1': _ident('LGPL-2.1'),
    'GNU LGPL-3': _ident('LGPL-3.0'),
    'GNU LGPL-3.0': _ident('LGPL-3.0'),
    'GNU LIBRARY GENERAL PUBLIC-2.0': _ident('LGPL-2.0'),
    'GNU LIBRARY GENERAL PUBLIC-2.1': _ident('LGPL-2.1'),
    'GNU LIBRARY OR LESSER GENERAL PUBLIC LICENSE VERSION 2.0 (LGPL-2': _ident('LGPL-2.0'),
    'GNU LIBRARY OR LESSER GENERAL PUBLIC LICENSE VERSION 3.0 (LGPL-3': _ident('LGPL-3.0'),
    'GPL (≥ 3)': _plus('GPL-3.0'),
    'GPL 2 WITH CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'GPL V2 WITH CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'GPL-2+ WITH AUTOCONF EXCEPTION': _plus('GPL-2.0-with-autoconf-exception'),
    'GPL-3+ WITH AUTOCONF EXCEPTION': _plus('GPL-3.0-with-autoconf-exception'),
    'GPL2 W/ CPE': _ident('GPL-2.0-with-classpath-exception'),
    'GPLV2 LICENSE, INCLUDES THE CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'GPLV2 WITH CLASSPATH EXCEPTION': _ident('GPL-2.0-with-classpath-exception'),
    'HSQLDB LICENSE, A BSD OPEN SOURCE': _ident('BSD-3-Clause'),
    'HTTP://ANT-CONTRIB.SOURCEFORGE.NET/TASKS/LICENSE.TXT': _ident('Apache-1.1'),
    'HTTP://ASM.OW2.ORG/LICENSE.HTML': _ident('BSD-3-Clause'),
    'HTTP://CREATIVECOMMONS.ORG/PUBLICDOMAIN/ZERO/1.0/LEGALCODE': _ident('CC0-1.0'),
    'HTTP://EN.WIKIPEDIA.ORG/WIKI/ZLIB_LICENSE': _ident('Zlib'),
    'HTTP://JSON.CODEPLEX.COM/LICENSE': _ident('MIT'),
    'HTTP://POLYMER.GITHUB.IO/LICENSE.TXT': _ident('BSD-3-Clause'),
    'HTTP://WWW.APACHE.ORG/LICENSES/LICENSE-2.0': _ident('Apache-2.0'),
    'HTTP://WWW.APACHE.ORG/LICENSES/LICENSE-2.0.HTML': _ident('Apache-2.0'),
    'HTTP://WWW.APACHE.ORG/LICENSES/LICENSE-2.0.TXT': _ident('Apache-2.0'),
    'HTTP://WWW.GNU.ORG/COPYLEFT/LESSER.HTML': _ident('LGPL-3.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-NC-ND/1.0': _ident('CC-BY-NC-ND-1.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-NC-ND/2.0': _ident('CC-BY-NC-ND-2.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-NC-ND/2.5': _ident('CC-BY-NC-ND-2.5'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-NC-ND/3.0': _ident('CC-BY-NC-ND-3.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-NC-ND/4.0': _ident('CC-BY-NC-ND-4.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-NC-SA/1.0': _ident('CC-BY-NC-SA-1.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-NC-SA/2.0': _ident('CC-BY-NC-SA-2.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-NC-SA/2.5': _ident('CC-BY-NC-SA-2.5'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-NC-SA/3.0': _ident('CC-BY-NC-SA-3.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-NC-SA/4.0': _ident('CC-BY-NC-SA-4.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-ND/1.0': _ident('CC-BY-ND-1.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-ND/2.0': _ident('CC-BY-ND-2.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-ND/2.5': _ident('CC-BY-ND-2.5'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-ND/3.0': _ident('CC-BY-ND-3.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-ND/4.0': _ident('CC-BY-ND-4.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-SA/1.0': _ident('CC-BY-SA-1.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-SA/2.0': _ident('CC-BY-SA-2.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-SA/2.5': _ident('CC-BY-SA-2.5'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-SA/3.0': _ident('CC-BY-SA-3.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY-SA/4.0': _ident('CC-BY-SA-4.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY/1.0': _ident('CC-BY-1.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY/2.0': _ident('CC-BY-2.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY/2.5': _ident('CC-BY-2.5'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY/3.0': _ident('CC-BY-3.0'),
    'HTTPS://CREATIVECOMMONS.ORG/LICENSES/BY/4.0': _ident('CC-BY-4.0'),
    'HTTPS://CREATIVECOMMONS.ORG/PUBLICDOMAIN/ZERO/1.0/': _ident('CC0-1.0'),
    'HTTPS://GITHUB.COM/DOTNET/CORE-SETUP/BLOB/MASTER/LICENSE.TXT': _ident('MIT'),
    'HTTPS://GITHUB.COM/DOTNET/COREFX/BLOB/MASTER/LICENSE.TXT': _ident('MIT'),
    'HTTPS://RAW.GITHUB.COM/RDFLIB/RDFLIB/MASTER/LICENSE': _ident('BSD-3-Clause'),
    'HTTPS://RAW.GITHUBUSERCONTENT.COM/ASPNET/ASPNETCORE/2.0.0/LICENSE.TXT': _ident('Apache-2.0'),
    'HTTPS://RAW.GITHUBUSERCONTENT.COM/ASPNET/HOME/2.0.0/LICENSE.TXT': _ident('Apache-2.0'),
    'HTTPS://RAW.GITHUBUSERCONTENT.COM/NUGET/NUGET.CLIENT/DEV/LICENSE.TXT': _ident('Apache-2.0'),
    'HTTPS://WWW.APACHE.ORG/LICENSES/LICENSE-2.0': _ident('Apache-2.0'),
    'HTTPS://WWW.ECLIPSE.ORG/LEGAL/EPL-V10.HTML': _ident('EPL-1.0'),
    'HTTPS://WWW.ECLIPSE.ORG/LEGAL/EPL-V20.HTML': _ident('EPL-2.0'),
    'IBM PUBLIC': _ident('IPL-1.0'),
    'ISC LICENSE (ISCL)': _ident('ISC'),
    'JYTHON SOFTWARE': _ident('Python-2.0'),
    'KIRKK.COM BSD': _ident('BSD-3-Clause'),
    'LESSER GENERAL PUBLIC LICENSE, VERSION 3 OR GREATER': _plus('LGPL-3.0'),
    'LICENSE AGREEMENT FOR OPEN SOURCE COMPUTER VISION LIBRARY (3-CLAUSE BSD LICENSE)': _ident('BSD-3-Clause'),
    'MIT (HTTP://MOOTOOLS.NET/LICENSE.TXT)': _ident('MIT'),
    'MIT / HTTP://REM.MIT-LICENSE.ORG': _ident('MIT'),
    'MIT LICENSE (HTTP://OPENSOURCE.ORG/LICENSES/MIT)': _ident('MIT'),
    'MIT LICENSE (MIT)': _ident('MIT'),
    'MIT LICENSE(MIT)': _ident('MIT'),
    'MIT LICENSED. HTTP://WWW.OPENSOURCE.ORG/LICENSES/MIT-LICENSE.PHP': _ident('MIT'),
    'MIT/EXPAT': _ident('MIT'),
    'MOCKRUNNER LICENSE, BASED ON APACHE SOFTWARE-1.1': _ident('Apache-1.1'),
    'MODIFIED BSD': _ident('BSD-3-Clause'),
    'MOZILLA PUBLIC LICENSE 1.0 (MPL)': _ident('MPL-1.0'),
    'MOZILLA PUBLIC LICENSE 1.1 (MPL-1.1': _ident('MPL-1.1'),
    'MOZILLA PUBLIC LICENSE 2.0 (MPL-2.0': _ident('MPL-2.0'),
    'MOZILLA PUBLIC-1.0': _ident('MPL-1.0'),
    'MOZILLA PUBLIC-1.1': _ident('MPL-1.1'),
    'MOZILLA PUBLIC-2.0': _ident('MPL-2.0'),
    'NCSA OPEN SOURCE': _ident('NCSA'),
    'NETSCAPE PUBLIC LICENSE (NPL)': _ident('NPL-1.0'),
    'NETSCAPE PUBLIC': _ident('NPL-1.0'),
    'NEW BSD': _ident('BSD-3-Clause'),
    'OPEN SOFTWARE LICENSE 3.0 (OSL-3.0': _ident('OSL-3.0'),
    'OPEN SOFTWARE-3.0': _ident('OSL-3.0'),
    'PERL ARTISTIC-2': _ident('Artistic-1.0-Perl'),
    'PUBLIC DOMAIN (CC0-1.0)': _ident('CC0-1.0'),
    'PUBLIC DOMAIN, PER CREATIVE COMMONS CC0': _ident('CC0-1.0'),
    'QT PUBLIC LICENSE (QPL)': _ident('QPL-1.0'),
    'QT PUBLIC': _ident('QPL-1.0'),
    'REVISED BSD': _ident('BSD-3-Clause'),
    "RUBY'S": _ident('Ruby'),
    'SEQUENCE LIBRARY LICENSE (BSD-LIKE)': _ident('BSD-3-Clause'),
    'SIL OPEN FONT LICENSE 1.1 (OFL-1.1': _ident('OFL-1.1'),
    'SIL OPEN FONT-1.1': _ident('OFL-1.1'),
    'SIMPLIFIED BSD LISCENCE': _ident('BSD-2-Clause'),
    'SIMPLIFIED BSD': _ident('BSD-2-Clause'),
    'SUN INDUSTRY STANDARDS SOURCE LICENSE (SISSL)': _ident('SISSL'),
    "PUBLIC DOMAIN": _ident("Unlicense"),
}

# reference normalize.go:578-583 — python classifiers we cannot split on
# and/or; keyed by the first word after the separator.
_PYTHON_EXCEPTIONS = {
    "lesser": "GNU Library or Lesser General Public License (LGPL)",
    "distribution": "Common Development and Distribution License 1.0 (CDDL-1.0)",
    "disclaimer": "Historical Permission Notice and Disclaimer (HPND)",
}

_SPLIT_RE = re.compile(r"(?:,?[_ ]+(?:or|and)[_ ]+)|(?:,[ ]*)")

_TEXT_KEYWORDS = (
    "http://", "https://", "(c)", "as-is", ";", "hereby",
    "permission to use", "permission is", "use in source",
    "use, copy, modify", "using",
)

# "X LICENSE, VERSION 2.0" / "X V2" / "X-V.2" → "X-2.0" style folding
_VERSION_PART = (
    r"([A-UW-Z)])( LICENSE)?\s*[,(-]?\s*"
    r"(V|V\.|VER|VER\.|VERSION|VERSION-|-)?\s*([1-9](\.\d)*)[)]?"
)
_VERSION_SUFFIX_RE = re.compile(_VERSION_PART + r"$")
_VERSION_ANY_RE = re.compile(_VERSION_PART, re.IGNORECASE)

_PLUS_SUFFIXES = ("+", "-OR-LATER", " OR LATER")
_ONLY_SUFFIXES = ("-ONLY", " ONLY")


def _standardize(name: str) -> SimpleExpr:
    """Uppercase, strip affixes, fold version suffix, extract plus
    (reference normalize.go:641-675)."""
    name = " ".join(name.split()).upper()
    if name.startswith("HTTP"):
        return SimpleExpr(name)
    name = name.replace("LICENCE", "LICENSE")
    name = name.removeprefix("THE ")
    for suf in (" LICENSE", " LICENSED", "-LICENSE", "-LICENSED"):
        name = name.removesuffix(suf)
    if name != "UNLICENSE":
        name = name.removesuffix("LICENSE")
    if name != "UNLICENSED":
        name = name.removesuffix("LICENSED")
    has_plus = False
    for suf in _PLUS_SUFFIXES:
        if name.endswith(suf):
            name = name.removesuffix(suf)
            has_plus = True
    for suf in _ONLY_SUFFIXES:
        name = name.removesuffix(suf)
    name = _VERSION_SUFFIX_RE.sub(r"\1-\4", name)
    return SimpleExpr(name, has_plus)


def _normalize_simple(e: SimpleExpr):
    name = e.license.strip()
    std = _standardize(name)
    found = _MAPPING.get(std.license)
    if found:
        return SimpleExpr(found[0], e.has_plus or found[1] or std.has_plus)
    return SimpleExpr(name, e.has_plus)


def normalize_license(expr):
    """Normalize a parsed expression node (reference normalize.go:682-691)."""
    if isinstance(expr, SimpleExpr):
        return _normalize_simple(expr)
    if isinstance(expr, CompoundExpr) and expr.op == "WITH":
        std = _standardize(str(expr))
        found = _MAPPING.get(std.license)
        if found:
            return SimpleExpr(found[0], found[1] or std.has_plus)
    return expr


def normalize(name: str) -> str:
    """Normalize a single free-form license name to its SPDX id."""
    return str(normalize_license(SimpleExpr(name)))


def normalize_spdx_expression(text: str) -> str:
    """Parse a full SPDX expression and normalize every leaf; returns
    the input unchanged when it does not parse."""
    try:
        expr = parse(text)
    except ValueError:
        return normalize(text)
    return str(normalize_expression(expr, normalize_license))


def is_license_text(s: str) -> bool:
    low = s.lower()
    return any(k in low for k in _TEXT_KEYWORDS)


def trim_license_text(text: str) -> str:
    words = text.split(" ")
    return " ".join(words[:3]) + "..."


def split_licenses(s: str) -> list[str]:
    """Split a declared-license string on ','/'or'/'and' separators with
    the version/later/python-classifier re-join rules
    (reference normalize.go:712-746)."""
    if not s:
        return []
    if is_license_text(s.lower()):
        return [LICENSE_TEXT_PREFIX + s]
    licenses: list[str] = []
    for maybe in _SPLIT_RE.split(s):
        if maybe is None:
            continue
        first = maybe.lower().split(" ", 1)[0]
        if licenses:
            if first in ("ver", "version"):
                licenses[-1] += ", " + maybe
                continue
            if first == "later":
                licenses[-1] += " or " + maybe
                continue
            if first in _PYTHON_EXCEPTIONS:
                full = _PYTHON_EXCEPTIONS[first]
                if full in (licenses[-1] + " or " + maybe,
                            licenses[-1] + " and " + maybe):
                    licenses[-1] = full
                continue
        licenses.append(maybe)
    return licenses


def lax_split_licenses(s: str) -> list[str]:
    """Space-separated split for messy fields like dpkg copyright
    (reference normalize.go:750-767)."""
    if not s:
        return []
    s = _VERSION_ANY_RE.sub(lambda m: f"{m.group(1)}-{m.group(4)}", s.upper())
    out = []
    for word in s.split():
        word = word.strip("()")
        if not word or word in ("AND", "OR"):
            continue
        out.append(normalize(word))
    return out
