"""SPDX license-expression model: lexer, recursive-descent parser, and
precedence-aware stringification.

Behavioral parity with reference pkg/licensing/expression/
(lexer.go:14-119, parser.go.y grammar, expression.go:27-89,
types.go:24-75): expressions are IDENT trees joined by OR < AND < WITH
(loosest to tightest binding), idents may carry a trailing '+', GNU
family licenses render as '-only' / '-or-later' instead of the bare
id / '+'.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = [
    "LicenseParseError", "SimpleExpr", "CompoundExpr", "parse",
    "normalize_expression", "normalize_for_spdx", "GNU_LICENSES",
]


class LicenseParseError(ValueError):
    pass


# reference expression/category.go:170-188 — GNU ids that take
# -only/-or-later suffixes per the SPDX spec.
GNU_LICENSES = frozenset({
    "AGPL-1.0", "AGPL-3.0",
    "GFDL-1.1-invariants", "GFDL-1.1-no-invariants", "GFDL-1.1",
    "GFDL-1.2-invariants", "GFDL-1.2-no-invariants", "GFDL-1.2",
    "GFDL-1.3-invariants", "GFDL-1.3-no-invariants", "GFDL-1.3",
    "GPL-1.0", "GPL-2.0", "GPL-3.0",
    "LGPL-2.0", "LGPL-2.1", "LGPL-3.0",
})

# binding strength; parenthesize a child whose op binds looser than its
# parent (reference expression.go:62-75 compares token ints the same way)
_PRECEDENCE = {"OR": 1, "AND": 2, "WITH": 3}


@dataclass(frozen=True)
class SimpleExpr:
    license: str
    has_plus: bool = False

    def __str__(self) -> str:
        if self.license in GNU_LICENSES:
            return self.license + ("-or-later" if self.has_plus else "-only")
        return self.license + ("+" if self.has_plus else "")


@dataclass(frozen=True)
class CompoundExpr:
    left: object
    op: str  # "AND" | "OR" | "WITH"
    right: object

    def __str__(self) -> str:
        def side(child) -> str:
            s = str(child)
            if (isinstance(child, CompoundExpr)
                    and _PRECEDENCE[self.op] > _PRECEDENCE[child.op]):
                return f"({s})"
            return s
        return f"{side(self.left)} {self.op} {side(self.right)}"


def _tokenize(text: str) -> list[str]:
    """Split into idents, operators, parens; a '+' glued to the end of a
    word stays attached to it (reference lexer.go:25-70)."""
    tokens: list[str] = []
    word: list[str] = []

    def flush():
        if word:
            tokens.append("".join(word))
            word.clear()

    for ch in text:
        if ch.isspace():
            flush()
        elif ch in "()":
            flush()
            tokens.append(ch)
        else:
            word.append(ch)
    flush()
    return tokens


_OPS = {"and": "AND", "or": "OR", "with": "WITH"}


class _Parser:
    def __init__(self, tokens: list[str]):
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> str | None:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> str:
        tok = self.peek()
        if tok is None:
            raise LicenseParseError("unexpected end of license expression")
        self.pos += 1
        return tok

    def parse_or(self):
        left = self.parse_and()
        while (tok := self.peek()) and _OPS.get(tok.lower()) == "OR":
            self.next()
            left = CompoundExpr(left, "OR", self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_with()
        while (tok := self.peek()) and _OPS.get(tok.lower()) == "AND":
            self.next()
            left = CompoundExpr(left, "AND", self.parse_with())
        return left

    def parse_with(self):
        left = self.parse_primary()
        if (tok := self.peek()) and _OPS.get(tok.lower()) == "WITH":
            self.next()
            left = CompoundExpr(left, "WITH", self.parse_primary())
        return left

    def parse_primary(self):
        tok = self.next()
        if tok == "(":
            inner = self.parse_or()
            if self.next() != ")":
                raise LicenseParseError("unbalanced parenthesis")
            return inner
        if tok == ")" or _OPS.get(tok.lower()):
            raise LicenseParseError(f"unexpected token {tok!r}")
        if tok.endswith("+") and len(tok) > 1:
            return SimpleExpr(tok[:-1], has_plus=True)
        return SimpleExpr(tok)


def parse(text: str):
    tokens = _tokenize(text)
    if not tokens:
        raise LicenseParseError("empty license expression")
    p = _Parser(tokens)
    expr = p.parse_or()
    if p.peek() is not None:
        # bare idents side by side ("MIT Apache-2.0") are not valid SPDX
        raise LicenseParseError(f"trailing tokens in {text!r}")
    return expr


def normalize_expression(expr, fn):
    """Recursively apply a SimpleExpr→Expression normalization fn
    (reference expression.go:39-55)."""
    normalized = fn(expr)
    if isinstance(normalized, CompoundExpr):
        return CompoundExpr(
            normalize_expression(normalized.left, fn),
            normalized.op.upper(),
            normalize_expression(normalized.right, fn),
        )
    return normalized


def normalize_for_spdx(expr):
    """Replace characters invalid in an SPDX idstring with '-'
    (reference expression.go:58-84)."""
    if not isinstance(expr, SimpleExpr):
        return expr
    out = []
    for c in expr.license:
        if (c.isascii() and c.isalnum()) or c in "-.:":
            out.append(c)
        else:
            out.append("-")
    return SimpleExpr("".join(out), expr.has_plus)
