"""Embedded SPDX license-text corpus for the full-text classifier.

Full license bodies compiled into the trigram matcher so --license-full
classifies real LICENSE files (reference pkg/licensing/classifier.go:36-87
ships google/licenseclassifier's SPDX corpus; this is the equivalent
subset covering the licenses that dominate real artifacts). License
texts are public standard documents; long bodies were taken from the
system license collection (/usr/share/common-licenses) and installed
package metadata, with per-project copyright lines stripped so the
template generalizes.
"""

# flake8: noqa: E501

TEXTS: dict[str, str] = {
    "Apache-2.0": """Apache License
                           Version 2.0, January 2004
                        http://www.apache.org/licenses/

   TERMS AND CONDITIONS FOR USE, REPRODUCTION, AND DISTRIBUTION

   1. Definitions.

      "License" shall mean the terms and conditions for use, reproduction,
      and distribution as defined by Sections 1 through 9 of this document.

      "Licensor" shall mean the copyright owner or entity authorized by
      the copyright owner that is granting the License.

      "Legal Entity" shall mean the union of the acting entity and all
      other entities that control, are controlled by, or are under common
      control with that entity. For the purposes of this definition,
      "control" means (i) the power, direct or indirect, to cause the
      direction or management of such entity, whether by contract or
      otherwise, or (ii) ownership of fifty percent (50%) or more of the
      outstanding shares, or (iii) beneficial ownership of such entity.

      "You" (or "Your") shall mean an individual or Legal Entity
      exercising permissions granted by this License.

      "Source" form shall mean the preferred form for making modifications,
      including but not limited to software source code, documentation
      source, and configuration files.

      "Object" form shall mean any form resulting from mechanical
      transformation or translation of a Source form, including but
      not limited to compiled object code, generated documentation,
      and conversions to other media types.

      "Work" shall mean the work of authorship, whether in Source or
      Object form, made available under the License, as indicated by a
      copyright notice that is included in or attached to the work
      (an example is provided in the Appendix below).

      "Derivative Works" shall mean any work, whether in Source or Object
      form, that is based on (or derived from) the Work and for which the
      editorial revisions, annotations, elaborations, or other modifications
      represent, as a whole, an original work of authorship. For the purposes
      of this License, Derivative Works shall not include works that remain
      separable from, or merely link (or bind by name) to the interfaces of,
      the Work and Derivative Works thereof.

      "Contribution" shall mean any work of authorship, including
      the original version of the Work and any modifications or additions
      to that Work or Derivative Works thereof, that is intentionally
      submitted to Licensor for inclusion in the Work by the copyright owner
      or by an individual or Legal Entity authorized to submit on behalf of
      the copyright owner. For the purposes of this definition, "submitted"
      means any form of electronic, verbal, or written communication sent
      to the Licensor or its representatives, including but not limited to
      communication on electronic mailing lists, source code control systems,
      and issue tracking systems that are managed by, or on behalf of, the
      Licensor for the purpose of discussing and improving the Work, but
      excluding communication that is conspicuously marked or otherwise
      designated in writing by the copyright owner as "Not a Contribution."

      "Contributor" shall mean Licensor and any individual or Legal Entity
      on behalf of whom a Contribution has been received by Licensor and
      subsequently incorporated within the Work.

   2. Grant of Copyright License. Subject to the terms and conditions of
      this License, each Contributor hereby grants to You a perpetual,
      worldwide, non-exclusive, no-charge, royalty-free, irrevocable
      copyright license to reproduce, prepare Derivative Works of,
      publicly display, publicly perform, sublicense, and distribute the
      Work and such Derivative Works in Source or Object form.

   3. Grant of Patent License. Subject to the terms and conditions of
      this License, each Contributor hereby grants to You a perpetual,
      worldwide, non-exclusive, no-charge, royalty-free, irrevocable
      (except as stated in this section) patent license to make, have made,
      use, offer to sell, sell, import, and otherwise transfer the Work,
      where such license applies only to those patent claims licensable
      by such Contributor that are necessarily infringed by their
      Contribution(s) alone or by combination of their Contribution(s)
      with the Work to which such Contribution(s) was submitted. If You
      institute patent litigation against any entity (including a
      cross-claim or counterclaim in a lawsuit) alleging that the Work
      or a Contribution incorporated within the Work constitutes direct
      or contributory patent infringement, then any patent licenses
      granted to You under this License for that Work shall terminate
      as of the date such litigation is filed.

   4. Redistribution. You may reproduce and distribute copies of the
      Work or Derivative Works thereof in any medium, with or without
      modifications, and in Source or Object form, provided that You
      meet the following conditions:

      (a) You must give any other recipients of the Work or
          Derivative Works a copy of this License; and

      (b) You must cause any modified files to carry prominent notices
          stating that You changed the files; and

      (c) You must retain, in the Source form of any Derivative Works
          that You distribute, all copyright, patent, trademark, and
          attribution notices from the Source form of the Work,
          excluding those notices that do not pertain to any part of
          the Derivative Works; and

      (d) If the Work includes a "NOTICE" text file as part of its
          distribution, then any Derivative Works that You distribute must
          include a readable copy of the attribution notices contained
          within such NOTICE file, excluding those notices that do not
          pertain to any part of the Derivative Works, in at least one
          of the following places: within a NOTICE text file distributed
          as part of the Derivative Works; within the Source form or
          documentation, if provided along with the Derivative Works; or,
          within a display generated by the Derivative Works, if and
          wherever such third-party notices normally appear. The contents
          of the NOTICE file are for informational purposes only and
          do not modify the License. You may add Your own attribution
          notices within Derivative Works that You distribute, alongside
          or as an addendum to the NOTICE text from the Work, provided
          that such additional attribution notices cannot be construed
          as modifying the License.

      You may add Your own copyright statement to Your modifications and
      may provide additional or different license terms and conditions
      for use, reproduction, or distribution of Your modifications, or
      for any such Derivative Works as a whole, provided Your use,
      reproduction, and distribution of the Work otherwise complies with
      the conditions stated in this License.

   5. Submission of Contributions. Unless You explicitly state otherwise,
      any Contribution intentionally submitted for inclusion in the Work
      by You to the Licensor shall be under the terms and conditions of
      this License, without any additional terms or conditions.
      Notwithstanding the above, nothing herein shall supersede or modify
      the terms of any separate license agreement you may have executed
      with Licensor regarding such Contributions.

   6. Trademarks. This License does not grant permission to use the trade
      names, trademarks, service marks, or product names of the Licensor,
      except as required for reasonable and customary use in describing the
      origin of the Work and reproducing the content of the NOTICE file.

   7. Disclaimer of Warranty. Unless required by applicable law or
      agreed to in writing, Licensor provides the Work (and each
      Contributor provides its Contributions) on an "AS IS" BASIS,
      WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or
      implied, including, without limitation, any warranties or conditions
      of TITLE, NON-INFRINGEMENT, MERCHANTABILITY, or FITNESS FOR A
      PARTICULAR PURPOSE. You are solely responsible for determining the
      appropriateness of using or redistributing the Work and assume any
      risks associated with Your exercise of permissions under this License.

   8. Limitation of Liability. In no event and under no legal theory,
      whether in tort (including negligence), contract, or otherwise,
      unless required by applicable law (such as deliberate and grossly
      negligent acts) or agreed to in writing, shall any Contributor be
      liable to You for damages, including any direct, indirect, special,
      incidental, or consequential damages of any character arising as a
      result of this License or out of the use or inability to use the
      Work (including but not limited to damages for loss of goodwill,
      work stoppage, computer failure or malfunction, or any and all
      other commercial damages or losses), even if such Contributor
      has been advised of the possibility of such damages.

   9. Accepting Warranty or Additional Liability. While redistributing
      the Work or Derivative Works thereof, You may choose to offer,
      and charge a fee for, acceptance of support, warranty, indemnity,
      or other liability obligations and/or rights consistent with this
      License. However, in accepting such obligations, You may act only
      on Your own behalf and on Your sole responsibility, not on behalf
      of any other Contributor, and only if You agree to indemnify,
      defend, and hold each Contributor harmless for any liability
      incurred by, or claims asserted against, such Contributor by reason
      of your accepting any such warranty or additional liability.

   END OF TERMS AND CONDITIONS

   APPENDIX: How to apply the Apache License to your work.

      To apply the Apache License to your work, attach the following
      boilerplate notice, with the fields enclosed by brackets "[]"
      replaced with your own identifying information. (Don't include
      the brackets!)  The text should be enclosed in the appropriate
      comment syntax for the file format. We also recommend that a
      file or class name and description of purpose be included on the
      same "printed page" as the copyright notice for easier
      identification within third-party archives.

   Copyright [yyyy] [name of copyright owner]

   Licensed under the Apache License, Version 2.0 (the "License");
   you may not use this file except in compliance with the License.
   You may obtain a copy of the License at

       http://www.apache.org/licenses/LICENSE-2.0

   Unless required by applicable law or agreed to in writing, software
   distributed under the License is distributed on an "AS IS" BASIS,
   WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
   See the License for the specific language governing permissions and
   limitations under the License.""",
    "BSD-2-Clause": """Redistribution and use in source and binary forms, with or without
modification, are permitted provided that the following conditions are met:

1. Redistributions of source code must retain the above copyright notice,
   this list of conditions and the following disclaimer.

2. Redistributions in binary form must reproduce the above copyright notice,
   this list of conditions and the following disclaimer in the documentation
   and/or other materials provided with the distribution.

THIS SOFTWARE IS PROVIDED BY THE COPYRIGHT HOLDERS AND CONTRIBUTORS "AS IS"
AND ANY EXPRESS OR IMPLIED WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE
IMPLIED WARRANTIES OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE
ARE DISCLAIMED. IN NO EVENT SHALL THE COPYRIGHT HOLDER OR CONTRIBUTORS BE
LIABLE FOR ANY DIRECT, INDIRECT, INCIDENTAL, SPECIAL, EXEMPLARY, OR
CONSEQUENTIAL DAMAGES (INCLUDING, BUT NOT LIMITED TO, PROCUREMENT OF
SUBSTITUTE GOODS OR SERVICES; LOSS OF USE, DATA, OR PROFITS; OR BUSINESS
INTERRUPTION) HOWEVER CAUSED AND ON ANY THEORY OF LIABILITY, WHETHER IN
CONTRACT, STRICT LIABILITY, OR TORT (INCLUDING NEGLIGENCE OR OTHERWISE)
ARISING IN ANY WAY OUT OF THE USE OF THIS SOFTWARE, EVEN IF ADVISED OF THE
POSSIBILITY OF SUCH DAMAGE.""",
    "BSD-3-Clause": """Redistribution and use in source and binary forms, with or without
modification, are permitted provided that the following conditions
are met:

 1. Redistributions of source code must retain the above copyright
    notice, this list of conditions and the following disclaimer.
 2. Redistributions in binary form must reproduce the above copyright
    notice, this list of conditions and the following disclaimer in
    the documentation and/or other materials provided with the
    distribution.
 3. Neither the name of the copyright holder nor the names of its
    contributors may be used to endorse or promote products derived
    from this software without specific prior written permission.

THIS SOFTWARE IS PROVIDED BY THE COPYRIGHT HOLDERS AND CONTRIBUTORS
"AS IS" AND ANY EXPRESS OR IMPLIED WARRANTIES, INCLUDING, BUT NOT
LIMITED TO, THE IMPLIED WARRANTIES OF MERCHANTABILITY AND FITNESS FOR
A PARTICULAR PURPOSE ARE DISCLAIMED. IN NO EVENT SHALL THE COPYRIGHT
HOLDER OR CONTRIBUTORS BE LIABLE FOR ANY DIRECT, INDIRECT, INCIDENTAL,
SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT NOT
LIMITED TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE,
DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY
THEORY OF LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT
(INCLUDING NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE
OF THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.""",
    "BSL-1.0": """Boost Software License - Version 1.0 - August 17th, 2003

Permission is hereby granted, free of charge, to any person or organization
obtaining a copy of the software and accompanying documentation covered by
this license (the "Software") to use, reproduce, display, distribute,
execute, and transmit the Software, and to prepare derivative works of the
Software, and to permit third-parties to whom the Software is furnished to
do so, all subject to the following:

The copyright notices in the Software and this entire statement, including
the above license grant, this restriction and the following disclaimer,
must be included in all copies of the Software, in whole or in part, and
all derivative works of the Software, unless such copies or derivative
works are solely in the form of machine-executable object code generated by
a source language processor.

THE SOFTWARE IS PROVIDED "AS IS", WITHOUT WARRANTY OF ANY KIND, EXPRESS OR
IMPLIED, INCLUDING BUT NOT LIMITED TO THE WARRANTIES OF MERCHANTABILITY,
FITNESS FOR A PARTICULAR PURPOSE, TITLE AND NON-INFRINGEMENT. IN NO EVENT
SHALL THE COPYRIGHT HOLDERS OR ANYONE DISTRIBUTING THE SOFTWARE BE LIABLE
FOR ANY DAMAGES OR OTHER LIABILITY, WHETHER IN CONTRACT, TORT OR OTHERWISE,
ARISING FROM, OUT OF OR IN CONNECTION WITH THE SOFTWARE OR THE USE OR OTHER
DEALINGS IN THE SOFTWARE.""",
    "CC0-1.0": """Creative Commons Legal Code

CC0 1.0 Universal

    CREATIVE COMMONS CORPORATION IS NOT A LAW FIRM AND DOES NOT PROVIDE
    LEGAL SERVICES. DISTRIBUTION OF THIS DOCUMENT DOES NOT CREATE AN
    ATTORNEY-CLIENT RELATIONSHIP. CREATIVE COMMONS PROVIDES THIS
    INFORMATION ON AN "AS-IS" BASIS. CREATIVE COMMONS MAKES NO WARRANTIES
    REGARDING THE USE OF THIS DOCUMENT OR THE INFORMATION OR WORKS
    PROVIDED HEREUNDER, AND DISCLAIMS LIABILITY FOR DAMAGES RESULTING FROM
    THE USE OF THIS DOCUMENT OR THE INFORMATION OR WORKS PROVIDED
    HEREUNDER.

Statement of Purpose

The laws of most jurisdictions throughout the world automatically confer
exclusive Copyright and Related Rights (defined below) upon the creator
and subsequent owner(s) (each and all, an "owner") of an original work of
authorship and/or a database (each, a "Work").

Certain owners wish to permanently relinquish those rights to a Work for
the purpose of contributing to a commons of creative, cultural and
scientific works ("Commons") that the public can reliably and without fear
of later claims of infringement build upon, modify, incorporate in other
works, reuse and redistribute as freely as possible in any form whatsoever
and for any purposes, including without limitation commercial purposes.
These owners may contribute to the Commons to promote the ideal of a free
culture and the further production of creative, cultural and scientific
works, or to gain reputation or greater distribution for their Work in
part through the use and efforts of others.

For these and/or other purposes and motivations, and without any
expectation of additional consideration or compensation, the person
associating CC0 with a Work (the "Affirmer"), to the extent that he or she
is an owner of Copyright and Related Rights in the Work, voluntarily
elects to apply CC0 to the Work and publicly distribute the Work under its
terms, with knowledge of his or her Copyright and Related Rights in the
Work and the meaning and intended legal effect of CC0 on those rights.

1. Copyright and Related Rights. A Work made available under CC0 may be
protected by copyright and related or neighboring rights ("Copyright and
Related Rights"). Copyright and Related Rights include, but are not
limited to, the following:

  i. the right to reproduce, adapt, distribute, perform, display,
     communicate, and translate a Work;
 ii. moral rights retained by the original author(s) and/or performer(s);
iii. publicity and privacy rights pertaining to a person's image or
     likeness depicted in a Work;
 iv. rights protecting against unfair competition in regards to a Work,
     subject to the limitations in paragraph 4(a), below;
  v. rights protecting the extraction, dissemination, use and reuse of data
     in a Work;
 vi. database rights (such as those arising under Directive 96/9/EC of the
     European Parliament and of the Council of 11 March 1996 on the legal
     protection of databases, and under any national implementation
     thereof, including any amended or successor version of such
     directive); and
vii. other similar, equivalent or corresponding rights throughout the
     world based on applicable law or treaty, and any national
     implementations thereof.

2. Waiver. To the greatest extent permitted by, but not in contravention
of, applicable law, Affirmer hereby overtly, fully, permanently,
irrevocably and unconditionally waives, abandons, and surrenders all of
Affirmer's Copyright and Related Rights and associated claims and causes
of action, whether now known or unknown (including existing as well as
future claims and causes of action), in the Work (i) in all territories
worldwide, (ii) for the maximum duration provided by applicable law or
treaty (including future time extensions), (iii) in any current or future
medium and for any number of copies, and (iv) for any purpose whatsoever,
including without limitation commercial, advertising or promotional
purposes (the "Waiver"). Affirmer makes the Waiver for the benefit of each
member of the public at large and to the detriment of Affirmer's heirs and
successors, fully intending that such Waiver shall not be subject to
revocation, rescission, cancellation, termination, or any other legal or
equitable action to disrupt the quiet enjoyment of the Work by the public
as contemplated by Affirmer's express Statement of Purpose.

3. Public License Fallback. Should any part of the Waiver for any reason
be judged legally invalid or ineffective under applicable law, then the
Waiver shall be preserved to the maximum extent permitted taking into
account Affirmer's express Statement of Purpose. In addition, to the
extent the Waiver is so judged Affirmer hereby grants to each affected
person a royalty-free, non transferable, non sublicensable, non exclusive,
irrevocable and unconditional license to exercise Affirmer's Copyright and
Related Rights in the Work (i) in all territories worldwide, (ii) for the
maximum duration provided by applicable law or treaty (including future
time extensions), (iii) in any current or future medium and for any number
of copies, and (iv) for any purpose whatsoever, including without
limitation commercial, advertising or promotional purposes (the
"License"). The License shall be deemed effective as of the date CC0 was
applied by Affirmer to the Work. Should any part of the License for any
reason be judged legally invalid or ineffective under applicable law, such
partial invalidity or ineffectiveness shall not invalidate the remainder
of the License, and in such case Affirmer hereby affirms that he or she
will not (i) exercise any of his or her remaining Copyright and Related
Rights in the Work or (ii) assert any associated claims and causes of
action with respect to the Work, in either case contrary to Affirmer's
express Statement of Purpose.

4. Limitations and Disclaimers.

 a. No trademark or patent rights held by Affirmer are waived, abandoned,
    surrendered, licensed or otherwise affected by this document.
 b. Affirmer offers the Work as-is and makes no representations or
    warranties of any kind concerning the Work, express, implied,
    statutory or otherwise, including without limitation warranties of
    title, merchantability, fitness for a particular purpose, non
    infringement, or the absence of latent or other defects, accuracy, or
    the present or absence of errors, whether or not discoverable, all to
    the greatest extent permissible under applicable law.
 c. Affirmer disclaims responsibility for clearing rights of other persons
    that may apply to the Work or any use thereof, including without
    limitation any person's Copyright and Related Rights in the Work.
    Further, Affirmer disclaims responsibility for obtaining any necessary
    consents, permissions or other rights required for any use of the
    Work.
 d. Affirmer understands and acknowledges that Creative Commons is not a
    party to this document and has no duty or obligation with respect to
    this CC0 or use of the Work.""",
    "GPL-2.0": """GNU GENERAL PUBLIC LICENSE
                       Version 2, June 1991

 51 Franklin Street, Fifth Floor, Boston, MA 02110-1301 USA
 Everyone is permitted to copy and distribute verbatim copies
 of this license document, but changing it is not allowed.

                            Preamble

  The licenses for most software are designed to take away your
freedom to share and change it.  By contrast, the GNU General Public
License is intended to guarantee your freedom to share and change free
software--to make sure the software is free for all its users.  This
General Public License applies to most of the Free Software
Foundation's software and to any other program whose authors commit to
using it.  (Some other Free Software Foundation software is covered by
the GNU Lesser General Public License instead.)  You can apply it to
your programs, too.

  When we speak of free software, we are referring to freedom, not
price.  Our General Public Licenses are designed to make sure that you
have the freedom to distribute copies of free software (and charge for
this service if you wish), that you receive source code or can get it
if you want it, that you can change the software or use pieces of it
in new free programs; and that you know you can do these things.

  To protect your rights, we need to make restrictions that forbid
anyone to deny you these rights or to ask you to surrender the rights.
These restrictions translate to certain responsibilities for you if you
distribute copies of the software, or if you modify it.

  For example, if you distribute copies of such a program, whether
gratis or for a fee, you must give the recipients all the rights that
you have.  You must make sure that they, too, receive or can get the
source code.  And you must show them these terms so they know their
rights.

  We protect your rights with two steps: (1) copyright the software, and
(2) offer you this license which gives you legal permission to copy,
distribute and/or modify the software.

  Also, for each author's protection and ours, we want to make certain
that everyone understands that there is no warranty for this free
software.  If the software is modified by someone else and passed on, we
want its recipients to know that what they have is not the original, so
that any problems introduced by others will not reflect on the original
authors' reputations.

  Finally, any free program is threatened constantly by software
patents.  We wish to avoid the danger that redistributors of a free
program will individually obtain patent licenses, in effect making the
program proprietary.  To prevent this, we have made it clear that any
patent must be licensed for everyone's free use or not licensed at all.

  The precise terms and conditions for copying, distribution and
modification follow.

                    GNU GENERAL PUBLIC LICENSE
   TERMS AND CONDITIONS FOR COPYING, DISTRIBUTION AND MODIFICATION

  0. This License applies to any program or other work which contains
a notice placed by the copyright holder saying it may be distributed
under the terms of this General Public License.  The "Program", below,
refers to any such program or work, and a "work based on the Program"
means either the Program or any derivative work under copyright law:
that is to say, a work containing the Program or a portion of it,
either verbatim or with modifications and/or translated into another
language.  (Hereinafter, translation is included without limitation in
the term "modification".)  Each licensee is addressed as "you".

Activities other than copying, distribution and modification are not
covered by this License; they are outside its scope.  The act of
running the Program is not restricted, and the output from the Program
is covered only if its contents constitute a work based on the
Program (independent of having been made by running the Program).
Whether that is true depends on what the Program does.

  1. You may copy and distribute verbatim copies of the Program's
source code as you receive it, in any medium, provided that you
conspicuously and appropriately publish on each copy an appropriate
copyright notice and disclaimer of warranty; keep intact all the
notices that refer to this License and to the absence of any warranty;
and give any other recipients of the Program a copy of this License
along with the Program.

You may charge a fee for the physical act of transferring a copy, and
you may at your option offer warranty protection in exchange for a fee.

  2. You may modify your copy or copies of the Program or any portion
of it, thus forming a work based on the Program, and copy and
distribute such modifications or work under the terms of Section 1
above, provided that you also meet all of these conditions:

    a) You must cause the modified files to carry prominent notices
    stating that you changed the files and the date of any change.

    b) You must cause any work that you distribute or publish, that in
    whole or in part contains or is derived from the Program or any
    part thereof, to be licensed as a whole at no charge to all third
    parties under the terms of this License.

    c) If the modified program normally reads commands interactively
    when run, you must cause it, when started running for such
    interactive use in the most ordinary way, to print or display an
    announcement including an appropriate copyright notice and a
    notice that there is no warranty (or else, saying that you provide
    a warranty) and that users may redistribute the program under
    these conditions, and telling the user how to view a copy of this
    License.  (Exception: if the Program itself is interactive but
    does not normally print such an announcement, your work based on
    the Program is not required to print an announcement.)

These requirements apply to the modified work as a whole.  If
identifiable sections of that work are not derived from the Program,
and can be reasonably considered independent and separate works in
themselves, then this License, and its terms, do not apply to those
sections when you distribute them as separate works.  But when you
distribute the same sections as part of a whole which is a work based
on the Program, the distribution of the whole must be on the terms of
this License, whose permissions for other licensees extend to the
entire whole, and thus to each and every part regardless of who wrote it.

Thus, it is not the intent of this section to claim rights or contest
your rights to work written entirely by you; rather, the intent is to
exercise the right to control the distribution of derivative or
collective works based on the Program.

In addition, mere aggregation of another work not based on the Program
with the Program (or with a work based on the Program) on a volume of
a storage or distribution medium does not bring the other work under
the scope of this License.

  3. You may copy and distribute the Program (or a work based on it,
under Section 2) in object code or executable form under the terms of
Sections 1 and 2 above provided that you also do one of the following:

    a) Accompany it with the complete corresponding machine-readable
    source code, which must be distributed under the terms of Sections
    1 and 2 above on a medium customarily used for software interchange; or,

    b) Accompany it with a written offer, valid for at least three
    years, to give any third party, for a charge no more than your
    cost of physically performing source distribution, a complete
    machine-readable copy of the corresponding source code, to be
    distributed under the terms of Sections 1 and 2 above on a medium
    customarily used for software interchange; or,

    c) Accompany it with the information you received as to the offer
    to distribute corresponding source code.  (This alternative is
    allowed only for noncommercial distribution and only if you
    received the program in object code or executable form with such
    an offer, in accord with Subsection b above.)

The source code for a work means the preferred form of the work for
making modifications to it.  For an executable work, complete source
code means all the source code for all modules it contains, plus any
associated interface definition files, plus the scripts used to
control compilation and installation of the executable.  However, as a
special exception, the source code distributed need not include
anything that is normally distributed (in either source or binary
form) with the major components (compiler, kernel, and so on) of the
operating system on which the executable runs, unless that component
itself accompanies the executable.

If distribution of executable or object code is made by offering
access to copy from a designated place, then offering equivalent
access to copy the source code from the same place counts as
distribution of the source code, even though third parties are not
compelled to copy the source along with the object code.

  4. You may not copy, modify, sublicense, or distribute the Program
except as expressly provided under this License.  Any attempt
otherwise to copy, modify, sublicense or distribute the Program is
void, and will automatically terminate your rights under this License.
However, parties who have received copies, or rights, from you under
this License will not have their licenses terminated so long as such
parties remain in full compliance.

  5. You are not required to accept this License, since you have not
signed it.  However, nothing else grants you permission to modify or
distribute the Program or its derivative works.  These actions are
prohibited by law if you do not accept this License.  Therefore, by
modifying or distributing the Program (or any work based on the
Program), you indicate your acceptance of this License to do so, and
all its terms and conditions for copying, distributing or modifying
the Program or works based on it.

  6. Each time you redistribute the Program (or any work based on the
Program), the recipient automatically receives a license from the
original licensor to copy, distribute or modify the Program subject to
these terms and conditions.  You may not impose any further
restrictions on the recipients' exercise of the rights granted herein.
You are not responsible for enforcing compliance by third parties to
this License.

  7. If, as a consequence of a court judgment or allegation of patent
infringement or for any other reason (not limited to patent issues),
conditions are imposed on you (whether by court order, agreement or
otherwise) that contradict the conditions of this License, they do not
excuse you from the conditions of this License.  If you cannot
distribute so as to satisfy simultaneously your obligations under this
License and any other pertinent obligations, then as a consequence you
may not distribute the Program at all.  For example, if a patent
license would not permit royalty-free redistribution of the Program by
all those who receive copies directly or indirectly through you, then
the only way you could satisfy both it and this License would be to
refrain entirely from distribution of the Program.

If any portion of this section is held invalid or unenforceable under
any particular circumstance, the balance of the section is intended to
apply and the section as a whole is intended to apply in other
circumstances.

It is not the purpose of this section to induce you to infringe any
patents or other property right claims or to contest validity of any
such claims; this section has the sole purpose of protecting the
integrity of the free software distribution system, which is
implemented by public license practices.  Many people have made
generous contributions to the wide range of software distributed
through that system in reliance on consistent application of that
system; it is up to the author/donor to decide if he or she is willing
to distribute software through any other system and a licensee cannot
impose that choice.

This section is intended to make thoroughly clear what is believed to
be a consequence of the rest of this License.

  8. If the distribution and/or use of the Program is restricted in
certain countries either by patents or by copyrighted interfaces, the
original copyright holder who places the Program under this License
may add an explicit geographical distribution limitation excluding
those countries, so that distribution is permitted only in or among
countries not thus excluded.  In such case, this License incorporates
the limitation as if written in the body of this License.

  9. The Free Software Foundation may publish revised and/or new versions
of the General Public License from time to time.  Such new versions will
be similar in spirit to the present version, but may differ in detail to
address new problems or concerns.

Each version is given a distinguishing version number.  If the Program
specifies a version number of this License which applies to it and "any
later version", you have the option of following the terms and conditions
either of that version or of any later version published by the Free
Software Foundation.  If the Program does not specify a version number of
this License, you may choose any version ever published by the Free Software
Foundation.

  10. If you wish to incorporate parts of the Program into other free
programs whose distribution conditions are different, write to the author
to ask for permission.  For software which is copyrighted by the Free
Software Foundation, write to the Free Software Foundation; we sometimes
make exceptions for this.  Our decision will be guided by the two goals
of preserving the free status of all derivatives of our free software and
of promoting the sharing and reuse of software generally.

                            NO WARRANTY

  11. BECAUSE THE PROGRAM IS LICENSED FREE OF CHARGE, THERE IS NO WARRANTY
FOR THE PROGRAM, TO THE EXTENT PERMITTED BY APPLICABLE LAW.  EXCEPT WHEN
OTHERWISE STATED IN WRITING THE COPYRIGHT HOLDERS AND/OR OTHER PARTIES
PROVIDE THE PROGRAM "AS IS" WITHOUT WARRANTY OF ANY KIND, EITHER EXPRESSED
OR IMPLIED, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED WARRANTIES OF
MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE.  THE ENTIRE RISK AS
TO THE QUALITY AND PERFORMANCE OF THE PROGRAM IS WITH YOU.  SHOULD THE
PROGRAM PROVE DEFECTIVE, YOU ASSUME THE COST OF ALL NECESSARY SERVICING,
REPAIR OR CORRECTION.

  12. IN NO EVENT UNLESS REQUIRED BY APPLICABLE LAW OR AGREED TO IN WRITING
WILL ANY COPYRIGHT HOLDER, OR ANY OTHER PARTY WHO MAY MODIFY AND/OR
REDISTRIBUTE THE PROGRAM AS PERMITTED ABOVE, BE LIABLE TO YOU FOR DAMAGES,
INCLUDING ANY GENERAL, SPECIAL, INCIDENTAL OR CONSEQUENTIAL DAMAGES ARISING
OUT OF THE USE OR INABILITY TO USE THE PROGRAM (INCLUDING BUT NOT LIMITED
TO LOSS OF DATA OR DATA BEING RENDERED INACCURATE OR LOSSES SUSTAINED BY
YOU OR THIRD PARTIES OR A FAILURE OF THE PROGRAM TO OPERATE WITH ANY OTHER
PROGRAMS), EVEN IF SUCH HOLDER OR OTHER PARTY HAS BEEN ADVISED OF THE
POSSIBILITY OF SUCH DAMAGES.

                     END OF TERMS AND CONDITIONS

            How to Apply These Terms to Your New Programs

  If you develop a new program, and you want it to be of the greatest
possible use to the public, the best way to achieve this is to make it
free software which everyone can redistribute and change under these terms.

  To do so, attach the following notices to the program.  It is safest
to attach them to the start of each source file to most effectively
convey the exclusion of warranty; and each file should have at least
the "copyright" line and a pointer to where the full notice is found.

    <one line to give the program's name and a brief idea of what it does.>

    This program is free software; you can redistribute it and/or modify
    it under the terms of the GNU General Public License as published by
    the Free Software Foundation; either version 2 of the License, or
    (at your option) any later version.

    This program is distributed in the hope that it will be useful,
    but WITHOUT ANY WARRANTY; without even the implied warranty of
    MERCHANTABILITY or FITNESS FOR A PARTICULAR PURPOSE.  See the
    GNU General Public License for more details.

    You should have received a copy of the GNU General Public License along
    with this program; if not, write to the Free Software Foundation, Inc.,
    51 Franklin Street, Fifth Floor, Boston, MA 02110-1301 USA.

Also add information on how to contact you by electronic and paper mail.

If the program is interactive, make it output a short notice like this
when it starts in an interactive mode:

    Gnomovision version 69, Copyright (C) year name of author
    Gnomovision comes with ABSOLUTELY NO WARRANTY; for details type `show w'.
    This is free software, and you are welcome to redistribute it
    under certain conditions; type `show c' for details.

The hypothetical commands `show w' and `show c' should show the appropriate
parts of the General Public License.  Of course, the commands you use may
be called something other than `show w' and `show c'; they could even be
mouse-clicks or menu items--whatever suits your program.

You should also get your employer (if you work as a programmer) or your
school, if any, to sign a "copyright disclaimer" for the program, if
necessary.  Here is a sample; alter the names:

  Yoyodyne, Inc., hereby disclaims all copyright interest in the program
  `Gnomovision' (which makes passes at compilers) written by James Hacker.

  <signature of Ty Coon>, 1 April 1989
  Ty Coon, President of Vice

This General Public License does not permit incorporating your program into
proprietary programs.  If your program is a subroutine library, you may
consider it more useful to permit linking proprietary applications with the
library.  If this is what you want to do, use the GNU Lesser General
Public License instead of this License.""",
    "GPL-3.0": """GNU GENERAL PUBLIC LICENSE
                       Version 3, 29 June 2007

 Everyone is permitted to copy and distribute verbatim copies
 of this license document, but changing it is not allowed.

                            Preamble

  The GNU General Public License is a free, copyleft license for
software and other kinds of works.

  The licenses for most software and other practical works are designed
to take away your freedom to share and change the works.  By contrast,
the GNU General Public License is intended to guarantee your freedom to
share and change all versions of a program--to make sure it remains free
software for all its users.  We, the Free Software Foundation, use the
GNU General Public License for most of our software; it applies also to
any other work released this way by its authors.  You can apply it to
your programs, too.

  When we speak of free software, we are referring to freedom, not
price.  Our General Public Licenses are designed to make sure that you
have the freedom to distribute copies of free software (and charge for
them if you wish), that you receive source code or can get it if you
want it, that you can change the software or use pieces of it in new
free programs, and that you know you can do these things.

  To protect your rights, we need to prevent others from denying you
these rights or asking you to surrender the rights.  Therefore, you have
certain responsibilities if you distribute copies of the software, or if
you modify it: responsibilities to respect the freedom of others.

  For example, if you distribute copies of such a program, whether
gratis or for a fee, you must pass on to the recipients the same
freedoms that you received.  You must make sure that they, too, receive
or can get the source code.  And you must show them these terms so they
know their rights.

  Developers that use the GNU GPL protect your rights with two steps:
(1) assert copyright on the software, and (2) offer you this License
giving you legal permission to copy, distribute and/or modify it.

  For the developers' and authors' protection, the GPL clearly explains
that there is no warranty for this free software.  For both users' and
authors' sake, the GPL requires that modified versions be marked as
changed, so that their problems will not be attributed erroneously to
authors of previous versions.

  Some devices are designed to deny users access to install or run
modified versions of the software inside them, although the manufacturer
can do so.  This is fundamentally incompatible with the aim of
protecting users' freedom to change the software.  The systematic
pattern of such abuse occurs in the area of products for individuals to
use, which is precisely where it is most unacceptable.  Therefore, we
have designed this version of the GPL to prohibit the practice for those
products.  If such problems arise substantially in other domains, we
stand ready to extend this provision to those domains in future versions
of the GPL, as needed to protect the freedom of users.

  Finally, every program is threatened constantly by software patents.
States should not allow patents to restrict development and use of
software on general-purpose computers, but in those that do, we wish to
avoid the special danger that patents applied to a free program could
make it effectively proprietary.  To prevent this, the GPL assures that
patents cannot be used to render the program non-free.

  The precise terms and conditions for copying, distribution and
modification follow.

                       TERMS AND CONDITIONS

  0. Definitions.

  "This License" refers to version 3 of the GNU General Public License.

  "Copyright" also means copyright-like laws that apply to other kinds of
works, such as semiconductor masks.

  "The Program" refers to any copyrightable work licensed under this
License.  Each licensee is addressed as "you".  "Licensees" and
"recipients" may be individuals or organizations.

  To "modify" a work means to copy from or adapt all or part of the work
in a fashion requiring copyright permission, other than the making of an
exact copy.  The resulting work is called a "modified version" of the
earlier work or a work "based on" the earlier work.

  A "covered work" means either the unmodified Program or a work based
on the Program.

  To "propagate" a work means to do anything with it that, without
permission, would make you directly or secondarily liable for
infringement under applicable copyright law, except executing it on a
computer or modifying a private copy.  Propagation includes copying,
distribution (with or without modification), making available to the
public, and in some countries other activities as well.

  To "convey" a work means any kind of propagation that enables other
parties to make or receive copies.  Mere interaction with a user through
a computer network, with no transfer of a copy, is not conveying.

  An interactive user interface displays "Appropriate Legal Notices"
to the extent that it includes a convenient and prominently visible
feature that (1) displays an appropriate copyright notice, and (2)
tells the user that there is no warranty for the work (except to the
extent that warranties are provided), that licensees may convey the
work under this License, and how to view a copy of this License.  If
the interface presents a list of user commands or options, such as a
menu, a prominent item in the list meets this criterion.

  1. Source Code.

  The "source code" for a work means the preferred form of the work
for making modifications to it.  "Object code" means any non-source
form of a work.

  A "Standard Interface" means an interface that either is an official
standard defined by a recognized standards body, or, in the case of
interfaces specified for a particular programming language, one that
is widely used among developers working in that language.

  The "System Libraries" of an executable work include anything, other
than the work as a whole, that (a) is included in the normal form of
packaging a Major Component, but which is not part of that Major
Component, and (b) serves only to enable use of the work with that
Major Component, or to implement a Standard Interface for which an
implementation is available to the public in source code form.  A
"Major Component", in this context, means a major essential component
(kernel, window system, and so on) of the specific operating system
(if any) on which the executable work runs, or a compiler used to
produce the work, or an object code interpreter used to run it.

  The "Corresponding Source" for a work in object code form means all
the source code needed to generate, install, and (for an executable
work) run the object code and to modify the work, including scripts to
control those activities.  However, it does not include the work's
System Libraries, or general-purpose tools or generally available free
programs which are used unmodified in performing those activities but
which are not part of the work.  For example, Corresponding Source
includes interface definition files associated with source files for
the work, and the source code for shared libraries and dynamically
linked subprograms that the work is specifically designed to require,
such as by intimate data communication or control flow between those
subprograms and other parts of the work.

  The Corresponding Source need not include anything that users
can regenerate automatically from other parts of the Corresponding
Source.

  The Corresponding Source for a work in source code form is that
same work.

  2. Basic Permissions.

  All rights granted under this License are granted for the term of
copyright on the Program, and are irrevocable provided the stated
conditions are met.  This License explicitly affirms your unlimited
permission to run the unmodified Program.  The output from running a
covered work is covered by this License only if the output, given its
content, constitutes a covered work.  This License acknowledges your
rights of fair use or other equivalent, as provided by copyright law.

  You may make, run and propagate covered works that you do not
convey, without conditions so long as your license otherwise remains
in force.  You may convey covered works to others for the sole purpose
of having them make modifications exclusively for you, or provide you
with facilities for running those works, provided that you comply with
the terms of this License in conveying all material for which you do
not control copyright.  Those thus making or running the covered works
for you must do so exclusively on your behalf, under your direction
and control, on terms that prohibit them from making any copies of
your copyrighted material outside their relationship with you.

  Conveying under any other circumstances is permitted solely under
the conditions stated below.  Sublicensing is not allowed; section 10
makes it unnecessary.

  3. Protecting Users' Legal Rights From Anti-Circumvention Law.

  No covered work shall be deemed part of an effective technological
measure under any applicable law fulfilling obligations under article
11 of the WIPO copyright treaty adopted on 20 December 1996, or
similar laws prohibiting or restricting circumvention of such
measures.

  When you convey a covered work, you waive any legal power to forbid
circumvention of technological measures to the extent such circumvention
is effected by exercising rights under this License with respect to
the covered work, and you disclaim any intention to limit operation or
modification of the work as a means of enforcing, against the work's
users, your or third parties' legal rights to forbid circumvention of
technological measures.

  4. Conveying Verbatim Copies.

  You may convey verbatim copies of the Program's source code as you
receive it, in any medium, provided that you conspicuously and
appropriately publish on each copy an appropriate copyright notice;
keep intact all notices stating that this License and any
non-permissive terms added in accord with section 7 apply to the code;
keep intact all notices of the absence of any warranty; and give all
recipients a copy of this License along with the Program.

  You may charge any price or no price for each copy that you convey,
and you may offer support or warranty protection for a fee.

  5. Conveying Modified Source Versions.

  You may convey a work based on the Program, or the modifications to
produce it from the Program, in the form of source code under the
terms of section 4, provided that you also meet all of these conditions:

    a) The work must carry prominent notices stating that you modified
    it, and giving a relevant date.

    b) The work must carry prominent notices stating that it is
    released under this License and any conditions added under section
    7.  This requirement modifies the requirement in section 4 to
    "keep intact all notices".

    c) You must license the entire work, as a whole, under this
    License to anyone who comes into possession of a copy.  This
    License will therefore apply, along with any applicable section 7
    additional terms, to the whole of the work, and all its parts,
    regardless of how they are packaged.  This License gives no
    permission to license the work in any other way, but it does not
    invalidate such permission if you have separately received it.

    d) If the work has interactive user interfaces, each must display
    Appropriate Legal Notices; however, if the Program has interactive
    interfaces that do not display Appropriate Legal Notices, your
    work need not make them do so.

  A compilation of a covered work with other separate and independent
works, which are not by their nature extensions of the covered work,
and which are not combined with it such as to form a larger program,
in or on a volume of a storage or distribution medium, is called an
"aggregate" if the compilation and its resulting copyright are not
used to limit the access or legal rights of the compilation's users
beyond what the individual works permit.  Inclusion of a covered work
in an aggregate does not cause this License to apply to the other
parts of the aggregate.

  6. Conveying Non-Source Forms.

  You may convey a covered work in object code form under the terms
of sections 4 and 5, provided that you also convey the
machine-readable Corresponding Source under the terms of this License,
in one of these ways:

    a) Convey the object code in, or embodied in, a physical product
    (including a physical distribution medium), accompanied by the
    Corresponding Source fixed on a durable physical medium
    customarily used for software interchange.

    b) Convey the object code in, or embodied in, a physical product
    (including a physical distribution medium), accompanied by a
    written offer, valid for at least three years and valid for as
    long as you offer spare parts or customer support for that product
    model, to give anyone who possesses the object code either (1) a
    copy of the Corresponding Source for all the software in the
    product that is covered by this License, on a durable physical
    medium customarily used for software interchange, for a price no
    more than your reasonable cost of physically performing this
    conveying of source, or (2) access to copy the
    Corresponding Source from a network server at no charge.

    c) Convey individual copies of the object code with a copy of the
    written offer to provide the Corresponding Source.  This
    alternative is allowed only occasionally and noncommercially, and
    only if you received the object code with such an offer, in accord
    with subsection 6b.

    d) Convey the object code by offering access from a designated
    place (gratis or for a charge), and offer equivalent access to the
    Corresponding Source in the same way through the same place at no
    further charge.  You need not require recipients to copy the
    Corresponding Source along with the object code.  If the place to
    copy the object code is a network server, the Corresponding Source
    may be on a different server (operated by you or a third party)
    that supports equivalent copying facilities, provided you maintain
    clear directions next to the object code saying where to find the
    Corresponding Source.  Regardless of what server hosts the
    Corresponding Source, you remain obligated to ensure that it is
    available for as long as needed to satisfy these requirements.

    e) Convey the object code using peer-to-peer transmission, provided
    you inform other peers where the object code and Corresponding
    Source of the work are being offered to the general public at no
    charge under subsection 6d.

  A separable portion of the object code, whose source code is excluded
from the Corresponding Source as a System Library, need not be
included in conveying the object code work.

  A "User Product" is either (1) a "consumer product", which means any
tangible personal property which is normally used for personal, family,
or household purposes, or (2) anything designed or sold for incorporation
into a dwelling.  In determining whether a product is a consumer product,
doubtful cases shall be resolved in favor of coverage.  For a particular
product received by a particular user, "normally used" refers to a
typical or common use of that class of product, regardless of the status
of the particular user or of the way in which the particular user
actually uses, or expects or is expected to use, the product.  A product
is a consumer product regardless of whether the product has substantial
commercial, industrial or non-consumer uses, unless such uses represent
the only significant mode of use of the product.

  "Installation Information" for a User Product means any methods,
procedures, authorization keys, or other information required to install
and execute modified versions of a covered work in that User Product from
a modified version of its Corresponding Source.  The information must
suffice to ensure that the continued functioning of the modified object
code is in no case prevented or interfered with solely because
modification has been made.

  If you convey an object code work under this section in, or with, or
specifically for use in, a User Product, and the conveying occurs as
part of a transaction in which the right of possession and use of the
User Product is transferred to the recipient in perpetuity or for a
fixed term (regardless of how the transaction is characterized), the
Corresponding Source conveyed under this section must be accompanied
by the Installation Information.  But this requirement does not apply
if neither you nor any third party retains the ability to install
modified object code on the User Product (for example, the work has
been installed in ROM).

  The requirement to provide Installation Information does not include a
requirement to continue to provide support service, warranty, or updates
for a work that has been modified or installed by the recipient, or for
the User Product in which it has been modified or installed.  Access to a
network may be denied when the modification itself materially and
adversely affects the operation of the network or violates the rules and
protocols for communication across the network.

  Corresponding Source conveyed, and Installation Information provided,
in accord with this section must be in a format that is publicly
documented (and with an implementation available to the public in
source code form), and must require no special password or key for
unpacking, reading or copying.

  7. Additional Terms.

  "Additional permissions" are terms that supplement the terms of this
License by making exceptions from one or more of its conditions.
Additional permissions that are applicable to the entire Program shall
be treated as though they were included in this License, to the extent
that they are valid under applicable law.  If additional permissions
apply only to part of the Program, that part may be used separately
under those permissions, but the entire Program remains governed by
this License without regard to the additional permissions.

  When you convey a copy of a covered work, you may at your option
remove any additional permissions from that copy, or from any part of
it.  (Additional permissions may be written to require their own
removal in certain cases when you modify the work.)  You may place
additional permissions on material, added by you to a covered work,
for which you have or can give appropriate copyright permission.

  Notwithstanding any other provision of this License, for material you
add to a covered work, you may (if authorized by the copyright holders of
that material) supplement the terms of this License with terms:

    a) Disclaiming warranty or limiting liability differently from the
    terms of sections 15 and 16 of this License; or

    b) Requiring preservation of specified reasonable legal notices or
    author attributions in that material or in the Appropriate Legal
    Notices displayed by works containing it; or

    c) Prohibiting misrepresentation of the origin of that material, or
    requiring that modified versions of such material be marked in
    reasonable ways as different from the original version; or

    d) Limiting the use for publicity purposes of names of licensors or
    authors of the material; or

    e) Declining to grant rights under trademark law for use of some
    trade names, trademarks, or service marks; or

    f) Requiring indemnification of licensors and authors of that
    material by anyone who conveys the material (or modified versions of
    it) with contractual assumptions of liability to the recipient, for
    any liability that these contractual assumptions directly impose on
    those licensors and authors.

  All other non-permissive additional terms are considered "further
restrictions" within the meaning of section 10.  If the Program as you
received it, or any part of it, contains a notice stating that it is
governed by this License along with a term that is a further
restriction, you may remove that term.  If a license document contains
a further restriction but permits relicensing or conveying under this
License, you may add to a covered work material governed by the terms
of that license document, provided that the further restriction does
not survive such relicensing or conveying.

  If you add terms to a covered work in accord with this section, you
must place, in the relevant source files, a statement of the
additional terms that apply to those files, or a notice indicating
where to find the applicable terms.

  Additional terms, permissive or non-permissive, may be stated in the
form of a separately written license, or stated as exceptions;
the above requirements apply either way.

  8. Termination.

  You may not propagate or modify a covered work except as expressly
provided under this License.  Any attempt otherwise to propagate or
modify it is void, and will automatically terminate your rights under
this License (including any patent licenses granted under the third
paragraph of section 11).

  However, if you cease all violation of this License, then your
license from a particular copyright holder is reinstated (a)
provisionally, unless and until the copyright holder explicitly and
finally terminates your license, and (b) permanently, if the copyright
holder fails to notify you of the violation by some reasonable means
prior to 60 days after the cessation.

  Moreover, your license from a particular copyright holder is
reinstated permanently if the copyright holder notifies you of the
violation by some reasonable means, this is the first time you have
received notice of violation of this License (for any work) from that
copyright holder, and you cure the violation prior to 30 days after
your receipt of the notice.

  Termination of your rights under this section does not terminate the
licenses of parties who have received copies or rights from you under
this License.  If your rights have been terminated and not permanently
reinstated, you do not qualify to receive new licenses for the same
material under section 10.

  9. Acceptance Not Required for Having Copies.

  You are not required to accept this License in order to receive or
run a copy of the Program.  Ancillary propagation of a covered work
occurring solely as a consequence of using peer-to-peer transmission
to receive a copy likewise does not require acceptance.  However,
nothing other than this License grants you permission to propagate or
modify any covered work.  These actions infringe copyright if you do
not accept this License.  Therefore, by modifying or propagating a
covered work, you indicate your acceptance of this License to do so.

  10. Automatic Licensing of Downstream Recipients.

  Each time you convey a covered work, the recipient automatically
receives a license from the original licensors, to run, modify and
propagate that work, subject to this License.  You are not responsible
for enforcing compliance by third parties with this License.

  An "entity transaction" is a transaction transferring control of an
organization, or substantially all assets of one, or subdividing an
organization, or merging organizations.  If propagation of a covered
work results from an entity transaction, each party to that
transaction who receives a copy of the work also receives whatever
licenses to the work the party's predecessor in interest had or could
give under the previous paragraph, plus a right to possession of the
Corresponding Source of the work from the predecessor in interest, if
the predecessor has it or can get it with reasonable efforts.

  You may not impose any further restrictions on the exercise of the
rights granted or affirmed under this License.  For example, you may
not impose a license fee, royalty, or other charge for exercise of
rights granted under this License, and you may not initiate litigation
(including a cross-claim or counterclaim in a lawsuit) alleging that
any patent claim is infringed by making, using, selling, offering for
sale, or importing the Program or any portion of it.

  11. Patents.

  A "contributor" is a copyright holder who authorizes use under this
License of the Program or a work on which the Program is based.  The
work thus licensed is called the contributor's "contributor version".

  A contributor's "essential patent claims" are all patent claims
owned or controlled by the contributor, whether already acquired or
hereafter acquired, that would be infringed by some manner, permitted
by this License, of making, using, or selling its contributor version,
but do not include claims that would be infringed only as a
consequence of further modification of the contributor version.  For
purposes of this definition, "control" includes the right to grant
patent sublicenses in a manner consistent with the requirements of
this License.

  Each contributor grants you a non-exclusive, worldwide, royalty-free
patent license under the contributor's essential patent claims, to
make, use, sell, offer for sale, import and otherwise run, modify and
propagate the contents of its contributor version.

  In the following three paragraphs, a "patent license" is any express
agreement or commitment, however denominated, not to enforce a patent
(such as an express permission to practice a patent or covenant not to
sue for patent infringement).  To "grant" such a patent license to a
party means to make such an agreement or commitment not to enforce a
patent against the party.

  If you convey a covered work, knowingly relying on a patent license,
and the Corresponding Source of the work is not available for anyone
to copy, free of charge and under the terms of this License, through a
publicly available network server or other readily accessible means,
then you must either (1) cause the Corresponding Source to be so
available, or (2) arrange to deprive yourself of the benefit of the
patent license for this particular work, or (3) arrange, in a manner
consistent with the requirements of this License, to extend the patent
license to downstream recipients.  "Knowingly relying" means you have
actual knowledge that, but for the patent license, your conveying the
covered work in a country, or your recipient's use of the covered work
in a country, would infringe one or more identifiable patents in that
country that you have reason to believe are valid.

  If, pursuant to or in connection with a single transaction or
arrangement, you convey, or propagate by procuring conveyance of, a
covered work, and grant a patent license to some of the parties
receiving the covered work authorizing them to use, propagate, modify
or convey a specific copy of the covered work, then the patent license
you grant is automatically extended to all recipients of the covered
work and works based on it.

  A patent license is "discriminatory" if it does not include within
the scope of its coverage, prohibits the exercise of, or is
conditioned on the non-exercise of one or more of the rights that are
specifically granted under this License.  You may not convey a covered
work if you are a party to an arrangement with a third party that is
in the business of distributing software, under which you make payment
to the third party based on the extent of your activity of conveying
the work, and under which the third party grants, to any of the
parties who would receive the covered work from you, a discriminatory
patent license (a) in connection with copies of the covered work
conveyed by you (or copies made from those copies), or (b) primarily
for and in connection with specific products or compilations that
contain the covered work, unless you entered into that arrangement,
or that patent license was granted, prior to 28 March 2007.

  Nothing in this License shall be construed as excluding or limiting
any implied license or other defenses to infringement that may
otherwise be available to you under applicable patent law.

  12. No Surrender of Others' Freedom.

  If conditions are imposed on you (whether by court order, agreement or
otherwise) that contradict the conditions of this License, they do not
excuse you from the conditions of this License.  If you cannot convey a
covered work so as to satisfy simultaneously your obligations under this
License and any other pertinent obligations, then as a consequence you may
not convey it at all.  For example, if you agree to terms that obligate you
to collect a royalty for further conveying from those to whom you convey
the Program, the only way you could satisfy both those terms and this
License would be to refrain entirely from conveying the Program.

  13. Use with the GNU Affero General Public License.

  Notwithstanding any other provision of this License, you have
permission to link or combine any covered work with a work licensed
under version 3 of the GNU Affero General Public License into a single
combined work, and to convey the resulting work.  The terms of this
License will continue to apply to the part which is the covered work,
but the special requirements of the GNU Affero General Public License,
section 13, concerning interaction through a network will apply to the
combination as such.

  14. Revised Versions of this License.

  The Free Software Foundation may publish revised and/or new versions of
the GNU General Public License from time to time.  Such new versions will
be similar in spirit to the present version, but may differ in detail to
address new problems or concerns.

  Each version is given a distinguishing version number.  If the
Program specifies that a certain numbered version of the GNU General
Public License "or any later version" applies to it, you have the
option of following the terms and conditions either of that numbered
version or of any later version published by the Free Software
Foundation.  If the Program does not specify a version number of the
GNU General Public License, you may choose any version ever published
by the Free Software Foundation.

  If the Program specifies that a proxy can decide which future
versions of the GNU General Public License can be used, that proxy's
public statement of acceptance of a version permanently authorizes you
to choose that version for the Program.

  Later license versions may give you additional or different
permissions.  However, no additional obligations are imposed on any
author or copyright holder as a result of your choosing to follow a
later version.

  15. Disclaimer of Warranty.

  THERE IS NO WARRANTY FOR THE PROGRAM, TO THE EXTENT PERMITTED BY
APPLICABLE LAW.  EXCEPT WHEN OTHERWISE STATED IN WRITING THE COPYRIGHT
HOLDERS AND/OR OTHER PARTIES PROVIDE THE PROGRAM "AS IS" WITHOUT WARRANTY
OF ANY KIND, EITHER EXPRESSED OR IMPLIED, INCLUDING, BUT NOT LIMITED TO,
THE IMPLIED WARRANTIES OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR
PURPOSE.  THE ENTIRE RISK AS TO THE QUALITY AND PERFORMANCE OF THE PROGRAM
IS WITH YOU.  SHOULD THE PROGRAM PROVE DEFECTIVE, YOU ASSUME THE COST OF
ALL NECESSARY SERVICING, REPAIR OR CORRECTION.

  16. Limitation of Liability.

  IN NO EVENT UNLESS REQUIRED BY APPLICABLE LAW OR AGREED TO IN WRITING
WILL ANY COPYRIGHT HOLDER, OR ANY OTHER PARTY WHO MODIFIES AND/OR CONVEYS
THE PROGRAM AS PERMITTED ABOVE, BE LIABLE TO YOU FOR DAMAGES, INCLUDING ANY
GENERAL, SPECIAL, INCIDENTAL OR CONSEQUENTIAL DAMAGES ARISING OUT OF THE
USE OR INABILITY TO USE THE PROGRAM (INCLUDING BUT NOT LIMITED TO LOSS OF
DATA OR DATA BEING RENDERED INACCURATE OR LOSSES SUSTAINED BY YOU OR THIRD
PARTIES OR A FAILURE OF THE PROGRAM TO OPERATE WITH ANY OTHER PROGRAMS),
EVEN IF SUCH HOLDER OR OTHER PARTY HAS BEEN ADVISED OF THE POSSIBILITY OF
SUCH DAMAGES.

  17. Interpretation of Sections 15 and 16.

  If the disclaimer of warranty and limitation of liability provided
above cannot be given local legal effect according to their terms,
reviewing courts shall apply local law that most closely approximates
an absolute waiver of all civil liability in connection with the
Program, unless a warranty or assumption of liability accompanies a
copy of the Program in return for a fee.

                     END OF TERMS AND CONDITIONS

            How to Apply These Terms to Your New Programs

  If you develop a new program, and you want it to be of the greatest
possible use to the public, the best way to achieve this is to make it
free software which everyone can redistribute and change under these terms.

  To do so, attach the following notices to the program.  It is safest
to attach them to the start of each source file to most effectively
state the exclusion of warranty; and each file should have at least
the "copyright" line and a pointer to where the full notice is found.

    <one line to give the program's name and a brief idea of what it does.>

    This program is free software: you can redistribute it and/or modify
    it under the terms of the GNU General Public License as published by
    the Free Software Foundation, either version 3 of the License, or
    (at your option) any later version.

    This program is distributed in the hope that it will be useful,
    but WITHOUT ANY WARRANTY; without even the implied warranty of
    MERCHANTABILITY or FITNESS FOR A PARTICULAR PURPOSE.  See the
    GNU General Public License for more details.

    You should have received a copy of the GNU General Public License
    along with this program.  If not, see <https://www.gnu.org/licenses/>.

Also add information on how to contact you by electronic and paper mail.

  If the program does terminal interaction, make it output a short
notice like this when it starts in an interactive mode:

    <program>  Copyright (C) <year>  <name of author>
    This program comes with ABSOLUTELY NO WARRANTY; for details type `show w'.
    This is free software, and you are welcome to redistribute it
    under certain conditions; type `show c' for details.

The hypothetical commands `show w' and `show c' should show the appropriate
parts of the General Public License.  Of course, your program's commands
might be different; for a GUI interface, you would use an "about box".

  You should also get your employer (if you work as a programmer) or school,
if any, to sign a "copyright disclaimer" for the program, if necessary.
For more information on this, and how to apply and follow the GNU GPL, see
<https://www.gnu.org/licenses/>.

  The GNU General Public License does not permit incorporating your program
into proprietary programs.  If your program is a subroutine library, you
may consider it more useful to permit linking proprietary applications with
the library.  If this is what you want to do, use the GNU Lesser General
Public License instead of this License.  But first, please read
<https://www.gnu.org/licenses/why-not-lgpl.html>.""",
    "ISC": """Permission to use, copy, modify, and/or distribute this software for any
purpose with or without fee is hereby granted, provided that the above
copyright notice and this permission notice appear in all copies.

THE SOFTWARE IS PROVIDED "AS IS" AND THE AUTHOR DISCLAIMS ALL WARRANTIES
WITH REGARD TO THIS SOFTWARE INCLUDING ALL IMPLIED WARRANTIES OF
MERCHANTABILITY AND FITNESS. IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR
ANY SPECIAL, DIRECT, INDIRECT, OR CONSEQUENTIAL DAMAGES OR ANY DAMAGES
WHATSOEVER RESULTING FROM LOSS OF USE, DATA OR PROFITS, WHETHER IN AN
ACTION OF CONTRACT, NEGLIGENCE OR OTHER TORTIOUS ACTION, ARISING OUT OF
OR IN CONNECTION WITH THE USE OR PERFORMANCE OF THIS SOFTWARE.""",
    "LGPL-2.1": """GNU LESSER GENERAL PUBLIC LICENSE
                       Version 2.1, February 1999

 51 Franklin Street, Fifth Floor, Boston, MA  02110-1301  USA
 Everyone is permitted to copy and distribute verbatim copies
 of this license document, but changing it is not allowed.

[This is the first released version of the Lesser GPL.  It also counts
 as the successor of the GNU Library Public License, version 2, hence
 the version number 2.1.]

                            Preamble

  The licenses for most software are designed to take away your
freedom to share and change it.  By contrast, the GNU General Public
Licenses are intended to guarantee your freedom to share and change
free software--to make sure the software is free for all its users.

  This license, the Lesser General Public License, applies to some
specially designated software packages--typically libraries--of the
Free Software Foundation and other authors who decide to use it.  You
can use it too, but we suggest you first think carefully about whether
this license or the ordinary General Public License is the better
strategy to use in any particular case, based on the explanations below.

  When we speak of free software, we are referring to freedom of use,
not price.  Our General Public Licenses are designed to make sure that
you have the freedom to distribute copies of free software (and charge
for this service if you wish); that you receive source code or can get
it if you want it; that you can change the software and use pieces of
it in new free programs; and that you are informed that you can do
these things.

  To protect your rights, we need to make restrictions that forbid
distributors to deny you these rights or to ask you to surrender these
rights.  These restrictions translate to certain responsibilities for
you if you distribute copies of the library or if you modify it.

  For example, if you distribute copies of the library, whether gratis
or for a fee, you must give the recipients all the rights that we gave
you.  You must make sure that they, too, receive or can get the source
code.  If you link other code with the library, you must provide
complete object files to the recipients, so that they can relink them
with the library after making changes to the library and recompiling
it.  And you must show them these terms so they know their rights.

  We protect your rights with a two-step method: (1) we copyright the
library, and (2) we offer you this license, which gives you legal
permission to copy, distribute and/or modify the library.

  To protect each distributor, we want to make it very clear that
there is no warranty for the free library.  Also, if the library is
modified by someone else and passed on, the recipients should know
that what they have is not the original version, so that the original
author's reputation will not be affected by problems that might be
introduced by others.


  Finally, software patents pose a constant threat to the existence of
any free program.  We wish to make sure that a company cannot
effectively restrict the users of a free program by obtaining a
restrictive license from a patent holder.  Therefore, we insist that
any patent license obtained for a version of the library must be
consistent with the full freedom of use specified in this license.

  Most GNU software, including some libraries, is covered by the
ordinary GNU General Public License.  This license, the GNU Lesser
General Public License, applies to certain designated libraries, and
is quite different from the ordinary General Public License.  We use
this license for certain libraries in order to permit linking those
libraries into non-free programs.

  When a program is linked with a library, whether statically or using
a shared library, the combination of the two is legally speaking a
combined work, a derivative of the original library.  The ordinary
General Public License therefore permits such linking only if the
entire combination fits its criteria of freedom.  The Lesser General
Public License permits more lax criteria for linking other code with
the library.

  We call this license the "Lesser" General Public License because it
does Less to protect the user's freedom than the ordinary General
Public License.  It also provides other free software developers Less
of an advantage over competing non-free programs.  These disadvantages
are the reason we use the ordinary General Public License for many
libraries.  However, the Lesser license provides advantages in certain
special circumstances.

  For example, on rare occasions, there may be a special need to
encourage the widest possible use of a certain library, so that it becomes
a de-facto standard.  To achieve this, non-free programs must be
allowed to use the library.  A more frequent case is that a free
library does the same job as widely used non-free libraries.  In this
case, there is little to gain by limiting the free library to free
software only, so we use the Lesser General Public License.

  In other cases, permission to use a particular library in non-free
programs enables a greater number of people to use a large body of
free software.  For example, permission to use the GNU C Library in
non-free programs enables many more people to use the whole GNU
operating system, as well as its variant, the GNU/Linux operating
system.

  Although the Lesser General Public License is Less protective of the
users' freedom, it does ensure that the user of a program that is
linked with the Library has the freedom and the wherewithal to run
that program using a modified version of the Library.

  The precise terms and conditions for copying, distribution and
modification follow.  Pay close attention to the difference between a
"work based on the library" and a "work that uses the library".  The
former contains code derived from the library, whereas the latter must
be combined with the library in order to run.


                  GNU LESSER GENERAL PUBLIC LICENSE
   TERMS AND CONDITIONS FOR COPYING, DISTRIBUTION AND MODIFICATION

  0. This License Agreement applies to any software library or other
program which contains a notice placed by the copyright holder or
other authorized party saying it may be distributed under the terms of
this Lesser General Public License (also called "this License").
Each licensee is addressed as "you".

  A "library" means a collection of software functions and/or data
prepared so as to be conveniently linked with application programs
(which use some of those functions and data) to form executables.

  The "Library", below, refers to any such software library or work
which has been distributed under these terms.  A "work based on the
Library" means either the Library or any derivative work under
copyright law: that is to say, a work containing the Library or a
portion of it, either verbatim or with modifications and/or translated
straightforwardly into another language.  (Hereinafter, translation is
included without limitation in the term "modification".)

  "Source code" for a work means the preferred form of the work for
making modifications to it.  For a library, complete source code means
all the source code for all modules it contains, plus any associated
interface definition files, plus the scripts used to control compilation
and installation of the library.

  Activities other than copying, distribution and modification are not
covered by this License; they are outside its scope.  The act of
running a program using the Library is not restricted, and output from
such a program is covered only if its contents constitute a work based
on the Library (independent of the use of the Library in a tool for
writing it).  Whether that is true depends on what the Library does
and what the program that uses the Library does.

  1. You may copy and distribute verbatim copies of the Library's
complete source code as you receive it, in any medium, provided that
you conspicuously and appropriately publish on each copy an
appropriate copyright notice and disclaimer of warranty; keep intact
all the notices that refer to this License and to the absence of any
warranty; and distribute a copy of this License along with the
Library.

  You may charge a fee for the physical act of transferring a copy,
and you may at your option offer warranty protection in exchange for a
fee.


  2. You may modify your copy or copies of the Library or any portion
of it, thus forming a work based on the Library, and copy and
distribute such modifications or work under the terms of Section 1
above, provided that you also meet all of these conditions:

    a) The modified work must itself be a software library.

    b) You must cause the files modified to carry prominent notices
    stating that you changed the files and the date of any change.

    c) You must cause the whole of the work to be licensed at no
    charge to all third parties under the terms of this License.

    d) If a facility in the modified Library refers to a function or a
    table of data to be supplied by an application program that uses
    the facility, other than as an argument passed when the facility
    is invoked, then you must make a good faith effort to ensure that,
    in the event an application does not supply such function or
    table, the facility still operates, and performs whatever part of
    its purpose remains meaningful.

    (For example, a function in a library to compute square roots has
    a purpose that is entirely well-defined independent of the
    application.  Therefore, Subsection 2d requires that any
    application-supplied function or table used by this function must
    be optional: if the application does not supply it, the square
    root function must still compute square roots.)

These requirements apply to the modified work as a whole.  If
identifiable sections of that work are not derived from the Library,
and can be reasonably considered independent and separate works in
themselves, then this License, and its terms, do not apply to those
sections when you distribute them as separate works.  But when you
distribute the same sections as part of a whole which is a work based
on the Library, the distribution of the whole must be on the terms of
this License, whose permissions for other licensees extend to the
entire whole, and thus to each and every part regardless of who wrote
it.

Thus, it is not the intent of this section to claim rights or contest
your rights to work written entirely by you; rather, the intent is to
exercise the right to control the distribution of derivative or
collective works based on the Library.

In addition, mere aggregation of another work not based on the Library
with the Library (or with a work based on the Library) on a volume of
a storage or distribution medium does not bring the other work under
the scope of this License.

  3. You may opt to apply the terms of the ordinary GNU General Public
License instead of this License to a given copy of the Library.  To do
this, you must alter all the notices that refer to this License, so
that they refer to the ordinary GNU General Public License, version 2,
instead of to this License.  (If a newer version than version 2 of the
ordinary GNU General Public License has appeared, then you can specify
that version instead if you wish.)  Do not make any other change in
these notices.


  Once this change is made in a given copy, it is irreversible for
that copy, so the ordinary GNU General Public License applies to all
subsequent copies and derivative works made from that copy.

  This option is useful when you wish to copy part of the code of
the Library into a program that is not a library.

  4. You may copy and distribute the Library (or a portion or
derivative of it, under Section 2) in object code or executable form
under the terms of Sections 1 and 2 above provided that you accompany
it with the complete corresponding machine-readable source code, which
must be distributed under the terms of Sections 1 and 2 above on a
medium customarily used for software interchange.

  If distribution of object code is made by offering access to copy
from a designated place, then offering equivalent access to copy the
source code from the same place satisfies the requirement to
distribute the source code, even though third parties are not
compelled to copy the source along with the object code.

  5. A program that contains no derivative of any portion of the
Library, but is designed to work with the Library by being compiled or
linked with it, is called a "work that uses the Library".  Such a
work, in isolation, is not a derivative work of the Library, and
therefore falls outside the scope of this License.

  However, linking a "work that uses the Library" with the Library
creates an executable that is a derivative of the Library (because it
contains portions of the Library), rather than a "work that uses the
library".  The executable is therefore covered by this License.
Section 6 states terms for distribution of such executables.

  When a "work that uses the Library" uses material from a header file
that is part of the Library, the object code for the work may be a
derivative work of the Library even though the source code is not.
Whether this is true is especially significant if the work can be
linked without the Library, or if the work is itself a library.  The
threshold for this to be true is not precisely defined by law.

  If such an object file uses only numerical parameters, data
structure layouts and accessors, and small macros and small inline
functions (ten lines or less in length), then the use of the object
file is unrestricted, regardless of whether it is legally a derivative
work.  (Executables containing this object code plus portions of the
Library will still fall under Section 6.)

  Otherwise, if the work is a derivative of the Library, you may
distribute the object code for the work under the terms of Section 6.
Any executables containing that work also fall under Section 6,
whether or not they are linked directly with the Library itself.


  6. As an exception to the Sections above, you may also combine or
link a "work that uses the Library" with the Library to produce a
work containing portions of the Library, and distribute that work
under terms of your choice, provided that the terms permit
modification of the work for the customer's own use and reverse
engineering for debugging such modifications.

  You must give prominent notice with each copy of the work that the
Library is used in it and that the Library and its use are covered by
this License.  You must supply a copy of this License.  If the work
during execution displays copyright notices, you must include the
copyright notice for the Library among them, as well as a reference
directing the user to the copy of this License.  Also, you must do one
of these things:

    a) Accompany the work with the complete corresponding
    machine-readable source code for the Library including whatever
    changes were used in the work (which must be distributed under
    Sections 1 and 2 above); and, if the work is an executable linked
    with the Library, with the complete machine-readable "work that
    uses the Library", as object code and/or source code, so that the
    user can modify the Library and then relink to produce a modified
    executable containing the modified Library.  (It is understood
    that the user who changes the contents of definitions files in the
    Library will not necessarily be able to recompile the application
    to use the modified definitions.)

    b) Use a suitable shared library mechanism for linking with the
    Library.  A suitable mechanism is one that (1) uses at run time a
    copy of the library already present on the user's computer system,
    rather than copying library functions into the executable, and (2)
    will operate properly with a modified version of the library, if
    the user installs one, as long as the modified version is
    interface-compatible with the version that the work was made with.

    c) Accompany the work with a written offer, valid for at
    least three years, to give the same user the materials
    specified in Subsection 6a, above, for a charge no more
    than the cost of performing this distribution.

    d) If distribution of the work is made by offering access to copy
    from a designated place, offer equivalent access to copy the above
    specified materials from the same place.

    e) Verify that the user has already received a copy of these
    materials or that you have already sent this user a copy.

  For an executable, the required form of the "work that uses the
Library" must include any data and utility programs needed for
reproducing the executable from it.  However, as a special exception,
the materials to be distributed need not include anything that is
normally distributed (in either source or binary form) with the major
components (compiler, kernel, and so on) of the operating system on
which the executable runs, unless that component itself accompanies
the executable.

  It may happen that this requirement contradicts the license
restrictions of other proprietary libraries that do not normally
accompany the operating system.  Such a contradiction means you cannot
use both them and the Library together in an executable that you
distribute.


  7. You may place library facilities that are a work based on the
Library side-by-side in a single library together with other library
facilities not covered by this License, and distribute such a combined
library, provided that the separate distribution of the work based on
the Library and of the other library facilities is otherwise
permitted, and provided that you do these two things:

    a) Accompany the combined library with a copy of the same work
    based on the Library, uncombined with any other library
    facilities.  This must be distributed under the terms of the
    Sections above.

    b) Give prominent notice with the combined library of the fact
    that part of it is a work based on the Library, and explaining
    where to find the accompanying uncombined form of the same work.

  8. You may not copy, modify, sublicense, link with, or distribute
the Library except as expressly provided under this License.  Any
attempt otherwise to copy, modify, sublicense, link with, or
distribute the Library is void, and will automatically terminate your
rights under this License.  However, parties who have received copies,
or rights, from you under this License will not have their licenses
terminated so long as such parties remain in full compliance.

  9. You are not required to accept this License, since you have not
signed it.  However, nothing else grants you permission to modify or
distribute the Library or its derivative works.  These actions are
prohibited by law if you do not accept this License.  Therefore, by
modifying or distributing the Library (or any work based on the
Library), you indicate your acceptance of this License to do so, and
all its terms and conditions for copying, distributing or modifying
the Library or works based on it.

  10. Each time you redistribute the Library (or any work based on the
Library), the recipient automatically receives a license from the
original licensor to copy, distribute, link with or modify the Library
subject to these terms and conditions.  You may not impose any further
restrictions on the recipients' exercise of the rights granted herein.
You are not responsible for enforcing compliance by third parties with
this License.


  11. If, as a consequence of a court judgment or allegation of patent
infringement or for any other reason (not limited to patent issues),
conditions are imposed on you (whether by court order, agreement or
otherwise) that contradict the conditions of this License, they do not
excuse you from the conditions of this License.  If you cannot
distribute so as to satisfy simultaneously your obligations under this
License and any other pertinent obligations, then as a consequence you
may not distribute the Library at all.  For example, if a patent
license would not permit royalty-free redistribution of the Library by
all those who receive copies directly or indirectly through you, then
the only way you could satisfy both it and this License would be to
refrain entirely from distribution of the Library.

If any portion of this section is held invalid or unenforceable under any
particular circumstance, the balance of the section is intended to apply,
and the section as a whole is intended to apply in other circumstances.

It is not the purpose of this section to induce you to infringe any
patents or other property right claims or to contest validity of any
such claims; this section has the sole purpose of protecting the
integrity of the free software distribution system which is
implemented by public license practices.  Many people have made
generous contributions to the wide range of software distributed
through that system in reliance on consistent application of that
system; it is up to the author/donor to decide if he or she is willing
to distribute software through any other system and a licensee cannot
impose that choice.

This section is intended to make thoroughly clear what is believed to
be a consequence of the rest of this License.

  12. If the distribution and/or use of the Library is restricted in
certain countries either by patents or by copyrighted interfaces, the
original copyright holder who places the Library under this License may add
an explicit geographical distribution limitation excluding those countries,
so that distribution is permitted only in or among countries not thus
excluded.  In such case, this License incorporates the limitation as if
written in the body of this License.

  13. The Free Software Foundation may publish revised and/or new
versions of the Lesser General Public License from time to time.
Such new versions will be similar in spirit to the present version,
but may differ in detail to address new problems or concerns.

Each version is given a distinguishing version number.  If the Library
specifies a version number of this License which applies to it and
"any later version", you have the option of following the terms and
conditions either of that version or of any later version published by
the Free Software Foundation.  If the Library does not specify a
license version number, you may choose any version ever published by
the Free Software Foundation.


  14. If you wish to incorporate parts of the Library into other free
programs whose distribution conditions are incompatible with these,
write to the author to ask for permission.  For software which is
copyrighted by the Free Software Foundation, write to the Free
Software Foundation; we sometimes make exceptions for this.  Our
decision will be guided by the two goals of preserving the free status
of all derivatives of our free software and of promoting the sharing
and reuse of software generally.

                            NO WARRANTY

  15. BECAUSE THE LIBRARY IS LICENSED FREE OF CHARGE, THERE IS NO
WARRANTY FOR THE LIBRARY, TO THE EXTENT PERMITTED BY APPLICABLE LAW.
EXCEPT WHEN OTHERWISE STATED IN WRITING THE COPYRIGHT HOLDERS AND/OR
OTHER PARTIES PROVIDE THE LIBRARY "AS IS" WITHOUT WARRANTY OF ANY
KIND, EITHER EXPRESSED OR IMPLIED, INCLUDING, BUT NOT LIMITED TO, THE
IMPLIED WARRANTIES OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR
PURPOSE.  THE ENTIRE RISK AS TO THE QUALITY AND PERFORMANCE OF THE
LIBRARY IS WITH YOU.  SHOULD THE LIBRARY PROVE DEFECTIVE, YOU ASSUME
THE COST OF ALL NECESSARY SERVICING, REPAIR OR CORRECTION.

  16. IN NO EVENT UNLESS REQUIRED BY APPLICABLE LAW OR AGREED TO IN
WRITING WILL ANY COPYRIGHT HOLDER, OR ANY OTHER PARTY WHO MAY MODIFY
AND/OR REDISTRIBUTE THE LIBRARY AS PERMITTED ABOVE, BE LIABLE TO YOU
FOR DAMAGES, INCLUDING ANY GENERAL, SPECIAL, INCIDENTAL OR
CONSEQUENTIAL DAMAGES ARISING OUT OF THE USE OR INABILITY TO USE THE
LIBRARY (INCLUDING BUT NOT LIMITED TO LOSS OF DATA OR DATA BEING
RENDERED INACCURATE OR LOSSES SUSTAINED BY YOU OR THIRD PARTIES OR A
FAILURE OF THE LIBRARY TO OPERATE WITH ANY OTHER SOFTWARE), EVEN IF
SUCH HOLDER OR OTHER PARTY HAS BEEN ADVISED OF THE POSSIBILITY OF SUCH
DAMAGES.

                     END OF TERMS AND CONDITIONS


           How to Apply These Terms to Your New Libraries

  If you develop a new library, and you want it to be of the greatest
possible use to the public, we recommend making it free software that
everyone can redistribute and change.  You can do so by permitting
redistribution under these terms (or, alternatively, under the terms of the
ordinary General Public License).

  To apply these terms, attach the following notices to the library.  It is
safest to attach them to the start of each source file to most effectively
convey the exclusion of warranty; and each file should have at least the
"copyright" line and a pointer to where the full notice is found.

    <one line to give the library's name and a brief idea of what it does.>

    This library is free software; you can redistribute it and/or
    modify it under the terms of the GNU Lesser General Public
    License as published by the Free Software Foundation; either
    version 2.1 of the License, or (at your option) any later version.

    This library is distributed in the hope that it will be useful,
    but WITHOUT ANY WARRANTY; without even the implied warranty of
    MERCHANTABILITY or FITNESS FOR A PARTICULAR PURPOSE.  See the GNU
    Lesser General Public License for more details.

    You should have received a copy of the GNU Lesser General Public
    License along with this library; if not, write to the Free Software
    Foundation, Inc., 51 Franklin Street, Fifth Floor, Boston, MA  02110-1301  USA

Also add information on how to contact you by electronic and paper mail.

You should also get your employer (if you work as a programmer) or your
school, if any, to sign a "copyright disclaimer" for the library, if
necessary.  Here is a sample; alter the names:

  Yoyodyne, Inc., hereby disclaims all copyright interest in the
  library `Frob' (a library for tweaking knobs) written by James Random Hacker.

  <signature of Ty Coon>, 1 April 1990
  Ty Coon, President of Vice

That's all there is to it!""",
    "LGPL-3.0": """GNU LESSER GENERAL PUBLIC LICENSE
                       Version 3, 29 June 2007

 Everyone is permitted to copy and distribute verbatim copies
 of this license document, but changing it is not allowed.


  This version of the GNU Lesser General Public License incorporates
the terms and conditions of version 3 of the GNU General Public
License, supplemented by the additional permissions listed below.

  0. Additional Definitions.

  As used herein, "this License" refers to version 3 of the GNU Lesser
General Public License, and the "GNU GPL" refers to version 3 of the GNU
General Public License.

  "The Library" refers to a covered work governed by this License,
other than an Application or a Combined Work as defined below.

  An "Application" is any work that makes use of an interface provided
by the Library, but which is not otherwise based on the Library.
Defining a subclass of a class defined by the Library is deemed a mode
of using an interface provided by the Library.

  A "Combined Work" is a work produced by combining or linking an
Application with the Library.  The particular version of the Library
with which the Combined Work was made is also called the "Linked
Version".

  The "Minimal Corresponding Source" for a Combined Work means the
Corresponding Source for the Combined Work, excluding any source code
for portions of the Combined Work that, considered in isolation, are
based on the Application, and not on the Linked Version.

  The "Corresponding Application Code" for a Combined Work means the
object code and/or source code for the Application, including any data
and utility programs needed for reproducing the Combined Work from the
Application, but excluding the System Libraries of the Combined Work.

  1. Exception to Section 3 of the GNU GPL.

  You may convey a covered work under sections 3 and 4 of this License
without being bound by section 3 of the GNU GPL.

  2. Conveying Modified Versions.

  If you modify a copy of the Library, and, in your modifications, a
facility refers to a function or data to be supplied by an Application
that uses the facility (other than as an argument passed when the
facility is invoked), then you may convey a copy of the modified
version:

   a) under this License, provided that you make a good faith effort to
   ensure that, in the event an Application does not supply the
   function or data, the facility still operates, and performs
   whatever part of its purpose remains meaningful, or

   b) under the GNU GPL, with none of the additional permissions of
   this License applicable to that copy.

  3. Object Code Incorporating Material from Library Header Files.

  The object code form of an Application may incorporate material from
a header file that is part of the Library.  You may convey such object
code under terms of your choice, provided that, if the incorporated
material is not limited to numerical parameters, data structure
layouts and accessors, or small macros, inline functions and templates
(ten or fewer lines in length), you do both of the following:

   a) Give prominent notice with each copy of the object code that the
   Library is used in it and that the Library and its use are
   covered by this License.

   b) Accompany the object code with a copy of the GNU GPL and this license
   document.

  4. Combined Works.

  You may convey a Combined Work under terms of your choice that,
taken together, effectively do not restrict modification of the
portions of the Library contained in the Combined Work and reverse
engineering for debugging such modifications, if you also do each of
the following:

   a) Give prominent notice with each copy of the Combined Work that
   the Library is used in it and that the Library and its use are
   covered by this License.

   b) Accompany the Combined Work with a copy of the GNU GPL and this license
   document.

   c) For a Combined Work that displays copyright notices during
   execution, include the copyright notice for the Library among
   these notices, as well as a reference directing the user to the
   copies of the GNU GPL and this license document.

   d) Do one of the following:

       0) Convey the Minimal Corresponding Source under the terms of this
       License, and the Corresponding Application Code in a form
       suitable for, and under terms that permit, the user to
       recombine or relink the Application with a modified version of
       the Linked Version to produce a modified Combined Work, in the
       manner specified by section 6 of the GNU GPL for conveying
       Corresponding Source.

       1) Use a suitable shared library mechanism for linking with the
       Library.  A suitable mechanism is one that (a) uses at run time
       a copy of the Library already present on the user's computer
       system, and (b) will operate properly with a modified version
       of the Library that is interface-compatible with the Linked
       Version.

   e) Provide Installation Information, but only if you would otherwise
   be required to provide such information under section 6 of the
   GNU GPL, and only to the extent that such information is
   necessary to install and execute a modified version of the
   Combined Work produced by recombining or relinking the
   Application with a modified version of the Linked Version. (If
   you use option 4d0, the Installation Information must accompany
   the Minimal Corresponding Source and Corresponding Application
   Code. If you use option 4d1, you must provide the Installation
   Information in the manner specified by section 6 of the GNU GPL
   for conveying Corresponding Source.)

  5. Combined Libraries.

  You may place library facilities that are a work based on the
Library side by side in a single library together with other library
facilities that are not Applications and are not covered by this
License, and convey such a combined library under terms of your
choice, if you do both of the following:

   a) Accompany the combined library with a copy of the same work based
   on the Library, uncombined with any other library facilities,
   conveyed under the terms of this License.

   b) Give prominent notice with the combined library that part of it
   is a work based on the Library, and explaining where to find the
   accompanying uncombined form of the same work.

  6. Revised Versions of the GNU Lesser General Public License.

  The Free Software Foundation may publish revised and/or new versions
of the GNU Lesser General Public License from time to time. Such new
versions will be similar in spirit to the present version, but may
differ in detail to address new problems or concerns.

  Each version is given a distinguishing version number. If the
Library as you received it specifies that a certain numbered version
of the GNU Lesser General Public License "or any later version"
applies to it, you have the option of following the terms and
conditions either of that published version or of any later version
published by the Free Software Foundation. If the Library as you
received it does not specify a version number of the GNU Lesser
General Public License, you may choose any version of the GNU Lesser
General Public License ever published by the Free Software Foundation.

  If the Library as you received it specifies that a proxy can decide
whether future versions of the GNU Lesser General Public License shall
apply, that proxy's public statement of acceptance of any version is
permanent authorization for you to choose that version for the
Library.""",
    "MIT": """The MIT License (MIT)


Permission is hereby granted, free of charge, to any person obtaining a copy
of this software and associated documentation files (the "Software"), to deal
in the Software without restriction, including without limitation the rights
to use, copy, modify, merge, publish, distribute, sublicense, and/or sell
copies of the Software, and to permit persons to whom the Software is
furnished to do so, subject to the following conditions:

The above copyright notice and this permission notice shall be included in all
copies or substantial portions of the Software.

THE SOFTWARE IS PROVIDED "AS IS", WITHOUT WARRANTY OF ANY KIND, EXPRESS OR
IMPLIED, INCLUDING BUT NOT LIMITED TO THE WARRANTIES OF MERCHANTABILITY,
FITNESS FOR A PARTICULAR PURPOSE AND NONINFRINGEMENT. IN NO EVENT SHALL THE
AUTHORS OR COPYRIGHT HOLDERS BE LIABLE FOR ANY CLAIM, DAMAGES OR OTHER
LIABILITY, WHETHER IN AN ACTION OF CONTRACT, TORT OR OTHERWISE, ARISING FROM,
OUT OF OR IN CONNECTION WITH THE SOFTWARE OR THE USE OR OTHER DEALINGS IN THE
SOFTWARE.""",
    "MPL-2.0": """Mozilla Public License Version 2.0
==================================

1. Definitions
--------------

1.1. "Contributor"
    means each individual or legal entity that creates, contributes to
    the creation of, or owns Covered Software.

1.2. "Contributor Version"
    means the combination of the Contributions of others (if any) used
    by a Contributor and that particular Contributor's Contribution.

1.3. "Contribution"
    means Covered Software of a particular Contributor.

1.4. "Covered Software"
    means Source Code Form to which the initial Contributor has attached
    the notice in Exhibit A, the Executable Form of such Source Code
    Form, and Modifications of such Source Code Form, in each case
    including portions thereof.

1.5. "Incompatible With Secondary Licenses"
    means

    (a) that the initial Contributor has attached the notice described
        in Exhibit B to the Covered Software; or

    (b) that the Covered Software was made available under the terms of
        version 1.1 or earlier of the License, but not also under the
        terms of a Secondary License.

1.6. "Executable Form"
    means any form of the work other than Source Code Form.

1.7. "Larger Work"
    means a work that combines Covered Software with other material, in 
    a separate file or files, that is not Covered Software.

1.8. "License"
    means this document.

1.9. "Licensable"
    means having the right to grant, to the maximum extent possible,
    whether at the time of the initial grant or subsequently, any and
    all of the rights conveyed by this License.

1.10. "Modifications"
    means any of the following:

    (a) any file in Source Code Form that results from an addition to,
        deletion from, or modification of the contents of Covered
        Software; or

    (b) any new file in Source Code Form that contains any Covered
        Software.

1.11. "Patent Claims" of a Contributor
    means any patent claim(s), including without limitation, method,
    process, and apparatus claims, in any patent Licensable by such
    Contributor that would be infringed, but for the grant of the
    License, by the making, using, selling, offering for sale, having
    made, import, or transfer of either its Contributions or its
    Contributor Version.

1.12. "Secondary License"
    means either the GNU General Public License, Version 2.0, the GNU
    Lesser General Public License, Version 2.1, the GNU Affero General
    Public License, Version 3.0, or any later versions of those
    licenses.

1.13. "Source Code Form"
    means the form of the work preferred for making modifications.

1.14. "You" (or "Your")
    means an individual or a legal entity exercising rights under this
    License. For legal entities, "You" includes any entity that
    controls, is controlled by, or is under common control with You. For
    purposes of this definition, "control" means (a) the power, direct
    or indirect, to cause the direction or management of such entity,
    whether by contract or otherwise, or (b) ownership of more than
    fifty percent (50%) of the outstanding shares or beneficial
    ownership of such entity.

2. License Grants and Conditions
--------------------------------

2.1. Grants

Each Contributor hereby grants You a world-wide, royalty-free,
non-exclusive license:

(a) under intellectual property rights (other than patent or trademark)
    Licensable by such Contributor to use, reproduce, make available,
    modify, display, perform, distribute, and otherwise exploit its
    Contributions, either on an unmodified basis, with Modifications, or
    as part of a Larger Work; and

(b) under Patent Claims of such Contributor to make, use, sell, offer
    for sale, have made, import, and otherwise transfer either its
    Contributions or its Contributor Version.

2.2. Effective Date

The licenses granted in Section 2.1 with respect to any Contribution
become effective for each Contribution on the date the Contributor first
distributes such Contribution.

2.3. Limitations on Grant Scope

The licenses granted in this Section 2 are the only rights granted under
this License. No additional rights or licenses will be implied from the
distribution or licensing of Covered Software under this License.
Notwithstanding Section 2.1(b) above, no patent license is granted by a
Contributor:

(a) for any code that a Contributor has removed from Covered Software;
    or

(b) for infringements caused by: (i) Your and any other third party's
    modifications of Covered Software, or (ii) the combination of its
    Contributions with other software (except as part of its Contributor
    Version); or

(c) under Patent Claims infringed by Covered Software in the absence of
    its Contributions.

This License does not grant any rights in the trademarks, service marks,
or logos of any Contributor (except as may be necessary to comply with
the notice requirements in Section 3.4).

2.4. Subsequent Licenses

No Contributor makes additional grants as a result of Your choice to
distribute the Covered Software under a subsequent version of this
License (see Section 10.2) or under the terms of a Secondary License (if
permitted under the terms of Section 3.3).

2.5. Representation

Each Contributor represents that the Contributor believes its
Contributions are its original creation(s) or it has sufficient rights
to grant the rights to its Contributions conveyed by this License.

2.6. Fair Use

This License is not intended to limit any rights You have under
applicable copyright doctrines of fair use, fair dealing, or other
equivalents.

2.7. Conditions

Sections 3.1, 3.2, 3.3, and 3.4 are conditions of the licenses granted
in Section 2.1.

3. Responsibilities
-------------------

3.1. Distribution of Source Form

All distribution of Covered Software in Source Code Form, including any
Modifications that You create or to which You contribute, must be under
the terms of this License. You must inform recipients that the Source
Code Form of the Covered Software is governed by the terms of this
License, and how they can obtain a copy of this License. You may not
attempt to alter or restrict the recipients' rights in the Source Code
Form.

3.2. Distribution of Executable Form

If You distribute Covered Software in Executable Form then:

(a) such Covered Software must also be made available in Source Code
    Form, as described in Section 3.1, and You must inform recipients of
    the Executable Form how they can obtain a copy of such Source Code
    Form by reasonable means in a timely manner, at a charge no more
    than the cost of distribution to the recipient; and

(b) You may distribute such Executable Form under the terms of this
    License, or sublicense it under different terms, provided that the
    license for the Executable Form does not attempt to limit or alter
    the recipients' rights in the Source Code Form under this License.

3.3. Distribution of a Larger Work

You may create and distribute a Larger Work under terms of Your choice,
provided that You also comply with the requirements of this License for
the Covered Software. If the Larger Work is a combination of Covered
Software with a work governed by one or more Secondary Licenses, and the
Covered Software is not Incompatible With Secondary Licenses, this
License permits You to additionally distribute such Covered Software
under the terms of such Secondary License(s), so that the recipient of
the Larger Work may, at their option, further distribute the Covered
Software under the terms of either this License or such Secondary
License(s).

3.4. Notices

You may not remove or alter the substance of any license notices
(including copyright notices, patent notices, disclaimers of warranty,
or limitations of liability) contained within the Source Code Form of
the Covered Software, except that You may alter any license notices to
the extent required to remedy known factual inaccuracies.

3.5. Application of Additional Terms

You may choose to offer, and to charge a fee for, warranty, support,
indemnity or liability obligations to one or more recipients of Covered
Software. However, You may do so only on Your own behalf, and not on
behalf of any Contributor. You must make it absolutely clear that any
such warranty, support, indemnity, or liability obligation is offered by
You alone, and You hereby agree to indemnify every Contributor for any
liability incurred by such Contributor as a result of warranty, support,
indemnity or liability terms You offer. You may include additional
disclaimers of warranty and limitations of liability specific to any
jurisdiction.

4. Inability to Comply Due to Statute or Regulation
---------------------------------------------------

If it is impossible for You to comply with any of the terms of this
License with respect to some or all of the Covered Software due to
statute, judicial order, or regulation then You must: (a) comply with
the terms of this License to the maximum extent possible; and (b)
describe the limitations and the code they affect. Such description must
be placed in a text file included with all distributions of the Covered
Software under this License. Except to the extent prohibited by statute
or regulation, such description must be sufficiently detailed for a
recipient of ordinary skill to be able to understand it.

5. Termination
--------------

5.1. The rights granted under this License will terminate automatically
if You fail to comply with any of its terms. However, if You become
compliant, then the rights granted under this License from a particular
Contributor are reinstated (a) provisionally, unless and until such
Contributor explicitly and finally terminates Your grants, and (b) on an
ongoing basis, if such Contributor fails to notify You of the
non-compliance by some reasonable means prior to 60 days after You have
come back into compliance. Moreover, Your grants from a particular
Contributor are reinstated on an ongoing basis if such Contributor
notifies You of the non-compliance by some reasonable means, this is the
first time You have received notice of non-compliance with this License
from such Contributor, and You become compliant prior to 30 days after
Your receipt of the notice.

5.2. If You initiate litigation against any entity by asserting a patent
infringement claim (excluding declaratory judgment actions,
counter-claims, and cross-claims) alleging that a Contributor Version
directly or indirectly infringes any patent, then the rights granted to
You by any and all Contributors for the Covered Software under Section
2.1 of this License shall terminate.

5.3. In the event of termination under Sections 5.1 or 5.2 above, all
end user license agreements (excluding distributors and resellers) which
have been validly granted by You or Your distributors under this License
prior to termination shall survive termination.

************************************************************************
*                                                                      *
*  6. Disclaimer of Warranty                                           *
*  -------------------------                                           *
*                                                                      *
*  Covered Software is provided under this License on an "as is"       *
*  basis, without warranty of any kind, either expressed, implied, or  *
*  statutory, including, without limitation, warranties that the       *
*  Covered Software is free of defects, merchantable, fit for a        *
*  particular purpose or non-infringing. The entire risk as to the     *
*  quality and performance of the Covered Software is with You.        *
*  Should any Covered Software prove defective in any respect, You     *
*  (not any Contributor) assume the cost of any necessary servicing,   *
*  repair, or correction. This disclaimer of warranty constitutes an   *
*  essential part of this License. No use of any Covered Software is   *
*  authorized under this License except under this disclaimer.         *
*                                                                      *
************************************************************************

************************************************************************
*                                                                      *
*  7. Limitation of Liability                                          *
*  --------------------------                                          *
*                                                                      *
*  Under no circumstances and under no legal theory, whether tort      *
*  (including negligence), contract, or otherwise, shall any           *
*  Contributor, or anyone who distributes Covered Software as          *
*  permitted above, be liable to You for any direct, indirect,         *
*  special, incidental, or consequential damages of any character      *
*  including, without limitation, damages for lost profits, loss of    *
*  goodwill, work stoppage, computer failure or malfunction, or any    *
*  and all other commercial damages or losses, even if such party      *
*  shall have been informed of the possibility of such damages. This   *
*  limitation of liability shall not apply to liability for death or   *
*  personal injury resulting from such party's negligence to the       *
*  extent applicable law prohibits such limitation. Some               *
*  jurisdictions do not allow the exclusion or limitation of           *
*  incidental or consequential damages, so this exclusion and          *
*  limitation may not apply to You.                                    *
*                                                                      *
************************************************************************

8. Litigation
-------------

Any litigation relating to this License may be brought only in the
courts of a jurisdiction where the defendant maintains its principal
place of business and such litigation shall be governed by laws of that
jurisdiction, without reference to its conflict-of-law provisions.
Nothing in this Section shall prevent a party's ability to bring
cross-claims or counter-claims.

9. Miscellaneous
----------------

This License represents the complete agreement concerning the subject
matter hereof. If any provision of this License is held to be
unenforceable, such provision shall be reformed only to the extent
necessary to make it enforceable. Any law or regulation which provides
that the language of a contract shall be construed against the drafter
shall not be used to construe this License against a Contributor.

10. Versions of the License
---------------------------

10.1. New Versions

Mozilla Foundation is the license steward. Except as provided in Section
10.3, no one other than the license steward has the right to modify or
publish new versions of this License. Each version will be given a
distinguishing version number.

10.2. Effect of New Versions

You may distribute the Covered Software under the terms of the version
of the License under which You originally received the Covered Software,
or under the terms of any subsequent version published by the license
steward.

10.3. Modified Versions

If you create software not governed by this License, and you want to
create a new license for such software, you may create and use a
modified version of this License if you rename the license and remove
any references to the name of the license steward (except to note that
such modified license differs from this License).

10.4. Distributing Source Code Form that is Incompatible With Secondary
Licenses

If You choose to distribute Source Code Form that is Incompatible With
Secondary Licenses under the terms of this version of the License, the
notice described in Exhibit B of this License must be attached.

Exhibit A - Source Code Form License Notice
-------------------------------------------

  This Source Code Form is subject to the terms of the Mozilla Public
  License, v. 2.0. If a copy of the MPL was not distributed with this
  file, You can obtain one at http://mozilla.org/MPL/2.0/.

If it is not possible or desirable to put the notice in a particular
file, then You may include the notice in a location (such as a LICENSE
file in a relevant directory) where a recipient would be likely to look
for such a notice.

You may add additional accurate notices of copyright ownership.

Exhibit B - "Incompatible With Secondary Licenses" Notice
---------------------------------------------------------

  This Source Code Form is "Incompatible With Secondary Licenses", as
  defined by the Mozilla Public License, v. 2.0.""",
    "Unlicense": """This is free and unencumbered software released into the public domain.

Anyone is free to copy, modify, publish, use, compile, sell, or
distribute this software, either in source code form or as a compiled
binary, for any purpose, commercial or non-commercial, and by any
means.

In jurisdictions that recognize copyright laws, the author or authors
of this software dedicate any and all copyright interest in the
software to the public domain. We make this dedication for the benefit
of the public at large and to the detriment of our heirs and
successors. We intend this dedication to be an overt act of
relinquishment in perpetuity of all present and future rights to this
software under copyright law.

THE SOFTWARE IS PROVIDED "AS IS", WITHOUT WARRANTY OF ANY KIND,
EXPRESS OR IMPLIED, INCLUDING BUT NOT LIMITED TO THE WARRANTIES OF
MERCHANTABILITY, FITNESS FOR A PARTICULAR PURPOSE AND
NONINFRINGEMENT. IN NO EVENT SHALL THE AUTHORS BE LIABLE FOR ANY
CLAIM, DAMAGES OR OTHER LIABILITY, WHETHER IN AN ACTION OF CONTRACT,
TORT OR OTHERWISE, ARISING FROM, OUT OF OR IN CONNECTION WITH THE
SOFTWARE OR THE USE OR OTHER DEALINGS IN THE SOFTWARE.

For more information, please refer to <https://unlicense.org>""",
    "Zlib": """This software is provided 'as-is', without any express or implied
warranty. In no event will the authors be held liable for any damages
arising from the use of this software.

Permission is granted to anyone to use this software for any purpose,
including commercial applications, and to alter it and redistribute it
freely, subject to the following restrictions:

1. The origin of this software must not be misrepresented; you must not
   claim that you wrote the original software. If you use this software
   in a product, an acknowledgment in the product documentation would be
   appreciated but is not required.
2. Altered source versions must be plainly marked as such, and must not be
   misrepresented as being the original software.
3. This notice may not be removed or altered from any source distribution.""",
}
