"""License full-text classification for `--license-full` scans.

The reference delegates to google/licenseclassifier v2
(pkg/licensing/classifier.go:36-87), a token-ngram matcher over the
SPDX corpus.  Shipping the full corpus is out of scope here; the same
ALGORITHM runs over distinctive excerpts of the licenses that dominate
real artifacts: each license compiles to a set of word trigrams, a
document's trigram set is intersected with it, and confidence is the
contained fraction — tolerant of reflowed text, punctuation and small
edits, unlike exact phrase search.  Explicit `SPDX-License-Identifier:`
tags classify at confidence 1.0.  Findings below the confidence level
are dropped, mirroring classifier.go:57-60.

Custom corpora extend coverage: `add_license_text(name, text)` compiles
any license body into the matcher at runtime.
"""

from __future__ import annotations

import re

from trivy_tpu.types.artifact import LicenseFile, LicenseFinding

# File type markers (reference fanal/types: LicenseTypeHeader / File)
TYPE_HEADER = "header"
TYPE_FILE = "license-file"

_SPDX_TAG_RE = re.compile(
    r"SPDX-License-Identifier:\s*([A-Za-z0-9+.\-() ]+?)\s*(?:\*/|-->|$)",
    re.MULTILINE,
)

# Phrases are matched against lowercased text with collapsed whitespace
# and stripped punctuation.  Every phrase list starts with the most
# distinctive sentence of the license body.
_FINGERPRINTS: dict[str, list[str]] = {
    "MIT": [
        "permission is hereby granted free of charge to any person "
        "obtaining a copy of this software",
        "the software is provided as is without warranty of any kind",
        "subject to the following conditions",
    ],
    "Apache-2.0": [
        "apache license version 2 0",
        "licensed under the apache license version 2 0",
        "unless required by applicable law or agreed to in writing",
        "www apache org licenses license 2 0",
    ],
    "BSD-3-Clause": [
        "redistribution and use in source and binary forms",
        "neither the name of",
        "this software is provided by the copyright holders and "
        "contributors as is",
    ],
    "BSD-2-Clause": [
        "redistribution and use in source and binary forms",
        "this software is provided by the copyright holders and "
        "contributors as is",
    ],
    "GPL-2.0": [
        "gnu general public license version 2",
        "free software foundation either version 2 of the license",
        "this program is distributed in the hope that it will be useful",
    ],
    "GPL-3.0": [
        "gnu general public license version 3",
        "free software foundation either version 3 of the license",
        "this program is distributed in the hope that it will be useful",
    ],
    "LGPL-2.1": [
        "gnu lesser general public license version 2 1",
        "free software foundation either version 2 1 of the license",
    ],
    "LGPL-3.0": [
        "gnu lesser general public license version 3",
        "free software foundation either version 3 of the license",
    ],
    "AGPL-3.0": [
        "gnu affero general public license",
        "free software foundation either version 3 of the license",
    ],
    "MPL-2.0": [
        "mozilla public license version 2 0",
        "this source code form is subject to the terms of the mozilla "
        "public license v 2 0",
    ],
    "ISC": [
        "permission to use copy modify and or distribute this software "
        "for any purpose with or without fee is hereby granted",
        "the software is provided as is and the author disclaims all "
        "warranties",
    ],
    "Unlicense": [
        "this is free and unencumbered software released into the "
        "public domain",
        "in jurisdictions that recognize copyright laws",
    ],
    "CC0-1.0": [
        "cc0 1 0 universal",
        "the person who associated a work with this deed has dedicated "
        "the work to the public domain",
    ],
    "EPL-2.0": [
        "eclipse public license v 2 0",
        "this program and the accompanying materials are made available "
        "under the terms of the eclipse public license 2 0",
    ],
    "EPL-1.0": [
        "eclipse public license v 1 0",
    ],
    "Zlib": [
        "this software is provided as is without any express or implied "
        "warranty",
        "altered source versions must be plainly marked as such",
        "the origin of this software must not be misrepresented",
    ],
    "BSL-1.0": [
        "boost software license version 1 0",
        "permission is hereby granted free of charge to any person or "
        "organization obtaining a copy of the software",
    ],
    "WTFPL": [
        "do what the fuck you want to public license",
    ],
    "PostgreSQL": [
        "permission to use copy modify and distribute this software and "
        "its documentation for any purpose without fee",
        "in no event shall the university of california be liable",
    ],
    "OpenSSL": [
        "this product includes software developed by the openssl project",
    ],
    "Artistic-2.0": [
        "the artistic license 2 0",
        "everyone is permitted to copy and distribute verbatim copies of "
        "this license document but changing it is not allowed",
    ],
    "OFL-1.1": [
        "sil open font license version 1 1",
    ],
    "CDDL-1.0": [
        "common development and distribution license cddl version 1 0",
    ],
    "EUPL-1.2": [
        "european union public licence v 1 2",
    ],
    "MS-PL": [
        "microsoft public license ms pl",
    ],
}

_NORM_RE = re.compile(r"[^a-z0-9]+")

_NGRAM = 3


def _ngrams(text: str) -> set[tuple[str, ...]]:
    words = text.split()
    if len(words) < _NGRAM:
        return {tuple(words)} if words else set()
    return {tuple(words[i:i + _NGRAM])
            for i in range(len(words) - _NGRAM + 1)}


_GRAM_SETS: dict[str, set] = {}


def _gram_set(name: str) -> set:
    """Compiled word-trigram set of a license's excerpt corpus."""
    grams = _GRAM_SETS.get(name)
    if grams is None:
        grams = set()
        for phrase in _FINGERPRINTS.get(name, ()):
            grams |= _ngrams(phrase)
        _GRAM_SETS[name] = grams
    return grams


def add_license_text(name: str, text: str) -> None:
    """Extend the matcher with a license body (user corpus)."""
    _FINGERPRINTS.setdefault(name, []).append(
        _NORM_RE.sub(" ", text.lower()).strip())
    _GRAM_SETS.pop(name, None)


def _finding(name: str, confidence: float) -> LicenseFinding:
    return LicenseFinding(
        name=name, confidence=confidence,
        link=f"https://spdx.org/licenses/{name}.html",
    )


def _normalize_text(data: bytes | str) -> str:
    if isinstance(data, bytes):
        data = data.decode("utf-8", errors="replace")
    return _NORM_RE.sub(" ", data.lower()).strip()


def classify(file_path: str, content: bytes | str,
             confidence_level: float = 0.75) -> LicenseFile | None:
    """Classify license text in a file; None when nothing matches."""
    raw = content.decode("utf-8", errors="replace") \
        if isinstance(content, bytes) else content

    findings: list[LicenseFinding] = []
    seen: set[str] = set()
    match_type = TYPE_FILE

    for m in _SPDX_TAG_RE.finditer(raw):
        expr = m.group(1).strip()
        for name in re.split(r"\s+(?:AND|OR|WITH)\s+|[()]", expr):
            name = name.strip()
            if name and name not in seen:
                seen.add(name)
                findings.append(_finding(name, 1.0))
        match_type = TYPE_HEADER

    norm = _normalize_text(raw)
    if norm:
        doc_grams = _ngrams(norm)
        for name in _FINGERPRINTS:
            if name in seen:
                continue
            grams = _gram_set(name)
            if not grams:
                continue
            conf = len(grams & doc_grams) / len(grams)
            if conf >= confidence_level:
                seen.add(name)
                findings.append(_finding(name, round(conf, 2)))
                match_type = TYPE_FILE

    # BSD-2 fingerprint is a subset of BSD-3; prefer the more specific hit
    names = {f.name for f in findings}
    if "BSD-3-Clause" in names and "BSD-2-Clause" in names:
        bsd3 = next(f for f in findings if f.name == "BSD-3-Clause")
        bsd2 = next(f for f in findings if f.name == "BSD-2-Clause")
        if bsd3.confidence >= bsd2.confidence:
            findings.remove(bsd2)

    if not findings:
        return None
    findings.sort(key=lambda f: (-f.confidence, f.name))
    return LicenseFile(type=match_type, file_path=file_path, findings=findings)
